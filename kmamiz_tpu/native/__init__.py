"""ctypes binding for the native C++ data-loader hot path.

Loads (building on first use if needed) `native/kmamiz_native.cpp` — the
C++ twin of the reference's Rust log parser (log_matcher.rs) — and exposes
drop-in equivalents of the Python implementations in
`kmamiz_tpu.core.envoy`. Every entry point degrades to the pure-Python
path when the toolchain or library is unavailable, so the framework never
hard-requires the extension. Call `available()` once at startup to keep
the one-time compile off the request path.
"""
from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

logger = logging.getLogger("kmamiz_tpu.native")

_FIELD_SEP = "\x1f"
_RECORD_SEP = "\x1e"

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SOURCES = [
    _REPO_ROOT / "native" / "kmamiz_native.cpp",
    _REPO_ROOT / "native" / "kmamiz_json.cpp",
    _REPO_ROOT / "native" / "kmamiz_spans.cpp",
]
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_LIB_PATH = _BUILD_DIR / "libkmamiz_native.so"
_BUILD_INFO_PATH = _BUILD_DIR / "build_info.json"
_FAIL_INFO_PATH = _BUILD_DIR / "build_failed.json"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _cpu_signature() -> str:
    """Stable fingerprint of this host's ISA (the cpu flags line): a
    -march=native .so restored from a build cache onto a smaller-ISA
    host would SIGILL on first call — no symbol/mtime check can catch
    that, so the loader compares this signature instead."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()
    except OSError:
        pass
    import platform

    return platform.machine()


def _isa_mismatch() -> bool:
    """True when build_info POSITIVELY says the .so was -march=native
    compiled for a different cpu (a restored cache from another host):
    loading such a library risks SIGILL, so it must never load as-is."""
    import json

    try:
        info = json.loads(_BUILD_INFO_PATH.read_text())
    except (OSError, ValueError):
        return False  # unknown provenance: prefer rebuild, allow load
    return info.get("march") == "native" and info.get("cpu") != _cpu_signature()


def _build_is_stale() -> bool:
    """True when the cached .so should rebuild: missing, older than a
    source, compiled for a different host ISA (restored caches), or of
    unknown provenance (no build_info — rebuild pins it to THIS host)."""
    if not _LIB_PATH.exists():
        return True
    if any(
        src.exists() and src.stat().st_mtime > _LIB_PATH.stat().st_mtime
        for src in _SOURCES
    ):
        return True
    if not _BUILD_INFO_PATH.exists():
        return True
    return _isa_mismatch()


def _src_mtimes() -> dict:
    return {
        src.name: src.stat().st_mtime for src in _SOURCES if src.exists()
    }


def _build_known_failed() -> bool:
    """True when a previous process already paid the compile attempt for
    exactly these sources on exactly this host and it failed: every fresh
    process would otherwise re-run the full g++ wall (~10 s) inside its
    first tick just to rediscover the same failure."""
    import json

    try:
        info = json.loads(_FAIL_INFO_PATH.read_text())
    except (OSError, ValueError):
        return False
    return (
        info.get("cpu") == _cpu_signature()
        and info.get("mtimes") == _src_mtimes()
    )


def _build() -> bool:
    import json

    if not all(src.exists() for src in _SOURCES):
        return False
    if _build_known_failed():
        return False
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)

    def cmd_for(arch_flags):
        return [
            os.environ.get("CXX", "g++"),
            "-O3",
            *arch_flags,
            "-shared",
            "-fPIC",
            "-pthread",
            "-std=c++17",
            "-o",
            str(_LIB_PATH),
            *[str(src) for src in _SOURCES],
        ]

    # -march=native first: the .so is built on the host that runs it (the
    # DP deployment builds in its own image), and the hash/number/memcpy
    # paths gain a few percent beyond the hand-dispatched AVX2 scans.
    # Portable fallback when the toolchain rejects it. The build records
    # its ISA so a cache-restored .so never runs on a smaller host.
    for arch, label in ((["-march=native"], "native"), ([], "generic")):
        try:
            subprocess.run(
                cmd_for(arch), check=True, capture_output=True, timeout=120
            )
            try:
                _BUILD_INFO_PATH.write_text(
                    json.dumps({"march": label, "cpu": _cpu_signature()})
                )
                _FAIL_INFO_PATH.unlink(missing_ok=True)
            except OSError:
                pass
            return True
        except (subprocess.SubprocessError, OSError) as err:
            last_err = err
    logger.warning(
        "native build failed, using pure-Python path: %s", last_err
    )
    try:  # negative-cache the failure so the next process skips the wall
        _FAIL_INFO_PATH.write_text(
            json.dumps({"cpu": _cpu_signature(), "mtimes": _src_mtimes()})
        )
    except OSError:
        pass
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if _build_is_stale():
            if not _build():
                # rebuild impossible (no toolchain, stripped sources):
                # a merely stale or unknown-provenance .so still LOADS
                # — staleness prefers a rebuild but must not veto the
                # native path (missing symbols are caught below). Only
                # a positive ISA mismatch refuses: that .so can SIGILL.
                if not _LIB_PATH.exists() or _isa_mismatch():
                    _load_failed = True
                    return None
                logger.warning(
                    "native rebuild unavailable; loading existing "
                    "libkmamiz_native.so as-is"
                )
        lib = _open_and_bind()
        if lib is None and _build():
            # a stale prebuilt .so can miss newer symbols even when the
            # mtime check passed (restored build caches); rebuild once
            lib = _open_and_bind()
        if lib is None:
            _load_failed = True
            return None
        _lib = lib
        return _lib


def _open_and_bind() -> Optional[ctypes.CDLL]:
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
        for name in (
            "km_parse_envoy_lines",
            "km_strip_istio_prefix",
            "km_process_body_groups",
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_size_t),
            ]
            fn.restype = ctypes.c_void_p
        lib.km_parse_spans.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.km_parse_spans.restype = ctypes.c_void_p
        lib.km_parse_spans_mt.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.km_parse_spans_mt.restype = ctypes.c_void_p
        lib.km_split_groups.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.km_split_groups.restype = ctypes.c_void_p
        lib.km_skipset_new.argtypes = []
        lib.km_skipset_new.restype = ctypes.c_void_p
        lib.km_skipset_free.argtypes = [ctypes.c_void_p]
        lib.km_skipset_free.restype = None
        lib.km_skipset_extend.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.km_skipset_extend.restype = ctypes.c_longlong
        lib.km_skipset_clear.argtypes = [ctypes.c_void_p]
        lib.km_skipset_clear.restype = None
        lib.km_skipset_size.argtypes = [ctypes.c_void_p]
        lib.km_skipset_size.restype = ctypes.c_ulonglong
        lib.km_parse_spans_hs.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.km_parse_spans_hs.restype = ctypes.c_void_p
        lib.km_session_new.argtypes = []
        lib.km_session_new.restype = ctypes.c_void_p
        lib.km_session_free.argtypes = [ctypes.c_void_p]
        lib.km_session_free.restype = None
        lib.km_session_ack.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint32,
            ctypes.c_uint32,
        ]
        lib.km_session_ack.restype = None
        lib.km_parse_spans_sess.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.km_parse_spans_sess.restype = ctypes.c_void_p
        lib.km_free.argtypes = [ctypes.c_void_p]
        lib.km_free.restype = None
        # graftprof counter exports: OPTIONAL — a prebuilt .so that
        # predates them must still serve the parse path (prof_counters()
        # then degrades to the zero snapshot)
        try:
            lib.km_prof_snapshot.argtypes = [
                ctypes.POINTER(ctypes.c_size_t)
            ]
            lib.km_prof_snapshot.restype = ctypes.c_void_p
            lib.km_prof_reset.argtypes = []
            lib.km_prof_reset.restype = None
        except AttributeError:
            logger.warning(
                "libkmamiz_native.so predates graftprof counters; "
                "native profiling reports zeros"
            )
        # columnar wire capability + parse-shard knob: OPTIONAL — a .so
        # without km_wire_caps predates the "KMZC" frame format (the
        # binding then transcodes frames to JSON in Python)
        try:
            lib.km_wire_caps.argtypes = []
            lib.km_wire_caps.restype = ctypes.c_uint
            lib.km_set_parse_shards.argtypes = [ctypes.c_int]
            lib.km_set_parse_shards.restype = None
            shards = os.environ.get("KMAMIZ_PARSE_SHARDS")
            if shards:
                lib.km_set_parse_shards(int(shards))
        except (AttributeError, ValueError):
            logger.warning(
                "libkmamiz_native.so predates the columnar wire; "
                "KMZC frames transcode through Python"
            )
        return lib
    except (OSError, AttributeError) as err:
        logger.warning("native load failed: %s", err)
        return None


def available() -> bool:
    return _load() is not None


def supports_columnar() -> bool:
    """True when the loaded .so decodes "KMZC" columnar frames natively
    (km_wire_caps bit 0). False -> parse_spans transcodes frames to
    Zipkin JSON through kmamiz_tpu.core.wire first."""
    lib = _load()
    return lib is not None and hasattr(lib, "km_wire_caps")


# -- graftprof native counters (telemetry/profiling) -------------------------

_PROF_SCALARS_V1 = (
    "parses",
    "spans",
    "merge_ns",
    "merge_lock_wait_ns",
    "merge_queue_depth_peak",
    "claim_contended",
    "intern_probes",
    "intern_hits",
)
# v2 appends the shard-table fold counters (lock-free merge rework);
# graftlint cross-checks these names against the ProfCounters struct in
# native/kmamiz_spans.cpp (prof-counter-wire rule).
_PROF_SCALARS = _PROF_SCALARS_V1 + ("fold_ns", "fold_chunks")
_PROF_HEADER_LEN = 8 + 8 * len(_PROF_SCALARS_V1)


def _prof_zero() -> dict:
    out = {"available": False, "version": 0, "shards_used": 0, "shards": []}
    for key in _PROF_SCALARS:
        out[key] = 0
    return out


def prof_counters() -> dict:
    """Cumulative graftprof counter snapshot from the native parse/merge
    pipeline (see km_prof_snapshot in native/kmamiz_spans.cpp).

    Never raises: without the library — or with a stale prebuilt .so
    missing the symbols — the zero snapshot returns (available=False)."""
    try:
        lib = _load()
        if lib is None or not hasattr(lib, "km_prof_snapshot"):
            return _prof_zero()
        out_len = ctypes.c_size_t(0)
        ptr = lib.km_prof_snapshot(ctypes.byref(out_len))
        if not ptr:
            return _prof_zero()
        try:
            raw = ctypes.string_at(ptr, out_len.value)
        finally:
            lib.km_free(ptr)
        if len(raw) < _PROF_HEADER_LEN:
            return _prof_zero()
        out = _prof_zero()
        out["available"] = True
        out["version"], out["shards_used"] = struct.unpack_from("<II", raw, 0)
        names = _PROF_SCALARS if out["version"] >= 2 else _PROF_SCALARS_V1
        if len(raw) < 8 + 8 * len(names):
            names = _PROF_SCALARS_V1
        scalars = struct.unpack_from(f"<{len(names)}Q", raw, 8)
        for key, val in zip(names, scalars):
            out[key] = val
        off = 8 + 8 * len(names)
        for _ in range(out["shards_used"]):
            if off + 24 > len(raw):
                break
            parse_ns, wait_ns, spans = struct.unpack_from("<3Q", raw, off)
            out["shards"].append(
                {"parse_ns": parse_ns, "wait_ns": wait_ns, "spans": spans}
            )
            off += 24
        return out
    except Exception:  # noqa: BLE001 - profiling must never break ingest
        return _prof_zero()


def prof_reset() -> None:
    """Zero the native graftprof counters (tests, flight-recorder cuts).
    No-op without the library or the symbol."""
    try:
        lib = _load()
        if lib is not None and hasattr(lib, "km_prof_reset"):
            lib.km_prof_reset()
    except Exception:  # noqa: BLE001 - profiling must never break ingest
        pass


def _call_buffer_fn(fn, payload: bytes, *extra) -> Optional[str]:
    lib = _load()
    if lib is None:
        return None
    out_len = ctypes.c_size_t(0)
    ptr = fn(payload, len(payload), *extra, ctypes.byref(out_len))
    if not ptr:
        return None
    try:
        return ctypes.string_at(ptr, out_len.value).decode("utf-8", "replace")
    finally:
        lib.km_free(ptr)


def strip_istio_proxy_prefix(lines: List[str]) -> Optional[List[str]]:
    """Native twin of core.envoy.strip_istio_proxy_prefix; None -> fall back."""
    lib = _load()
    if lib is None:
        return None
    raw = _call_buffer_fn(lib.km_strip_istio_prefix, "\n".join(lines).encode())
    if raw is None:
        return None
    return raw.split("\n")[:-1] if raw else []


def parse_envoy_lines(lines: List[str]) -> Optional[List[dict]]:
    """Native twin of the per-line parse inside core.envoy.parse_envoy_logs:
    returns raw field dicts (no namespace/pod/id-map decoration), or None
    when the extension is unavailable."""
    lib = _load()
    if lib is None:
        return None
    raw = _call_buffer_fn(lib.km_parse_envoy_lines, "\n".join(lines).encode())
    if raw is None:
        return None
    records = []
    for record in raw.split(_RECORD_SEP):
        if not record:
            continue
        fields = record.split(_FIELD_SEP)
        if len(fields) != 12:
            continue
        (
            time_str,
            log_type,
            request_id,
            trace_id,
            span_id,
            parent_span_id,
            method,
            path,
            status,
            content_type,
            body,
            body_present,
        ) = fields
        if not path:  # the method/path regex requires a non-empty path
            method = ""
        records.append(
            {
                "time": time_str,
                "type": log_type,
                "requestId": request_id,
                "traceId": trace_id,
                "spanId": span_id,
                "parentSpanId": parent_span_id,
                "method": method or None,
                "path": path or None,
                "status": status or None,
                "contentType": content_type or None,
                "body": body if body_present == "1" else None,
            }
        )
    return records


# ---------------------------------------------------------------------------
# raw Zipkin JSON -> SoA span arrays (native/kmamiz_spans.cpp)
# ---------------------------------------------------------------------------

# naming-shape presence bits (must match kmamiz_spans.cpp)
SHAPE_HAS_METHOD = 1 << 2
SHAPE_HAS_SVC = 1 << 3
SHAPE_HAS_NS = 1 << 4
SHAPE_HAS_REV = 1 << 5
SHAPE_HAS_MESH = 1 << 6


def parse_threads() -> int:
    """Worker count for the native span scan: KMAMIZ_PARSE_THREADS, else 0
    (auto = hardware concurrency, capped at 16 in the extension)."""
    try:
        return int(os.environ.get("KMAMIZ_PARSE_THREADS", "0"))
    except ValueError:
        return 0


def effective_parse_threads() -> int:
    """The worker count the native scan actually runs with: the raw
    setting when explicit, else the same hardware-concurrency-capped-at-16
    resolution kmamiz_spans.cpp applies to 0/auto. Benchmarks report this
    instead of the raw env so results are comparable across machines."""
    raw = parse_threads()
    if raw > 0:
        return raw
    return max(1, min(os.cpu_count() or 1, 16))


def encode_skip_entry(tid) -> bytes:
    """One skip-set entry in the km_parse_spans_mt blob layout
    (u8 present + u32 len + utf8 bytes; None markers encode as absent).
    Callers that parse repeatedly against a growing processed set cache
    these encodings instead of re-walking the whole set every call
    (DataProcessor keeps an incremental blob)."""
    if tid is None:
        return struct.pack("<BI", 0, 0)
    b = str(tid).encode("utf-8", "surrogatepass")
    return struct.pack("<BI", 1, len(b)) + b


class SkipSet:
    """Persistent native processed-trace set (km_skipset_* C API).

    Replaces the per-parse skip blob on the streaming path: the
    DataProcessor extends it incrementally as traces register
    (`extend` takes the same skip-entry bytes `encode_skip_entry`
    produces, sans count header) and passes the handle to every parse —
    so the parse stops re-encoding and re-hashing the whole processed
    set per chunk. Falls back transparently: when the extension is
    unavailable, `handle` is None and callers use the blob path.
    Thread-safe on the native side (per-probe mutex)."""

    __slots__ = ("_lib", "_handle")

    def __init__(self) -> None:
        self._lib = _load()
        self._handle = self._lib.km_skipset_new() if self._lib else None

    @property
    def handle(self):
        return self._handle

    def extend(self, entries: bytes) -> int:
        """Add skip-entry records; returns records walked (-1 = malformed)."""
        if self._handle is None or not entries:
            return 0
        return int(
            self._lib.km_skipset_extend(
                self._handle, bytes(entries), len(entries)
            )
        )

    def clear(self) -> None:
        if self._handle is not None:
            self._lib.km_skipset_clear(self._handle)

    def __len__(self) -> int:
        if self._handle is None:
            return 0
        return int(self._lib.km_skipset_size(self._handle))

    def __del__(self) -> None:
        handle, self._handle = self._handle, None
        if handle is not None and self._lib is not None:
            try:
                self._lib.km_skipset_free(handle)
            except (OSError, AttributeError):  # interpreter teardown
                pass


def _unpack_timings(prescan_us: int, parse_us: int, merge_packed: int) -> dict:
    # threads<<25 | merge_us (25-bit µs, ~33 s cap) — see kmamiz_spans.cpp
    return {
        "prescan_us": prescan_us,
        "parse_us": parse_us,
        "merge_us": merge_packed & 0x01FFFFFF,
        "threads": merge_packed >> 25,
    }


def _read_shape_records(buf, pos: int, count: int):
    """`count` serialized shape records (u8 url_present + u8 bits + 7x
    length-prefixed field bytes) -> (records, new_pos). Fields stay raw
    BYTES tuples: consumers cache resolutions keyed on them and decode
    only on a cache miss."""
    shapes = []
    for _ in range(count):
        url_present = buf[pos] != 0
        bits = buf[pos + 1]
        pos += 2
        fields = []
        for _f in range(7):
            (flen,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            fields.append(bytes(buf[pos : pos + flen]))
            pos += flen
        shapes.append((tuple(fields), url_present, bits))
    return shapes, pos


def _read_status_records(buf, pos: int, count: int):
    statuses = []
    for _ in range(count):
        (slen,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        statuses.append(buf[pos : pos + slen].decode("utf-8", "surrogatepass"))
        pos += slen
    return statuses, pos


def _decode_session_payload(buf) -> Optional[dict]:
    """Decode the session wire format (header ok=2): span columns carry
    session-GLOBAL shape/status ids; shape/status strings appear only
    for the unacked tail [base..total). Raises like the v1 decode on
    malformed buffers (the caller's except clauses handle both)."""
    import numpy as np

    (
        _fmt,
        n,
        shapes_total,
        statuses_total,
        shape_base,
        status_base,
        n_groups,
        prescan_us,
        parse_us,
        merge_packed,
    ) = struct.unpack_from("<10I", buf, 0)
    timings = _unpack_timings(prescan_us, parse_us, merge_packed)
    pos = 40
    latency_ms = np.frombuffer(buf, np.float64, n, pos)
    pos += 8 * n
    timestamp_raw = np.frombuffer(buf, np.float64, n, pos)
    pos += 8 * n
    shape_max_ts_ms = np.frombuffer(buf, np.float64, shapes_total, pos)
    pos += 8 * shapes_total
    parent_idx = np.frombuffer(buf, np.int32, n, pos)
    pos += 4 * n
    shape_id = np.frombuffer(buf, np.int32, n, pos)
    pos += 4 * n
    status_id = np.frombuffer(buf, np.int32, n, pos)
    pos += 4 * n
    trace_of = np.frombuffer(buf, np.int32, n, pos)
    pos += 4 * n
    kind = np.frombuffer(buf, np.int8, n, pos)
    pos += n

    new_shapes, pos = _read_shape_records(buf, pos, shapes_total - shape_base)
    new_statuses, pos = _read_status_records(
        buf, pos, statuses_total - status_base
    )

    # kept trace ids, vectorized: presence + length arrays give every
    # record's offset in one cumsum; the ASCII fast path decodes the
    # whole interleaved section once and slices strings out of it (tids
    # are hex in real Zipkin data). The interleaved records are
    # byte-identical to encode_skip_entry layout, so the raw slice also
    # serves as the caller's incremental dedup-blob append.
    present = np.frombuffer(buf, np.uint8, n_groups, pos)
    pos += n_groups
    tlens = np.frombuffer(buf, np.uint32, n_groups, pos).astype(np.int64)
    pos += 4 * n_groups
    blob_len = 5 * n_groups + int(tlens.sum())
    kept_blob = buf[pos : pos + blob_len]
    if len(kept_blob) != blob_len:
        raise ValueError("truncated kept-trace-id section")
    pos += blob_len
    starts = 5 * (np.arange(n_groups, dtype=np.int64) + 1)
    starts[1:] += np.cumsum(tlens[:-1])
    ends = starts + tlens
    present_l = (present != 0).tolist()
    if kept_blob.isascii():
        s = kept_blob.decode("ascii")
        trace_ids = [
            s[a:b] if p else None
            for a, b, p in zip(starts.tolist(), ends.tolist(), present_l)
        ]
    else:
        trace_ids = [
            kept_blob[a:b].decode("utf-8", "surrogatepass") if p else None
            for a, b, p in zip(starts.tolist(), ends.tolist(), present_l)
        ]

    return {
        "n_spans": int(n),
        "kind": kind,
        "parent_idx": parent_idx,
        "shape_id": shape_id,
        "status_id": status_id,
        "trace_of": trace_of,
        "latency_ms": latency_ms,
        "timestamp_us": timestamp_raw.astype(np.int64),
        "shape_max_ts_ms": shape_max_ts_ms,
        "trace_ids": trace_ids,
        "trace_ids_blob": kept_blob,
        "timings": timings,
        "session_format": True,
        "shape_base": int(shape_base),
        "shapes_total": int(shapes_total),
        "status_base": int(status_base),
        "statuses_total": int(statuses_total),
        "new_shapes": new_shapes,
        "new_statuses": new_statuses,
    }


class ParseSession:
    """Persistent native parse session (km_session_* C API).

    Keeps the shape/status intern tables alive across parse calls so a
    chunked stream stops re-serializing and re-decoding ~10k identical
    naming shapes per page: spans arrive with session-global ids and
    only NEW (unacknowledged) shapes/statuses carry strings. The caller
    acks after successfully consuming a payload; a rejected payload
    (e.g. invalid UTF-8 in a field) is simply never acked and its
    additions re-emit next call."""

    __slots__ = ("_lib", "_handle")

    def __init__(self) -> None:
        self._lib = _load()
        self._handle = self._lib.km_session_new() if self._lib else None

    @property
    def handle(self):
        return self._handle

    def ack(self, shapes_known: int, statuses_known: int) -> None:
        if self._handle is not None:
            self._lib.km_session_ack(
                self._handle, int(shapes_known), int(statuses_known)
            )

    def __del__(self) -> None:
        handle, self._handle = self._handle, None
        if handle is not None and self._lib is not None:
            try:
                self._lib.km_session_free(handle)
            except (OSError, AttributeError):  # interpreter teardown
                pass


def parse_spans(
    raw: bytes,
    skip_trace_ids: Sequence = (),
    threads: Optional[int] = None,
    skip_blob: Optional[bytes] = None,
    skipset: "Optional[SkipSet]" = None,
    session: "Optional[ParseSession]" = None,
) -> Optional[dict]:
    """Scan a raw Zipkin JSON response ([[span,...],...]) into SoA arrays.

    skip_trace_ids: already-processed trace ids (may contain None, matching
    DataProcessor._filter_traces semantics); groups whose first span carries
    one are dropped whole.

    threads: native worker count (None -> KMAMIZ_PARSE_THREADS env, 0 ->
    auto). The parallel scan preserves exact sequential semantics: group
    dedup runs in document order during the prescan, and duplicate span
    ids resolve first-position/last-wins via a document-order fixup.

    skip_blob: pre-encoded full skip blob (u32 count + encode_skip_entry
    per id) that REPLACES skip_trace_ids when given — callers with a
    large, slowly-growing processed set pass a cached blob so each parse
    doesn't re-encode the whole set.

    Returns None when the extension is unavailable or the input is
    malformed (callers fall back to json.loads + spans_to_batch), else a
    dict with numpy arrays (kind/parent_idx/shape_id/status_id/trace_of/
    latency_ms/timestamp_us), the distinct naming shapes
    [(fields7, url_present, presence_bits)], shape_max_ts_ms, distinct
    status strings, the kept trace ids (None markers preserved), and a
    "timings" dict (native phase wall times, for honest bench accounting).
    """
    import numpy as np

    lib = _load()
    if lib is None:
        return None
    if threads is None:
        threads = parse_threads()
    out_len = ctypes.c_size_t(0)
    # the json buffer crosses ctypes without a copy (c_char_p on bytes)
    raw = bytes(raw) if not isinstance(raw, bytes) else raw
    if raw[:4] == b"KMZC" and not hasattr(lib, "km_wire_caps"):
        # stale prebuilt .so without the columnar decoder: transcode the
        # frame to Zipkin JSON in Python (same rows, host-speed only)
        from kmamiz_tpu.core import wire

        raw = wire.columnar_to_json(raw)
        if raw is None:
            return None
    # explicit blob-style skip args take precedence over the persistent
    # handles: a caller that passes skip_trace_ids/skip_blob means THAT
    # set, and silently consulting a different (handle) set instead
    # would merge traces the caller asked to skip
    if skip_trace_ids or skip_blob is not None:
        session = None
        skipset = None
    if session is not None and session.handle is not None:
        # persistent-session path: global ids + delta shape emission
        ptr = lib.km_parse_spans_sess(
            session.handle,
            skipset.handle if skipset is not None else None,
            raw,
            len(raw),
            int(threads),
            ctypes.byref(out_len),
        )
    elif skipset is not None and skipset.handle is not None:
        # persistent-set path: no per-call blob at all
        ptr = lib.km_parse_spans_hs(
            skipset.handle,
            raw,
            len(raw),
            int(threads),
            ctypes.byref(out_len),
        )
    else:
        if skip_blob is None:
            skip_blob = bytearray(struct.pack("<I", len(skip_trace_ids)))
            for t in skip_trace_ids:
                skip_blob += encode_skip_entry(t)
        ptr = lib.km_parse_spans_mt(
            bytes(skip_blob),
            len(skip_blob),
            raw,
            len(raw),
            int(threads),
            ctypes.byref(out_len),
        )
    if not ptr:
        return None
    try:
        buf = ctypes.string_at(ptr, out_len.value)
    finally:
        lib.km_free(ptr)

    try:
        (fmt,) = struct.unpack_from("<I", buf, 0)
        if fmt == 2:
            return _decode_session_payload(buf)
        (
            ok,
            n,
            n_shapes,
            n_statuses,
            n_groups,
            prescan_us,
            parse_us,
            merge_packed,
        ) = struct.unpack_from("<8I", buf, 0)
        if ok != 1:
            return None
        timings = _unpack_timings(prescan_us, parse_us, merge_packed)
        pos = 32
        # read-only VIEWS over `buf` (which the arrays keep alive via
        # .base): raw_spans_to_batch copies once into its padded arrays,
        # so eager copies here would be a second full pass
        latency_ms = np.frombuffer(buf, np.float64, n, pos)
        pos += 8 * n
        timestamp_raw = np.frombuffer(buf, np.float64, n, pos)
        pos += 8 * n
        shape_max_ts_ms = np.frombuffer(buf, np.float64, n_shapes, pos)
        pos += 8 * n_shapes
        parent_idx = np.frombuffer(buf, np.int32, n, pos)
        pos += 4 * n
        shape_id = np.frombuffer(buf, np.int32, n, pos)
        pos += 4 * n
        status_id = np.frombuffer(buf, np.int32, n, pos)
        pos += 4 * n
        trace_of = np.frombuffer(buf, np.int32, n, pos)
        pos += 4 * n
        kind = np.frombuffer(buf, np.int8, n, pos)
        pos += n

        shapes, pos = _read_shape_records(buf, pos, n_shapes)
        statuses, pos = _read_status_records(buf, pos, n_statuses)

        trace_ids = []
        for _ in range(n_groups):
            present = buf[pos] != 0
            (tlen,) = struct.unpack_from("<I", buf, pos + 1)
            pos += 5
            tid = buf[pos : pos + tlen].decode("utf-8", "surrogatepass")
            pos += tlen
            trace_ids.append(tid if present else None)
    except UnicodeDecodeError:
        # string fields carried invalid UTF-8: JSON must be UTF-8, so the
        # payload is malformed — reject, exactly like the json.loads path
        logger.warning("span payload contains invalid UTF-8; rejected")
        return None
    except (struct.error, IndexError, ValueError):
        # ValueError: np.frombuffer on a truncated buffer (stale .so ABI)
        logger.warning("native span decode failed, using Python path")
        return None

    return {
        "n_spans": int(n),
        "kind": kind,
        "parent_idx": parent_idx,
        "shape_id": shape_id,
        "status_id": status_id,
        "trace_of": trace_of,
        "latency_ms": latency_ms,
        "timestamp_us": timestamp_raw.astype(np.int64),
        "shapes": shapes,
        "shape_max_ts_ms": shape_max_ts_ms,
        "statuses": statuses,
        "trace_ids": trace_ids,
        "timings": timings,
    }


def split_groups(raw: bytes, n_chunks: int) -> Optional[List[bytes]]:
    """Split a raw Zipkin response into <= n_chunks standalone responses,
    each covering whole trace groups (for the streaming ingest pipeline).
    Returns None when the extension is unavailable or the input is
    malformed."""
    lib = _load()
    if lib is None:
        return None
    raw = bytes(raw) if not isinstance(raw, bytes) else raw
    out_len = ctypes.c_size_t(0)
    ptr = lib.km_split_groups(raw, len(raw), int(n_chunks), ctypes.byref(out_len))
    if not ptr:
        return None
    try:
        buf = ctypes.string_at(ptr, out_len.value)
    finally:
        lib.km_free(ptr)
    try:
        (n_ranges,) = struct.unpack_from("<I", buf, 0)
        chunks = []
        pos = 4
        for _ in range(n_ranges):
            begin, end = struct.unpack_from("<2Q", buf, pos)
            pos += 16
            chunks.append(b"[" + raw[begin:end] + b"]")
        return chunks
    except (struct.error, IndexError):
        return None


# ---------------------------------------------------------------------------
# batched JSON body merge + schema inference (native/kmamiz_json.cpp, the
# C++ twin of the reference's Rust json_utils.rs)
# ---------------------------------------------------------------------------

BodyGroup = Tuple[Sequence[Optional[str]], bool]  # (bodies, want_interface)


def process_body_groups(
    groups: Sequence[BodyGroup],
) -> Optional[List[Optional[Tuple[Optional[str], Optional[str], bool]]]]:
    """Fold merge_string_body over each group's bodies and (optionally) infer
    the merged body's interface string, all in one native call.

    Returns one entry per group:
      (merged_body_or_None, interface_or_None, interface_needs_python)
    or None for a group the native side delegates back to pure Python
    (excessive nesting). Returns None overall when the extension is
    unavailable or the call fails.
    """
    lib = _load()
    if lib is None:
        return None
    buf = bytearray()
    buf += struct.pack("<I", len(groups))
    for bodies, want_interface in groups:
        buf.append(1 if want_interface else 0)
        buf += struct.pack("<I", len(bodies))
        for body in bodies:
            if body is None:
                buf.append(0)
            else:
                raw = body.encode("utf-8", "surrogatepass")
                buf.append(1)
                buf += struct.pack("<I", len(raw))
                buf += raw

    out_len = ctypes.c_size_t(0)
    payload = bytes(buf)
    ptr = lib.km_process_body_groups(payload, len(payload), ctypes.byref(out_len))
    if not ptr:
        return None
    try:
        raw_out = ctypes.string_at(ptr, out_len.value)
    finally:
        lib.km_free(ptr)

    try:
        pos = 0
        (n_groups,) = struct.unpack_from("<I", raw_out, pos)
        pos += 4
        results: List[Optional[Tuple[Optional[str], Optional[str], bool]]] = []
        for _ in range(n_groups):
            status = raw_out[pos]
            pos += 1
            if status == 1:  # python-fallback group
                results.append(None)
                continue
            merged: Optional[str] = None
            if raw_out[pos]:
                pos += 1
                (mlen,) = struct.unpack_from("<I", raw_out, pos)
                pos += 4
                merged = raw_out[pos : pos + mlen].decode("utf-8", "surrogatepass")
                pos += mlen
            else:
                pos += 1
            iface_flag = raw_out[pos]
            pos += 1
            interface: Optional[str] = None
            if iface_flag == 1:
                (ilen,) = struct.unpack_from("<I", raw_out, pos)
                pos += 4
                interface = raw_out[pos : pos + ilen].decode(
                    "utf-8", "surrogatepass"
                )
                pos += ilen
            results.append((merged, interface, iface_flag == 2))
        return results
    except (struct.error, IndexError):
        logger.warning("native body-group decode failed, using Python path")
        return None
