"""The fleet coordinator: one logical DP endpoint over N workers.

Routes every KMZC/JSON ingest frame to the tenant's ring owner, folds
the workers' host-local graphs into one aggregate view through the
existing shape-keyed merge programs (hierarchical two-level merge:
worker-local window merges are level one, the coordinator's
``fold_named_edges`` set-union is level two — the host-tier analogue of
the device mesh's ICI-then-DCN reduce), and carries the migration
machinery's routing state: per-tenant overrides that flip atomically at
commit, and drain queues that hold frames during a handoff so a
mid-migration burst loses nothing.

Transports decouple the decision logic from deployment shape:
``LocalTransport`` calls :class:`~kmamiz_tpu.fleet.worker.FleetWorker`
methods directly (in-process fleets — tests, default soak);
``HTTPTransport`` speaks the DP server's ``/fleet/*`` routes (real
worker processes — bench, ``KMAMIZ_FLEET_PROC=1``).
"""
from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from typing import Dict, Iterable, List, Optional

from kmamiz_tpu import fleet as fleet_mod
from kmamiz_tpu.fleet.ring import HashRing, RingError
from kmamiz_tpu.telemetry.profiling.events import now_ms

logger = logging.getLogger(__name__)


class TransportError(RuntimeError):
    """A worker could not be reached or answered a non-2xx."""


class LocalTransport:
    """Direct method dispatch onto in-process FleetWorker instances."""

    def __init__(self, workers: Dict[str, "FleetWorker"]) -> None:
        self._workers = dict(workers)

    def _worker(self, worker_id: str):
        try:
            return self._workers[worker_id]
        except KeyError:
            raise TransportError(f"unknown worker {worker_id!r}") from None

    def ingest(self, worker_id: str, tenant: str, raw: bytes) -> dict:
        return self._worker(worker_id).ingest(tenant, raw)

    def signature(self, worker_id: str, tenant: str) -> str:
        return self._worker(worker_id).signature(tenant)

    def export_edges(self, worker_id: str, tenant: str) -> dict:
        return self._worker(worker_id).export_edges(tenant)

    def drain(self, worker_id: str, tenant: str) -> dict:
        return self._worker(worker_id).drain(tenant)

    def wal_export(self, worker_id: str, tenant: str) -> bytes:
        return self._worker(worker_id).wal_export(tenant)

    def wal_import(self, worker_id: str, tenant: str, data: bytes) -> dict:
        return self._worker(worker_id).wal_import(tenant, data)

    def commit_import(self, worker_id: str, tenant: str) -> dict:
        return self._worker(worker_id).commit_import(tenant)

    def abort_import(self, worker_id: str, tenant: str) -> dict:
        return self._worker(worker_id).abort_import(tenant)

    def drop_tenant(self, worker_id: str, tenant: str) -> dict:
        return self._worker(worker_id).drop_tenant(tenant)

    def timings(self, worker_id: str) -> dict:
        worker = self._worker(worker_id)
        return {"worker": worker.summary()}


class HTTPTransport:
    """The same verbs over the DP server's /fleet/* routes. Tenant
    addressing rides the path prefix (/t/<tenant>/...), matching the
    router's resolution order (docs/TENANCY.md)."""

    def __init__(
        self, endpoints: Dict[str, str], timeout_s: float = 30.0
    ) -> None:
        # worker id -> base URL, e.g. {"w0": "http://127.0.0.1:8601"}
        self._endpoints = dict(endpoints)
        self._timeout_s = timeout_s

    def _url(self, worker_id: str, tenant: Optional[str], path: str) -> str:
        try:
            base = self._endpoints[worker_id].rstrip("/")
        except KeyError:
            raise TransportError(f"unknown worker {worker_id!r}") from None
        prefix = f"/t/{tenant}" if tenant else ""
        return f"{base}{prefix}{path}"

    def _request(
        self, url: str, data: Optional[bytes] = None, raw: bool = False
    ):
        req = urllib.request.Request(url, data=data)
        if data is not None:
            req.add_header("Content-Type", "application/octet-stream")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout_s) as resp:
                body = resp.read()
        except (urllib.error.URLError, OSError, TimeoutError) as err:
            raise TransportError(f"{url}: {err}") from err
        return body if raw else json.loads(body)

    def ingest(self, worker_id: str, tenant: str, raw: bytes) -> dict:
        return self._request(self._url(worker_id, tenant, "/ingest"), raw)

    def signature(self, worker_id: str, tenant: str) -> str:
        out = self._request(self._url(worker_id, tenant, "/fleet/signature"))
        return out["signature"]

    def export_edges(self, worker_id: str, tenant: str) -> dict:
        return self._request(self._url(worker_id, tenant, "/fleet/export"))

    def drain(self, worker_id: str, tenant: str) -> dict:
        return self._request(self._url(worker_id, tenant, "/fleet/drain"), b"")

    def wal_export(self, worker_id: str, tenant: str) -> bytes:
        return self._request(
            self._url(worker_id, tenant, "/fleet/wal"), raw=True
        )

    def wal_import(self, worker_id: str, tenant: str, data: bytes) -> dict:
        return self._request(
            self._url(worker_id, tenant, "/fleet/wal-import"), data
        )

    def commit_import(self, worker_id: str, tenant: str) -> dict:
        return self._request(
            self._url(worker_id, tenant, "/fleet/wal-commit"), b""
        )

    def abort_import(self, worker_id: str, tenant: str) -> dict:
        return self._request(
            self._url(worker_id, tenant, "/fleet/wal-abort"), b""
        )

    def drop_tenant(self, worker_id: str, tenant: str) -> dict:
        return self._request(self._url(worker_id, tenant, "/fleet/drop"), b"")

    def timings(self, worker_id: str) -> dict:
        return self._request(self._url(worker_id, None, "/timings"))


class FleetCoordinator:
    """Ring-driven routing + migration bookkeeping over a transport."""

    def __init__(self, ring: HashRing, transport) -> None:
        self._ring = ring
        self._transport = transport
        # routing state shared across request threads and the migration
        # thread: every read/write holds _lock (graftlint's
        # unguarded-shared-state rule scans this module)
        self._lock = threading.RLock()
        # begin_drain waits on this until the tenant's in-flight ingest
        # sends (dispatched pre-drain, still on the wire) have landed, so
        # a frame can never slip onto the source AFTER drain() captured
        # the signature/record count it must reproduce on the target
        self._barrier = threading.Condition(self._lock)
        self._overrides: Dict[str, str] = {}
        self._draining: set = set()
        self._queues: Dict[str, List[bytes]] = {}
        self._inflight: Dict[str, int] = {}

    @property
    def transport(self):
        return self._transport

    def swap_transport(self, transport):
        """Replace the transport, returning the old one — the soak's
        mid-handoff injection point and the chaos harness's worker-death
        stand-in both splice proxies in here."""
        with self._lock:
            old, self._transport = self._transport, transport
            return old

    @property
    def ring(self) -> HashRing:
        with self._lock:
            return self._ring

    def owner(self, tenant: str) -> str:
        """Migration override first, ring second — the override IS the
        flipped ring entry until a ring rebuild absorbs it."""
        with self._lock:
            override = self._overrides.get(tenant)
            if override is not None:
                return override
            return self._ring.owner(tenant)

    # -- ingest routing ------------------------------------------------------

    def route_ingest(self, tenant: str, raw: bytes) -> Optional[dict]:
        """Send one frame to the tenant's owner; while the tenant is
        draining for migration the frame parks in its queue instead
        (released to whichever side the migration resolves to), so a
        handoff never drops an in-flight window. A backlog left behind
        by an earlier failed queue release delivers first, preserving
        arrival order. Returns the worker's ingest summary, or None for
        a frame that is (still) queued."""
        with self._lock:
            if tenant in self._draining:
                self._queues.setdefault(tenant, []).append(raw)
                fleet_mod.incr("framesQueuedDuringDrain")
                return None
            worker_id = self.owner(tenant)
            backlog = self._queues.pop(tenant, None)
            if backlog:
                backlog.append(raw)
            else:
                backlog = None
                self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        if backlog is not None:
            summaries = self._flush(tenant, worker_id, backlog)
            # the new frame is last in the backlog: its summary came
            # back only if the whole backlog flushed
            if len(summaries) == len(backlog):
                return summaries[-1]
            return None
        try:
            summary = self._transport.ingest(worker_id, tenant, raw)
        finally:
            self._ingest_done(tenant)
        fleet_mod.incr("framesRouted")
        return summary

    def _ingest_done(self, tenant: str) -> None:
        with self._lock:
            n = self._inflight.get(tenant, 0) - 1
            if n > 0:
                self._inflight[tenant] = n
            else:
                self._inflight.pop(tenant, None)
            self._barrier.notify_all()

    # -- hierarchical fold ---------------------------------------------------

    def fold(self, tenants: Iterable[str], graph) -> int:
        """Level-two merge: pull each tenant's name-based edge export
        from its owner and set-union everything into ``graph`` (an
        EndpointGraph — usually the coordinator's aggregate store). The
        fold rides merge_edges' pow2-padded warm programs, so folding a
        freshly joined worker compiles nothing new. Returns total live
        edges folded."""
        folded = 0
        for tenant in tenants:
            export = self._transport.export_edges(self.owner(tenant), tenant)
            folded += graph.fold_named_edges(export)
        fleet_mod.incr("folds")
        fleet_mod.incr("foldedEdges", folded)
        return folded

    # -- migration hooks (fleet/migration.py drives these) -------------------

    def begin_drain(
        self, tenant: str, barrier_timeout_s: Optional[float] = None
    ) -> str:
        """Mark a tenant draining (frames queue from here on) and wait
        for the tenant's in-flight ingest sends to land before
        returning, so the source's drain() snapshot cannot race a frame
        already on the wire. Returns the current owner (the migration
        source). A barrier timeout rolls the drain flag back — frames
        queued while waiting stay parked and route_ingest's backlog
        path delivers them — and raises RingError."""
        if barrier_timeout_s is None:
            timeout_ms = fleet_mod.drain_timeout_ms()
            barrier_timeout_s = timeout_ms / 1000.0 if timeout_ms else 30.0
        with self._lock:
            if tenant in self._draining:
                raise RingError(f"tenant {tenant!r} is already draining")
            self._draining.add(tenant)
            self._queues.setdefault(tenant, [])
            deadline = now_ms() + barrier_timeout_s * 1000.0
            while self._inflight.get(tenant, 0):
                remaining = (deadline - now_ms()) / 1000.0
                if remaining <= 0:
                    self._draining.discard(tenant)
                    raise RingError(
                        f"tenant {tenant!r} drain barrier timed out with "
                        f"{self._inflight[tenant]} ingest send(s) in flight"
                    )
                self._barrier.wait(remaining)
            return self.owner(tenant)

    def commit_migration(self, tenant: str, target: str) -> List[dict]:
        """Flip the ring entry (override) to the target and release the
        drain queue there, in arrival order. The flip and the queue
        capture are atomic; the flush itself happens outside the lock so
        slow worker I/O never blocks routing of other tenants."""
        with self._lock:
            if target not in self._ring.workers:
                raise RingError(f"target {target!r} is not on the ring")
            self._overrides[tenant] = target
            self._draining.discard(tenant)
            queued = self._queues.pop(tenant, [])
        return self._flush(tenant, target, queued)

    def abort_migration(self, tenant: str) -> List[dict]:
        """Migration failed: clear the drain flag WITHOUT touching the
        ring and release the queue back to the unchanged owner — the
        source keeps serving from its intact state (no split-brain)."""
        with self._lock:
            self._draining.discard(tenant)
            queued = self._queues.pop(tenant, [])
            owner = self.owner(tenant)
        return self._flush(tenant, owner, queued)

    def _flush(
        self, tenant: str, worker_id: str, queued: List[bytes]
    ) -> List[dict]:
        """Replay parked frames to ``worker_id`` in arrival order.
        Never raises and never loses a frame: a send that fails (worker
        unreachable mid-release — the kill -9 abort path) or a fresh
        drain starting mid-flush puts the unsent remainder back at the
        FRONT of the tenant's queue, where the next drain resolution or
        route_ingest's backlog path delivers it. Returns the summaries
        of the frames that did land."""
        summaries: List[dict] = []
        for pos, raw in enumerate(queued):
            with self._lock:
                if tenant in self._draining:
                    self._requeue_locked(tenant, queued[pos:])
                    return summaries
                self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
            try:
                summaries.append(
                    self._transport.ingest(worker_id, tenant, raw)
                )
            except Exception as err:  # noqa: BLE001 - frames must survive
                with self._lock:
                    self._requeue_locked(tenant, queued[pos:])
                logger.warning(
                    "fleet flush to %s failed for tenant %s (%s frame(s) "
                    "re-queued): %s",
                    worker_id,
                    tenant,
                    len(queued) - pos,
                    err,
                )
                return summaries
            finally:
                self._ingest_done(tenant)
            fleet_mod.incr("framesRouted")
        return summaries

    def _requeue_locked(self, tenant: str, frames: List[bytes]) -> None:
        self._queues.setdefault(tenant, [])[:0] = frames
        fleet_mod.incr("framesRequeued", len(frames))

    def snapshot(self) -> dict:
        """Routing-state view for /timings and the grafana ring panel."""
        with self._lock:
            return {
                "ring": self._ring.describe(),
                "overrides": dict(self._overrides),
                "draining": sorted(self._draining),
                "queuedFrames": {
                    t: len(q) for t, q in self._queues.items() if q
                },
                "inflight": dict(self._inflight),
            }
