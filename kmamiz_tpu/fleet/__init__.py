"""graftfleet: sharded multi-process ingest fleet (docs/FLEET.md).

One host's front end (PR 12/15: sharded native parse, KMZC columnar
wire, per-tenant WAL) scales out behind one logical DP endpoint: N
worker processes own disjoint tenant sets assigned by a seeded
consistent-hash ring (:mod:`.ring`), a coordinator folds their
host-local graphs through the existing shape-keyed merge programs
(:mod:`.coordinator`), and tenants move between workers live via
WAL-handoff migration (:mod:`.migration`) with graftpilot/graftcost
forecasts scoring the placement (:mod:`.placement`).

Env knobs (docs/ENVIRONMENT.md):

- ``KMAMIZ_FLEET_SIZE`` — worker count behind the endpoint (default 1;
  >= 2 turns fleet routing on).
- ``KMAMIZ_FLEET_VNODES`` — virtual nodes per worker on the ring
  (default 64).
- ``KMAMIZ_FLEET_SEED`` — ring hash seed; every process that shares it
  computes identical tenant placements (default 0).
- ``KMAMIZ_FLEET_COORD_PORT`` — coordinator bind port (default 0 = an
  ephemeral port, test-friendly).
- ``KMAMIZ_FLEET_DRAIN_TIMEOUT_MS`` — ceiling on a migration's drain
  phase before it aborts back to the source (default 5000).

This module owns the fleet-wide counters surfaced as the ``fleet``
section of ``/timings`` (snapshot); like every other subsystem registry
they are process-wide, so tests reset them via ``reset_for_tests``.
"""
from __future__ import annotations

import os
import threading

_DEFAULT_VNODES = 64
_DEFAULT_DRAIN_TIMEOUT_MS = 5000.0


def fleet_size() -> int:
    """Workers behind the logical endpoint (KMAMIZ_FLEET_SIZE, >= 1)."""
    try:
        return max(1, int(os.environ.get("KMAMIZ_FLEET_SIZE", "1")))
    except ValueError:
        return 1


def fleet_vnodes() -> int:
    """Virtual nodes per worker on the ring (KMAMIZ_FLEET_VNODES)."""
    try:
        return max(1, int(os.environ.get("KMAMIZ_FLEET_VNODES", str(_DEFAULT_VNODES))))
    except ValueError:
        return _DEFAULT_VNODES


def fleet_seed() -> int:
    """Ring hash seed (KMAMIZ_FLEET_SEED) — identical across processes
    by construction, so every front end routes a tenant the same way."""
    try:
        return int(os.environ.get("KMAMIZ_FLEET_SEED", "0"))
    except ValueError:
        return 0


def coordinator_port() -> int:
    """Coordinator bind port (KMAMIZ_FLEET_COORD_PORT, 0 = ephemeral)."""
    try:
        return max(0, int(os.environ.get("KMAMIZ_FLEET_COORD_PORT", "0")))
    except ValueError:
        return 0


def drain_timeout_ms() -> float:
    """Migration drain-phase ceiling (KMAMIZ_FLEET_DRAIN_TIMEOUT_MS)."""
    try:
        return max(
            0.0,
            float(
                os.environ.get(
                    "KMAMIZ_FLEET_DRAIN_TIMEOUT_MS",
                    str(_DEFAULT_DRAIN_TIMEOUT_MS),
                )
            ),
        )
    except ValueError:
        return _DEFAULT_DRAIN_TIMEOUT_MS


def enabled() -> bool:
    """Fleet routing mode is on when more than one worker is configured."""
    return fleet_size() >= 2


# -- fleet-wide counters (the `fleet` /timings section) ----------------------
# each also mirrors into a graftscope registry counter (preallocated at
# import — incr runs on the frame-routing hot path), feeding the
# grafana Fleet row's kmamiz_fleet_* series
from kmamiz_tpu.telemetry.registry import REGISTRY

_PROM_COUNTERS = {
    "framesRouted": REGISTRY.counter(
        "kmamiz_fleet_frames_routed_total",
        "Ingest frames the coordinator dispatched to a ring owner",
    ),
    "framesQueuedDuringDrain": REGISTRY.counter(
        "kmamiz_fleet_frames_queued_total",
        "Frames parked in a drain queue while their tenant migrated",
    ),
    "framesRequeued": REGISTRY.counter(
        "kmamiz_fleet_frames_requeued_total",
        "Queued frames put back after a failed release (none dropped)",
    ),
    "folds": REGISTRY.counter(
        "kmamiz_fleet_folds_total",
        "Hierarchical level-two folds into an aggregate graph",
    ),
    "foldedEdges": REGISTRY.counter(
        "kmamiz_fleet_folded_edges_total",
        "Live edges set-unioned by coordinator folds",
    ),
    "migrationsStarted": REGISTRY.counter(
        "kmamiz_fleet_migrations_started_total",
        "Live tenant migrations entered (drain began)",
    ),
    "migrationsCompleted": REGISTRY.counter(
        "kmamiz_fleet_migrations_completed_total",
        "Migrations that replayed bit-exact and flipped the ring entry",
    ),
    "migrationsAborted": REGISTRY.counter(
        "kmamiz_fleet_migrations_aborted_total",
        "Migrations aborted back to the source (no split-brain path)",
    ),
}

_counters_lock = threading.Lock()


def _fresh_counters() -> dict:
    return {
        "framesRouted": 0,
        "framesQueuedDuringDrain": 0,
        "framesRequeued": 0,
        "folds": 0,
        "foldedEdges": 0,
        "migrationsStarted": 0,
        "migrationsCompleted": 0,
        "migrationsAborted": 0,
    }


_counters = _fresh_counters()


def incr(name: str, by: int = 1) -> None:
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + by
    handle = _PROM_COUNTERS.get(name)
    if handle is not None:
        handle.inc(by)


def snapshot() -> dict:
    """The `fleet` section of /timings: static knob values plus the
    routing/migration counters accumulated since the last reset."""
    with _counters_lock:
        counters = dict(_counters)
    return {
        "size": fleet_size(),
        "vnodes": fleet_vnodes(),
        "seed": fleet_seed(),
        "enabled": enabled(),
        **counters,
    }


def reset_for_tests() -> None:
    """Drop the process-wide fleet counters (conftest autouse)."""
    global _counters
    with _counters_lock:
        _counters = _fresh_counters()


from kmamiz_tpu.fleet.ring import HashRing, RingError  # noqa: E402

__all__ = [
    "HashRing",
    "RingError",
    "coordinator_port",
    "drain_timeout_ms",
    "enabled",
    "fleet_seed",
    "fleet_size",
    "fleet_vnodes",
    "incr",
    "reset_for_tests",
    "snapshot",
]
