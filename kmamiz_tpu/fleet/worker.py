"""One fleet front end: per-tenant processors under a worker identity.

A ``FleetWorker`` is the unit the ring assigns tenants to. Each owned
tenant gets its own ``DataProcessor`` (the full PR-12 ingest path:
sharded native parse, KMZC decode, quarantine, graph merge) whose WAL
logs under the WORKER's namespace — ``<wal-root>/workers/<worker-id>/
tenants/<tenant>`` — so a migration ships exactly one directory's worth
of records and two workers never contend on one WAL file.

The class runs in two modes:

- **in-process** (tests, the default scenario soak): N ``FleetWorker``
  instances in one process behind a ``LocalTransport`` — every routing,
  fold, and migration decision is identical to the multi-process
  deployment, without N jax startups per test.
- **subprocess** (bench, ``KMAMIZ_FLEET_PROC=1`` soaks): ``main()``
  boots a real ``DataProcessorServer`` per worker; the coordinator
  speaks the ``/fleet/*`` routes over HTTP (``HTTPTransport``).
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional

from kmamiz_tpu.fleet.ring import RingError
from kmamiz_tpu.resilience.chaos import graph_signature
from kmamiz_tpu.resilience.wal import IngestWAL
from kmamiz_tpu.tenancy.arena import valid_tenant


def _stub_source(_look_back: int, _end_ts: int, _limit: int) -> List[list]:
    """Fleet workers are ingest-driven; the poll source stays empty."""
    return []


class FleetWorker:
    """Per-tenant processors + WAL namespaces under one worker id."""

    def __init__(
        self,
        worker_id: str,
        wal_root: Optional[str] = None,
        trace_source: Optional[Callable] = None,
    ) -> None:
        if not isinstance(worker_id, str) or not valid_tenant(worker_id):
            raise RingError(f"invalid worker id: {worker_id!r}")
        self.worker_id = worker_id
        self._wal_root = wal_root
        self._trace_source = trace_source or _stub_source
        # tenant processors are created lazily on first frame; creation
        # and the migration-time swap both serialize here
        self._lock = threading.RLock()
        self._procs: Dict[str, "DataProcessor"] = {}
        # replayed-but-unverified migration imports stage here until the
        # coordinator's signature check commits (or aborts) them — an
        # aborted handoff never leaves a divergent graph serving
        self._pending_imports: Dict[str, "DataProcessor"] = {}
        self._frames = 0
        self._spans = 0

    # -- tenant processors ---------------------------------------------------

    def _tenant_wal(self, tenant: str) -> Optional[IngestWAL]:
        if self._wal_root is None:
            return None
        return IngestWAL(
            os.path.join(
                self._wal_root, "workers", self.worker_id, "tenants", tenant
            )
        )

    def _fresh_processor(self, tenant: str) -> "DataProcessor":
        from kmamiz_tpu.server.processor import DataProcessor

        return DataProcessor(
            self._trace_source,
            use_device_stats=False,
            tenant=tenant,
            wal=self._tenant_wal(tenant),
        )

    def processor(self, tenant: str) -> "DataProcessor":
        """Get-or-create the tenant's processor (ring owners only — the
        coordinator enforces placement, the worker just serves)."""
        with self._lock:
            proc = self._procs.get(tenant)
            if proc is None:
                proc = self._fresh_processor(tenant)
                self._procs[tenant] = proc
            return proc

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._procs)

    def drop_tenant(self, tenant: str) -> dict:
        """Forget a migrated-away tenant (its WAL directory stays on
        disk as the abort-path safety net until the next import)."""
        with self._lock:
            proc = self._procs.pop(tenant, None)
        if proc is not None and proc.wal is not None:
            proc.wal.close()
        return {
            "tenant": tenant,
            "worker": self.worker_id,
            "dropped": proc is not None,
        }

    # -- ingest / fold surface ----------------------------------------------

    def ingest(self, tenant: str, raw: bytes) -> dict:
        summary = self.processor(tenant).ingest_raw_window(raw)
        with self._lock:
            self._frames += 1
            self._spans += int(summary.get("spans", 0))
        return summary

    def signature(self, tenant: str) -> str:
        return graph_signature(self.processor(tenant).graph)

    def export_edges(self, tenant: str) -> dict:
        return self.processor(tenant).graph.export_named_edges()

    # -- migration surface (fleet/migration.py drives these) -----------------

    def drain(self, tenant: str) -> dict:
        """Quiesce a tenant for handoff: retire in-flight merges at the
        graph's stage_fence, then report the pre-drain signature and the
        durable record count the target must reproduce."""
        proc = self.processor(tenant)
        proc.graph.stage_fence()
        wal = proc.wal
        return {
            "tenant": tenant,
            "worker": self.worker_id,
            "signature": graph_signature(proc.graph),
            "walRecords": wal.record_count() if wal is not None else 0,
        }

    def wal_export(self, tenant: str) -> bytes:
        wal = self.processor(tenant).wal
        if wal is None:
            raise RuntimeError(
                f"tenant {tenant!r} has no WAL on worker {self.worker_id!r}"
                " (migration needs durability; set a wal_root)"
            )
        return wal.export_handoff()

    def wal_import(self, tenant: str, data: bytes) -> dict:
        """Receive a migrating tenant: a FRESH processor (empty dedup
        map, empty graph, truncated WAL namespace) imports the shipped
        records and replays them in order — id assignment follows replay
        order, so the rebuilt graph's signature is bit-exact with the
        source's pre-drain one. The rebuilt processor only STAGES here
        (phase one): it starts serving when the coordinator's
        signature/record-count verification calls commit_import, and an
        aborted migration discards it via abort_import without ever
        touching the tenant's live entry."""
        proc = self._fresh_processor(tenant)
        if proc.wal is None:
            raise RuntimeError(
                f"worker {self.worker_id!r} has no wal_root; cannot import"
            )
        proc.wal.truncate()
        imported = proc.wal.import_handoff(data)
        replayed = proc.replay_wal()
        with self._lock:
            stale = self._pending_imports.pop(tenant, None)
            self._pending_imports[tenant] = proc
        if (
            stale is not None
            and stale.wal is not None
            and stale.wal is not proc.wal
        ):
            stale.wal.close()
        return {
            "tenant": tenant,
            "worker": self.worker_id,
            "records": imported,
            "replayed": replayed["replayed"],
            "spans": replayed["spans"],
            "signature": graph_signature(proc.graph),
        }

    def commit_import(self, tenant: str) -> dict:
        """Phase two: the coordinator verified the replay — install the
        staged processor as the tenant's live entry (replacing any stale
        one) so the first post-flip frame serves the migrated graph."""
        with self._lock:
            proc = self._pending_imports.pop(tenant, None)
            if proc is None:
                raise RingError(
                    f"no pending import for tenant {tenant!r} on worker "
                    f"{self.worker_id!r}"
                )
            old = self._procs.get(tenant)
            self._procs[tenant] = proc
        if old is not None and old.wal is not None and old.wal is not proc.wal:
            old.wal.close()
        return {"tenant": tenant, "worker": self.worker_id, "installed": True}

    def abort_import(self, tenant: str) -> dict:
        """The migration aborted: discard the staged processor. The
        tenant's live entry (if any) was never touched, so this worker
        keeps serving exactly what it served before the handoff."""
        with self._lock:
            proc = self._pending_imports.pop(tenant, None)
        if proc is not None and proc.wal is not None:
            proc.wal.close()
        return {
            "tenant": tenant,
            "worker": self.worker_id,
            "dropped": proc is not None,
        }

    def summary(self) -> dict:
        with self._lock:
            return {
                "worker": self.worker_id,
                "tenants": sorted(self._procs),
                "frames": self._frames,
                "spans": self._spans,
            }


def main(argv: Optional[List[str]] = None) -> None:
    """Subprocess worker entry: a DataProcessorServer whose /fleet/*
    routes serve this worker's slice. The parent namespaces durability
    by pointing KMAMIZ_WAL_DIR at the worker's own directory before
    spawn, so from_env-created tenant WALs land per-worker exactly like
    the in-process _tenant_wal layout."""
    import argparse
    import logging

    from kmamiz_tpu.server.dp_server import DataProcessorServer
    from kmamiz_tpu.server.processor import DataProcessor

    ap = argparse.ArgumentParser(description="kmamiz fleet worker")
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)

    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "WARNING").upper())
    processor = DataProcessor(_stub_source, use_device_stats=False)
    recovered = processor.replay_wal()
    if recovered["replayed"]:
        logging.getLogger("kmamiz_tpu.fleet.worker").info(
            "worker %s wal replay: %s", args.worker_id, recovered
        )
    server = DataProcessorServer(processor, host=args.host, port=args.port)
    # the parent discovers the bound port from this line (ephemeral-port
    # friendly, same contract as the scenario runner's child processes)
    print(f"FLEET_WORKER_READY {args.worker_id} {server.port}", flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()
