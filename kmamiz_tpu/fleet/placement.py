"""Forecast-driven placement: which worker should own a tenant next.

Pure scoring over observable state — no I/O, no clocks — so the same
inputs always produce the same plan (the scenario runner replays
placement decisions deterministically). Cost estimates come from the
planes that already forecast per-tenant load: graftpilot's predicted
tick costs (``control.predicted_costs``) and graftcost's learned
program-cost model (``cost.predicted_tenant_costs``); a tenant neither
plane has seen yet scores at the default weight, so placement works
ungated and merely sharpens as forecasts arrive.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from kmamiz_tpu import control as ctl_plane
from kmamiz_tpu import cost as cost_plane
from kmamiz_tpu.fleet.ring import HashRing

#: weight for a tenant with no forecast from either plane
DEFAULT_TENANT_WEIGHT = 1.0


def tenant_weights(tenants: Iterable[str]) -> Dict[str, float]:
    """Forecasted relative load per tenant: the max of graftpilot's
    predicted tick cost and graftcost's predicted run cost (both in ms;
    max, not sum, because they estimate the same underlying work from
    different signals), floored at the default weight."""
    pilot = ctl_plane.predicted_costs()
    learned = cost_plane.predicted_tenant_costs()
    weights = {}
    for tenant in tenants:
        forecast = max(
            float(pilot.get(tenant, 0.0)), float(learned.get(tenant, 0.0))
        )
        weights[tenant] = forecast if forecast > 0.0 else DEFAULT_TENANT_WEIGHT
    return weights


def worker_loads(
    ring: HashRing,
    tenants: Iterable[str],
    weights: Optional[Dict[str, float]] = None,
    overrides: Optional[Dict[str, str]] = None,
) -> Dict[str, float]:
    """worker -> summed forecast weight under the current placement
    (ring plus any migration overrides)."""
    tenants = list(tenants)
    if weights is None:
        weights = tenant_weights(tenants)
    overrides = overrides or {}
    loads = {worker: 0.0 for worker in ring.workers}
    for tenant in tenants:
        owner = overrides.get(tenant) or ring.owner(tenant)
        loads[owner] = loads.get(owner, 0.0) + weights.get(
            tenant, DEFAULT_TENANT_WEIGHT
        )
    return loads


def pick_target(
    ring: HashRing,
    tenant: str,
    tenants: Iterable[str],
    weights: Optional[Dict[str, float]] = None,
    overrides: Optional[Dict[str, str]] = None,
) -> str:
    """Least-loaded worker for a tenant about to move, its own weight
    excluded from every candidate (moving it empties its slot at the
    source). Deterministic tie-break on worker id."""
    tenants = list(tenants)
    if weights is None:
        weights = tenant_weights(tenants)
    loads = worker_loads(ring, tenants, weights=weights, overrides=overrides)
    overrides = overrides or {}
    current = overrides.get(tenant) or ring.owner(tenant)
    own = weights.get(tenant, DEFAULT_TENANT_WEIGHT)
    loads[current] -= own
    return min(sorted(loads), key=lambda worker: loads[worker])


def rebalance_plan(
    ring: HashRing,
    tenants: Iterable[str],
    weights: Optional[Dict[str, float]] = None,
    overrides: Optional[Dict[str, str]] = None,
    imbalance_ratio: float = 2.0,
    max_moves: int = 1,
) -> List[Tuple[str, str, str]]:
    """(tenant, source, target) moves that shrink forecast imbalance.

    Conservative by design: migrations cost a drain + replay, so the
    plan proposes at most ``max_moves`` and only while the hottest
    worker carries more than ``imbalance_ratio`` times the coldest's
    forecast load. Each proposed move takes the hottest worker's
    heaviest tenant to the coldest worker — the move with the best
    imbalance reduction per migration."""
    tenants = list(tenants)
    if weights is None:
        weights = tenant_weights(tenants)
    overrides = dict(overrides or {})
    moves: List[Tuple[str, str, str]] = []
    for _ in range(max(0, max_moves)):
        loads = worker_loads(
            ring, tenants, weights=weights, overrides=overrides
        )
        hot = max(sorted(loads), key=lambda worker: loads[worker])
        cold = min(sorted(loads), key=lambda worker: loads[worker])
        if hot == cold or loads[hot] <= loads[cold] * imbalance_ratio:
            break
        owned = [
            t
            for t in tenants
            if (overrides.get(t) or ring.owner(t)) == hot
        ]
        if len(owned) <= 1:
            break  # one hot tenant IS the load; moving it just moves the hotspot
        victim = max(
            sorted(owned),
            key=lambda t: weights.get(t, DEFAULT_TENANT_WEIGHT),
        )
        moves.append((victim, hot, cold))
        overrides[victim] = cold
    return moves
