"""Live tenant migration: drain -> ship WAL -> replay -> flip ring.

The handoff protocol (docs/FLEET.md):

1. **drain** — the coordinator marks the tenant draining (new frames
   queue, nothing is dropped) and asks the source worker to quiesce at
   the graph's ``stage_fence()``; the source answers with its pre-drain
   ``graph_signature`` and durable record count.
2. **ship** — the source's per-tenant WAL namespace serializes into one
   handoff blob (``IngestWAL.export_handoff``).
3. **replay** — the target imports the blob into a fresh WAL namespace
   and replays it through a fresh processor; replay order drives
   interner id assignment, so the rebuilt graph must hash bit-exact to
   the source's pre-drain signature. A mismatch is corruption, not a
   judgment call: the migration aborts. The rebuilt processor only
   STAGES on the target (``wal_import`` is phase one of a two-phase
   install) — it does not serve until the verification commits it.
4. **flip** — only after the signature check does the coordinator
   commit the staged processor on the target (``commit_import``), flip
   the ring entry, and release the drained queue to the target; the
   source then drops its copy, so exactly one worker serves the tenant.

ANY failure — source unreachable (kill -9 mid-handoff), torn blob whose
replay diverges, signature mismatch, drain timeout — takes the abort
path: the staged import is discarded (``abort_import``), the ring entry
never flipped, the queue releases back to the source, and the tenant
keeps serving from its intact last-good state on the source. A queue
release that itself hits an unreachable worker re-queues the unsent
frames instead of dropping them (coordinator._flush). There is no
intermediate state in which two workers both claim the tenant.
"""
from __future__ import annotations

from typing import Optional

from kmamiz_tpu import fleet as fleet_mod
from kmamiz_tpu.telemetry.profiling import events as prof_events


class MigrationError(RuntimeError):
    """The handoff failed; the coordinator has already aborted back to
    the source when this is raised from migrate_tenant."""


def migrate_tenant(
    coordinator,
    tenant: str,
    target: str,
    drain_timeout_ms: Optional[float] = None,
) -> dict:
    """Move one tenant to ``target`` through the WAL-handoff protocol.
    Returns a result dict (``ok``, ``source``, ``target``,
    ``signature``, ``records``, ``queuedReleased``); raises
    MigrationError after aborting when any stage fails."""
    if drain_timeout_ms is None:
        drain_timeout_ms = fleet_mod.drain_timeout_ms()
    transport = coordinator.transport
    # validate BEFORE begin_drain: a trivially bad request (unknown
    # target, tenant already there) must fail without ever pausing the
    # tenant's traffic or taking the abort/flush path
    if target not in coordinator.ring.workers:
        raise MigrationError(f"target {target!r} is not on the ring")
    if coordinator.owner(tenant) == target:
        raise MigrationError(f"tenant {tenant!r} already lives on {target!r}")
    source = coordinator.begin_drain(tenant)
    fleet_mod.incr("migrationsStarted")
    t0_ms = prof_events.now_ms()
    staged = False
    try:
        if source == target:  # owner flipped between the check and drain
            raise MigrationError(
                f"tenant {tenant!r} already lives on {target!r}"
            )
        pre = transport.drain(source, tenant)
        blob = transport.wal_export(source, tenant)
        _check_drain_budget(t0_ms, drain_timeout_ms, tenant)
        imported = transport.wal_import(target, tenant, blob)
        staged = True
        if imported["signature"] != pre["signature"]:
            raise MigrationError(
                f"tenant {tenant!r} replay diverged: target "
                f"{imported['signature'][:12]} != source pre-drain "
                f"{pre['signature'][:12]}"
            )
        if imported["records"] != pre["walRecords"]:
            raise MigrationError(
                f"tenant {tenant!r} handoff lost records: shipped "
                f"{imported['records']} of {pre['walRecords']}"
            )
        # verification passed: install the staged processor on the
        # target FIRST, so the flip below releases the queue into the
        # migrated graph, never a lazily-created empty one
        transport.commit_import(target, tenant)
    except Exception as err:
        if staged:
            try:  # best-effort: the target may be unreachable too
                transport.abort_import(target, tenant)
            except Exception:  # noqa: BLE001 - abort must not mask err
                pass
        coordinator.abort_migration(tenant)
        fleet_mod.incr("migrationsAborted")
        if isinstance(err, MigrationError):
            raise
        raise MigrationError(
            f"tenant {tenant!r} migration {source!r} -> {target!r} "
            f"failed: {err}"
        ) from err
    released = coordinator.commit_migration(tenant, target)
    fleet_mod.incr("migrationsCompleted")
    try:
        # the source forgets the tenant: exactly one worker serves it
        # post-flip even if the coordinator later rebuilds its overrides
        transport.drop_tenant(source, tenant)
    except Exception:  # noqa: BLE001 - committed; cleanup is best-effort
        pass
    return {
        "ok": True,
        "tenant": tenant,
        "source": source,
        "target": target,
        "signature": imported["signature"],
        "records": imported["records"],
        "queuedReleased": len(released),
        "drainMs": round(prof_events.now_ms() - t0_ms, 1),
    }


def _check_drain_budget(t0_ms: float, budget_ms: float, tenant: str) -> None:
    elapsed_ms = prof_events.now_ms() - t0_ms
    if budget_ms and elapsed_ms > budget_ms:
        raise MigrationError(
            f"tenant {tenant!r} drain exceeded "
            f"{budget_ms:.0f}ms (took {elapsed_ms:.0f}ms)"
        )
