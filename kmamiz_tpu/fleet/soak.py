"""Archetype 10: the fleet-migration soak (docs/FLEET.md).

Drives a 4-worker fleet through the seeded ``fleet-migration`` scenario:
three tenants consistent-hash-spread across the ring take steady
traffic; at the storyline's ``tenant-migration`` tick the coordinator
live-migrates tenant ``alpha`` to the placement plane's least-loaded
pick — with one window deliberately arriving MID-HANDOFF (injected
between drain and WAL export), so the drain queue's zero-loss promise is
exercised, not assumed. Scored like every runner scorecard:

- **zero lost spans** — every trace id the driver routed (including the
  mid-handoff window) is in the final owner's dedup registry;
- **bit-exact** — each tenant's live graph signature equals a serial
  reference replay of its full ordered ingest log on a fresh processor;
- **zero steady recompiles** — after the rehearsal phase's program
  snapshot, the soak (migration replay and the coordinator's
  hierarchical fold included) dispatches only warm programs;
- **fold consistency** — the two-level merge's aggregate edge count
  equals the sum of the per-tenant stores (tenants' namespaces are
  disjoint, so the fold must neither lose nor invent edges).

Workers are in-process (``LocalTransport``) by default so the soak fits
the tier-1 budget; the coordination logic — ring, drain queue, handoff
protocol, fold — is byte-identical to the multi-process deployment,
which ``bench.py``'s fleet section exercises with real subprocess
workers over ``HTTPTransport``.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List

from kmamiz_tpu import fleet as fleet_mod
from kmamiz_tpu.fleet import migration as migration_mod
from kmamiz_tpu.fleet import placement
from kmamiz_tpu.fleet.coordinator import FleetCoordinator, LocalTransport
from kmamiz_tpu.fleet.ring import HashRing
from kmamiz_tpu.telemetry.profiling import events as prof_events
from kmamiz_tpu.fleet.worker import FleetWorker

class _MidHandoffTransport:
    """Transport proxy that fires a callback between the migration's
    drain and WAL-export steps — the deterministic stand-in for a frame
    racing the handoff. The callback routes a real window through the
    coordinator, which MUST park it in the drain queue and release it to
    whichever side the migration resolves to."""

    def __init__(self, inner, on_export) -> None:
        self._inner = inner
        self._on_export = on_export

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def wal_export(self, worker_id: str, tenant: str) -> bytes:
        self._on_export()
        return self._inner.wal_export(worker_id, tenant)


def run_fleet_scenario(
    spec, tmpdir: str, verbose: bool = False
) -> dict:
    """Run the fleet-migration scenario; returns a runner-shaped card."""
    from kmamiz_tpu.core import programs
    from kmamiz_tpu.graph.store import EndpointGraph
    from kmamiz_tpu.resilience.chaos import graph_signature
    from kmamiz_tpu.scenarios.factory import spec_signature
    from kmamiz_tpu.scenarios.topology import trace_group
    from kmamiz_tpu.telemetry.slo import percentile

    t_start_ms = prof_events.now_ms()
    size = max(2, fleet_mod.fleet_size()) if fleet_mod.enabled() else 4
    ring = HashRing(
        [f"w{i}" for i in range(size)],
        vnodes=fleet_mod.fleet_vnodes(),
        seed=fleet_mod.fleet_seed(),
    )
    workers = {
        w: FleetWorker(w, wal_root=os.path.join(tmpdir, "fleet-wal"))
        for w in ring.workers
    }
    coordinator = FleetCoordinator(ring, LocalTransport(workers))

    tenant_names = [p.tenant for p in spec.tenants]
    state: dict = {
        "latencies": [],
        "posts": 0,
        "errors": [],
        # per-tenant ordered ingest log (raw bytes, arrival order) — the
        # serial reference replays exactly this
        "expected": {t: [] for t in tenant_names},
        "expected_traces": {t: [] for t in tenant_names},
        "snapshot": None,
        "migration": None,
        "queued_mid_handoff": 0,
    }

    def window_bytes(plan, tick: int, count: int) -> bytes:
        prefix = f"{spec.name}-{plan.tenant}"
        return json.dumps(
            [trace_group(plan.topology, prefix, tick, i) for i in range(count)]
        ).encode()

    def route(plan, raw: bytes) -> None:
        tenant = plan.tenant
        state["expected"][tenant].append(raw)
        for group in json.loads(raw):
            state["expected_traces"][tenant].append(group[0]["traceId"])
        t0 = prof_events.now_ms()
        summary = coordinator.route_ingest(tenant, raw)
        state["latencies"].append(prof_events.now_ms() - t0)
        state["posts"] += 1
        if summary is not None and summary.get("quarantined"):
            state["errors"].append(
                f"{tenant}: window quarantined ({summary.get('reason')})"
            )

    migration_event = next(
        (
            ev
            for _t, ev in spec.events()
            if ev.kind == "tenant-migration"
        ),
        None,
    )
    migrating_tenant = next(
        (
            p.tenant
            for p in spec.tenants
            if any(ev.kind == "tenant-migration" for ev in p.events)
        ),
        None,
    )

    def fire_migration(tick: int) -> None:
        tenant = migrating_tenant
        target = placement.pick_target(
            coordinator.ring,
            tenant,
            tenant_names,
            overrides=coordinator.snapshot()["overrides"],
        )
        if target == coordinator.owner(tenant):
            # the least-loaded pick is the current owner: move to the
            # deterministic next worker so the soak always migrates
            others = [w for w in ring.workers if w != target]
            target = others[0]
        plan = next(p for p in spec.tenants if p.tenant == tenant)

        def mid_handoff_window() -> None:
            # distinct trace prefix: this window is EXTRA traffic racing
            # the handoff, not a duplicate of the tick's regular window
            raw = json.dumps(
                [trace_group(plan.topology, f"{spec.name}-{tenant}-mid", tick, 0)]
            ).encode()
            state["expected"][tenant].append(raw)
            for group in json.loads(raw):
                state["expected_traces"][tenant].append(group[0]["traceId"])
            queued = coordinator.route_ingest(tenant, raw)
            state["posts"] += 1
            if queued is not None:
                state["errors"].append(
                    "mid-handoff window bypassed the drain queue"
                )
            else:
                state["queued_mid_handoff"] += 1

        real_transport = coordinator.transport
        coordinator.swap_transport(
            _MidHandoffTransport(real_transport, mid_handoff_window)
        )
        try:
            state["migration"] = migration_mod.migrate_tenant(
                coordinator, tenant, target
            )
        except migration_mod.MigrationError as err:
            state["errors"].append(f"migration failed: {err}")
        finally:
            coordinator.swap_transport(real_transport)

    def rehearse(plan) -> None:
        """Pre-soak shape rehearsal, runner-style (steady recompiles
        must be ZERO from the snapshot on). Ordering matters: the
        terminal-shape warmup pushes EVERY topology path first, so the
        tenant's graph holds its full edge set at final capacity, and
        only then are the tick-window span shapes replayed — each
        (window shape, store capacity) pair the soak and the migration
        replay will dispatch lands its compile here. Rehearsal windows
        route through the coordinator like real traffic and join the
        expected log, so the bit-exactness oracle replays them too."""
        topo = plan.topology
        warm = [
            trace_group(topo, f"{spec.name}-warm", 0, p_i)
            for p_i in range(len(topo.paths))
        ]
        route(plan, json.dumps(warm).encode())
        rehearsed = set()
        shapes = [
            # the mid-handoff injection window is a single path-0 group
            [trace_group(topo, f"{spec.name}-wm", 0, 0)]
        ]
        for t in range(spec.n_ticks):
            count = plan.traffic[t % len(plan.traffic)]
            shapes.append(
                [
                    trace_group(topo, f"{spec.name}-wr{t}", t, i)
                    for i in range(count)
                ]
            )
        for groups in shapes:
            shape_key = tuple(sorted(len(g) for g in groups))
            if not groups or shape_key in rehearsed:
                continue
            rehearsed.add(shape_key)
            route(plan, json.dumps(groups).encode())

    try:
        for plan in spec.tenants:
            rehearse(plan)
        # force every deferred window merge to land (and compile) now,
        # so the snapshot below truly marks steady state
        for plan in spec.tenants:
            owner = workers[coordinator.owner(plan.tenant)]
            _ = owner.processor(plan.tenant).graph.capacity
        # trial fold into a throwaway aggregate: the edge sets are final
        # after the terminal-shape warmup, so this dispatches exactly
        # the union shapes the measured post-soak fold will
        coordinator.fold(tenant_names, EndpointGraph())
        state["snapshot"] = programs.snapshot()
        for tick in range(spec.n_ticks):
            if (
                migration_event is not None
                and tick == migration_event.at_tick
                and migrating_tenant is not None
            ):
                fire_migration(tick)
            for plan in spec.tenants:
                count = plan.traffic[tick % len(plan.traffic)]
                route(plan, window_bytes(plan, tick, count))
    except Exception as err:  # noqa: BLE001 - scorecard, not crash
        state["errors"].append(f"{type(err).__name__}: {err}")

    # aggregate fold (hierarchical level two) INSIDE the gated region:
    # it must ride the rehearsed warm union programs
    aggregate = EndpointGraph()
    try:
        folded_edges = coordinator.fold(tenant_names, aggregate)
    except Exception as err:  # noqa: BLE001
        folded_edges = -1
        state["errors"].append(f"fold failed: {err}")
    steady_recompiles = (
        sum(programs.new_compiles_since(state["snapshot"]).values())
        if state["snapshot"] is not None
        else -1
    )

    live_sigs: Dict[str, str] = {}
    live_edges: Dict[str, int] = {}
    lost_spans = 0
    missing: List[str] = []
    for plan in spec.tenants:
        owner = workers[coordinator.owner(plan.tenant)]
        proc = owner.processor(plan.tenant)
        live_sigs[plan.tenant] = graph_signature(proc.graph)
        live_edges[plan.tenant] = int(proc.graph.n_edges)
        with proc._dedup_lock:
            processed = set(proc._processed)
        for tid in state["expected_traces"][plan.tenant]:
            if tid not in processed:
                lost_spans += 1
                missing.append(f"{plan.tenant}:{tid}")

    ref_sigs = _reference_signatures(spec, state)

    mig = state["migration"]
    gates = {
        "no_errors": not state["errors"],
        "bit_exact": all(
            live_sigs[t] == ref_sigs[t] for t in tenant_names
        ),
        "zero_lost_spans": lost_spans == 0,
        "zero_steady_recompiles": steady_recompiles == 0,
        "migration_committed": bool(mig and mig.get("ok")),
        "mid_handoff_queued": (
            state["queued_mid_handoff"] >= 1
            and bool(mig and mig.get("queuedReleased", 0) >= 1)
        ),
        "fold_consistent": folded_edges == sum(live_edges.values()),
    }
    from kmamiz_tpu.analysis.concurrency import witness

    lock_witness = None
    if witness.installed():
        report = witness.check()
        gates["lock_witness_acyclic"] = report.acyclic
        # a witnessed edge the static model missed is an extractor blind
        # spot — the soak fails so the model gets fixed, not ignored
        gates["lock_witness_covered"] = (
            not report.uncovered and not report.unknown_sites
        )
        lock_witness = {
            "edges": report.edge_count,
            "acquires": report.acquire_count,
            "cycles": report.cycles,
            "uncovered": [list(p) for p in report.uncovered],
            "unknownSites": report.unknown_sites,
            "peerEdges": report.peer_edges,
        }
    lat = sorted(state["latencies"])
    card = {
        "name": spec.name,
        "archetype": spec.archetype,
        "spec_signature": spec_signature(spec),
        "n_ticks": spec.n_ticks,
        "tenants": tenant_names,
        "posts": state["posts"],
        "stale_serves": 0,
        "stale_rate": 0.0,
        "p50_tick_ms": round(percentile(lat, 0.50), 2),
        "p95_tick_ms": round(percentile(lat, 0.95), 2),
        "p99_tick_ms": round(percentile(lat, 0.99), 2),
        "lost_spans": lost_spans,
        "missing_traces": missing[:8],
        "quarantined": 0,
        "expected_poisons": 0,
        "recovery_ms": 0.0,
        "recoveries": {},
        "steady_recompiles": steady_recompiles,
        "mid_tick_compiles": 0,
        "signatures": live_sigs,
        "migration": mig,
        "fleet": {
            **fleet_mod.snapshot(),
            "coordinator": coordinator.snapshot(),
            "foldedEdges": folded_edges,
            "workers": {w: workers[w].summary() for w in ring.workers},
        },
        "wal": None,
        "lock_witness": lock_witness,
        "errors": state["errors"][:4],
        "gates": gates,
        "pass": all(gates.values()),
        "wall_s": round((prof_events.now_ms() - t_start_ms) / 1000.0, 1),
    }
    if not card["pass"]:
        from kmamiz_tpu.telemetry.profiling import recorder

        failed = sorted(g for g, ok in gates.items() if not ok)
        card["flight_artifact"] = recorder.record(
            f"scenario-{spec.name}", ",".join(failed), force=True
        )
    if verbose:
        import sys

        print(
            f"{spec.name}: pass={card['pass']} gates={gates}",
            file=sys.stderr,
        )
    return card


def _reference_signatures(spec, state: dict) -> Dict[str, str]:
    """Serial bit-exactness oracle: replay each tenant's full ordered
    ingest log on a fresh single-process DataProcessor (WAL off) — the
    fleet's drain/handoff/replay choreography must land every tenant on
    exactly this graph."""
    from kmamiz_tpu.resilience.chaos import graph_signature
    from kmamiz_tpu.scenarios.runner import scoped_env
    from kmamiz_tpu.server.processor import DataProcessor

    sigs: Dict[str, str] = {}
    with scoped_env({"KMAMIZ_INGEST_MAX_BYTES": None, "KMAMIZ_WAL": "0"}):
        for plan in spec.tenants:
            ref = DataProcessor(
                trace_source=lambda _lb, _t, _lim: [],
                use_device_stats=False,
            )
            for raw in state["expected"][plan.tenant]:
                ref.ingest_raw_window(raw)
            sigs[plan.tenant] = graph_signature(ref.graph)
    return sigs
