"""Seeded consistent-hash ring: tenant -> worker placement.

Every fleet process — workers, the coordinator, and any front end — must
agree on which worker owns a tenant WITHOUT talking to each other, so
placement is a pure function of (seed, worker ids, vnode count, tenant
name). Hashes are sha256 over explicit strings: Python's builtin
``hash`` is salted per process (PYTHONHASHSEED) and would scatter the
fleet's routing tables.

Each worker projects ``vnodes`` points onto a 64-bit ring; a tenant maps
to the first worker point clockwise of its own hash. Vnodes give the
classic consistent-hashing properties the migration path depends on:

- adding or removing one worker moves only the tenants whose arc it
  owned (minimal disruption — the resize tests pin this), and
- load spreads near-uniformly without any central assignment state.

Worker ids and tenants share the tenancy arena's name charset
(`tenancy/arena.valid_tenant`): both become path components (per-worker
WAL namespaces live under ``<wal-dir>/workers/<worker-id>``), so the
same traversal-safe validation applies.
"""
from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterable, List, Sequence, Tuple

from kmamiz_tpu.tenancy.arena import valid_tenant


class RingError(ValueError):
    """Invalid ring construction (bad/duplicate worker id, bad tenant)."""


def _point(seed: int, key: str) -> int:
    """Deterministic 64-bit ring coordinate for a key under a seed."""
    digest = hashlib.sha256(f"{seed}|{key}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


class HashRing:
    """Immutable seeded ring over a fixed worker set."""

    __slots__ = ("_workers", "_vnodes", "_seed", "_points", "_keys")

    def __init__(
        self, workers: Sequence[str], vnodes: int = 64, seed: int = 0
    ) -> None:
        if not workers:
            raise RingError("ring needs at least one worker")
        if vnodes < 1:
            raise RingError(f"vnodes must be >= 1, got {vnodes}")
        seen = set()
        for worker in workers:
            if not isinstance(worker, str) or not valid_tenant(worker):
                raise RingError(f"invalid worker id: {worker!r}")
            if worker in seen:
                raise RingError(f"duplicate worker id: {worker!r}")
            seen.add(worker)
        self._workers: Tuple[str, ...] = tuple(workers)
        self._vnodes = int(vnodes)
        self._seed = int(seed)
        points: List[Tuple[int, str]] = []
        for worker in self._workers:
            for i in range(self._vnodes):
                # the worker id is part of the hashed string, so equal
                # points across workers (astronomically rare) still sort
                # deterministically by the (point, worker) pair
                points.append((_point(self._seed, f"{worker}#{i}"), worker))
        points.sort()
        self._points = points
        self._keys = [p for p, _w in points]

    @property
    def workers(self) -> Tuple[str, ...]:
        return self._workers

    @property
    def vnodes(self) -> int:
        return self._vnodes

    @property
    def seed(self) -> int:
        return self._seed

    def owner(self, tenant: str) -> str:
        """The worker owning a tenant: first vnode clockwise of the
        tenant's hash (wrapping past the top of the ring)."""
        if not isinstance(tenant, str) or not valid_tenant(tenant):
            raise RingError(f"invalid tenant name: {tenant!r}")
        h = _point(self._seed, f"tenant|{tenant}")
        i = bisect.bisect_right(self._keys, h)
        if i == len(self._keys):
            i = 0
        return self._points[i][1]

    def assignment(self, tenants: Iterable[str]) -> Dict[str, str]:
        """tenant -> worker for a tenant set (one bisect per tenant)."""
        return {tenant: self.owner(tenant) for tenant in tenants}

    def with_workers(self, workers: Sequence[str]) -> "HashRing":
        """A resized ring sharing this one's seed and vnode count — the
        grow/shrink path; only tenants on the changed arcs move."""
        return HashRing(workers, vnodes=self._vnodes, seed=self._seed)

    def spread(self, tenants: Iterable[str]) -> Dict[str, int]:
        """worker -> owned-tenant count (placement diagnostics)."""
        counts = {worker: 0 for worker in self._workers}
        for tenant in tenants:
            counts[self.owner(tenant)] += 1
        return counts

    def describe(self) -> dict:
        """Ring table snapshot for /timings and the grafana panel."""
        return {
            "workers": list(self._workers),
            "vnodes": self._vnodes,
            "seed": self._seed,
            "points": len(self._points),
        }
