from kmamiz_tpu.api.router import ApiServer, IRequestHandler, Request, Response, Router

__all__ = ["ApiServer", "IRequestHandler", "Request", "Response", "Router"]
