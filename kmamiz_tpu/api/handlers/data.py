"""Data REST handler: aggregates, history, labels, interfaces, snapshots.

Equivalent of /root/reference/src/handler/DataService.ts, including the
testing endpoints gated by ENABLE_TESTING_ENDPOINTS (clear / import /
force-aggregate) and the simulator-only clone-from-production route.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.server.import_export import ImportExportHandler
from kmamiz_tpu.server.initializer import AppContext


class DataHandler(IRequestHandler):
    def __init__(
        self,
        ctx: AppContext,
        import_export: Optional[ImportExportHandler] = None,
    ) -> None:
        super().__init__("data")
        self._ctx = ctx
        self._import_export = import_export or ImportExportHandler(ctx)

        self.add_route("get", "/aggregate/:namespace?", self._aggregate)
        self.add_route("get", "/serviceDisplayInfo", self._service_display_info)
        self.add_route("get", "/history/:namespace?", self._history)
        self.add_route("get", "/datatype/:uniqueLabelName", self._datatype)

        # label CRUD (DataService.ts:103-132)
        self.add_route("get", "/label", self._get_labels)
        self.add_route("get", "/label/user", self._get_user_labels)
        self.add_route("post", "/label/user", self._post_user_labels)
        self.add_route("delete", "/label/user", self._delete_user_labels)

        # tagged interfaces (DataService.ts:134-165)
        self.add_route("get", "/interface", self._get_interfaces)
        self.add_route("post", "/interface", self._post_interface)
        self.add_route("delete", "/interface", self._delete_interface)

        self.add_route("post", "/sync", self._sync)
        self.add_route("get", "/export", self._export)

        if ctx.settings.simulator_mode:
            self.add_route(
                "post", "/cloneDataFromProductionService", self._clone
            )
        if ctx.settings.enable_testing_endpoints:
            self.add_route("delete", "/clear", self._clear)
            self.add_route("post", "/import", self._import)
            self.add_route("post", "/aggregate", self._force_aggregate)

    # -- reads ---------------------------------------------------------------

    def _aggregate(self, req: Request) -> Response:
        return Response(
            payload=self.get_aggregated_data(
                req.params.get("namespace"),
                req.query_int("notBefore"),
                req.query.get("filter"),
            )
        )

    def get_aggregated_data(
        self,
        namespace: Optional[str] = None,
        not_before_ms: Optional[int] = None,
        filter_prefix: Optional[str] = None,
    ) -> Optional[dict]:
        data = self._ctx.service_utils.get_realtime_aggregated_data(
            namespace, not_before_ms
        )
        if not filter_prefix or not data:
            return data
        return {
            **data,
            "services": [
                s
                for s in data["services"]
                if s["uniqueServiceName"].startswith(filter_prefix)
            ],
        }

    def _service_display_info(self, req: Request) -> Response:
        return Response(
            payload=self.get_service_display_info(req.query.get("filter"))
        )

    def get_service_display_info(
        self, filter_prefix: Optional[str] = None
    ) -> List[dict]:
        """Per-service endpoint counts from the labeled dependency cache
        (DataService.ts:216-273)."""
        dependencies = self._ctx.cache.get("LabeledEndpointDependencies").get_data()
        if not dependencies:
            return []
        service_map: Dict[str, dict] = {}
        for dep in dependencies.to_json():
            ep = dep["endpoint"]
            key = ep["uniqueServiceName"]
            entry = service_map.setdefault(
                key,
                {
                    "uniqueServiceName": key,
                    "service": ep["service"],
                    "namespace": ep["namespace"],
                    "version": ep["version"],
                    "endpointSet": set(),
                },
            )
            label_or_path = ep.get("labelName") or ep.get("path")
            entry["endpointSet"].add(
                f"{ep['version']}\t{ep['method']}\t{label_or_path}"
            )
        result = [
            {
                "uniqueServiceName": e["uniqueServiceName"],
                "service": e["service"],
                "namespace": e["namespace"],
                "version": e["version"],
                "endpointCount": len(e["endpointSet"]),
            }
            for e in service_map.values()
        ]
        if filter_prefix:
            result = [
                r
                for r in result
                if r["uniqueServiceName"].startswith(filter_prefix)
            ]
        return result

    def _history(self, req: Request) -> Response:
        return Response(
            payload=self._ctx.service_utils.get_realtime_historical_data(
                req.params.get("namespace"), req.query_int("notBefore")
            )
        )

    def _datatype(self, req: Request) -> Response:
        label_name = req.params.get("uniqueLabelName", "")
        if not label_name:
            return Response.status_only(400)
        result = self.get_endpoint_data_type(label_name)
        return Response(payload=result) if result else Response.status_only(404)

    def get_endpoint_data_type(self, unique_label_name: str) -> Optional[dict]:
        """Merge all datatypes sharing one label (DataService.ts:277-301)."""
        parts = unique_label_name.split("\t")
        if len(parts) < 5:
            return None
        service, namespace, version, method, label = parts[:5]
        unique_service_name = f"{service}\t{namespace}\t{version}"

        datatypes = self._ctx.cache.get("LabelMapping").get_endpoint_data_types_by_label(
            label,
            unique_service_name,
            method,
            self._ctx.cache.get("EndpointDataType").get_data() or [],
        )
        if not datatypes:
            return None
        merged = datatypes[0]
        for d in datatypes[1:]:
            merged = merged.merge_schema_with(d)
        return {**merged.to_json(), "labelName": label}

    def get_endpoint_data_types_map(
        self, unique_label_names: List[str]
    ) -> Dict[str, dict]:
        """Per-label merged datatypes, trimmed for the frontend
        (DataService.ts:303-335): one latest schema per status, samples
        dropped."""
        out: Dict[str, dict] = {}
        for name in unique_label_names:
            data_type = self.get_endpoint_data_type(name)
            if not data_type:
                continue
            cloned = json.loads(json.dumps(data_type))
            latest: Dict[str, dict] = {}
            for schema in cloned["schemas"]:
                existing = latest.get(schema["status"])
                if not existing or schema["time"] > existing["time"]:
                    latest[schema["status"]] = schema
            for schema in latest.values():
                schema.pop("requestSample", None)
                schema.pop("responseSample", None)
            cloned["schemas"] = list(latest.values())
            out[name] = cloned
        return out

    # -- labels --------------------------------------------------------------

    def _get_labels(self, req: Request) -> Response:
        label_map = self._ctx.cache.get("LabelMapping").get_data()
        return Response(payload=[[k, v] for k, v in (label_map or {}).items()])

    def _get_user_labels(self, req: Request) -> Response:
        data = self._ctx.cache.get("UserDefinedLabel").get_data()
        return Response(payload=data) if data else Response.status_only(404)

    def _post_user_labels(self, req: Request) -> Response:
        labels = req.json()
        if not labels or not labels.get("labels"):
            return Response.status_only(400)
        self._ctx.cache.get("UserDefinedLabel").update(labels)
        self._ctx.service_utils.update_label()
        return Response.status_only(201)

    def _delete_user_labels(self, req: Request) -> Response:
        label = req.json()
        if not label:
            return Response.status_only(400)
        self._ctx.cache.get("UserDefinedLabel").delete(
            label["label"], label["uniqueServiceName"], label["method"]
        )
        self._ctx.service_utils.update_label()
        return Response.status_only(204)

    # -- tagged interfaces ---------------------------------------------------

    def _get_interfaces(self, req: Request) -> Response:
        unique_label_name = req.query.get("uniqueLabelName")
        if not unique_label_name:
            return Response.status_only(400)
        return Response(
            payload=self._ctx.cache.get("TaggedInterfaces").get_data(
                unique_label_name
            )
        )

    def _post_interface(self, req: Request) -> Response:
        tagged = req.json()
        if not tagged:
            return Response.status_only(400)
        self._ctx.cache.get("TaggedInterfaces").add(tagged)
        return Response.status_only(201)

    def _delete_interface(self, req: Request) -> Response:
        body = req.json() or {}
        unique_label_name = body.get("uniqueLabelName")
        user_label = body.get("userLabel")
        if not unique_label_name or not user_label:
            return Response.status_only(400)
        ok = self.delete_tagged_interface(unique_label_name, user_label)
        return Response.status_only(204 if ok else 400)

    def delete_tagged_interface(self, unique_label_name: str, user_label: str) -> bool:
        cache = self._ctx.cache.get("TaggedInterfaces")
        existing = next(
            (
                i
                for i in cache.get_data(unique_label_name)
                if i.get("userLabel") == user_label
            ),
            None,
        )
        if not existing or existing.get("boundToSwagger"):
            return False
        cache.delete(unique_label_name, user_label)
        return True

    # -- snapshots / control -------------------------------------------------

    def _sync(self, req: Request) -> Response:
        self._ctx.dispatch.sync_all()
        return Response.status_only(200)

    def _export(self, req: Request) -> Response:
        return Response(
            raw_body=self._import_export.export_tgz(),
            content_type="application/tar+gzip",
        )

    def _clone(self, req: Request) -> Response:
        base_url = self._ctx.extra.get("production_service_url", "")
        result = self._import_export.clone_data_from_production_service(base_url)
        if result["isSuccess"]:
            return Response(status=201, payload={"message": "ok"})
        return Response(
            status=500,
            payload={"message": f"Internal Server Error: {result['message']}"},
        )

    def _clear(self, req: Request) -> Response:
        self._import_export.clear_data()
        return Response.status_only(200)

    def _import(self, req: Request) -> Response:
        try:
            pairs = self._import_export.read_tgz(req.body)
            ok = self._import_export.import_data(pairs)
            return Response.status_only(201 if ok else 400)
        except Exception:  # noqa: BLE001 - malformed upload
            return Response.status_only(400)

    def _force_aggregate(self, req: Request) -> Response:
        self._ctx.operator.create_historical_and_aggregated_data()
        return Response.status_only(204)
