"""Alert REST handler: 3-sigma risk-violation detection.

Equivalent of /root/reference/src/handler/AlertService.ts: a service
violates when its latest risk exceeds mean + 3 standard deviations of its
risk history; violations persist for one hour and highlight the endpoint
with the worst server-error rate.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional

from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.server.initializer import AppContext

ALERT_TIMEOUT_MS = 3_600_000  # AlertService.ts:12


class AlertHandler(IRequestHandler):
    def __init__(
        self,
        ctx: AppContext,
        now_ms: Callable[[], float] = lambda: time.time() * 1000,
    ) -> None:
        super().__init__("alert")
        self._ctx = ctx
        self._now_ms = now_ms
        self._last_update_time = 0.0
        self._violation: Dict[str, dict] = {}
        # the reference mutates its violation map on Node's single event
        # loop; here concurrent GET /alert/violation requests run on
        # their own threads, so detection + expiry + the sorted read
        # serialize (review r5: unlocked, one request's fresh violation
        # could vanish under another's expiry rebuild)
        self._violation_lock = threading.Lock()
        self.add_route("get", "/violation/:namespace?", self._violation_route)

    def _violation_route(self, req: Request) -> Response:
        with self._violation_lock:
            self.gather_risk_violations(
                req.params.get("namespace"),
                req.query_int("notBefore") or 86_400_000,
            )
            result = sorted(
                self._violation.values(),
                key=lambda v: v["timeoutAt"],
                reverse=True,
            )
        return Response(payload=result)

    def _clear_timed_out(self) -> None:
        now = self._now_ms()
        self._violation = {
            k: v for k, v in self._violation.items() if v["timeoutAt"] > now
        }

    def gather_risk_violations(
        self, namespace: Optional[str] = None, not_before_ms: int = 86_400_000
    ) -> None:
        """Caller holds _violation_lock (the route does; direct callers
        in tests are single-threaded)."""
        self._clear_timed_out()
        update_time = self._ctx.cache.get("LookBackRealtimeData").last_update
        if self._last_update_time == update_time:
            return
        self._last_update_time = update_time

        historical = self._ctx.service_utils.get_realtime_historical_data(
            namespace, not_before_ms
        )
        now = self._now_ms()
        for s in self.get_services_with_violation(historical):
            highlight = (
                self._determine_endpoint_to_highlight(s)
                or f"{s['service']}\t{s['namespace']}"
            )
            vid = f"{s['uniqueServiceName']}\t{highlight}"
            self._violation[vid] = {
                "id": vid,
                "uniqueServiceName": s["uniqueServiceName"],
                "displayName": (
                    f"{s['service']}.{s['namespace']} ({s['version']})"
                ),
                "occursAt": self._violation.get(vid, {}).get("occursAt", now),
                "timeoutAt": now + ALERT_TIMEOUT_MS,
                "highlightNodeName": highlight,
            }

    @staticmethod
    def get_services_with_violation(historical: List[dict]) -> List[dict]:
        """AlertService.ts:77-116: latest risk > mean + 3 sigma of history."""
        if not historical:
            return []
        historical.sort(key=lambda h: h["date"])
        stats: Dict[str, dict] = {}
        for h in historical:
            for s in h["services"]:
                risk = s.get("risk")
                if not risk or risk <= 0:
                    continue
                e = stats.setdefault(
                    s["uniqueServiceName"],
                    {"count": 0, "sum": 0.0, "quadraticSum": 0.0},
                )
                e["count"] += 1
                e["sum"] += risk
                e["quadraticSum"] += risk ** 2

        latest_services = historical[-1]["services"]
        latest = {
            s["uniqueServiceName"]: s.get("risk") or 0
            for s in latest_services
            if (s.get("risk") or 0) > 0
        }
        violating = set()
        for name, e in stats.items():
            mean = e["sum"] / e["count"]
            std = math.sqrt(max(e["quadraticSum"] / e["count"] - mean ** 2, 0))
            if latest.get(name, 0) > mean + 3 * std:
                violating.add(name)
        return [
            s for s in latest_services if s["uniqueServiceName"] in violating
        ]

    @staticmethod
    def _determine_endpoint_to_highlight(service_data: dict) -> Optional[str]:
        endpoints = service_data.get("endpoints") or []
        if not endpoints:
            return None

        def error_rate(e: dict) -> float:
            requests = e.get("requests") or 0
            return (e.get("serverErrors") or 0) / requests if requests else 0.0

        worst = max(endpoints, key=error_rate)
        return (
            f"{worst['uniqueServiceName']}\t{worst['method']}\t"
            f"{worst.get('labelName')}"
        )
