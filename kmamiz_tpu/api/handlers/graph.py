"""Graph REST handler: dependency graphs, chords, charts, and scorers.

Equivalent of /root/reference/src/handler/GraphService.ts. Graph views and
charts are cache reads followed by pure host computations. The SCORER
routes (cohesion / instability / coupling) are served from the device
kernels (kmamiz_tpu.ops.scorers over the DP process's resident
EndpointGraph) whenever the app embeds a DataProcessor — the device
returns integer count arrays and the handler assembles the exact ratios in
float64, so payloads match the host implementation bit-for-bit. The host
path remains the parity oracle and the fallback (`?scorer=host`, no
processor, empty graph, or any device error).
"""
from __future__ import annotations

import logging
import math
import threading
from typing import Callable, List, Optional

import numpy as np

from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.domain.endpoint_data_type import EndpointDataType
from kmamiz_tpu.domain.endpoint_dependencies import EndpointDependencies
from kmamiz_tpu.server.initializer import AppContext

logger = logging.getLogger("kmamiz_tpu.api.graph")


class GraphHandler(IRequestHandler):
    def __init__(self, ctx: AppContext) -> None:
        super().__init__("graph")
        self._ctx = ctx

        self.add_route("get", "/dependency/endpoint/:namespace?", self._dependency)
        self.add_route(
            "get", "/dependency/service/:namespace?", self._service_dependency
        )
        self.add_route("get", "/chord/direct/:namespace?", self._chord_direct)
        self.add_route("get", "/chord/indirect/:namespace?", self._chord_indirect)
        self.add_route("get", "/line/:namespace?", self._line)
        self.add_route("get", "/statistics/:namespace?", self._statistics)
        self.add_route("get", "/cohesion/:namespace?", self._cohesion)
        self.add_route("get", "/instability/:namespace?", self._instability)
        self.add_route("get", "/coupling/:namespace?", self._coupling)
        self.add_route("get", "/requests/:uniqueName", self._requests)

    # -- routes --------------------------------------------------------------

    def _dependency(self, req: Request) -> Response:
        graph = self.get_dependency_graph(req.params.get("namespace"))
        return Response(payload=graph) if graph else Response.status_only(404)

    def _service_dependency(self, req: Request) -> Response:
        graph = self.get_service_dependency_graph(req.params.get("namespace"))
        return Response(payload=graph) if graph else Response.status_only(404)

    def _chord_direct(self, req: Request) -> Response:
        return Response(
            payload=self.get_direct_service_chord(req.params.get("namespace"))
        )

    def _chord_indirect(self, req: Request) -> Response:
        return Response(
            payload=self.get_indirect_service_chord(req.params.get("namespace"))
        )

    def _line(self, req: Request) -> Response:
        return Response(
            payload=self.get_line_chart_data(
                req.params.get("namespace"), req.query_int("notBefore")
            )
        )

    def _statistics(self, req: Request) -> Response:
        return Response(
            payload=self.get_service_historical_statistics(
                req.params.get("namespace"), req.query_int("notBefore")
            )
        )

    def _cohesion(self, req: Request) -> Response:
        return Response(
            payload=self.get_service_cohesion(
                req.params.get("namespace"),
                force_host=req.query.get("scorer") == "host",
            )
        )

    def _instability(self, req: Request) -> Response:
        return Response(
            payload=self.get_service_instability(
                req.params.get("namespace"),
                force_host=req.query.get("scorer") == "host",
            )
        )

    def _coupling(self, req: Request) -> Response:
        return Response(
            payload=self.get_service_coupling(
                req.params.get("namespace"),
                force_host=req.query.get("scorer") == "host",
            )
        )

    def _requests(self, req: Request) -> Response:
        return Response(
            payload=self.get_request_info_chart_data(
                req.params["uniqueName"],
                req.query.get("ignoreServiceVersion") == "true",
                req.query_int("notBefore") or 86_400_000,
            )
        )

    # -- graph views (GraphService.ts:113-180) -------------------------------

    def _labeled_dependencies(
        self, namespace: Optional[str] = None
    ) -> Optional[EndpointDependencies]:
        return self._ctx.cache.get("LabeledEndpointDependencies").get_data(namespace)

    def get_dependency_graph(self, namespace: Optional[str] = None) -> dict:
        dependencies = self._labeled_dependencies(namespace)
        if not dependencies:
            return self.get_empty_graph_data()
        return dependencies.to_graph_data()

    def get_empty_graph_data(self) -> dict:
        return EndpointDependencies([]).to_graph_data()

    def get_service_dependency_graph(self, namespace: Optional[str] = None) -> dict:
        return self.to_service_dependency_graph(self.get_dependency_graph(namespace))

    @staticmethod
    def to_service_dependency_graph(endpoint_graph: dict) -> dict:
        """Collapse the endpoint graph to service granularity
        (GraphService.ts:131-155)."""
        link_set = {}
        for l in endpoint_graph["links"]:
            source = "\t".join(l["source"].split("\t")[:2])
            target = "\t".join(l["target"].split("\t")[:2])
            link_set[f"{source}\n{target}"] = None
        links = [
            {"source": s, "target": t}
            for s, t in (k.split("\n") for k in link_set)
        ]
        nodes = [n for n in endpoint_graph["nodes"] if n["id"] == n["group"]]
        for n in nodes:
            in_between = [l for l in links if l["source"] == n["id"]]
            n["linkInBetween"] = in_between
            n["dependencies"] = [l["target"] for l in in_between]
        return {"nodes": nodes, "links": links}

    # -- chord views (GraphService.ts:157-180) -------------------------------

    def get_direct_service_chord(self, namespace: Optional[str] = None) -> dict:
        dependencies = self._labeled_dependencies(namespace)
        if not dependencies:
            return {"nodes": [], "links": []}
        direct = [
            {
                **ep,
                "dependingOn": [
                    d for d in ep["dependingOn"] if d["distance"] == 1
                ],
            }
            for ep in dependencies.to_json()
        ]
        return EndpointDependencies(direct).to_chord_data()

    def get_indirect_service_chord(self, namespace: Optional[str] = None) -> dict:
        dependencies = self._labeled_dependencies(namespace)
        if not dependencies:
            return {"nodes": [], "links": []}
        return dependencies.to_chord_data()

    # -- charts (GraphService.ts:182-292) ------------------------------------

    def get_line_chart_data(
        self,
        namespace: Optional[str] = None,
        not_before_ms: Optional[int] = None,
    ) -> dict:
        """not_before_ms is a look-back duration (the API's notBefore)."""
        historical = self._ctx.service_utils.get_realtime_historical_data(
            namespace, not_before_ms
        )
        if not historical:
            return {"dates": [], "metrics": [], "services": []}

        historical.sort(key=lambda h: h["date"])
        first_services = sorted(
            historical[0]["services"], key=lambda s: s["uniqueServiceName"]
        )
        services = [
            f"{s['service']}.{s['namespace']} ({s['version']})"
            for s in first_services
        ]
        dates: List[float] = []
        metrics: List[List[List[float]]] = []
        for h in historical:
            dates.append(h["date"])
            rows = sorted(h["services"], key=lambda s: s["uniqueServiceName"])
            metrics.append(
                [
                    [
                        s["requests"],
                        s["requestErrors"],
                        s["serverErrors"],
                        s["latencyCV"],
                        s.get("latencyMean", 0),
                        s.get("risk") or 0,
                    ]
                    for s in rows
                ]
            )
        return {"dates": dates, "services": services, "metrics": metrics}

    def get_service_historical_statistics(
        self,
        namespace: Optional[str] = None,
        not_before_ms: Optional[int] = None,
    ) -> List[dict]:
        historical = self._ctx.service_utils.get_realtime_historical_data(
            namespace, not_before_ms
        )
        stats: dict = {}
        for h in historical:
            for si in h["services"]:
                key = si["uniqueServiceName"]
                if key not in stats:
                    service, ns, version = key.split("\t")
                    stats[key] = {
                        "name": f"{service}.{ns} ({version})",
                        "totalLatencyMean": 0.0,
                        "totalRequests": 0,
                        "totalServerError": 0,
                        "totalRequestError": 0,
                        "validCount": 0,
                    }
                mean = si.get("latencyMean")
                if isinstance(mean, (int, float)) and math.isfinite(mean):
                    stats[key]["totalLatencyMean"] += mean
                    stats[key]["validCount"] += 1
                stats[key]["totalRequests"] += si["requests"]
                stats[key]["totalRequestError"] += si["requestErrors"]
                stats[key]["totalServerError"] += si["serverErrors"]
        return [
            {
                "uniqueServiceName": key,
                "name": v["name"],
                "latencyMean": v["totalLatencyMean"] / v["validCount"],
                "serverErrorRate": (
                    v["totalServerError"] / v["totalRequests"]
                    if v["totalRequests"]
                    else 0
                ),
                "requestErrorsRate": (
                    v["totalRequestError"] / v["totalRequests"]
                    if v["totalRequests"]
                    else 0
                ),
            }
            for key, v in stats.items()
            if v["validCount"] != 0
        ]

    # -- scorers (GraphService.ts:294-379) -----------------------------------
    # Served from the device graph when available (VERDICT r1 #2); the host
    # implementations below each device method are the parity oracle and
    # fallback.

    def _device_graph(self):
        proc = getattr(self._ctx, "processor", None)
        graph = getattr(proc, "graph", None) if proc is not None else None
        if graph is None or graph.n_edges == 0:
            return None
        # labels feed the device ml tables; drop them when the label map
        # has refreshed since the last scorer call
        label_map = self._ctx.cache.get("LabelMapping")
        version = label_map.last_update if label_map is not None else None
        if version != getattr(self, "_label_version", None):
            graph.invalidate_labels()
            self._label_version = version
        return graph

    def _label_of(self) -> Optional[Callable[[str], Optional[str]]]:
        label_map = self._ctx.cache.get("LabelMapping")
        if label_map is None:
            return None
        return label_map.get_label

    # -- scorer payload cache (VERDICT r2 #2) --------------------------------
    # The device kernels refresh in ~10 ms but the labeled, sorted,
    # JSON-shaped payload was rebuilt on every request (the reference
    # recomputes per request too — GraphService.ts:294-379 — and SURVEY
    # §3.4 flags exactly that). Payloads cache keyed by (graph version,
    # label-map freshness, namespace, scorer-specific freshness); every
    # window merge bumps graph.version, so invalidation is automatic.
    # Not used when a deprecated-endpoint threshold is configured (the
    # fresh-mask is then time-varying and must be recomputed per request)
    # or for the ?scorer=host oracle path.

    def _scorer_cached(self, kind: str, namespace, extra_key, builder):
        from kmamiz_tpu.config import parse_threshold_ms, settings

        if parse_threshold_ms(settings.deprecated_endpoint_threshold):
            return builder()
        processor = getattr(self._ctx, "processor", None)
        if processor is None:  # simulator / serve-only: host path, uncached
            return builder()
        label_map = self._ctx.cache.get("LabelMapping")
        key = (
            processor.graph.version,
            label_map.last_update if label_map is not None else None,
            namespace,
            extra_key,
        )
        lock = getattr(self, "_scorer_cache_lock", None)
        if lock is None:
            lock = self.__dict__.setdefault(
                "_scorer_cache_lock", threading.Lock()
            )
        with lock:
            cache = getattr(self, "_scorer_payload_cache", None)
            if cache is None:
                cache = self._scorer_payload_cache = {}
            hit = cache.get((kind, namespace))
            if hit is not None and hit[0] == key:
                return hit[1]
            # evict entries from older graph versions (the namespace
            # axis is caller-controlled; without this the dict grows per
            # distinct query). Mutation and iteration both happen under
            # the lock: dashboards poll several scorer routes
            # concurrently after a version bump (review r5).
            stale = [k for k, v in cache.items() if v[0][0] != key[0]]
            for k in stale:
                del cache[k]
        payload = builder()  # device work happens OUTSIDE the lock
        with lock:
            cache[(kind, namespace)] = (key, payload)
        return payload

    @staticmethod
    def _service_rows(graph, namespace):
        """(sid, uniqueServiceName, display name) for active services in
        the namespace, display-name sorted like every host scorer."""
        active = graph.active_services()
        rows = []
        for sid in range(len(graph.interner.services)):
            if sid >= len(active) or not active[sid]:
                continue
            usn = graph.interner.services.lookup(sid)
            service, ns, version = (usn.split("\t") + ["", ""])[:3]
            if namespace and ns != namespace:
                continue
            rows.append((sid, usn, f"{service}.{ns} ({version})"))
        rows.sort(key=lambda r: r[2])
        return rows

    def _device_usage_cohesion(self, graph, namespace) -> List[dict]:
        # raw endpoint granularity: the reference's labeled view never
        # merges records for cohesion (EndpointDependencies.ts:565-612)
        coh = graph.usage_cohesion()
        total = np.asarray(coh.total_endpoints)
        p_owner = np.asarray(coh.pair_owner)
        p_consumer = np.asarray(coh.pair_consumer)
        p_consumes = np.asarray(coh.pair_consumes)
        p_valid = np.asarray(coh.pair_valid)
        consumers_of: dict = {}
        for i in np.nonzero(p_valid)[0]:
            consumers_of.setdefault(int(p_owner[i]), []).append(
                (int(p_consumer[i]), int(p_consumes[i]))
            )
        services = graph.interner.services
        out = []
        for sid, usn, _name in self._service_rows(graph, namespace):
            consumers = [
                {"uniqueServiceName": services.lookup(c), "consumes": n}
                for c, n in consumers_of.get(sid, [])
            ]
            total_eps = int(total[sid]) if sid < len(total) else 0
            # exact f64 ratio from integer counts (kernel floats are f32)
            cohesion = 0.0
            if total_eps and consumers:
                cohesion = sum(
                    c["consumes"] / total_eps for c in consumers
                ) / len(consumers)
            out.append(
                {
                    "uniqueServiceName": usn,
                    "totalEndpoints": total_eps,
                    "consumers": consumers,
                    "endpointUsageCohesion": cohesion,
                }
            )
        return out

    def get_service_cohesion(
        self, namespace: Optional[str] = None, force_host: bool = False
    ) -> List[dict]:
        if force_host:
            return self._build_service_cohesion(namespace, True)
        dt_cache = self._ctx.cache.get("EndpointDataType")
        dt_lu = dt_cache.last_update if dt_cache is not None else None
        return self._scorer_cached(
            "cohesion",
            namespace,
            dt_lu,
            lambda: self._build_service_cohesion(namespace, False),
        )

    def _build_service_cohesion(
        self, namespace: Optional[str], force_host: bool
    ) -> List[dict]:
        graph = None if force_host else self._device_graph()
        usage_cohesions: Optional[List[dict]] = None
        if graph is not None:
            try:
                usage_cohesions = self._device_usage_cohesion(graph, namespace)
            except Exception:  # noqa: BLE001 - host fallback
                logger.exception("device cohesion failed; host fallback")

        if usage_cohesions is None:
            # host oracle path only: relabeling the whole record set is the
            # exact cost the device offload avoids
            dependencies = self._labeled_dependencies(namespace)
            if not dependencies:
                return []
            usage_cohesions = dependencies.to_service_endpoint_cohesion()

        label_map = self._ctx.cache.get("LabelMapping")
        data_types = []
        for e in self._ctx.cache.get("EndpointDataType").get_data():
            raw = dict(e.to_json())
            raw["labelName"] = (
                label_map.get_label(raw["uniqueEndpointName"])
                or raw["uniqueEndpointName"]
            )
            data_types.append(EndpointDataType(raw))

        data_cohesion = {
            d["uniqueServiceName"]: d
            for d in EndpointDataType.get_service_cohesion(data_types)
        }

        results = []
        for u in usage_cohesions:
            name = u["uniqueServiceName"]
            service, ns, version = name.split("\t")
            d = data_cohesion.get(name)
            data_score = d["cohesiveness"] if d else 0
            results.append(
                {
                    "uniqueServiceName": name,
                    "isDatatypeMatched": d is not None,
                    "name": f"{service}.{ns} ({version})",
                    "dataCohesion": data_score,
                    "usageCohesion": u["endpointUsageCohesion"],
                    "totalInterfaceCohesion": (
                        data_score + u["endpointUsageCohesion"]
                    )
                    / 2,
                    "endpointCohesion": d["endpointCohesion"] if d else [],
                    "totalEndpoints": u["totalEndpoints"],
                    "consumers": u["consumers"],
                }
            )
        return sorted(results, key=lambda r: r["name"])

    def get_service_instability(
        self, namespace: Optional[str] = None, force_host: bool = False
    ) -> List[dict]:
        if force_host:
            return self._build_service_instability(namespace, True)
        return self._scorer_cached(
            "instability",
            namespace,
            None,
            lambda: self._build_service_instability(namespace, False),
        )

    def _build_service_instability(
        self, namespace: Optional[str], force_host: bool
    ) -> List[dict]:
        graph = None if force_host else self._device_graph()
        if graph is not None:
            try:
                scores = graph.service_scores(self._label_of())
                on = np.asarray(scores.instability_on)
                by = np.asarray(scores.instability_by)
                out = []
                for sid, usn, name in self._service_rows(graph, namespace):
                    d_on, d_by = int(on[sid]), int(by[sid])
                    total = d_on + d_by
                    out.append(
                        {
                            "uniqueServiceName": usn,
                            "name": name,
                            "dependingBy": d_by,
                            "dependingOn": d_on,
                            # exact f64 ratio from the integer counts
                            "instability": d_on / total if total else 0,
                        }
                    )
                return out
            except Exception:  # noqa: BLE001 - host fallback
                logger.exception("device instability failed; host fallback")
        dependencies = self._labeled_dependencies(namespace)
        if not dependencies:
            return []
        return sorted(
            dependencies.to_service_instability(), key=lambda r: r["name"]
        )

    def get_service_coupling(
        self, namespace: Optional[str] = None, force_host: bool = False
    ) -> List[dict]:
        if force_host:
            return self._build_service_coupling(namespace, True)
        return self._scorer_cached(
            "coupling",
            namespace,
            None,
            lambda: self._build_service_coupling(namespace, False),
        )

    def _build_service_coupling(
        self, namespace: Optional[str], force_host: bool
    ) -> List[dict]:
        graph = None if force_host else self._device_graph()
        if graph is not None:
            try:
                scores = graph.service_scores(self._label_of())
                ais = np.asarray(scores.ais)
                ads = np.asarray(scores.ads)
                out = []
                for sid, usn, name in self._service_rows(graph, namespace):
                    d_ais, d_ads = int(ais[sid]), int(ads[sid])
                    out.append(
                        {
                            "uniqueServiceName": usn,
                            "name": name,
                            "ais": d_ais,
                            "ads": d_ads,
                            "acs": d_ais * d_ads,
                        }
                    )
                return out
            except Exception:  # noqa: BLE001 - host fallback
                logger.exception("device coupling failed; host fallback")
        dependencies = self._labeled_dependencies(namespace)
        if not dependencies:
            return []
        return sorted(
            dependencies.to_service_coupling(), key=lambda r: r["name"]
        )

    # -- per-endpoint request chart (GraphService.ts:381-448) ----------------

    def get_request_info_chart_data(
        self,
        unique_name: str,
        ignore_service_version: bool = False,
        not_before_ms: int = 86_400_000,
    ) -> dict:
        parts = unique_name.split("\t")
        # the reference's loose destructuring yields an empty chart for a
        # malformed name (GraphService.ts:385-388), not an error
        service = parts[0] if len(parts) > 0 else ""
        namespace = parts[1] if len(parts) > 1 else ""
        version = parts[2] if len(parts) > 2 else ""
        method = parts[3] if len(parts) > 3 else None
        label_name = parts[4] if len(parts) > 4 else None
        is_endpoint = bool(method and label_name)
        unique_service_name = f"{service}\t{namespace}\t{version}"

        historical = self._ctx.service_utils.get_realtime_historical_data(
            None, not_before_ms
        )
        filtered = [
            s
            for h in historical
            for s in h["services"]
            if (
                s["service"] == service and s["namespace"] == namespace
                if ignore_service_version
                else s["uniqueServiceName"] == unique_service_name
            )
        ]
        filtered.sort(key=lambda s: s["date"])

        if is_endpoint:
            source = []
            for s in filtered:
                endpoint = next(
                    (
                        e
                        for e in s["endpoints"]
                        if e.get("labelName") == label_name
                        and e["method"] == method
                    ),
                    None,
                )
                source.append({"date": s["date"], "risk": None, **(endpoint or {})})
        else:
            source = filtered

        chart = {
            "time": [],
            "requests": [],
            "clientErrors": [],
            "serverErrors": [],
            "latencyCV": [],
            "risks": None if is_endpoint else [],
            "totalRequestCount": 0,
            "totalClientErrors": 0,
            "totalServerErrors": 0,
        }
        for s in source:
            client_error = s.get("requestErrors") or 0
            server_error = s.get("serverErrors") or 0
            request = (s.get("requests") or 0) - server_error - client_error
            chart["time"].append(s["date"])
            chart["requests"].append(request)
            chart["clientErrors"].append(client_error)
            chart["serverErrors"].append(server_error)
            chart["latencyCV"].append(s.get("latencyCV") or 0)
            if not is_endpoint:
                chart["risks"].append(s.get("risk") or 0)
            chart["totalRequestCount"] += request
            chart["totalClientErrors"] += client_error
            chart["totalServerErrors"] += server_error
        return chart
