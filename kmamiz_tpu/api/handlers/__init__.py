from kmamiz_tpu.api.handlers.alert import AlertHandler
from kmamiz_tpu.api.handlers.comparator import ComparatorHandler
from kmamiz_tpu.api.handlers.configuration import ConfigurationHandler
from kmamiz_tpu.api.handlers.data import DataHandler
from kmamiz_tpu.api.handlers.graph import GraphHandler
from kmamiz_tpu.api.handlers.health import HealthHandler
from kmamiz_tpu.api.handlers.model import ModelHandler
from kmamiz_tpu.api.handlers.swagger import SwaggerHandler
from kmamiz_tpu.api.handlers.telemetry import TelemetryHandler

__all__ = [
    "AlertHandler",
    "ComparatorHandler",
    "ConfigurationHandler",
    "DataHandler",
    "GraphHandler",
    "HealthHandler",
    "ModelHandler",
    "SwaggerHandler",
    "TelemetryHandler",
]
