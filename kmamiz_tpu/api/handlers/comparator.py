"""Comparator REST handler: tagged diff snapshots of the whole system view.

Equivalent of /root/reference/src/handler/ComparatorService.ts: a diff
snapshot captures the dependency graph, every scorer's output, and the
per-endpoint datatype map, either stored locally (production) or pushed to
the production instance (simulator).
"""
from __future__ import annotations

import json
import urllib.request
from typing import Optional

from kmamiz_tpu.api.handlers.data import DataHandler
from kmamiz_tpu.api.handlers.graph import GraphHandler
from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.server.initializer import AppContext


class ComparatorHandler(IRequestHandler):
    def __init__(
        self,
        ctx: AppContext,
        graph_handler: Optional[GraphHandler] = None,
        data_handler: Optional[DataHandler] = None,
    ) -> None:
        super().__init__("comparator")
        self._ctx = ctx
        self._graph = graph_handler or GraphHandler(ctx)
        self._data = data_handler or DataHandler(ctx)

        self.add_route("get", "/tags", self._tags)
        self.add_route("get", "/diffData", self._get_diff)
        self.add_route("post", "/diffData", self._post_diff)
        self.add_route("delete", "/diffData", self._delete_diff)
        if not ctx.settings.simulator_mode:
            self.add_route("post", "/diffData/simulator", self._post_from_simulator)

    def _tags(self, req: Request) -> Response:
        return Response(
            payload=self._ctx.cache.get("TaggedDiffDatas").get_tags_with_time()
        )

    def _get_diff(self, req: Request) -> Response:
        return Response(payload=self.get_tagged_diff_data(req.query.get("tag")))

    def _post_diff(self, req: Request) -> Response:
        body = req.json() or {}
        tag = body.get("tag")
        if not tag:
            return Response.status_only(400)

        snapshot = self._snapshot(tag)
        if self._ctx.settings.simulator_mode:
            return self._push_to_production(snapshot)
        self._ctx.cache.get("TaggedDiffDatas").add(snapshot)
        return Response.status_only(200)

    def _post_from_simulator(self, req: Request) -> Response:
        tagged = req.json()
        if not tagged:
            return Response.status_only(400)
        self._ctx.cache.get("TaggedDiffDatas").add(tagged)
        return Response.status_only(200)

    def _delete_diff(self, req: Request) -> Response:
        body = req.json() or {}
        tag = body.get("tag")
        if not tag:
            return Response.status_only(400)
        self._ctx.cache.get("TaggedDiffDatas").delete(tag)
        return Response.status_only(200)

    # -- snapshot assembly (ComparatorService.ts:35-88,130-160) --------------

    def _snapshot(self, tag: str) -> dict:
        graph_data = self._graph.get_dependency_graph()
        return {
            "tag": tag,
            "graphData": graph_data,
            "cohesionData": self._graph.get_service_cohesion(),
            "couplingData": self._graph.get_service_coupling(),
            "instabilityData": self._graph.get_service_instability(),
            "endpointDataTypesMap": self._data.get_endpoint_data_types_map(
                [n["id"] for n in graph_data["nodes"]]
            ),
        }

    def get_tagged_diff_data(self, tag: Optional[str] = None) -> dict:
        if tag:
            diff = self._ctx.cache.get("TaggedDiffDatas").get_data_by_tag(tag)
            return {
                "tag": tag,
                "graphData": (diff or {}).get("graphData")
                or self._graph.get_empty_graph_data(),
                "cohesionData": (diff or {}).get("cohesionData") or [],
                "couplingData": (diff or {}).get("couplingData") or [],
                "instabilityData": (diff or {}).get("instabilityData") or [],
                "endpointDataTypesMap": (diff or {}).get("endpointDataTypesMap")
                or {},
            }
        graph_data = self._graph.get_dependency_graph()
        node_ids = [
            n["id"] for n in graph_data["nodes"] if n["id"] != n["group"]
        ]
        return {
            "tag": tag,
            "graphData": graph_data,
            "cohesionData": self._graph.get_service_cohesion(),
            "couplingData": self._graph.get_service_coupling(),
            "instabilityData": self._graph.get_service_instability(),
            "endpointDataTypesMap": self._data.get_endpoint_data_types_map(
                node_ids
            ),
        }

    def _push_to_production(self, snapshot: dict) -> Response:
        """Simulator -> production push (ComparatorService.ts:47-79)."""
        base_url = self._ctx.extra.get("production_service_url", "")
        if not base_url:
            return Response.status_only(500)
        snapshot = {**snapshot, "tag": f"[from Simulator] {snapshot['tag']}"}
        try:
            req = urllib.request.Request(
                f"{base_url}/api/v1/comparator/diffData/simulator",
                data=json.dumps(snapshot).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as res:
                if res.status >= 400:
                    return Response(
                        status=500, payload={"message": res.read().decode()}
                    )
            return Response.status_only(200)
        except Exception:  # noqa: BLE001 - production unreachable
            return Response.status_only(500)
