"""Forecast routes: serve the trained graph head against live features.

The model families (models/graphsage.py, models/gat.py) train offline on
simulator or replayed data (tools/eval_models_large.py, MODELS.md); this
handler closes the loop by running a checkpointed head against the
features the realtime tick produces online (DataProcessor._observe_history
-> history_model_features) over the live dependency graph:

- `GET /model/status` — checkpoint metadata + feature freshness.
- `GET /model/forecast` — per-endpoint anomaly probability and predicted
  latency for the upcoming hour. With the STLGT continual trainer live
  (KMAMIZ_STLGT=1, docs/STLGT.md) the route grows `?quantile=` (p50|
  p95|p99|all) and `?horizon=` (hours) parameters and a `stlgt` payload
  section: per-endpoint latency quantiles plus the top per-edge
  attribution scores; with no checkpoint configured the live STLGT
  params serve the legacy shape too (model "stlgt-live").

Configuration: KMAMIZ_MODEL_DIR points at a trainer checkpoint directory
(models/checkpoint.py). Only identity-free heads serve here (num_nodes=0
in the checkpoint): node-identity embeddings are transductive and cannot
be aligned with a live, growing endpoint set — the inductive history
features exist precisely so the deployable model does not need them
(MODELS.md round 4).
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Optional

import numpy as np

from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.server.initializer import AppContext

logger = logging.getLogger("kmamiz_tpu.api.model")


class ModelHandler(IRequestHandler):
    def __init__(self, ctx: AppContext) -> None:
        super().__init__("model")
        self._ctx = ctx
        self._lock = threading.Lock()
        self._loaded = None  # (params, meta, model_module) | None
        self._load_error: Optional[str] = None
        # a missing/empty checkpoint directory, a mid-rewrite sidecar, or
        # a vanished step directory are TRANSIENT (the trainer may not
        # have written — or be rewriting — its step): such failures
        # re-attempt on later requests, rate-limited, instead of pinning
        # a 503 until restart. Terminal errors (no model dir configured,
        # embedding checkpoints, unexpected exceptions) cache permanently.
        self._error_transient = False
        self._next_retry = 0.0
        # (snapshot-identity, payload): forecasts change once per hour
        # fold; polls in between serve the memoized payload
        self._forecast_cache = None

        self.add_route("get", "/status", self._status)
        self.add_route("get", "/forecast", self._forecast)

    RETRY_SECONDS = 5.0

    def _mark_transient(self, msg: str) -> None:
        """Record a transient load failure (rate-limited retry). Caller
        holds self._lock; returns None so `return self._mark_transient(...)`
        reads as the failure exit."""
        self._load_error = msg
        self._error_transient = True
        self._next_retry = time.monotonic() + self.RETRY_SECONDS
        return None

    # -- checkpoint loading (lazy, once) -------------------------------------

    def _load(self):
        with self._lock:
            if self._loaded is not None:
                return self._loaded
            if self._load_error is not None and (
                not self._error_transient
                or time.monotonic() < self._next_retry
            ):
                return None
            directory = self._ctx.settings.model_dir
            if not directory:
                self._load_error = "KMAMIZ_MODEL_DIR not configured"
                return None
            # every path below is terminal unless it explicitly marks
            # itself transient; without this reset, a raising load after
            # a prior transient failure would inherit transient=True with
            # an expired retry deadline — re-attempting the full load on
            # EVERY request with no rate limit
            self._error_transient = False
            try:
                import jax

                from kmamiz_tpu.models import checkpoint as ckpt
                from kmamiz_tpu.models import gat, graphsage

                step = ckpt.latest_complete_step(directory)
                if step is None:
                    return self._mark_transient(
                        f"no complete checkpoint in {directory}"
                    )
                meta = ckpt.load_metadata(directory, step) or {}
                if not meta:
                    # sidecar vanished between listing and read: the
                    # trainer is mid-rewrite of this step — same
                    # transient class as "not written yet"
                    return self._mark_transient(
                        f"checkpoint step {step} metadata unreadable "
                        f"(trainer mid-write?)"
                    )
                if int(meta.get("num_nodes", 0)):
                    self._load_error = (
                        "checkpoint uses node-identity embeddings; only "
                        "identity-free heads serve against a live endpoint "
                        "set (retrain without --embeddings)"
                    )
                    return None
                model = gat if meta.get("model") == "gat" else graphsage
                template = model.init_params(
                    jax.random.PRNGKey(0),
                    hidden=int(meta["hidden"]),
                    num_features=int(meta["num_features"]),
                    num_nodes=0,
                )
                optimizer = model.make_optimizer(float(meta.get("lr", 1e-3)))
                restored = ckpt.restore_checkpoint(
                    directory, template, optimizer.init(template), step=step
                )
                if restored is None:
                    # the step directory disappeared between listing and
                    # restore (trainer re-saving the same step): transient
                    # — a complete checkpoint reappears moments later
                    return self._mark_transient(
                        f"restore failed for {directory}"
                    )
                params, _opt, meta = restored
                self._loaded = (params, dict(meta), model)
                self._load_error = None  # clear a prior transient failure
                logger.info(
                    "forecast model loaded from %s step %s", directory, step
                )
            except OSError as err:
                # filesystem races with a concurrently-writing trainer
                # (step dir pruned mid-restore, etc) are the same
                # transient class as "not written yet"
                logger.warning("forecast model load raced a writer: %s", err)
                return self._mark_transient(f"model load raced a writer: {err}")
            except Exception as err:  # noqa: BLE001 - surfaced via /status
                self._load_error = f"model load failed: {err}"
                logger.exception("forecast model load failed")
            return self._loaded

    # -- routes --------------------------------------------------------------

    def _status(self, req: Request) -> Response:
        loaded = self._load()
        dp = self._ctx.processor
        snap = getattr(dp, "forecast_snapshot", None) if dp else None
        payload = {
            "modelLoaded": loaded is not None,
            "modelDir": self._ctx.settings.model_dir,
            "error": self._load_error,
            "featureHourReady": snap is not None,
            "predictedHour": snap["predicted_hour"] if snap else None,
            "numEndpoints": int(snap["features"].shape[0]) if snap else 0,
        }
        if loaded is not None:
            _params, meta, model = loaded
            payload["checkpoint"] = {
                "model": meta.get("model"),
                "step": meta.get("step"),
                "hidden": meta.get("hidden"),
                "numFeatures": meta.get("num_features"),
                "loss": meta.get("loss"),
            }
        return Response(payload=payload)

    #: quantile selector values the route accepts (column order matches
    #: models/stlgt/model.QUANTILES)
    _QUANTILE_COLS = {"p50": 0, "p95": 1, "p99": 2}
    #: attribution edges returned per forecast (highest STLGT edge gate)
    _TOP_EDGES = 20

    def _forecast(self, req: Request) -> Response:
        # live STLGT params (continual trainer's last-good) serve the
        # quantile surface — and the whole route when no checkpoint is
        # configured; a checkpointed head alone serves the legacy shape
        from kmamiz_tpu.models import stlgt as stlgt_pkg

        live = stlgt_pkg.serving_params()
        loaded = self._load()
        if loaded is None and live is None:
            return Response(
                status=503, payload={"error": self._load_error}
            )
        qsel = (req.query.get("quantile") or "all").lower()
        if qsel != "all" and qsel not in self._QUANTILE_COLS:
            return Response(
                status=400,
                payload={
                    "error": f"unknown quantile {qsel!r} "
                    "(p50|p95|p99|all)"
                },
            )
        horizon = req.query_int("horizon") or 1
        horizon = max(1, int(horizon))
        # sqrt-H widening has no natural ceiling: an absurd H would
        # widen p99 past any plausible latency (and make forecast-driven
        # admission control shed everything). Beyond the configured max
        # the request is a caller error, not a forecast.
        hmax = stlgt_pkg.horizon_max()
        if horizon > hmax:
            return Response(
                status=400,
                payload={
                    "error": f"horizon {horizon} exceeds "
                    f"KMAMIZ_STLGT_HORIZON_MAX={hmax}: sqrt-horizon "
                    "widening is not meaningful that far out"
                },
            )
        if (qsel != "all" or horizon != 1) and live is None:
            # the quantile/horizon surface is STLGT's: without a
            # refreshed trainer there is no last-good to fall back to
            return Response(
                status=503,
                payload={
                    "error": "quantile/horizon forecasts need the STLGT "
                    "continual trainer (KMAMIZ_STLGT=1) to have completed "
                    "a refresh"
                },
            )
        dp = self._ctx.processor
        # ONE attribute read: the fold publishes features + matching
        # edges + names + hour together, so no torn mixtures and no
        # clamped edge ids from endpoints interned after the fold
        snap = getattr(dp, "forecast_snapshot", None) if dp else None
        if snap is None:
            return Response(
                status=503,
                payload={
                    "error": "no completed feature hour yet (the first "
                    "forecast is available after one full hour of ticks)"
                },
            )
        # memoize per published snapshot: the fold replaces the snapshot
        # dict wholesale once per hour, while dashboards poll every few
        # seconds — re-running the model forward + full-endpoint JSON
        # assembly per poll would be thousands of redundant forwards per
        # hour at 10k endpoints. Keyed on the fold's (graph version,
        # label epoch, hour) cache_key — the scorer cache's keying
        # discipline — with snapshot identity as both tiebreak and
        # fallback for restored snapshots that predate the key.
        snap_key = snap.get("cache_key") or id(snap)
        # the memo key grows the STLGT dimensions: a trainer refresh
        # (params version bump) or a different quantile/horizon selection
        # must recompute, while same-key polls stay memoized with zero
        # forwards and zero compiles
        memo_key = (
            snap_key,
            live["version"] if live is not None else 0,
            qsel,
            horizon,
        )
        cached = self._forecast_cache
        if cached is not None and cached[4] == memo_key:
            # pre-encoded (and pre-gzipped) bytes ride the response so
            # polls skip both the ~1 MB json.dumps and the per-request
            # gzip; .payload stays for in-process dispatch consumers
            return Response(
                payload=cached[1], raw_body=cached[2], raw_gzip=cached[3]
            )
        feats = snap["features"]
        names = snap["names"]

        stlgt_section = None
        q_ms = s_prob = gate = None
        if live is not None:
            from kmamiz_tpu.models.stlgt import serving as stlgt_serving

            q_ms, s_prob, gate = stlgt_serving.quantile_forward(
                live["params"],
                feats,
                snap["src"],
                snap["dst"],
                snap["mask"],
                live["model"],
            )
            if horizon > 1:
                # multi-hour horizon: widen the tail spread by the
                # independent-increments heuristic (sqrt scaling of the
                # above-median excess; docs/STLGT.md#horizon) — p50 is
                # carried flat, the tail columns grow
                scale = float(np.sqrt(horizon))
                q_ms = q_ms.copy()
                q_ms[:, 1:] = q_ms[:, :1] + (
                    q_ms[:, 1:] - q_ms[:, :1]
                ) * scale
            cols = (
                self._QUANTILE_COLS
                if qsel == "all"
                else {qsel: self._QUANTILE_COLS[qsel]}
            )
            stlgt_endpoints = [
                {
                    "uniqueEndpointName": names[i],
                    "anomalyProbability": round(float(s_prob[i]), 4),
                    "latencyQuantilesMs": {
                        level: round(float(max(q_ms[i, c], 0.0)), 2)
                        for level, c in cols.items()
                    },
                }
                for i in np.argsort(-s_prob)
            ]
            edge_mask = np.asarray(snap["mask"], dtype=bool)
            src_ids = np.asarray(snap["src"])
            dst_ids = np.asarray(snap["dst"])
            n = len(names)
            attributions = []
            for e in np.argsort(-gate):
                if len(attributions) >= self._TOP_EDGES:
                    break
                e = int(e)
                if not edge_mask[e]:
                    continue
                s, d = int(src_ids[e]), int(dst_ids[e])
                if s >= n or d >= n:
                    continue
                attributions.append(
                    {
                        "source": names[s],
                        "target": names[d],
                        "score": round(float(gate[e]), 4),
                    }
                )
            stlgt_section = {
                "paramsVersion": live["version"],
                "quantile": qsel,
                "horizon": horizon,
                "quantileLevels": list(live["quantiles"]),
                "endpoints": stlgt_endpoints,
                "attributions": attributions,
            }

        if loaded is not None:
            params, meta, model = loaded
            if feats.shape[1] != int(meta["num_features"]):
                return Response(
                    status=409,
                    payload={
                        "error": (
                            # graftlint: disable=shape-hazard -- 409 reject payload, a diagnostic not a cache key
                            f"feature width {feats.shape[1]} != checkpoint's "
                            f"{meta['num_features']} (train with the matching "
                            "feature layout)"
                        )
                    },
                )
            from kmamiz_tpu.models import serving

            # bucket-padded jitted forward (models/serving.py): the compiled
            # program is keyed by pow2 capacity buckets, so a growing endpoint
            # set recompiles O(log N) times instead of every fold; timings
            # land on /timings as model_forward + modelServe
            lat_ms, prob = serving.forecast_forward(
                params, feats, snap["src"], snap["dst"], snap["mask"], model
            )
            model_name = meta.get("model")
        else:
            # no checkpoint configured: the live STLGT head serves the
            # legacy shape too (p50 column + its anomaly probability)
            lat_ms, prob = q_ms[:, 0], s_prob
            model_name = "stlgt-live"
        order = np.argsort(-prob)
        endpoints = [
            {
                "uniqueEndpointName": names[i],
                "anomalyProbability": round(float(prob[i]), 4),
                "predictedLatencyMs": round(float(max(lat_ms[i], 0.0)), 2),
            }
            for i in order
        ]
        payload = {
            "predictedHour": snap["predicted_hour"],
            "model": model_name,
            "endpoints": endpoints,
        }
        if stlgt_section is not None:
            payload["stlgt"] = stlgt_section
        import gzip

        encoded = json.dumps(payload).encode()
        zipped = gzip.compress(encoded)
        self._forecast_cache = (snap, payload, encoded, zipped, memo_key)
        return Response(payload=payload, raw_body=encoded, raw_gzip=zipped)
