"""Telemetry REST handler (graftscope; docs/OBSERVABILITY.md).

The in-process app's view of the same instruments the standalone DP
server exposes at bare paths: Prometheus text exposition, the tick-span
ring as Zipkin v2 JSON, and the on-demand jax.profiler capture.

Routes (under the /api/v1 prefix):
- GET  /telemetry/metrics  — Prometheus text format 0.0.4
- GET  /telemetry/traces   — Zipkin v2 trace groups of recent ticks
- POST /telemetry/profile  — {"durationMs": N, "dir": optional}
"""
from __future__ import annotations

from typing import Optional

from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.telemetry import REGISTRY, TRACER
from kmamiz_tpu.telemetry import device as tel_device


class TelemetryHandler(IRequestHandler):
    def __init__(self, ctx: Optional[object] = None) -> None:
        super().__init__("telemetry")
        self._ctx = ctx
        self.add_route("get", "/metrics", self._metrics)
        self.add_route("get", "/traces", self._traces)
        self.add_route("post", "/profile", self._profile)

    def _metrics(self, req: Request) -> Response:
        return Response(
            raw_body=REGISTRY.render().encode("utf-8"),
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )

    def _traces(self, req: Request) -> Response:
        return Response(payload=TRACER.export_zipkin())

    def _profile(self, req: Request) -> Response:
        parsed = req.json()
        body = parsed if isinstance(parsed, dict) else {}
        out = tel_device.capture_profile(
            body.get("durationMs", 100), body.get("dir")
        )
        return Response(status=200 if out.get("ok") else 409, payload=out)
