"""Configuration REST handler (reference src/handler/ConfigurationService.ts)."""
from __future__ import annotations

from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.server.initializer import AppContext


class ConfigurationHandler(IRequestHandler):
    def __init__(self, ctx: AppContext) -> None:
        super().__init__("configuration")
        self._ctx = ctx
        self.add_route("get", "/config", self._config)

    def _config(self, req: Request) -> Response:
        return Response(
            payload={"SimulatorMode": self._ctx.settings.simulator_mode}
        )
