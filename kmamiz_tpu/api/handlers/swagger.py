"""Swagger REST handler: OpenAPI docs per service + version tags.

Equivalent of /root/reference/src/handler/SwaggerService.ts; tagging a
swagger version also freezes the backing interfaces as tagged interfaces
bound to the swagger (SwaggerService.ts:112-147).
"""
from __future__ import annotations

import json
from typing import List, Optional

import yaml

from kmamiz_tpu.analytics.swagger import from_endpoints
from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.server.initializer import AppContext


class SwaggerHandler(IRequestHandler):
    def __init__(self, ctx: AppContext) -> None:
        super().__init__("swagger")
        self._ctx = ctx
        self.add_route("get", "/tags/:uniqueServiceName", self._get_tags)
        self.add_route("post", "/tags", self._post_tag)
        self.add_route("delete", "/tags", self._delete_tag)
        self.add_route("get", "/yaml/:uniqueServiceName", self._get_yaml)
        self.add_route("get", "/:uniqueServiceName", self._get_swagger)

    def _get_swagger(self, req: Request) -> Response:
        name = req.params.get("uniqueServiceName")
        if not name:
            return Response.status_only(400)
        return Response(payload=self.get_swagger(name, req.query.get("tag")))

    def _get_yaml(self, req: Request) -> Response:
        name = req.params.get("uniqueServiceName")
        if not name:
            return Response.status_only(400)
        doc = self.get_swagger(name, req.query.get("tag"))
        return Response(
            raw_body=yaml.safe_dump(doc, sort_keys=False).encode(),
            content_type="text/yaml",
        )

    def _get_tags(self, req: Request) -> Response:
        name = req.params.get("uniqueServiceName")
        if not name:
            return Response.status_only(400)
        return Response(payload=self.get_tags(name))

    def _post_tag(self, req: Request) -> Response:
        tagged = req.json()
        if not tagged:
            return Response.status_only(400)
        self.add_tagged_swagger(tagged)
        return Response.status_only(200)

    def _delete_tag(self, req: Request) -> Response:
        body = req.json() or {}
        name, tag = body.get("uniqueServiceName"), body.get("tag")
        if not name or not tag:
            return Response.status_only(400)
        self.delete_tagged_swagger(name, tag)
        return Response.status_only(200)

    # -- document assembly (SwaggerService.ts:72-110) ------------------------

    def get_swagger(
        self, unique_service_name: str, tag: Optional[str] = None
    ) -> dict:
        if tag:
            existing = self._ctx.cache.get("TaggedSwaggers").get_data(
                unique_service_name, tag
            )
            if existing:
                doc = json.loads(existing[0]["openApiDocument"])
                doc["info"]["version"] = tag
                return doc

        service, namespace, version = unique_service_name.split("\t")
        label_map = self._ctx.cache.get("LabelMapping")
        endpoints = []
        for e in self._ctx.cache.get("EndpointDataType").get_data():
            raw = e.to_json()
            if raw["uniqueServiceName"] != unique_service_name:
                continue
            endpoints.append(
                {
                    **raw,
                    "labelName": label_map.get_label(raw["uniqueEndpointName"]),
                }
            )
        return from_endpoints(
            f"{service}.{namespace}",
            version,
            endpoints,
            endpoints_from_label=label_map.get_endpoints_from_label,
        )

    def get_tags(self, unique_service_name: str) -> List[str]:
        docs = self._ctx.cache.get("TaggedSwaggers").get_data(unique_service_name)
        return [
            t["tag"]
            for t in sorted(docs, key=lambda d: d.get("time") or 0, reverse=True)
        ]

    # -- tagging (SwaggerService.ts:112-170) ---------------------------------

    def add_tagged_swagger(self, tagged: dict) -> None:
        self._ctx.cache.get("TaggedSwaggers").add(tagged)

        # the reference's tagging freezes interfaces grouped by the
        # datatypes' LABEL (SwaggerService.ts:112-147, where labelName
        # was stamped onto the cached objects by an earlier getSwagger);
        # this port's cached datatypes are immutable, so resolve the
        # label through the label map here — the same resolution
        # get_swagger uses — instead of reading a field that is never
        # set (review r5: every datatype merged into one None-keyed
        # bucket otherwise, cross-contaminating schemas)
        label_map = self._ctx.cache.get("LabelMapping")
        merged: dict = {}
        for d in self._ctx.cache.get("EndpointDataType").get_data():
            raw = d.to_json()
            if raw["uniqueServiceName"] != tagged["uniqueServiceName"]:
                continue
            name = label_map.get_label(raw["uniqueEndpointName"])
            merged[name] = (
                merged[name].merge_schema_with(d) if name in merged else d
            )

        interfaces = self._ctx.cache.get("TaggedInterfaces")
        for name, d in merged.items():
            dt = d.to_json()
            status_map: dict = {}
            for s in sorted(dt["schemas"], key=lambda s: s["time"]):
                status_map[s["status"]] = s
            for s in status_map.values():
                interfaces.add(
                    {
                        "timestamp": s["time"],
                        "requestSchema": s.get("requestSchema") or "",
                        "responseSchema": s.get("responseSchema") or "",
                        "userLabel": f"{tagged['tag']}-{s['status']}",
                        "uniqueLabelName": (
                            f"{dt['uniqueServiceName']}\t{dt['method']}\t"
                            f"{name}"
                        ),
                        "boundToSwagger": True,
                    }
                )

    def delete_tagged_swagger(self, unique_service_name: str, tag: str) -> None:
        interfaces = self._ctx.cache.get("TaggedInterfaces")
        for i in interfaces.get_data():
            if (
                i.get("boundToSwagger")
                and i["uniqueLabelName"].startswith(unique_service_name)
                and i["userLabel"].startswith(f"{tag}-")
            ):
                interfaces.delete(i["uniqueLabelName"], i["userLabel"])
        self._ctx.cache.get("TaggedSwaggers").delete(unique_service_name, tag)
