"""Health REST handler (reference src/handler/HealthService.ts)."""
from __future__ import annotations

import time

from kmamiz_tpu.api.router import IRequestHandler, Request, Response


class HealthHandler(IRequestHandler):
    def __init__(self) -> None:
        super().__init__("health")
        self.add_route("get", "/", self._health)

    def _health(self, req: Request) -> Response:
        return Response(
            payload={"status": "UP", "serverTime": int(time.time() * 1000)}
        )
