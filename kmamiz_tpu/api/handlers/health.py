"""Health REST handler (reference src/handler/HealthService.ts).

Beyond the reference's bare liveness probe, GET /timings exposes the
process-wide step timer (per-phase tick timings: parse / pack / transfer
/ merge / scorers) and the device graph's scorer-cache counters, so the
pipeline can be inspected in production without a profiler attached.
"""
from __future__ import annotations

import time
from typing import Optional

from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.core import programs
from kmamiz_tpu.core.profiling import step_timer
from kmamiz_tpu.resilience import metrics as res_metrics


class HealthHandler(IRequestHandler):
    def __init__(self, ctx: Optional[object] = None) -> None:
        super().__init__("health")
        self._ctx = ctx
        self.add_route("get", "/", self._health)
        self.add_route("get", "/timings", self._timings)

    def _health(self, req: Request) -> Response:
        """Liveness + readiness: while the boot prewarm plan is running
        (core/programs.py), status is WARMING and — unless
        KMAMIZ_PREWARM_READY_GATE=0 — the HTTP status is 503, which the
        deploy readinessProbe (deploy/kmamiz-tpu.yaml) reads as
        not-ready, keeping traffic off the compile walls."""
        warm = programs.warm_state()
        if warm.get("status") == "warming" and programs.ready_gate_enabled():
            return Response(
                status=503,
                payload={
                    "status": "WARMING",
                    "serverTime": int(time.time() * 1000),
                    "prewarm": warm,
                },
            )
        return Response(
            payload={
                "status": "UP",
                "serverTime": int(time.time() * 1000),
                "prewarm": warm,
                # resilience at a glance: breaker states, scheduler-job
                # failure streaks, quarantine totals, watchdog trips
                "resilience": res_metrics.resilience_summary(),
            }
        )

    def _timings(self, req: Request) -> Response:
        payload = {
            "serverTime": int(time.time() * 1000),
            "phases": step_timer.summary(),
        }
        graph = getattr(
            getattr(self._ctx, "processor", None), "graph", None
        )
        if graph is not None and hasattr(graph, "scorer_cache_stats"):
            payload["scorerCache"] = graph.scorer_cache_stats()
        from kmamiz_tpu.models import serving

        payload["modelServe"] = serving.serve_stats()
        # per-program compile counters (compiles / compileMs / buckets):
        # a steady-state tick after warm-up must add 0 compiles
        payload["programs"] = programs.summary()
        # ingestDropped (ring backpressure), dpFallback, breakers, WAL,
        # quarantine, watchdog — the fault-layer counters (ISSUE 5)
        payload["resilience"] = res_metrics.resilience_summary()
        return Response(payload=payload)
