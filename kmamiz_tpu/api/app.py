"""Application assembly + entry point.

Equivalent of /root/reference/index.ts: builds the object graph, picks the
startup mode (production / simulator / serve-only / read-only), registers
every REST handler on the router, and tears down gracefully by flushing all
caches to the store (index.ts:95-113). Run with:

    python -m kmamiz_tpu.api.app
"""
from __future__ import annotations

import logging
import signal
from typing import Optional

from kmamiz_tpu.api.handlers import (
    AlertHandler,
    ComparatorHandler,
    ConfigurationHandler,
    DataHandler,
    GraphHandler,
    HealthHandler,
    ModelHandler,
    SwaggerHandler,
    TelemetryHandler,
)
from kmamiz_tpu.api.router import ApiServer, Router
from kmamiz_tpu.config import Settings, settings as default_settings
from kmamiz_tpu.server.import_export import ImportExportHandler
from kmamiz_tpu.server.initializer import AppContext, Initializer

logger = logging.getLogger("kmamiz_tpu.app")


def build_production_context(app_settings: Optional[Settings] = None) -> AppContext:
    """Assemble a context with live ingestion clients and the in-process
    data processor, the way index.ts wires ZipkinService / KubernetesService
    into the realtime worker. Modes that never touch the mesh (simulator /
    serve-only / read-only) get no clients.

    Boot-latency note (VERDICT r4 #7): serve-only answers /health ~2.5 s
    after exec on the dev harness — ~0.6 s of that is this package; the
    rest is the harness's sitecustomize importing jax into EVERY python
    process before any app code runs (python -X importtime shows
    site → axon.register → jax at ~1.9 s). On a stock image without
    that site hook the serve-only boot is the ~0.6 s app share, since
    no kmamiz_tpu serve-only path imports jax."""
    s = app_settings or default_settings
    zipkin = k8s = processor = None
    # read-only mode keeps the clients: the reference still runs the
    # forceKMamizSync startup handshake there (index.ts:57-60); schedules
    # that would use them are simply never registered
    if not (s.simulator_mode or s.serve_only):
        from kmamiz_tpu.ingestion import KubernetesClient, ZipkinClient
        from kmamiz_tpu.server.processor import DataProcessor

        if not s.read_only_mode:
            # one-time native-extension build, off the request path.
            # Read-only mode skips it (VERDICT r4 #7): it never ingests
            # raw spans, and a cold probe compiles the C++ loader —
            # tens of seconds a mode that only reads the store must not
            # pay at boot
            from kmamiz_tpu import native

            native.available()
        zipkin = ZipkinClient(s.zipkin_url)
        if s.is_running_in_kubernetes:
            k8s = KubernetesClient.from_service_account(s.kube_api_host)
        else:
            k8s = KubernetesClient(s.kube_api_host)
        processor = DataProcessor(
            trace_source=zipkin.get_trace_list, k8s_source=k8s
        )
    return AppContext.build(
        app_settings=s,
        processor=processor,
        zipkin_client=zipkin,
        k8s_client=k8s,
    )


def build_router(
    ctx: AppContext,
    import_export: Optional[ImportExportHandler] = None,
) -> Router:
    """Register every handler's routes under /api/v{N} (Routes.ts:20-30)."""
    router = Router(
        api_version=ctx.settings.api_version,
        static_dir=ctx.settings.static_dir,
        wasm_path=ctx.settings.wasm_path,
    )
    import_export = import_export or ImportExportHandler(ctx)

    graph = GraphHandler(ctx)
    data = DataHandler(ctx, import_export)
    handlers = [
        data,
        graph,
        SwaggerHandler(ctx),
        AlertHandler(ctx),
        ComparatorHandler(ctx, graph_handler=graph, data_handler=data),
        ConfigurationHandler(ctx),
        HealthHandler(ctx),
        ModelHandler(ctx),
        TelemetryHandler(ctx),
    ]
    try:  # simulator routes only exist when the simulator package is in use
        from kmamiz_tpu.simulator.handler import SimulationHandler

        if ctx.settings.simulator_mode:
            handlers.append(SimulationHandler(ctx))
    except ImportError:
        pass

    for h in handlers:
        router.add_handler(h)
    for line in router.route_list:
        logger.debug("route %s", line)
    return router


class Application:
    """One framework instance: context + router + HTTP server + teardown."""

    def __init__(
        self,
        app_settings: Optional[Settings] = None,
        ctx: Optional[AppContext] = None,
    ) -> None:
        self.settings = app_settings or (
            ctx.settings if ctx is not None else default_settings
        )
        self.ctx = ctx or AppContext.build(app_settings=self.settings)
        self.initializer = Initializer(self.ctx)
        self.import_export = ImportExportHandler(self.ctx)
        self.router = None
        self.server: Optional[ApiServer] = None

    def start_up(self) -> None:
        """Mode switch (index.ts:55-92)."""
        s = self.settings
        if s.is_running_in_kubernetes and self.ctx.k8s_client is not None:
            # ask the instance being replaced to flush first (index.ts:57-60)
            self.ctx.k8s_client.force_kmamiz_sync(
                s.service_port, s.api_version, simulator_mode=s.simulator_mode
            )
        if s.simulator_mode:
            logger.info("Starting in simulator mode.")
            self.initializer.simulation_server_startup()
        elif s.serve_only:
            logger.info("Serve-only mode; registering caches without schedules.")
            self.initializer.register_data_caches()
        else:
            aggregated = self.ctx.store.get_aggregated_data()
            if s.reset_endpoint_dependencies:
                self.initializer.force_recreate_endpoint_dependencies()
            self.initializer.production_server_startup()
            rl_data = self.ctx.cache.get("CombinedRealtimeData").get_data()
            if aggregated is None and (
                rl_data is None or not rl_data.to_json()
            ):
                logger.info("Database is empty, running first-time setup.")
                try:  # index.ts:78-84: a failed backfill must not block startup
                    self.initializer.first_time_setup()
                except Exception:  # noqa: BLE001
                    logger.exception("Cannot run first time setup, skipping.")
        self.router = build_router(self.ctx, self.import_export)

    def listen(self, host: str = "0.0.0.0", port: Optional[int] = None) -> None:
        assert self.router is not None, "call start_up() first"
        self.server = ApiServer(
            self.router, host=host, port=port if port is not None else int(self.settings.port)
        )
        self.server.start()
        logger.info("API server listening on port %s", self.server.port)

    def tear_down(self) -> None:
        """Graceful exit: stop schedules, flush all caches (index.ts:97-112)."""
        logger.info("Flushing caches to store before exit.")
        self.ctx.scheduler.stop()
        if not self.settings.read_only_mode and not self.settings.serve_only:
            if self.settings.simulator_mode:
                # index.ts:101-102: the simulator never keeps data in the store
                self.ctx.store.clear_database()
            else:
                self.ctx.dispatch.sync_all()
        if self.server:
            self.server.stop()


def main() -> None:
    from kmamiz_tpu.core import logger as klog

    logging.basicConfig(level=logging.INFO)
    klog.configure()  # apply LOG_LEVEL (Logger.ts:22-30)
    from kmamiz_tpu.core import compile_cache

    compile_cache.enable_from_env()  # before the first jit dispatch
    app = Application(ctx=build_production_context())
    app.start_up()
    # boot prewarm plan (core/programs.py): hints-first AOT warm of the
    # registered hot programs on a daemon thread; /api/v1/health answers
    # 503 WARMING until done (readinessProbe gate, deploy/kmamiz-tpu.yaml)
    from kmamiz_tpu.core import programs

    graph = getattr(app.ctx.processor, "graph", None)
    programs.boot_prewarm_from_env(graph=graph)
    app.listen()

    def _exit(signum, frame):
        app.tear_down()
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _exit)
    signal.signal(signal.SIGINT, _exit)
    signal.pause()


if __name__ == "__main__":
    main()
