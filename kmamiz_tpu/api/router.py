"""Minimal HTTP routing layer for the REST API.

Equivalent of the reference's Express stack (src/routes/Routes.ts +
src/entities/TRequestHandler.ts): handlers register (method, path) routes
with `:param` / optional `:param?` segments under /api/v{N}; responses are
JSON by default with the same 5-second cache-control the reference sets
(Routes.ts:16), gzip-compressed when the client accepts it. Built on
stdlib ThreadingHTTPServer like the DP server — no web framework in the
image is needed.
"""
from __future__ import annotations

import gzip
import json
import logging
import re
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

logger = logging.getLogger("kmamiz_tpu.api")


@dataclass
class Request:
    method: str
    path: str
    params: Dict[str, str] = field(default_factory=dict)
    query: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def json(self) -> Any:
        if not self.body:
            return None
        try:
            return json.loads(self.body)
        except json.JSONDecodeError:
            return None

    def query_int(self, name: str) -> Optional[int]:
        raw = self.query.get(name)
        try:
            return int(raw) if raw is not None else None
        except ValueError:
            return None


@dataclass
class Response:
    status: int = 200
    payload: Any = None  # JSON-encoded unless raw_body is set
    raw_body: Optional[bytes] = None
    # optional pre-compressed twin of raw_body: handlers serving a
    # memoized large body (e.g. the hourly forecast) cache the gzip once
    # instead of re-compressing ~1 MB per poll; MUST be
    # gzip.compress(raw_body) or absent
    raw_gzip: Optional[bytes] = None
    content_type: str = "application/json"
    headers: Dict[str, str] = field(default_factory=dict)

    @staticmethod
    def status_only(code: int) -> "Response":
        # Express's res.sendStatus: status text as plain-text body, except
        # 204/304 which must not carry one (RFC 7230 §3.3)
        return Response(
            status=code,
            raw_body=b"" if code in (204, 304) else str(code).encode(),
            content_type="text/plain",
        )


Handler = Callable[[Request], Response]

_PARAM_RE = re.compile(r":([A-Za-z_][A-Za-z0-9_]*)(\?)?")


def compile_path(path: str) -> re.Pattern:
    """'/graph/dependency/endpoint/:namespace?' -> anchored regex with
    named groups; optional params also absorb their leading slash."""
    out = []
    idx = 0
    for m in _PARAM_RE.finditer(path):
        literal = re.escape(path[idx : m.start()])
        name, optional = m.group(1), m.group(2)
        if optional:
            # make the preceding slash part of the optional group
            if literal.endswith("/"):
                literal = literal[:-1]
            out.append(literal)
            out.append(f"(?:/(?P<{name}>[^/]+))?")
        else:
            out.append(literal)
            out.append(f"(?P<{name}>[^/]+)")
        idx = m.end()
    out.append(re.escape(path[idx:]))
    return re.compile("^" + "".join(out) + "/?$")


@dataclass
class Route:
    method: str
    pattern: re.Pattern
    handler: Handler
    raw_path: str


_STATIC_TYPES = {
    ".html": "text/html",
    ".js": "application/javascript",
    ".css": "text/css",
    ".json": "application/json",
    ".svg": "image/svg+xml",
    ".png": "image/png",
    ".ico": "image/x-icon",
    ".map": "application/json",
    ".woff2": "font/woff2",
    ".wasm": "application/wasm",
}


class Router:
    """Route table with the reference's /api/v{N} prefix, plus the entry
    point's static serving (index.ts:46-53): the SPA build from static_dir
    with index.html fallback for client-side routes, and the Envoy filter
    binary at /wasm."""

    def __init__(
        self,
        api_version: str = "1",
        static_dir: str = "",
        wasm_path: str = "",
    ) -> None:
        self.prefix = f"/api/v{api_version}"
        self._routes: List[Route] = []
        self.static_dir = static_dir
        self.wasm_path = wasm_path

    def add(self, method: str, path: str, handler: Handler) -> None:
        full = (self.prefix + path).rstrip("/") or "/"
        self._routes.append(
            Route(method.upper(), compile_path(full), handler, full)
        )

    def add_handler(self, handler_obj: "IRequestHandler") -> None:
        for method, path, fn in handler_obj.routes:
            self.add(method, path, fn)

    @property
    def route_list(self) -> List[str]:
        return [f"[{r.method}] {r.raw_path}" for r in self._routes]

    def dispatch(self, method: str, target: str, body: bytes = b"") -> Response:
        split = urlsplit(target)
        path = split.path
        # query values: parse_qs already percent-decodes ONCE — a second
        # unquote corrupted any value containing a %-escape after one
        # decode (the dashboard single-encodes; review r5). Express also
        # decodes query values exactly once.
        query = {k: v[0] for k, v in parse_qs(split.query).items() if v}
        matched_path = False
        for route in self._routes:
            m = route.pattern.match(path)
            if not m:
                continue
            matched_path = True
            if route.method != method.upper():
                continue
            # path params decode TWICE: Express decodes captured params,
            # and every reference handler then calls decodeURIComponent
            # on them again (DataService.ts:57, SwaggerService.ts:24 …)
            # — clients following that convention double-encode names
            # containing tabs/slashes (review r5)
            params = {
                k: unquote(unquote(v))
                for k, v in m.groupdict().items()
                if v is not None
            }
            req = Request(
                method=method.upper(),
                path=path,
                params=params,
                query=query,
                body=body,
            )
            try:
                return route.handler(req)
            except Exception:  # noqa: BLE001 - handler bugs -> 500, not crash
                logger.exception("handler error on %s %s", method, path)
                return Response.status_only(500)
        if matched_path:
            return Response.status_only(405)
        if method.upper() == "GET" and not path.startswith(self.prefix):
            static = self._serve_static(path)
            if static is not None:
                return static
        return Response.status_only(404)

    def _serve_static(self, path: str) -> Optional[Response]:
        import os

        static_cache = {"Cache-Control": "max-age=3600"}  # index.ts:47
        if path == "/wasm" and self.wasm_path and os.path.isfile(self.wasm_path):
            with open(self.wasm_path, "rb") as f:
                return Response(
                    status=200,
                    raw_body=f.read(),
                    content_type="application/wasm",
                    headers=static_cache,
                )
        if not self.static_dir:
            return None
        root = os.path.realpath(self.static_dir)
        if not os.path.isdir(root):
            return None
        rel = unquote(path).lstrip("/") or "index.html"
        candidate = os.path.realpath(os.path.join(root, rel))
        # confine to the static root (no traversal via .. or symlinks out)
        if not (candidate == root or candidate.startswith(root + os.sep)):
            return None
        if not os.path.isfile(candidate):
            # SPA fallback: unknown extension-less paths load the app shell
            if "." in os.path.basename(rel):
                return None
            candidate = os.path.join(root, "index.html")
            if not os.path.isfile(candidate):
                return None
        ext = os.path.splitext(candidate)[1].lower()
        with open(candidate, "rb") as f:
            return Response(
                status=200,
                raw_body=f.read(),
                content_type=_STATIC_TYPES.get(ext, "application/octet-stream"),
                headers=static_cache,
            )


class IRequestHandler:
    """Handler base: collects (method, sub-path, fn) triples under an
    identifier prefix (reference TRequestHandler.ts:4-34)."""

    def __init__(self, identifier: str = "") -> None:
        self._identifier = identifier
        self.routes: List[Tuple[str, str, Handler]] = []

    def add_route(self, method: str, path: str, handler: Handler) -> None:
        self.routes.append((method, f"/{self._identifier}{path}", handler))


def make_http_handler(router: Router, cache_max_age: int = 5):
    class ApiHTTPHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *args) -> None:
            logger.debug("%s " + fmt, self.address_string(), *args)

        def _respond(self, response: Response, head: bool = False) -> None:
            if response.status in (204, 304):  # bodyless statuses (RFC 7230)
                body = b""
            elif response.raw_body is not None:
                body = response.raw_body
            else:
                body = json.dumps(response.payload).encode()
            accept = self.headers.get("Accept-Encoding", "")
            use_gzip = "gzip" in accept and len(body) > 512
            if use_gzip:
                if (
                    response.raw_gzip is not None
                    and body is response.raw_body
                ):
                    body = response.raw_gzip
                else:
                    body = gzip.compress(body)
            self.send_response(response.status)
            bodyless = response.status in (204, 304)
            if not bodyless:  # RFC 7230 §3.3.2: no body framing on 204/304
                self.send_header("Content-Type", response.content_type)
            if "Cache-Control" not in response.headers:
                self.send_header("Cache-Control", f"max-age={cache_max_age}")
            # the reference mounts cors() on every route (index.ts)
            self.send_header("Access-Control-Allow-Origin", "*")
            if use_gzip:
                self.send_header("Content-Encoding", "gzip")
            for k, v in response.headers.items():
                self.send_header(k, v)
            if not bodyless:
                self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if not bodyless and not head:
                self.wfile.write(body)

        def _read_chunked(self) -> bytes:
            """Minimal Transfer-Encoding: chunked reader — Node/Express
            accepts chunked request bodies, and clients that stream
            (curl --data from a pipe, HTTP libraries) send them; reading
            only Content-Length silently treated those bodies as empty
            (review r5)."""
            out = bytearray()
            while True:
                size_line = self.rfile.readline(65536).strip()
                size = int(size_line.split(b";", 1)[0], 16)
                if size == 0:
                    # drain optional trailers up to the final blank line
                    while True:
                        line = self.rfile.readline(65536)
                        if line in (b"\r\n", b"\n", b""):
                            break
                    return bytes(out)
                out += self.rfile.read(size)
                self.rfile.readline(65536)  # CRLF after each chunk

        def _read_body(self) -> bytes:
            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                raw = self._read_chunked()
            else:
                length = int(self.headers.get("Content-Length", 0) or 0)
                raw = self.rfile.read(length) if length else b""
            if self.headers.get("Content-Encoding") == "gzip":
                raw = gzip.decompress(raw)
            return raw

        def _handle(self, method: str) -> None:
            try:
                body = self._read_body()
                response = router.dispatch(method, self.path, body)
            except Exception:  # noqa: BLE001
                logger.exception("dispatch error")
                response = Response.status_only(500)
            self._respond(response)

        def do_GET(self) -> None:
            self._handle("GET")

        def do_HEAD(self) -> None:
            # Express answers HEAD like GET: same headers (true
            # Content-Length included), no body bytes
            try:
                response = router.dispatch("GET", self.path, b"")
            except Exception:  # noqa: BLE001
                logger.exception("dispatch error")
                response = Response.status_only(500)
            self._respond(response, head=True)

        def do_OPTIONS(self) -> None:
            # CORS preflight: the reference mounts cors() globally
            # (index.ts app.use(cors())) — a cross-origin dashboard must
            # get its preflight answered, not a 501 (review r5)
            self.send_response(204)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Access-Control-Allow-Methods",
                "GET, POST, PUT, DELETE, OPTIONS",
            )
            self.send_header(
                "Access-Control-Allow-Headers",
                self.headers.get("Access-Control-Request-Headers")
                or "Content-Type",
            )
            self.end_headers()

        def do_POST(self) -> None:
            self._handle("POST")

        def do_DELETE(self) -> None:
            self._handle("DELETE")

        def do_PUT(self) -> None:
            self._handle("PUT")

    return ApiHTTPHandler


class ApiServer:
    """Threaded HTTP server for the REST API (reference index.ts app.listen)."""

    def __init__(self, router: Router, host: str = "0.0.0.0", port: int = 3000) -> None:
        self._server = ThreadingHTTPServer(
            (host, port), make_http_handler(router)
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="api-server", daemon=True
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
