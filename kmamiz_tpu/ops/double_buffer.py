"""Double-buffered host->device uploads (KMAMIZ_UPLOAD_DEPTH).

`jax.device_put` is asynchronous: it enqueues the copy and returns a
future-like Array immediately, and any kernel dispatched on that array
is sequenced after the copy on the DEVICE stream — the host never has
to wait for the bytes to land before dispatching. The legacy ingest
path nevertheless called `jax.block_until_ready` right after every
`device_put` so `transfer_ms` measured the raw copy; on the dev
harness's ~10 MB/s tunnel that synchronous wait was ~3.9 s of dead
host time per big window (`e2e_tunnel_transfer_ms` in BASELINE.json)
during which the device sat idle too.

`UploadPipeline` keeps up to `depth` upload GROUPS in flight instead:
window N's copy streams while the host packs window N+1 and the device
walks window N-1. The host blocks only when the in-flight window is
full — and then only on the OLDEST group, which by that point has had
one-or-more whole windows of wall time to complete. `transfer_ms`
becomes the wait the host ACTUALLY paid (the pipeline's stall), which
is the number the ingest critical path sees; the old full-copy wall is
still visible to the bench as `upload_stats()["blocked_ms"]` vs wall.

depth 0 restores the legacy synchronous behavior bit-for-bit (the
device arrays a group returns are identical either way — only the WHEN
of the host-side wait moves, never device values, so graph results are
unaffected by the knob).

The pipeline is NOT thread-safe on its own; GraphStore owns one and
touches it only under the store lock (the same discipline as the
staged-window list).
"""
from __future__ import annotations

import os
from collections import deque
from typing import Optional

from kmamiz_tpu.telemetry.profiling import events as prof_events

#: two windows in flight hides one full copy behind one full
#: pack+dispatch without pinning more than two windows of host+device
#: staging memory — the classic double buffer
DEFAULT_DEPTH = 2


def upload_depth(depth: Optional[int] = None) -> int:
    """The configured in-flight window count (KMAMIZ_UPLOAD_DEPTH,
    default 2, floor 0 = legacy synchronous uploads)."""
    if depth is not None:
        return max(0, int(depth))
    try:
        return max(0, int(os.environ.get("KMAMIZ_UPLOAD_DEPTH", DEFAULT_DEPTH)))
    except ValueError:
        return DEFAULT_DEPTH


class UploadPipeline:
    """Depth-bounded window of in-flight host->device upload groups."""

    def __init__(self, depth: Optional[int] = None) -> None:
        self.depth = upload_depth(depth)
        self._in_flight: deque = deque()
        self.uploads = 0
        self.blocked_ms = 0.0
        self.peak_in_flight = 0
        # stage hand-off fences noted by the stream engine (graftstream):
        # each one is a merge->score boundary that drained this pipeline
        self.fences = 0

    def note_fence(self) -> None:
        """Count one explicit stage hand-off fence (GraphStore
        stage_fence); the drain itself is the caller's, this only keeps
        the pipelining observable in stats()."""
        self.fences += 1

    def put(self, host_arrays, sharding=None):
        """Issue one group of device_puts; returns (device_arrays,
        blocked_ms). blocked_ms is the host wait this call actually
        paid: the full copy at depth 0, only the pipeline stall (retire
        of groups past `depth`) otherwise."""
        import jax

        t0 = prof_events.now_ms()
        if sharding is None:
            out = [jax.device_put(a) for a in host_arrays]
        else:
            out = [jax.device_put(a, sharding) for a in host_arrays]
        self.uploads += 1
        if self.depth <= 0:
            # legacy path: the copy must finish before the host moves on
            # graftlint: disable=host-sync-in-hot-path -- KMAMIZ_UPLOAD_DEPTH=0 compat: blocking IS the requested behavior and the measurement
            jax.block_until_ready(out)
            return out, prof_events.now_ms() - t0
        self._in_flight.append(out)
        while len(self._in_flight) > self.depth:
            # graftlint: disable=host-sync-in-hot-path -- pipeline retire: bounded backpressure on the OLDEST in-flight copy, the one wait double buffering cannot hide
            jax.block_until_ready(self._in_flight.popleft())
        self.peak_in_flight = max(self.peak_in_flight, len(self._in_flight))
        blocked = prof_events.now_ms() - t0
        self.blocked_ms += blocked
        return out, blocked

    def drain(self) -> float:
        """Retire every in-flight group; returns the ms spent waiting.
        Called at the stream's existing device fence (finalize/read), so
        in steady state the copies are long done and this is ~free."""
        if not self._in_flight:
            return 0.0
        import jax

        t0 = prof_events.now_ms()
        while self._in_flight:
            # graftlint: disable=host-sync-in-hot-path -- drain runs at the pre-existing read fence, not inside the per-window loop
            jax.block_until_ready(self._in_flight.popleft())
        waited = prof_events.now_ms() - t0
        self.blocked_ms += waited
        return waited

    def stats(self) -> dict:
        # depth 0 is the legacy synchronous mode: put() blocks inline and
        # never accounts blocked_ms, so per-upload stall rates are only
        # meaningful when pipelined — report the mode explicitly and keep
        # every derived rate guarded (uploads can be 0 on a fresh store)
        pipelined = self.depth > 0
        return {
            "depth": self.depth,
            "mode": "pipelined" if pipelined else "sync",
            "uploads": self.uploads,
            "in_flight": len(self._in_flight),
            "peak_in_flight": self.peak_in_flight,
            "blocked_ms": round(self.blocked_ms, 1),
            "fences": self.fences,
            "blocked_ms_per_upload": (
                round(self.blocked_ms / self.uploads, 3)
                if pipelined and self.uploads
                else 0.0
            ),
        }
