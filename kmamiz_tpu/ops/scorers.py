"""Device graph scorers over flat edge arrays.

TPU-native reformulation of the per-request O(V*E) object traversals in
/root/reference/src/classes/EndpointDependencies.ts:369-657 and
/root/reference/src/utils/RiskAnalyzer.ts: the endpoint-dependency edge set
lives as fixed-capacity int32 arrays (see kmamiz_tpu.graph.store), and every
scorer is a pipeline of lexsort -> unique-mask -> segment_sum steps — no
Python loops, no int64 (TPU runs with x64 off), one XLA program per
capacity.

Semantics mirrored from the reference:
- link details count DISTINCT (linked endpoint's service, method+label,
  direction, distance) tuples per owning service
  (EndpointDependencies.ts:412-470);
- instability counts linked services with any by/on detail (:614-641);
- ACS counts distance-1 linked services, gateway services get AIS+1
  (RiskAnalyzer.ts:145-169);
- relying factor sums by_count/distance (+1 gateway) (:124-137);
- usage cohesion averages consumed-endpoint fractions over consumer
  services (EndpointDependencies.ts:565-612). Note: the reference counts
  dependency ROWS as totalEndpoints; in production those are merged
  per-endpoint by the cache's combineWith (keyed uniqueEndpointName), and
  this kernel implements that steady-state per-endpoint semantics — the
  reference's un-merged first-window quirk is not reproduced.

Edge convention: (src_ep, dst_ep, dist) means src depends-ON dst (src is
the CLIENT-side ancestor, dst the SERVER-side descendant), i.e. dst is
depended-BY src.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kmamiz_tpu.core import programs
from kmamiz_tpu.ops import sparse
from kmamiz_tpu.ops.sortutil import SENTINEL, lex_unique, scatter_compact


class ServiceScores(NamedTuple):
    instability_on: jnp.ndarray  # distinct linked services depended on
    instability_by: jnp.ndarray  # distinct linked services depending by
    instability: jnp.ndarray  # Ce/(Ce+Ca)
    ais: jnp.ndarray
    ads: jnp.ndarray
    acs: jnp.ndarray  # ais * ads
    relying_factor: jnp.ndarray
    is_gateway: jnp.ndarray  # bool


def service_scores(
    src_ep: jnp.ndarray,
    dst_ep: jnp.ndarray,
    dist: jnp.ndarray,
    mask: jnp.ndarray,
    ep_service: jnp.ndarray,
    ep_ml: jnp.ndarray,
    ep_has_record: jnp.ndarray,
    num_services: int,
    dist_bits: "int | None" = None,
) -> ServiceScores:
    """All service-level structure scorers: trace-time dispatcher between
    the legacy lexsort pipeline (service_scores_xla) and the packed-key
    sparse pipeline (service_scores_sparse, KMAMIZ_SPARSE != xla).

    src_ep/dst_ep/dist/mask: flat edge arrays (capacity-padded).
    ep_service: int32[num_endpoints] service of each endpoint.
    ep_ml: int32[num_endpoints] method+label intern id of each endpoint
    (labelName masking collapses endpoints sharing a label, exactly like the
    reference's `${method}\\t${labelName}` keying).
    ep_has_record: bool[num_endpoints] — endpoints with a dependency record
    (seen as SERVER spans); gateway detection only considers these.
    dist_bits: the caller's STATIC promise that every valid row has
    0 <= dist < 2**dist_bits (the store derives it from its tracked
    _min_dist/_max_dist; bench's synthetic distances are 1..7). None
    means "unknown" and always takes the legacy path — the sparse
    relying-factor dedup packs dist into its sort key and is only exact
    under the promise.
    """
    if dist_bits is not None and sparse.use_sparse() and _sparse_scorer_ok(
        num_services, int(ep_service.shape[0]), int(src_ep.shape[0]), dist_bits
    ):
        return service_scores_sparse(
            src_ep,
            dst_ep,
            dist,
            mask,
            ep_service,
            ep_ml,
            ep_has_record,
            num_services=num_services,
            dist_bits=dist_bits,
        )
    return service_scores_xla(
        src_ep,
        dst_ep,
        dist,
        mask,
        ep_service,
        ep_ml,
        ep_has_record,
        num_services=num_services,
    )


@programs.register("scorers.service_scores")
@partial(jax.jit, static_argnames=("num_services",))
def service_scores_xla(
    src_ep: jnp.ndarray,
    dst_ep: jnp.ndarray,
    dist: jnp.ndarray,
    mask: jnp.ndarray,
    ep_service: jnp.ndarray,
    ep_ml: jnp.ndarray,
    ep_has_record: jnp.ndarray,
    num_services: int,
) -> ServiceScores:
    """The legacy full pipeline (5-key lexsort counting core); kept as the
    KMAMIZ_SPARSE=xla fallback and the parity oracle for the sparse path.
    Registered under the historical program name so persisted prewarm
    hints keep replaying."""
    rows = edge_direction_tuples(
        src_ep, dst_ep, dist, mask, ep_service, ep_ml, ep_has_record
    )
    is_gateway = gateway_mask(
        dst_ep, mask, ep_service, ep_has_record, num_services
    )
    return score_tuple_rows(*rows, is_gateway, num_services=num_services)


def _sparse_scorer_ok(
    num_services: int, num_endpoints: int, capacity: int, dist_bits: int
) -> bool:
    """Static packing gates for the sparse counting core: every packed
    sort key must stay strictly below SENTINEL in int32."""
    if not (0 < dist_bits <= 6):
        return False
    gid_bits = max(1, (max(num_endpoints, 2) - 1).bit_length())
    return (
        2 * num_services * num_services < SENTINEL
        and num_services * (1 << gid_bits) < SENTINEL
        and capacity * (1 << dist_bits) < SENTINEL
    )


@programs.register("scorers.service_scores_sparse")
@partial(jax.jit, static_argnames=("num_services", "dist_bits"))
def service_scores_sparse(
    src_ep: jnp.ndarray,
    dst_ep: jnp.ndarray,
    dist: jnp.ndarray,
    mask: jnp.ndarray,
    ep_service: jnp.ndarray,
    ep_ml: jnp.ndarray,
    ep_has_record: jnp.ndarray,
    num_services: int,
    dist_bits: int = 3,
) -> ServiceScores:
    """Sparse counting core: packed-int32 single-key UNSTABLE sorts per
    direction table instead of the 8M-row 5-key stable lexsort (~6.7 s of
    the 8.9 s 100k refresh, measured same-box; a 1-key unstable sort of
    one 4M direction table measures ~0.3 s).

    Semantics match score_tuple_rows lane for lane:

    - "on" side needs only PAIR distincts (owner, linked service) and
      d==1 existence, so its key is (owner*S + linked)*2 + (d != 1) — one
      PAYLOAD-FREE sort (the d1 bit rides in the key), counts via
      boundary-prefix differences over searchsorted owner ranges.
    - "by" side pair lanes (instability_by, ais) mirror the same trick
      with owner and linked swapped — a second payload-free pair sort.
    - the relying factor dedups (owner, linked svc, ml, dist). Endpoints
      dense-rank into gid by (service, ml) (sparse.dense_rank_pairs —
      one 100k-row sort). The triple key (owner, gid, dist) needs
      ~34 bits — it cannot ride one int32 — but the EXACT-multiplier
      pair key owner*NUM_ENDPOINTS + gid leaves one spare bit whenever
      2*S*n_ep < SENTINEL, so the dedup splits into DCAP/2 payload-free
      PARTITION sorts, one per distance pair {2p, 2p+1}, each key
      (owner*n_ep + gid)*2 + (d & 1) with off-partition rows parked at
      SENTINEL. Sentinel-heavy inputs sort ~2x faster than full tables
      (134 ms vs 290 ms at the 4M bench shape), and the per-partition
      1/d weights are Python scalars — no weight-table gather. Shapes
      where the exact packing does not fit fall back to the previous
      formulation: one (key, dist)-payload sort plus a nearly-sorted
      run_id*DCAP + dist sort (payload columns make the variadic sort
      ~4.5x slower than payload-free — 1310 ms vs 290 ms same box,
      regardless of payload dtype width — hence the partition design).
    - "triple contains a distance-1 row" replaces the legacy "first row
      with dist >= 1 has dist == 1" test — equivalent, because a group's
      minimum-over-dist>=1 equals 1 iff some row has dist == 1. The pair
      sorts read it straight off the key's d1 bit; the payload fallback
      computes it order-free via a no-earlier-d1-in-run prefix test
      (sparse.run_start_index), so no stable sort is needed.

    Every integer-derived lane (instability_on/by, instability, ais, ads,
    acs, is_gateway) is bit-exact vs the legacy path: the counts are
    identical int32 prefix-boundary differences. relying_factor sums the
    same distinct-tuple contributions in a different order (per-distance
    count times 1/d instead of a row scatter), so it — and the risk lanes
    downstream — carry fp32 tolerance (pinned by tests).
    """
    is_gateway = gateway_mask(
        dst_ep, mask, ep_service, ep_has_record, num_services
    )

    S = num_services
    n_ep = ep_service.shape[0]
    gid_bits = max(1, (max(int(n_ep), 2) - 1).bit_length())
    gid_cap = 1 << gid_bits
    dcap = 1 << dist_bits

    src_safe = jnp.maximum(src_ep, 0)
    dst_safe = jnp.maximum(dst_ep, 0)
    src_svc = ep_service[src_safe]
    dst_svc = ep_service[dst_safe]
    src_rec = ep_has_record[src_safe]
    dst_rec = ep_has_record[dst_safe]
    d32 = dist.astype(jnp.int32)
    svc_ids = jnp.arange(S, dtype=jnp.int32)

    def _ranged_count(flags, lo, hi):
        c = sparse.exclusive_cumsum(flags)
        return (c[hi] - c[lo]).astype(jnp.float32)

    # -- "on" direction: owner = src service, linked = dst service ----------
    # The on-side lanes only need, per (owner, linked) pair, existence and
    # "contains a distance-1 row" — one BIT. Packing that bit into the key
    # (d1 rows sort first within their pair) makes the sort payload-free:
    # a bare 1-key unstable sort measures ~0.37 s at the 4M bench shape vs
    # ~1.34 s when the dist column rides along as a payload, same box.
    valid_on = mask & src_rec
    key_on = jnp.where(
        valid_on,
        (src_svc * S + dst_svc) * 2 + (d32 != 1).astype(jnp.int32),
        SENTINEL,
    )
    k_on = jax.lax.sort(key_on, is_stable=False)
    ok_on = k_on != SENTINEL
    pair_on = k_on >> 1
    first_on = jnp.concatenate([ok_on[:1], pair_on[1:] != pair_on[:-1]]) & ok_on
    # adjacent owner blocks share their boundary: hi[s] == lo[s+1], so one
    # S+1-point searchsorted replaces the lo/hi pair
    b_on = jnp.searchsorted(k_on, jnp.arange(S + 1, dtype=jnp.int32) * (S * 2))
    inst_on = _ranged_count(first_on, b_on[:-1], b_on[1:])
    # a pair's first row has the d1 bit (LSB == 0) iff ANY of its rows is
    # distance 1 — same predicate _group_has_d1 derives from the payload
    ads = _ranged_count(first_on & ((k_on & 1) == 0), b_on[:-1], b_on[1:])

    # -- "by" direction pair lanes: the same trick, owner = dst service -----
    valid_by = mask & dst_rec
    key_pby = jnp.where(
        valid_by,
        (dst_svc * S + src_svc) * 2 + (d32 != 1).astype(jnp.int32),
        SENTINEL,
    )
    k_pby = jax.lax.sort(key_pby, is_stable=False)
    ok_pby = k_pby != SENTINEL
    pair_pby = k_pby >> 1
    first_pby = (
        jnp.concatenate([ok_pby[:1], pair_pby[1:] != pair_pby[:-1]]) & ok_pby
    )
    b_pby = jnp.searchsorted(
        k_pby, jnp.arange(S + 1, dtype=jnp.int32) * (S * 2)
    )
    inst_by = _ranged_count(first_pby, b_pby[:-1], b_pby[1:])
    ais_links = _ranged_count(
        first_pby & ((k_pby & 1) == 0), b_pby[:-1], b_pby[1:]
    )

    total = inst_on + inst_by
    instability = jnp.where(total > 0, inst_on / jnp.maximum(total, 1), 0.0)
    ais = ais_links + is_gateway.astype(jnp.float32)
    acs = ais * ads

    # -- relying factor: distinct (owner, gid, dist), weight 1/max(d, 1) ----
    gid, _svc_of_gid = sparse.dense_rank_pairs(ep_service, ep_ml)
    cap_rows = int(src_ep.shape[0])
    # 420 = lcm 1..7: every 1/max(d, 1) weight for d < 8 is an integral
    # multiple of 1/420, so int32 prefix sums of 420/d stay exact
    w420 = (420, 420, 210, 140, 105, 84, 70, 60)
    if (
        dist_bits <= 3
        and 2 * S * n_ep < SENTINEL
        and cap_rows * 420 < SENTINEL
    ):
        # partition path: one payload-free sort per distance pair
        # {2p, 2p+1}, the EXACT-multiplier key (owner*n_ep + gid)*2 +
        # (d & 1) with off-partition rows parked at SENTINEL. Each sort
        # is duplicate/sentinel-heavy and measures ~2x faster than a
        # full-table key sort; per-partition weights are static scalars.
        base = dst_svc * n_ep + gid[src_safe]
        bq = jnp.arange(S + 1, dtype=jnp.int32) * (n_ep * 2)
        rfw = jnp.zeros(S, jnp.int32)
        for p in range(dcap // 2):
            in_p = valid_by & ((d32 >> 1) == p)
            kp = jax.lax.sort(
                jnp.where(in_p, base * 2 + (d32 & 1), SENTINEL),
                is_stable=False,
            )
            okp = kp != SENTINEL
            firstp = jnp.concatenate([okp[:1], kp[1:] != kp[:-1]]) & okp
            w_even, w_odd = w420[2 * p], w420[2 * p + 1]
            if w_even == w_odd:
                wrow = jnp.where(firstp, w_even, 0)
            else:
                wrow = jnp.where(
                    firstp, jnp.where((kp & 1) == 0, w_even, w_odd), 0
                )
            ws = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(wrow)]
            )
            bp = jnp.searchsorted(kp, bq)
            rfw = rfw + (ws[bp[1:]] - ws[bp[:-1]])
        rf = rfw.astype(jnp.float32) / 420.0
    else:
        # payload fallback: the triple key cannot ride one int32, so dist
        # travels as a sort payload — one (key_by, dist) variadic sort,
        # then a nearly-sorted run_id*DCAP + dist sort for the distincts
        key_by = jnp.where(
            valid_by, dst_svc * gid_cap + gid[src_safe], SENTINEL
        )
        k_by, d_by = jax.lax.sort((key_by, d32), num_keys=1, is_stable=False)
        ok_by = k_by != SENTINEL
        run_first = jnp.concatenate([ok_by[:1], k_by[1:] != k_by[:-1]]) & ok_by
        b_by = jnp.searchsorted(
            k_by, jnp.arange(S + 1, dtype=jnp.int32) * gid_cap
        )
        # run ids are exclusive-prefix counts of run starts, so owner run
        # ranges come from the SAME searchsorted positions as the counts
        c_run = sparse.exclusive_cumsum(run_first)
        run_id = c_run[1:] - 1
        dq = jnp.clip(d_by, 0, dcap - 1)
        key2 = jnp.where(ok_by, run_id * dcap + dq, SENTINEL)
        ks2 = jax.lax.sort(key2, is_stable=False)
        ok2 = ks2 != SENTINEL
        first2 = jnp.concatenate([ok2[:1], ks2[1:] != ks2[:-1]]) & ok2
        p2 = jnp.searchsorted(ks2, c_run[b_by] * dcap)
        dval = ks2 & (dcap - 1)
        if dist_bits == 3 and cap_rows * 420 < SENTINEL:
            wsum = jnp.concatenate(
                [
                    jnp.zeros(1, jnp.int32),
                    jnp.cumsum(
                        jnp.where(
                            first2, jnp.array(w420, jnp.int32)[dval], 0
                        )
                    ),
                ]
            )
            rf = (wsum[p2[1:]] - wsum[p2[:-1]]).astype(jnp.float32) / 420.0
        else:
            rf = jnp.zeros(S, jnp.float32)
            for dv in range(dcap):
                cd = sparse.exclusive_cumsum(first2 & (dval == dv))
                rf = rf + (cd[p2[1:]] - cd[p2[:-1]]).astype(
                    jnp.float32
                ) / float(max(dv, 1))
    rf = rf + is_gateway.astype(jnp.float32)

    return ServiceScores(
        instability_on=inst_on,
        instability_by=inst_by,
        instability=instability,
        ais=ais,
        ads=ads,
        acs=acs,
        relying_factor=rf,
        is_gateway=is_gateway,
    )


def edge_direction_tuples(
    src_ep, dst_ep, dist, mask, ep_service, ep_ml, ep_has_record
):
    """Expand flat edges into BOTH direction-tuple rows:
    "on" = owner src sees linked dst; "by" = owner dst sees linked src —
    distinct (owner, linked_svc, dir, dist, linked_ml) tuples feed
    score_tuple_rows. Shared by the single-device scorer and the
    per-shard stage of the mesh-sharded scorer. Returns (owner, linked,
    ddir, ddist, linked_ml, both_mask).

    Each direction exists only where its OWNER endpoint holds a
    dependency record: the reference derives dependingOn/dependingBy
    details by iterating RECORDS, which only SERVER-seen endpoints own
    (domain/traces.py:177-181; EndpointDependencies.ts:369-470 walks
    this.dependencies). An edge whose ancestor endpoint was never a
    SERVER span (PRODUCER/kindless ancestors, or a warm-start
    dependingOn target absent from the cache page) must not give that
    ancestor's service instability_on/ADS — the host scorer reports
    nothing for it (review r5). The LINKED side stays ungated: a
    record's detail lists its counterpart endpoint regardless of the
    counterpart's own recordness."""
    src_safe = jnp.maximum(src_ep, 0)
    dst_safe = jnp.maximum(dst_ep, 0)
    src_svc = ep_service[src_safe]
    dst_svc = ep_service[dst_safe]
    src_ml = ep_ml[src_safe]
    dst_ml = ep_ml[dst_safe]
    src_rec = ep_has_record[src_safe]
    dst_rec = ep_has_record[dst_safe]
    dist32 = dist.astype(jnp.int32)
    owner = jnp.concatenate([src_svc, dst_svc])
    linked = jnp.concatenate([dst_svc, src_svc])
    linked_ml = jnp.concatenate([dst_ml, src_ml])
    ddist = jnp.concatenate([dist32, dist32])
    ddir = jnp.concatenate(
        [jnp.zeros_like(dist32), jnp.ones_like(dist32)]
    )  # 0 = on/SERVER, 1 = by/CLIENT
    both_mask = jnp.concatenate([mask & src_rec, mask & dst_rec])
    return owner, linked, ddir, ddist, linked_ml, both_mask


def gateway_mask(
    dst_ep, mask, ep_service, ep_has_record, num_services, by_deg=None
):
    """bool[num_services]: a service owning an endpoint record with zero
    depended-by edges (reference: dependency.find(d =>
    d.dependingBy.length === 0)). The mesh-sharded scorer passes its
    psum-merged partial degrees as `by_deg`; single-device computes them
    here."""
    num_endpoints = ep_service.shape[0]
    if by_deg is None:
        by_deg = jax.ops.segment_sum(
            mask.astype(jnp.float32),
            jnp.where(mask, dst_ep, num_endpoints),
            num_segments=num_endpoints + 1,
        )[:-1]
    gateway_ep = ep_has_record & (by_deg == 0)
    return (
        jax.ops.segment_max(
            gateway_ep.astype(jnp.int32), ep_service, num_segments=num_services
        )
        > 0
    )


def score_tuple_rows(
    owner: jnp.ndarray,
    linked: jnp.ndarray,
    ddir: jnp.ndarray,
    ddist: jnp.ndarray,
    linked_ml: jnp.ndarray,
    both_mask: jnp.ndarray,
    is_gateway: jnp.ndarray,
    num_services: int,
) -> ServiceScores:
    """The counting core of service_scores over flat direction-tuple rows
    (owner, linked, dir, dist, ml): global dedup, prefix-boundary
    distincts, searchsorted per-owner reductions. Shared by the
    single-device scorer (rows built straight from edges) and the
    mesh-sharded scorer (rows locally deduped per shard first —
    parallel.mesh.sharded_service_scores); duplicate rows across shards
    collapse in the global lex_unique here, so both paths are exact.

    Key order exploits two properties (each worth ~100 ms at the
    100k-endpoint scale, where scatter-based segment ops dominate):
    (owner, linked, dir) FIRST makes every per-owner reduction a
    contiguous run of the sorted order — cumsum + searchsorted boundary
    differences instead of 8M-row TPU scatters; dist BEFORE ml makes
    the first row of each (owner, linked, dir) triple carry the
    triple's MINIMUM distance, so "triple contains a distance-1 row"
    reads off that row directly."""
    (s_owner, s_linked, s_dir, s_dist, _s_ml), uniq = lex_unique(
        (owner, linked, ddir, ddist, linked_ml), both_mask
    )

    park = num_services
    owner_seg = jnp.where(uniq, s_owner, park)
    row_valid = s_owner != SENTINEL

    # per-owner reductions over the sorted rows: rows of service k occupy
    # [lo[k], hi[k]), parked rows (SENTINEL owner) sort past every id.
    # Counts cumsum in int32, which is exact (values are 0/1 and the
    # total fits easily), so the boundary difference equals the scatter
    # segment_sum bit for bit.
    svc_ids = jnp.arange(num_services, dtype=jnp.int32)
    lo = jnp.searchsorted(s_owner, svc_ids, side="left")
    hi = jnp.searchsorted(s_owner, svc_ids, side="right")

    def owner_count(flags) -> jnp.ndarray:
        c = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(flags.astype(jnp.int32))]
        )
        return (c[hi] - c[lo]).astype(jnp.float32)

    # -- distinct (owner, linked, direction): prefix boundaries --------------
    prefix_neq = (
        (s_owner[1:] != s_owner[:-1])
        | (s_linked[1:] != s_linked[:-1])
        | (s_dir[1:] != s_dir[:-1])
    )
    triple_first = jnp.concatenate([jnp.array([True]), prefix_neq]) & row_valid
    fdir = s_dir == 0
    inst_on = owner_count(triple_first & fdir)
    inst_by = owner_count(triple_first & ~fdir)
    total = inst_on + inst_by
    instability = jnp.where(total > 0, inst_on / jnp.maximum(total, 1), 0.0)

    # -- ACS at distance 1: triples containing any distance-1 row ------------
    # dist sorts before ml, so rows within a triple are min-dist-first.
    # The test must read the triple's FIRST ROW WITH dist >= 1 (not its
    # first row outright): warm-start records can carry distance 0 or
    # below (graph/store.py tracks _min_dist for exactly this class),
    # and such a row sorting first must not hide a genuine distance-1
    # link behind it. With all-dist>=1 data this reduces to the
    # first-row read. At most one row per triple sets the flag.
    prev_dist = jnp.concatenate([s_dist[:1], s_dist[:-1]])
    same_triple_as_prev = jnp.concatenate(
        [jnp.array([False]), ~prefix_neq]
    )
    first_ge1 = (
        (s_dist >= 1)
        & (~same_triple_as_prev | (prev_dist < 1))
        & row_valid
    )
    d1_row = first_ge1 & (s_dist == 1)
    ads = owner_count(d1_row & fdir)
    ais_links = owner_count(d1_row & ~fdir)

    ais = ais_links + is_gateway.astype(jnp.float32)
    acs = ais * ads

    # -- relying factor: sum by_count/distance over details ------------------
    rf_contrib = (
        uniq.astype(jnp.float32)
        * (s_dir == 1)
        / jnp.maximum(s_dist, 1).astype(jnp.float32)
    )
    rf = jax.ops.segment_sum(rf_contrib, owner_seg, num_segments=park + 1)[:-1]
    rf = rf + is_gateway.astype(jnp.float32)

    return ServiceScores(
        instability_on=inst_on,
        instability_by=inst_by,
        instability=instability,
        ais=ais,
        ads=ads,
        acs=acs,
        relying_factor=rf,
        is_gateway=is_gateway,
    )


class CohesionScores(NamedTuple):
    total_endpoints: jnp.ndarray  # endpoint records per service
    consumer_count: jnp.ndarray  # distinct consumer services
    usage_cohesion: jnp.ndarray  # SIUC
    # (owner, consumer, consumes) pair table for the HTTP payload's
    # `consumers` list; rows where pair_valid, order lexsorted by
    # (owner, consumer) — the reference emits insertion order instead
    pair_owner: jnp.ndarray
    pair_consumer: jnp.ndarray
    pair_consumes: jnp.ndarray
    pair_valid: jnp.ndarray


@programs.register("scorers.usage_cohesion")
@partial(jax.jit, static_argnames=("num_services",))
def usage_cohesion(
    src_ep: jnp.ndarray,
    dst_ep: jnp.ndarray,
    dist: jnp.ndarray,
    mask: jnp.ndarray,
    ep_service: jnp.ndarray,
    ep_has_record: jnp.ndarray,
    num_services: int,
) -> CohesionScores:
    """SIUC: for each service, average over consumer services of
    (distinct endpoints consumed / total endpoint records).

    Distinctness is by RAW endpoint id: the reference's labeled view only
    decorates records with labelName — toServiceEndpointCohesion counts
    uniqueEndpointNames (EndpointDependencies.ts:565-612) — so label
    collapsing must NOT apply here."""
    park = num_services
    total_endpoints = jax.ops.segment_sum(
        ep_has_record.astype(jnp.float32),
        jnp.where(ep_has_record, ep_service, park),
        num_segments=park + 1,
    )[:-1]

    # distance-1 by-edges: consumer = svc[src], consumed endpoint = dst.
    # ONE sort keyed (owner, consumer, consumed_ep): identical
    # (consumer, ep) pairs share their owner (owner = svc[ep]), so pair
    # distincts are full-row boundaries and (owner, consumer) groups are
    # prefix boundaries of the same order — no second lexsort.
    d1 = mask & (dist == 1)
    consumer = ep_service[jnp.maximum(src_ep, 0)]
    owner = ep_service[jnp.maximum(dst_ep, 0)]
    (g_owner, g_consumer, _g_ep), pair_first = lex_unique(
        (owner, consumer, dst_ep), d1
    )
    row_valid = g_owner != SENTINEL
    group_first = (
        jnp.concatenate(
            [
                jnp.array([True]),
                (g_owner[1:] != g_owner[:-1])
                | (g_consumer[1:] != g_consumer[:-1]),
            ]
        )
        & row_valid
    )
    cap = g_owner.shape[0]
    group_gid = jnp.cumsum(group_first.astype(jnp.int32)) - 1
    # consumed endpoints per (owner, consumer) group
    pair_counts = jax.ops.segment_sum(
        pair_first.astype(jnp.float32),
        jnp.maximum(group_gid, 0),
        num_segments=cap,
    )
    owner_total = total_endpoints[jnp.minimum(g_owner, park - 1)]
    consumes_at_first = pair_counts[jnp.maximum(group_gid, 0)]
    # a service owning ZERO endpoint records must not appear at all:
    # the reference's toServiceEndpointCohesion iterates record-owning
    # services only (EndpointDependencies.ts:565-612) — a warm-start
    # dependingOn target without its own record in the page would
    # otherwise gain a spurious consumer entry (review r5)
    group_emit = group_first & (owner_total > 0)
    frac = jnp.where(
        group_emit,
        consumes_at_first / jnp.maximum(owner_total, 1),
        0.0,
    )
    pair_owner_seg = jnp.where(group_emit, g_owner, park)
    frac_sum = jax.ops.segment_sum(frac, pair_owner_seg, num_segments=park + 1)[:-1]
    consumer_count = jax.ops.segment_sum(
        group_emit.astype(jnp.float32), pair_owner_seg, num_segments=park + 1
    )[:-1]
    cohesion = jnp.where(
        consumer_count > 0, frac_sum / jnp.maximum(consumer_count, 1), 0.0
    )
    return CohesionScores(
        total_endpoints=total_endpoints,
        consumer_count=consumer_count,
        usage_cohesion=cohesion,
        pair_owner=jnp.where(group_emit, g_owner, SENTINEL),
        pair_consumer=jnp.where(group_emit, g_consumer, SENTINEL),
        pair_consumes=consumes_at_first,
        pair_valid=group_emit,
    )


# ---------------------------------------------------------------------------
# risk pipeline (RiskAnalyzer.ts) as dense vector math
# ---------------------------------------------------------------------------


def _fixed_ratio(v: jnp.ndarray) -> jnp.ndarray:
    mx = jnp.max(v)
    return jnp.where(mx == 0, v, v / jnp.maximum(mx, 1e-30))


def _linear(v: jnp.ndarray, minimum: float = 0.1) -> jnp.ndarray:
    return _fixed_ratio(v) * (1 - minimum) + minimum


def _sigmoid_adj(v: jnp.ndarray) -> jnp.ndarray:
    z = 2 * jnp.log(3.0)
    return 1 / (1 + jnp.exp(-z * (v - 1.5)))


class RiskScores(NamedTuple):
    impact: jnp.ndarray
    probability: jnp.ndarray
    risk: jnp.ndarray
    norm_risk: jnp.ndarray


@programs.register("scorers.risk_scores")
@jax.jit
def risk_scores(
    relying_factor: jnp.ndarray,
    acs: jnp.ndarray,
    replicas: jnp.ndarray,
    request_count: jnp.ndarray,
    error_count: jnp.ndarray,
    cv_weighted_sum: jnp.ndarray,
    active: jnp.ndarray,
) -> RiskScores:
    """risk = impact x probability per service (RiskAnalyzer.ts:10-122).

    active: bool[num_services] — services present in this window (the host
    pipeline only scores services with data; inactive lanes produce 0).
    """
    minimum = 0.01
    norm_rf = _fixed_ratio(relying_factor)
    norm_acs = _fixed_ratio(acs)
    raw_impact = (norm_rf + norm_acs) / jnp.maximum(replicas, 1)
    impact = _linear(raw_impact)

    total = jnp.maximum(jnp.sum(jnp.where(active, request_count, 0.0)), 1.0)
    invoke_p = jnp.where(active, request_count / total, 0.0)
    error_rate = jnp.where(
        active, error_count / jnp.maximum(request_count, 1.0), 0.0
    )
    norm_pro = invoke_p * (1 - minimum) + minimum
    norm_err = error_rate * (1 - minimum) + minimum
    base_prob = _linear(norm_pro * norm_err, minimum)

    latency_cv = jnp.where(
        active, cv_weighted_sum / jnp.maximum(request_count, 1.0), 0.0
    )
    reliability = _sigmoid_adj(latency_cv)
    raw_prob = reliability * jnp.maximum(base_prob, minimum)
    prob = raw_prob * (1 - minimum) + minimum

    risk = jnp.where(active, impact * prob, 0.0)
    masked = jnp.where(active, risk, jnp.inf)
    mn = jnp.min(masked)
    mx = jnp.max(jnp.where(active, risk, -jnp.inf))
    rng = mx - mn
    # device variant: degenerate windows normalize every service to 0.1
    # (the host path preserves the reference's single-element quirk)
    norm = jnp.where(
        active,
        jnp.where(rng == 0, 0.1, (risk - mn) / jnp.maximum(rng, 1e-30) * 0.9 + 0.1),
        0.0,
    )
    return RiskScores(impact=impact, probability=prob, risk=risk, norm_risk=norm)


# -- incremental (dirty-service) recompute support ---------------------------
#
# Every ServiceScores lane for service s is a function of ONLY the edges
# incident to s's endpoints: direction tuples owned by s come from such
# edges; by-degree feeds gateway_mask per ENDPOINT before the per-service
# max, and an endpoint's degree counts only its own incident edges. So the
# edge subset { e : src_svc(e) in D or dst_svc(e) in D } reproduces every
# dirty service's lanes bit-for-bit: lex_unique sorts identical tuple
# values identically regardless of input order, the int32 cumsum counts
# are order-free, and the float32 relying-factor segment sums see the
# dirty owner's rows in the same sorted order as the full run. Lanes of
# NON-dirty services computed from the subset are garbage (their edges are
# only partially present) — merge_service_lanes discards them.


@programs.register("scorers.dirty_edge_subset")
@jax.jit
def dirty_edge_subset(src_ep, dst_ep, dist, mask, ep_service, dirty_svc):
    """Order-preserving compaction of the edges incident to any dirty
    service. Returns (src, dst, dist, kept_count) at the input capacity;
    the caller syncs kept_count once and slices to a pow2 sub-capacity
    before running the scorer kernel over the (much smaller) subset."""
    ep_cap = ep_service.shape[0]
    src_dirty = dirty_svc[ep_service[jnp.clip(src_ep, 0, ep_cap - 1)]]
    dst_dirty = dirty_svc[ep_service[jnp.clip(dst_ep, 0, ep_cap - 1)]]
    keep = mask & (src_dirty | dst_dirty)
    (s, d, ds), kept = scatter_compact((src_ep, dst_ep, dist), keep)
    return s, d, ds, kept.sum()


@programs.register("scorers.merge_service_lanes")
@jax.jit
def merge_service_lanes(
    dirty_svc: jnp.ndarray, inc: ServiceScores, base: ServiceScores
) -> ServiceScores:
    """Lane-wise splice of an incremental recompute into cached scores:
    dirty services take the subset-recomputed value (exact — see module
    note above), everything else keeps its cached lane."""
    return ServiceScores(
        *[jnp.where(dirty_svc, a, b) for a, b in zip(inc, base)]
    )
