"""graftsparse: fused SDDMM/SpMM kernels over the flat CSR edge arrays.

The device-compute spine has four consumers of per-edge gather ->
elementwise -> segment-reduce chains: the service scorers
(ops/scorers.py), the packed ancestor walk (graph/store.py windows), the
GraphSAGE ``neighbor_mean`` and the STLGT sigmoid-gated neighbor bias.
At the 100k-endpoint / 4M-edge regime the XLA formulations either
materialize padded-dense intermediates (the [T, L, L] one-hot walk) or
pay a 5-key comparator lexsort over 8M direction rows (~6.7 s of the
8.9 s refresh, measured same-box). This module is the shared sparse
backend behind all four:

- **Fused SDDMM/SpMM Pallas kernels** (FusedMM, arXiv:2011.06391; dense-
  hardware sparse GNN training, arXiv:1906.11786): one kernel does
  edge-gather (one-hot MXU matmul against the node table), the per-edge
  elementwise SDDMM half (dot + sigmoid gate), and the SpMM
  segment-reduce back to endpoint rows — blocked over EDGE TILES with the
  node table resident in VMEM, so no [E, H] message array ever lands in
  HBM and the padded-dense adjacency is never materialized. Used by the
  STLGT neighbor bias (gated mode) and GraphSAGE neighbor sums (plain
  mode) when the backend is ``pallas``/``pallas_interpret``.
- **Sparse counting primitives** for the scorer rewrite
  (``dense_rank_pairs``, ``run_start_index``): the scorers replace the
  8M-row 5-key lexsort with packed-int32 single-key UNSTABLE sorts per
  direction table (unstable 1-key sort of 4M rows measures ~0.3 s vs
  ~1.8 s/pass stable and ~6.7 s for the 5-key comparator, same box) —
  see scorers.py for the counting core built on these.

Backend knob (mirrored in config.Settings):

- ``KMAMIZ_SPARSE=sparse`` (default): scorers use the packed-key sparse
  counting path, the dependency walk picks the flat-gather variant on
  CPU hosts (the MXU packed walk stays default on TPU, where it measures
  >=50x faster); GraphSAGE/STLGT keep their gather/segment-sum XLA code,
  which already IS the sparse formulation for those shapes.
- ``KMAMIZ_SPARSE=pallas``: additionally routes the STLGT bias and
  GraphSAGE neighbor sums through the fused Pallas kernel (auto-falls
  back to interpret mode off-TPU, and to XLA when the node table
  exceeds the VMEM budget — see ``fused_fits``).
- ``KMAMIZ_SPARSE=pallas_interpret``: fused kernels in interpret mode
  everywhere (CI/CPU parity testing).
- ``KMAMIZ_SPARSE=xla``: every consumer keeps the legacy dense/XLA path
  bit-for-bit (the fallback the parity tests pin against).

``KMAMIZ_SPARSE_TILE`` sets the edge-tile block (default 256, f32
(8, 128)-aligned); ``KMAMIZ_SPARSE_NODE_MAX`` bounds the VMEM-resident
node table for the fused kernels (default 2048 rows; at tile=256 that is
two 2 MB one-hot tiles + three node tables well inside 16 MB VMEM).

Parity contract (pinned by tests/test_ops_sparse.py and the per-consumer
parity tests): integer-derived lanes are bit-exact across backends;
float reductions whose addend ORDER changes (relying factor, fused-kernel
matmul accumulation) are pinned at fp32 tolerance.
"""
from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kmamiz_tpu.core import programs

# jax renamed TPUCompilerParams -> CompilerParams (~0.6); take whichever
# this jax ships
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

_VALID_BACKENDS = ("xla", "sparse", "pallas", "pallas_interpret")

_backend_cache: Optional[str] = None
_tile_cache: Optional[int] = None
_node_max_cache: Optional[int] = None


def backend() -> str:
    """Process-wide sparse backend, cached after first read (the store and
    scorers bake it into registered-program dispatch; tests flipping the
    env var must call reset_for_tests — conftest does)."""
    global _backend_cache
    if _backend_cache is None:
        val = os.environ.get("KMAMIZ_SPARSE", "sparse").strip().lower()
        if val not in _VALID_BACKENDS:
            raise ValueError(
                f"KMAMIZ_SPARSE={val!r} not in {_VALID_BACKENDS}"
            )
        _backend_cache = val
    return _backend_cache


def tile_size() -> int:
    """Edge-tile block for the fused kernels (KMAMIZ_SPARSE_TILE)."""
    global _tile_cache
    if _tile_cache is None:
        t = int(os.environ.get("KMAMIZ_SPARSE_TILE", "256"))
        if t < 8 or t % 8:
            raise ValueError(f"KMAMIZ_SPARSE_TILE={t} must be a multiple of 8")
        _tile_cache = t
    return _tile_cache


def node_budget() -> int:
    """Max VMEM-resident node-table rows for the fused kernels."""
    global _node_max_cache
    if _node_max_cache is None:
        _node_max_cache = int(os.environ.get("KMAMIZ_SPARSE_NODE_MAX", "2048"))
    return _node_max_cache


def reset_for_tests() -> None:
    """Drop the cached knob reads (tests monkeypatching KMAMIZ_SPARSE*)."""
    global _backend_cache, _tile_cache, _node_max_cache
    _backend_cache = None
    _tile_cache = None
    _node_max_cache = None


def use_sparse() -> bool:
    """Sparse counting/walk paths enabled (any backend but xla)."""
    return backend() != "xla"


def fused_enabled() -> bool:
    """Fused Pallas SDDMM/SpMM kernels requested for the model consumers."""
    return backend() in ("pallas", "pallas_interpret")


def fused_interpret() -> bool:
    """Interpret-mode flag for the fused kernels: forced by the
    pallas_interpret backend, and automatic off-TPU (Mosaic kernels only
    compile for TPU; CPU CI runs the same kernel interpreted)."""
    return backend() == "pallas_interpret" or jax.default_backend() != "tpu"


def fused_fits(num_nodes: int) -> bool:
    """Whether the node table fits the fused kernels' VMEM budget; larger
    windows fall back to the XLA gather/segment-sum path."""
    return num_nodes <= node_budget()


def _pad_to(n: int, mult: int) -> int:
    return -(-n // mult) * mult


# ---------------------------------------------------------------------------
# fused SDDMM/SpMM kernel (edge-tile grid, VMEM-resident node table)
# ---------------------------------------------------------------------------
#
# grid = (e_pad // tile,), "arbitrary": the bias/degree outputs accumulate
# across every edge tile into the same [N, H] / [1, N] VMEM block
# (initialized at tile 0), while the per-edge gate writes one [1, tile]
# block per step. Gathers and scatters both ride the MXU as one-hot
# matmuls over [tile, N] masks built in-kernel from broadcasted_iota —
# the only O(E*N) object is a single VMEM tile, never an HBM array.


def _fused_kernel(
    src_ref,
    dst_ref,
    mask_ref,
    v_ref,
    *rest,
    gated: bool,
    inv_sqrt_h: float,
):
    if gated:
        q_ref, k_ref, b_ref, bias_ref, deg_ref, gate_ref = rest
    else:
        bias_ref, deg_ref, gate_ref = rest

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        bias_ref[:, :] = jnp.zeros_like(bias_ref)
        deg_ref[:, :] = jnp.zeros_like(deg_ref)

    src = src_ref[0, :]  # [T] int32, parked at n_pad when invalid
    dst = dst_ref[0, :]
    m = mask_ref[0, :]  # [T] f32

    tile = src.shape[0]
    n_pad = v_ref.shape[0]
    local = jax.lax.broadcasted_iota(jnp.int32, (tile, n_pad), 1)
    # parked ids (n_pad) match no iota column -> all-zero one-hot rows,
    # so invalid edges gather zeros and scatter nothing
    oh_src = (src[:, None] == local).astype(jnp.float32)
    oh_dst = (dst[:, None] == local).astype(jnp.float32)

    _dot = partial(
        jax.lax.dot_general,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    row_dot = partial(_dot, dimension_numbers=(((1,), (0,)), ((), ())))
    # contract the EDGE axis of both operands: [T, N] x [T, H] -> [N, H]
    scatter_dot = partial(_dot, dimension_numbers=(((0,), (0,)), ((), ())))

    v_src = row_dot(oh_src, v_ref[:, :])  # [T, H] edge-gather (SpMM in)
    v_dst = row_dot(oh_dst, v_ref[:, :])

    if gated:
        q_e = row_dot(oh_src, q_ref[:, :])
        k_e = row_dot(oh_dst, k_ref[:, :])
        # SDDMM half: per-edge scaled dot + sigmoid gate on the VPU
        aff = jnp.sum(q_e * k_e, axis=1) * inv_sqrt_h
        g = jax.nn.sigmoid(aff + b_ref[0, 0]) * m
    else:
        g = m
    gate_ref[0, :] = g

    gv_src = g[:, None] * v_src
    gv_dst = g[:, None] * v_dst
    # SpMM half: segment-reduce both directions back to endpoint rows
    bias_ref[:, :] += scatter_dot(oh_dst, gv_src) + scatter_dot(oh_src, gv_dst)
    deg_ref[0, :] += (
        row_dot(g[None, :], oh_dst)[0, :] + row_dot(g[None, :], oh_src)[0, :]
    )


def _fused_call(
    src_ep,
    dst_ep,
    edge_mask,
    v,
    q,
    k,
    b_edge,
    gated: bool,
    tile: int,
    interpret: bool,
):
    n, h = v.shape
    e = src_ep.shape[0]
    e_pad = _pad_to(max(e, 1), tile)
    n_pad = _pad_to(n + 1, 128)  # +1 spill column keeps the park id in-grid
    h_pad = _pad_to(max(h, 1), 128)

    def _park(ep):
        ep = jnp.where(edge_mask, jnp.clip(ep, 0, n - 1), n_pad)
        return jnp.pad(
            ep.astype(jnp.int32), (0, e_pad - e), constant_values=n_pad
        )[None, :]

    src_p = _park(src_ep)
    dst_p = _park(dst_ep)
    mask_p = jnp.pad(edge_mask.astype(jnp.float32), (0, e_pad - e))[None, :]

    def _table(t):
        return jnp.pad(t.astype(jnp.float32), ((0, n_pad - n), (0, h_pad - h)))

    edge_spec = pl.BlockSpec((1, tile), lambda i: (0, i))
    table_spec = pl.BlockSpec((n_pad, h_pad), lambda i: (0, 0))

    in_specs = [edge_spec, edge_spec, edge_spec, table_spec]
    operands = [src_p, dst_p, mask_p, _table(v)]
    if gated:
        in_specs += [table_spec, table_spec, pl.BlockSpec((1, 1), lambda i: (0, 0))]
        operands += [_table(q), _table(k), b_edge.reshape(1, 1).astype(jnp.float32)]

    bias, deg, gate = pl.pallas_call(
        partial(
            _fused_kernel,
            gated=gated,
            inv_sqrt_h=1.0 / float(max(h, 1)) ** 0.5,
        ),
        grid=(e_pad // tile,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((n_pad, h_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, n_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, tile), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, h_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, e_pad), jnp.float32),
        ],
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*operands)
    return bias[:n, :h], deg[0, :n], gate[0, :e]


@programs.register("sparse.fused_gated_bias")
@partial(jax.jit, static_argnames=("tile", "interpret"))
def fused_gated_bias(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    b_edge: jnp.ndarray,
    src_ep: jnp.ndarray,
    dst_ep: jnp.ndarray,
    edge_mask: jnp.ndarray,
    tile: int = 256,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused STLGT neighbor bias: SDDMM gate
    ``sigmoid((q[src] . k[dst]) / sqrt(H) + b_edge) * mask`` and the
    bidirectional gated SpMM in one kernel.

    Returns (bias_sum[N, H], gate_deg[N], gate[E]) — UN-normalized sums;
    the model divides by max(gate_deg, 1) exactly as the XLA path does.
    """
    return _fused_call(
        src_ep, dst_ep, edge_mask, v, q, k, b_edge,
        gated=True, tile=tile, interpret=interpret,
    )


@programs.register("sparse.fused_neighbor_sums")
@partial(jax.jit, static_argnames=("tile", "interpret"))
def fused_neighbor_sums(
    h: jnp.ndarray,
    src_ep: jnp.ndarray,
    dst_ep: jnp.ndarray,
    edge_mask: jnp.ndarray,
    tile: int = 256,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused GraphSAGE neighbor aggregation: bidirectional masked SpMM
    plus the degree reduction in one kernel.

    Returns (agg[N, F], deg[N]); ``neighbor_mean`` divides agg by
    max(deg, 1) exactly as the XLA path does.
    """
    agg, deg, _gate = _fused_call(
        src_ep, dst_ep, edge_mask, h, None, None, None,
        gated=False, tile=tile, interpret=interpret,
    )
    return agg, deg


# ---------------------------------------------------------------------------
# sparse counting primitives (scorer building blocks, plain XLA)
# ---------------------------------------------------------------------------


def dense_rank_pairs(
    a: jnp.ndarray, b: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense rank of (a, b) pairs: returns (gid[N] int32, a_of_gid[N])
    where gid is the 0-based rank of row (a[i], b[i]) in the sorted
    distinct-pair order and a_of_gid[g] recovers a for group g (slots
    past the group count are 0). The rank order is (a, b)-lexicographic,
    so within any fixed a the gid is monotone in b and CONTIGUOUS per a —
    the property the sparse scorer's packed by-side keys rely on. One
    2-key sort + one scatter over N rows (~10 ms at 100k endpoints,
    measured same-box vs ~6.7 s for the 8M-row 5-key lexsort it replaces).
    """
    n = a.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    s_a, s_b, s_i = jax.lax.sort(
        (a.astype(jnp.int32), b.astype(jnp.int32), iota), num_keys=2
    )
    first = jnp.concatenate(
        [
            jnp.ones(1, dtype=bool),
            (s_a[1:] != s_a[:-1]) | (s_b[1:] != s_b[:-1]),
        ]
    )
    rank_sorted = jnp.cumsum(first.astype(jnp.int32)) - 1
    gid = jnp.zeros(n, jnp.int32).at[s_i].set(rank_sorted)
    # idempotent per-group scatter: every row of group g writes the same a
    a_of_gid = jnp.zeros(n, jnp.int32).at[rank_sorted].max(s_a)
    return gid, a_of_gid


def run_start_index(first: jnp.ndarray) -> jnp.ndarray:
    """For each row of a sorted table, the index of its run's first row
    (``first`` marks run boundaries). A cummax over (first ? i : -1) —
    no scatter, no segment ids. Rows before any boundary clamp to 0."""
    n = first.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    return jnp.maximum(
        jax.lax.cummax(jnp.where(first, iota, jnp.int32(-1))), 0
    )


def exclusive_cumsum(flags: jnp.ndarray) -> jnp.ndarray:
    """int32 exclusive prefix sum with a trailing total, length N+1:
    out[i] = number of set flags strictly before i. Boundary differences
    out[hi] - out[lo] over it are bit-exact distinct counts."""
    return jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(flags.astype(jnp.int32))]
    )
