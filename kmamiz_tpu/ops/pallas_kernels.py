"""Pallas TPU kernels for the window-pipeline hot ops.

The span-window groupby (window_stats) is a segment reduction: ~1M spans
scatter-add into ~80k (endpoint, status) segments. XLA lowers
jax.ops.segment_sum to scatter, which the TPU executes with serialized
index handling; this module reformulates the reduction as ONE-HOT MATMUL
so it rides the MXU instead:

    partial[m, S_blk] += values[m, K_blk] @ one_hot[K_blk, S_blk]

with the grid arranged (segment blocks outer/parallel, span blocks
inner/arbitrary) so each output tile accumulates in VMEM across span
blocks. The timestamp max reduction shares the same one-hot mask on the
VPU. This is the classic TPU sparse-reduction shape (SpMM via dense
masking — see PAPERS.md) applied to the reference's hottest loop
(kmamiz_data_processor/src/data/realtime_data.rs:31-121 groupby).

Use KMAMIZ_SEGMENT_BACKEND=pallas to switch the DataProcessor stats path
(server/processor.py consults segment_backend()); window_stats also takes
`backend=` directly.

Honest result of the backend shoot-out (v5e-1, tunnel-rtt-adjusted,
fori-chained — the r2 sweep):

    spans    segments   xla scatter   pallas one-hot
    32k      512        15.0 ms*      14.6 ms*
    32k      4,096      14.8 ms*      15.6 ms*
    131k     4,096      16.7 ms       19.6 ms
    2M       80,000     75.5 ms       1,270 ms
    (* small shapes are dispatch-overhead-bound; the backends tie)

The dense one-hot does N*S work, so it cannot win at the production
shape and only ties where overhead dominates — XLA's scatter stays the
default, and that is a measured conclusion, not a guess. The MXU idea
DOES win where the operand structure fits the systolic array: the
trace-row-packed ancestor walk (window.dependency_edges_packed), built
on this kernel's one-hot-einsum pattern with row-LOCAL (64-slot)
one-hots, beats the flat gather walk by >=50x at 1M spans at the SAME
depth cap (flat ~0.7-1.1 s/window; packed under ~20 ms, inside the
tunnel's measurement noise — reported per-run as walk_* in bench.py)
and has been the production default since round 1. Numerical note: matmul accumulation reassociates float
adds, so sums can differ from the scatter path by float32 rounding
(tests/test_ops_window.py asserts tight rtol, counts and maxes exact).
"""
from __future__ import annotations

import os
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams (~0.6); take whichever
# this jax ships
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

# block sizes: K spans x BS segments per tile; both ride the f32 (8, 128)
# tiling and keep the one-hot tile (K*BS*4B = 1MB) well inside VMEM
SPAN_BLOCK = 512
SEG_BLOCK = 512


def segment_backend(default: str = "xla") -> str:
    """Process-wide segment-reduction backend: 'xla' (scatter) or 'pallas'
    (one-hot MXU matmul). Overridable via KMAMIZ_SEGMENT_BACKEND."""
    return os.environ.get("KMAMIZ_SEGMENT_BACKEND", default)


def _segment_stats_kernel(seg_ref, vals_ref, ts_ref, sums_ref, maxs_ref):
    n_idx = pl.program_id(1)

    @pl.when(n_idx == 0)
    def _init():
        sums_ref[:, :] = jnp.zeros_like(sums_ref)
        maxs_ref[:, :] = jnp.zeros_like(maxs_ref)

    seg = seg_ref[0, :]  # [K] int32 segment id per span
    seg_base = pl.program_id(0) * SEG_BLOCK
    # one_hot[k, s] = 1 iff span k belongs to segment (seg_base + s)
    local = jax.lax.broadcasted_iota(jnp.int32, (SPAN_BLOCK, SEG_BLOCK), 1)
    one_hot = (seg[:, None] == seg_base + local).astype(jnp.float32)

    # all m stat rows reduce in one MXU pass: [m, K] @ [K, BS] -> [m, BS].
    # HIGHEST precision: the default lowers f32 matmul to bf16 MXU passes,
    # which costs ~0.5% relative error on latency sums
    sums_ref[:, :] += jnp.dot(
        vals_ref[:, :],
        one_hot,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )

    # timestamp max on the VPU over the same mask, in int32 (f32 would
    # round offsets above 2^24); identity 0: rel timestamps are
    # non-negative and empty segments report 0
    ts = ts_ref[0, :]
    masked = jnp.where(one_hot > 0, ts[:, None], 0)
    maxs_ref[:, :] = jnp.maximum(maxs_ref[:, :], jnp.max(masked, axis=0)[None, :])


@partial(jax.jit, static_argnames=("num_segments", "interpret"))
def segment_stats_matmul(
    values: jnp.ndarray,
    seg: jnp.ndarray,
    ts: jnp.ndarray,
    num_segments: int,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Segment-sum every row of values[m, N] and segment-max ts[N] by
    seg[N] int32 ids in [0, num_segments); rows with seg >= num_segments
    are dropped (the caller parks padded/invalid spans there).

    Returns (sums[m, num_segments] f32, ts_max[num_segments] int32).
    """
    m, n = values.shape
    n_pad = -(-n // SPAN_BLOCK) * SPAN_BLOCK
    # at least one spill block so parked ids stay in-range of the iota grid
    s_pad = -(-(num_segments + 1) // SEG_BLOCK) * SEG_BLOCK

    values = jnp.pad(values.astype(jnp.float32), ((0, 0), (0, n_pad - n)))
    # padded spans park at num_segments (first spill slot)
    seg = jnp.pad(
        seg.astype(jnp.int32), (0, n_pad - n), constant_values=num_segments
    )
    seg = jnp.where(seg >= num_segments, num_segments, seg)
    ts = jnp.pad(ts.astype(jnp.int32), (0, n_pad - n))

    grid = (s_pad // SEG_BLOCK, n_pad // SPAN_BLOCK)
    sums, maxs = pl.pallas_call(
        _segment_stats_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, SPAN_BLOCK), lambda s, n_: (0, n_)),
            pl.BlockSpec((m, SPAN_BLOCK), lambda s, n_: (0, n_)),
            pl.BlockSpec((1, SPAN_BLOCK), lambda s, n_: (0, n_)),
        ],
        out_specs=[
            pl.BlockSpec((m, SEG_BLOCK), lambda s, n_: (0, s)),
            pl.BlockSpec((1, SEG_BLOCK), lambda s, n_: (0, s)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, s_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, s_pad), jnp.int32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(seg[None, :], values, ts[None, :])
    return sums[:, :num_segments], maxs[0, :num_segments]
