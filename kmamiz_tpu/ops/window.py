"""Device kernels for the span-window pipeline (the DP hot path).

TPU-native reformulation of the reference hot loops
(/root/reference/kmamiz_data_processor/src/data_processor.rs:75-126):

- The per-span parent-chain walk (trace.rs:110-212 / Traces.ts:128-143)
  becomes a fixed-iteration ancestor enumeration: first resolve each span's
  nearest non-CLIENT ancestor by iterated pointer jumps, then hop that
  skip-pointer MAX_DEPTH times, emitting (ancestor, descendant, distance)
  edge triples. No data-dependent control flow; everything is gathers over
  int32 arrays, which XLA vectorizes across the whole window.
- Every Map-groupby (realtime_data.rs:31-121 / RealtimeDataList.ts:23-33)
  becomes segment reductions keyed by endpoint*num_statuses+status, with CV
  in the sum/sum-of-squares form the Rust DP already uses.

All kernels take fixed-shape padded arrays (see core.spans.SpanBatch) so
XLA compiles once per padded size.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kmamiz_tpu.core import programs
from kmamiz_tpu.core.spans import KIND_CLIENT, KIND_SERVER

MAX_CLIENT_SKIP = 16  # max run of consecutive CLIENT spans in a parent chain
MAX_DEPTH = 32  # max SERVER-ancestor depth recorded (trace trees are shallow)


@programs.register("window.skip_client_parents")
@partial(jax.jit, static_argnames=("max_client_skip",))
def skip_client_parents(
    parent_idx: jnp.ndarray,
    kind: jnp.ndarray,
    valid: jnp.ndarray,
    max_client_skip: int = MAX_CLIENT_SKIP,
) -> jnp.ndarray:
    """For each span, the index of its nearest non-CLIENT strict ancestor
    within the window (-1 if none)."""
    safe_parent = jnp.where(valid, parent_idx, -1)

    def step(c, _):
        c_safe = jnp.maximum(c, 0)
        is_client_parent = (c >= 0) & (kind[c_safe] == KIND_CLIENT)
        nxt = jnp.where(is_client_parent, safe_parent[c_safe], c)
        return nxt, None

    c0 = safe_parent
    c, _ = jax.lax.scan(step, c0, None, length=max_client_skip)
    # a chain of >max_client_skip CLIENT spans leaves a CLIENT as the carry;
    # mask it to -1 (truncation) rather than emitting a CLIENT ancestor
    still_client = (c >= 0) & (kind[jnp.maximum(c, 0)] == KIND_CLIENT)
    return jnp.where(still_client, -1, c)


@programs.register("window.dependency_edges")
@partial(jax.jit, static_argnames=("max_depth", "max_client_skip"))
def dependency_edges(
    parent_idx: jnp.ndarray,
    kind: jnp.ndarray,
    valid: jnp.ndarray,
    endpoint_id: jnp.ndarray,
    max_depth: int = MAX_DEPTH,
    max_client_skip: int = MAX_CLIENT_SKIP,
) -> NamedTuple:
    """Enumerate (ancestor_endpoint, descendant_endpoint, distance) triples.

    Returns arrays of shape [n, max_depth]: ancestor_ep, descendant_ep,
    distance, mask. A row i contributes edges only if span i is a valid
    SERVER span; ancestors are its non-CLIENT ancestor chain, distance
    counted per recorded hop exactly like the reference walk.
    """
    skip = skip_client_parents(parent_idx, kind, valid, max_client_skip)
    is_server = valid & (kind == KIND_SERVER)

    def step(anc, _):
        anc_safe = jnp.maximum(anc, 0)
        nxt = jnp.where(anc >= 0, skip[anc_safe], -1)
        return nxt, anc

    _, ancestors = jax.lax.scan(step, skip, None, length=max_depth)
    # ancestors: [max_depth, n] -> [n, max_depth]
    ancestors = ancestors.T
    anc_valid = (ancestors >= 0) & is_server[:, None]
    anc_safe = jnp.maximum(ancestors, 0)

    class Edges(NamedTuple):
        ancestor_ep: jnp.ndarray
        descendant_ep: jnp.ndarray
        distance: jnp.ndarray
        mask: jnp.ndarray
        ancestor_span: jnp.ndarray

    distances = jnp.arange(1, max_depth + 1, dtype=jnp.int32)[None, :]
    return Edges(
        ancestor_ep=jnp.where(anc_valid, endpoint_id[anc_safe], -1),
        descendant_ep=jnp.where(anc_valid, endpoint_id[:, None], -1),
        distance=jnp.where(anc_valid, distances, 0),
        mask=anc_valid,
        ancestor_span=jnp.where(anc_valid, ancestors, -1),
    )


class PackedEdges(NamedTuple):
    ancestor_ep: jnp.ndarray  # int32[T*L, max_depth]
    descendant_ep: jnp.ndarray  # int32[T*L, max_depth]
    distance: jnp.ndarray  # int32[T*L, max_depth]
    mask: jnp.ndarray  # bool[T*L, max_depth]
    ancestor_slot: jnp.ndarray  # int32[T*L, max_depth] (packed flat index)


@programs.register("window.dependency_edges_packed")
@partial(jax.jit, static_argnames=("max_depth", "max_client_skip"))
def dependency_edges_packed(
    parent_slot: jnp.ndarray,
    kind: jnp.ndarray,
    valid: jnp.ndarray,
    endpoint_id: jnp.ndarray,
    max_depth: int = MAX_DEPTH,
    max_client_skip: int = MAX_CLIENT_SKIP,
) -> PackedEdges:
    """dependency_edges over trace-packed rows ([T, L] from
    core.spans.pack_trace_rows): every ancestor hop is a row-local one-hot
    einsum batched over traces on the MXU instead of an HBM gather — TPU
    gathers cost ~6.6 ms per 1M elements while the batched einsum is
    bandwidth-bound (~10x cheaper for the full walk).

    Semantics match dependency_edges exactly (CLIENT-skip via pointer
    doubling, depth-capped ancestor chains); only the row layout and the
    meaning of ancestor_slot (packed flat index, not batch index) differ.
    """
    t_rows, l_slots = parent_slot.shape
    iota = jnp.arange(l_slots, dtype=jnp.int32)
    f32 = jnp.float32

    def onehot(idx):
        # [T, L] slot ids -> [T, L, L] one-hot rows; idx < 0 -> zero row
        return (idx[:, :, None] == iota[None, None, :]).astype(f32)

    def oh_gather(oh, x, precision=None):
        # out[t, j] = x[t, idx[t, j]] (0 where the one-hot row is zero)
        return jnp.einsum("tji,ti->tj", oh, x.astype(f32), precision=precision)

    def gather_slot(idx, x):
        # int slot gather with -1 passthrough (slot values are < L, exact
        # under the MXU's bf16 passes)
        g = oh_gather(onehot(idx), x)
        return jnp.where(idx < 0, -1, g.astype(jnp.int32))

    is_client = kind == KIND_CLIENT
    safe_parent = jnp.where(valid & (parent_slot >= 0), parent_slot, -1)

    # CLIENT-skip by pointer doubling: h is identity on non-CLIENT slots and
    # parent on CLIENT slots, so h^k applies exactly k conditional hops
    # (-1 absorbs). Binary decomposition keeps h^max_client_skip EXACT for
    # any cap, matching skip_client_parents' truncation step for step.
    h = jnp.where(is_client, safe_parent, iota[None, :])
    result = jnp.broadcast_to(iota[None, :], h.shape)  # h^0 = identity
    k = max_client_skip
    power = h
    while k:
        if k & 1:
            # h^(a+b)[j] = h^a[h^b[j]]  (powers of one function commute)
            result = gather_slot(result, power)
        k >>= 1
        if k:
            power = gather_slot(power, power)
    skip_raw = gather_slot(safe_parent, result)
    # chains longer than the cap leave a CLIENT slot: truncate to -1,
    # mirroring skip_client_parents
    oh_skip = onehot(skip_raw)
    still_client = (skip_raw >= 0) & (
        oh_gather(oh_skip, is_client.astype(f32)) > 0.5
    )
    skip = jnp.where(still_client, -1, skip_raw)

    is_server = valid & (kind == KIND_SERVER)
    skip_f = skip.astype(f32)
    ep_f = endpoint_id.astype(f32)
    row_base = (jnp.arange(t_rows, dtype=jnp.int32) * l_slots)[:, None]

    anc = skip
    anc_eps, anc_slots, masks = [], [], []
    for _ in range(max_depth):
        oh = onehot(anc)
        step_mask = (anc >= 0) & is_server
        # endpoint ids exceed bf16's exact-int range; HIGHEST keeps the
        # extraction f32-exact
        ep_d = oh_gather(oh, ep_f, precision=jax.lax.Precision.HIGHEST)
        anc_eps.append(jnp.where(step_mask, ep_d.astype(jnp.int32), -1))
        anc_slots.append(jnp.where(step_mask, row_base + anc, -1))
        masks.append(step_mask)
        nxt = oh_gather(oh, skip_f)
        anc = jnp.where(anc < 0, -1, nxt.astype(jnp.int32))

    def stack(parts):
        return jnp.stack(parts, axis=-1).reshape(t_rows * l_slots, max_depth)

    mask = stack(masks)
    distances = jnp.arange(1, max_depth + 1, dtype=jnp.int32)[None, :]
    return PackedEdges(
        ancestor_ep=stack(anc_eps),
        descendant_ep=jnp.where(
            mask, endpoint_id.reshape(-1, 1), -1
        ),
        distance=jnp.where(mask, distances, 0),
        mask=mask,
        ancestor_slot=stack(anc_slots),
    )


@programs.register("window.dependency_edges_packed_sparse")
@partial(jax.jit, static_argnames=("max_depth", "max_client_skip"))
def dependency_edges_packed_sparse(
    parent_slot: jnp.ndarray,
    kind: jnp.ndarray,
    valid: jnp.ndarray,
    endpoint_id: jnp.ndarray,
    max_depth: int = MAX_DEPTH,
    max_client_skip: int = MAX_CLIENT_SKIP,
) -> PackedEdges:
    """dependency_edges_packed without the [T, L, L] one-hot adjacency:
    every hop is a row-local int32 take_along_axis over the packed rows.
    On CPU hosts the gather is a plain indexed load and the one-hot
    einsum's O(T*L*L) flops are pure overhead, so the sparse backend
    routes the walk here (the MXU einsum stays the TPU default — see
    graph/store.py's sparse_walk dispatch).

    Bit-exact against dependency_edges_packed: same CLIENT-skip pointer
    doubling, same truncation, same PackedEdges layout — integer gathers
    cannot even round where the einsum needed Precision.HIGHEST.
    """
    t_rows, l_slots = parent_slot.shape
    iota = jnp.arange(l_slots, dtype=jnp.int32)

    def gather_slot(idx, x):
        # out[t, j] = x[t, idx[t, j]] with -1 passthrough
        g = jnp.take_along_axis(x, jnp.maximum(idx, 0), axis=1)
        return jnp.where(idx < 0, -1, g)

    is_client = kind == KIND_CLIENT
    safe_parent = jnp.where(valid & (parent_slot >= 0), parent_slot, -1)

    # CLIENT-skip by pointer doubling, mirroring dependency_edges_packed
    h = jnp.where(is_client, safe_parent, iota[None, :])
    result = jnp.broadcast_to(iota[None, :], h.shape)
    k = max_client_skip
    power = h
    while k:
        if k & 1:
            result = gather_slot(result, power)
        k >>= 1
        if k:
            power = gather_slot(power, power)
    skip_raw = gather_slot(safe_parent, result)
    still_client = (skip_raw >= 0) & jnp.take_along_axis(
        is_client, jnp.maximum(skip_raw, 0), axis=1
    )
    skip = jnp.where(still_client, -1, skip_raw)

    is_server = valid & (kind == KIND_SERVER)
    row_base = (jnp.arange(t_rows, dtype=jnp.int32) * l_slots)[:, None]

    anc = skip
    anc_eps, anc_slots, masks = [], [], []
    for _ in range(max_depth):
        anc_safe = jnp.maximum(anc, 0)
        step_mask = (anc >= 0) & is_server
        ep_d = jnp.take_along_axis(endpoint_id, anc_safe, axis=1)
        anc_eps.append(jnp.where(step_mask, ep_d, -1))
        anc_slots.append(jnp.where(step_mask, row_base + anc, -1))
        masks.append(step_mask)
        nxt = jnp.take_along_axis(skip, anc_safe, axis=1)
        anc = jnp.where(anc < 0, -1, nxt)

    def stack(parts):
        return jnp.stack(parts, axis=-1).reshape(t_rows * l_slots, max_depth)

    mask = stack(masks)
    distances = jnp.arange(1, max_depth + 1, dtype=jnp.int32)[None, :]
    return PackedEdges(
        ancestor_ep=stack(anc_eps),
        descendant_ep=jnp.where(
            mask, endpoint_id.reshape(-1, 1), -1
        ),
        distance=jnp.where(mask, distances, 0),
        mask=mask,
        ancestor_slot=stack(anc_slots),
    )


class WindowStats(NamedTuple):
    """Per-(endpoint, status) segment statistics for one window."""

    count: jnp.ndarray  # float[S]
    error_4xx: jnp.ndarray  # float[S]
    error_5xx: jnp.ndarray  # float[S]
    latency_sum: jnp.ndarray  # float[S]
    latency_sq_sum: jnp.ndarray  # float[S]
    latency_mean: jnp.ndarray  # float[S]
    latency_cv: jnp.ndarray  # float[S]
    latest_timestamp_rel: jnp.ndarray  # int32[S] (max offset from window base)


@programs.register("window.stats")
@partial(jax.jit, static_argnames=("num_endpoints", "num_statuses", "backend"))
def window_stats(
    endpoint_id: jnp.ndarray,
    status_id: jnp.ndarray,
    status_class: jnp.ndarray,
    latency_ms: jnp.ndarray,
    timestamp_rel: jnp.ndarray,
    valid_server: jnp.ndarray,
    num_endpoints: int,
    num_statuses: int,
    backend: str = "xla",
) -> WindowStats:
    """Segment-combine per (endpoint, status): request count, 4xx/5xx counts,
    latency mean + CV (sum/sum-of-squares form, matching the Rust DP's
    realtime_data.rs:52-81), and latest timestamp.

    timestamp_rel: int32 microsecond offsets from the window base (absolute
    µs don't fit int32, and the TPU path runs with x64 off — the caller adds
    the base back on the host).

    backend: 'xla' (scatter-based segment ops), 'pallas' / 'pallas_interpret'
    (one-hot MXU matmul kernel, kmamiz_tpu.ops.pallas_kernels)."""
    num_segments = num_endpoints * num_statuses
    seg = endpoint_id * num_statuses + status_id
    seg = jnp.where(valid_server, seg, num_segments)  # park invalid rows

    w = valid_server.astype(latency_ms.dtype)
    ones = w
    if backend.startswith("pallas"):
        from kmamiz_tpu.ops.pallas_kernels import segment_stats_matmul

        interpret = backend == "pallas_interpret"
        lat_f = latency_ms.astype(jnp.float32)
        values = jnp.stack(
            [
                ones.astype(jnp.float32),
                (ones * (status_class == 4)).astype(jnp.float32),
                (ones * (status_class == 5)).astype(jnp.float32),
                lat_f * w,
                lat_f * lat_f * w,
            ]
        )
        sums, ts_f = segment_stats_matmul(
            values,
            seg,
            jnp.where(valid_server, timestamp_rel, 0),
            num_segments,
            interpret=interpret,
        )
        count, e4, e5, lat_sum, lat_sq = sums
        ts = ts_f.astype(jnp.int32)
        safe_count = jnp.maximum(count, 1)
        mean = lat_sum / safe_count
        resid = (latency_ms - mean[jnp.minimum(seg, num_segments - 1)]) * w
        resid_sq, _ = segment_stats_matmul(
            (resid * resid)[None, :].astype(jnp.float32),
            seg,
            jnp.zeros_like(timestamp_rel),
            num_segments,
            interpret=interpret,
        )
        variance = resid_sq[0] / safe_count
    else:
        # ONE vector-valued scatter for all five sums: TPU scatter cost is
        # dominated by per-index handling, so [N, 5] is ~3x cheaper than
        # five separate [N] segment_sums
        lat_w = latency_ms * w
        data = jnp.stack(
            [
                ones,
                ones * (status_class == 4),
                ones * (status_class == 5),
                lat_w,
                latency_ms * lat_w,
            ],
            axis=1,
        )
        sums = jax.ops.segment_sum(data, seg, num_segments=num_segments + 1)[:-1]
        count, e4, e5, lat_sum, lat_sq = (sums[:, i] for i in range(5))
        ts = jax.ops.segment_max(
            jnp.where(valid_server, timestamp_rel, 0),
            seg,
            num_segments=num_segments + 1,
        )[:-1]
        ts = jnp.where(count > 0, ts, 0)  # empty segments: 0, not int32 min

        safe_count = jnp.maximum(count, 1)
        mean = lat_sum / safe_count
        # two-pass variance: sum of squared residuals against the segment
        # mean. The naive E[x^2]-E[x]^2 form cancels catastrophically in
        # float32 (the production TPU dtype); one extra segment_sum buys
        # f64-like stability.
        resid = (latency_ms - mean[jnp.minimum(seg, num_segments - 1)]) * w
        variance = (
            jax.ops.segment_sum(
                resid * resid, seg, num_segments=num_segments + 1
            )[:-1]
            / safe_count
        )
    std = jnp.sqrt(jnp.maximum(variance, 0.0))
    cv = jnp.where(mean != 0, std / jnp.maximum(mean, 1e-300), 0.0)
    return WindowStats(
        count=count,
        error_4xx=e4,
        error_5xx=e5,
        latency_sum=lat_sum,
        latency_sq_sum=lat_sq,
        latency_mean=jnp.where(count > 0, mean, 0.0),
        latency_cv=jnp.where(count > 0, cv, 0.0),
        latest_timestamp_rel=ts,
    )


@programs.register("window.service_stats")
@partial(jax.jit, static_argnames=("num_services",))
def service_stats(
    service_of_segment: jnp.ndarray,
    stats_count: jnp.ndarray,
    stats_error_5xx: jnp.ndarray,
    stats_cv: jnp.ndarray,
    num_services: int,
):
    """Roll (endpoint,status) segments up to services: request counts, 5xx
    counts, and combined-weighted latency-CV sums (the risk pipeline's
    GetLatencyCVOfServices shape, RiskAnalyzer.ts:228-248)."""
    seg = jnp.where(stats_count > 0, service_of_segment, num_services)
    count = jax.ops.segment_sum(stats_count, seg, num_segments=num_services + 1)[:-1]
    err5 = jax.ops.segment_sum(stats_error_5xx, seg, num_segments=num_services + 1)[:-1]
    cv_weighted = jax.ops.segment_sum(
        stats_cv * stats_count, seg, num_segments=num_services + 1
    )[:-1]
    return count, err5, cv_weighted
