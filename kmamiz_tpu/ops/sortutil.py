"""Lexicographic sort/unique over int32 key columns.

TPU-friendly replacement for int64 key packing: JAX on TPU runs with x64
disabled by default, so wide packed keys silently truncate. All dedup in
the graph pipeline instead sorts tuples of int32 columns with ONE
variadic lax.sort (num_keys = all columns — XLA's sort compares the keys
lexicographically inside a single sort pass, measured 2.6x faster on TPU
at 8M x 5 keys than jnp.lexsort's one-pass-per-key loop) and marks first
occurrences. INT32_MAX doubles as the parked-row sentinel.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

SENTINEL = jnp.iinfo(jnp.int32).max


def park_invalid(cols: Sequence[jnp.ndarray], valid: jnp.ndarray) -> List[jnp.ndarray]:
    """Replace invalid rows with the sentinel in every column."""
    return [jnp.where(valid, c.astype(jnp.int32), SENTINEL) for c in cols]


def lex_unique(
    cols: Sequence[jnp.ndarray], valid: jnp.ndarray
) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Sort rows lexicographically (cols[0] most significant) and mark the
    first occurrence of each distinct valid row.

    Returns (sorted_cols, unique_mask); parked rows sort to the end and are
    never marked unique.
    """
    parked = park_invalid(cols, valid)
    # one variadic sort: every column is a key (first column primary);
    # rows identical across ALL columns are interchangeable, so the
    # unstable comparator changes nothing observable
    sorted_cols = list(jax.lax.sort(tuple(parked), num_keys=len(parked)))
    neq = jnp.zeros(sorted_cols[0].shape[0] - 1, dtype=bool)
    for c in sorted_cols:
        neq = neq | (c[1:] != c[:-1])
    first = jnp.concatenate([jnp.array([True]), neq])
    is_valid = sorted_cols[0] != SENTINEL
    return sorted_cols, first & is_valid


def scatter_compact(
    cols: Sequence[jnp.ndarray], keep: jnp.ndarray
) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Pack kept rows into a prefix (input order preserved), parking the
    tail at SENTINEL. cumsum + scatter — ~2x cheaper than the sort it
    replaces, and order-preserving, so sorted input stays sorted."""
    n = cols[0].shape[0]
    pos = jnp.cumsum(keep) - 1
    dest = jnp.where(keep, pos, n)  # dropped rows land in a trash slot
    out = []
    for c in cols:
        buf = jnp.full(n + 1, SENTINEL, dtype=jnp.int32)
        buf = buf.at[dest].set(jnp.where(keep, c.astype(jnp.int32), SENTINEL))
        out.append(buf[:n])
    return out, out[0] != SENTINEL


def compact_unique(
    cols: Sequence[jnp.ndarray], valid: jnp.ndarray
) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """lex_unique, then push duplicate/parked rows to the tail so distinct
    valid rows form a sorted prefix. Returns (cols, valid_mask)."""
    sorted_cols, uniq = lex_unique(cols, valid)
    return scatter_compact(sorted_cols, uniq)


# single-int32-key packing for (src, dst, dist) edge rows: 14+14+3 bits.
# Usable when the CALLER statically guarantees src/dst < 2^14 - 1 and
# 1 <= dist <= 8 (the -1 keeps the max packed key below SENTINEL); the
# graph store checks those bounds host-side and falls back to the
# 3-column path otherwise. One single-key sort + one scatter is ~2x
# cheaper than the 3-column lexsort pair on TPU (measured 1M int32:
# sort 30 ms + scatter 30 ms vs 95 ms compact_unique).
EDGE_KEY_EP_BITS = 14
EDGE_KEY_DIST_BITS = 3
EDGE_KEY_MAX_EP = (1 << EDGE_KEY_EP_BITS) - 1  # ids must be < this
EDGE_KEY_MAX_DIST = 1 << EDGE_KEY_DIST_BITS  # dist must be <= this


def compact_unique_edges_packed(
    src: jnp.ndarray,
    dst: jnp.ndarray,
    dist: jnp.ndarray,
    valid: jnp.ndarray,
) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """compact_unique over (src, dst, dist) via one packed int32 key.

    Ordering matches the 3-column lexsort (the packing is monotone in
    (src, dst, dist)), so outputs are interchangeable with compact_unique.
    """
    shift = EDGE_KEY_EP_BITS + EDGE_KEY_DIST_BITS
    key = (
        (src.astype(jnp.int32) << shift)
        | (dst.astype(jnp.int32) << EDGE_KEY_DIST_BITS)
        | (dist.astype(jnp.int32) - 1)
    )
    key = jnp.where(valid, key, SENTINEL)
    skey = jnp.sort(key)
    neq = jnp.concatenate([jnp.array([True]), skey[1:] != skey[:-1]])
    keep = neq & (skey != SENTINEL)
    (ckey,), valid_out = scatter_compact([skey], keep)
    dist_mask = EDGE_KEY_MAX_DIST - 1
    src_o = jnp.where(valid_out, ckey >> shift, SENTINEL)
    dst_o = jnp.where(
        valid_out, (ckey >> EDGE_KEY_DIST_BITS) & EDGE_KEY_MAX_EP, SENTINEL
    )
    dist_o = jnp.where(valid_out, (ckey & dist_mask) + 1, SENTINEL)
    return [src_o, dst_o, dist_o], valid_out
