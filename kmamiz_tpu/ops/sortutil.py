"""Lexicographic sort/unique over int32 key columns.

TPU-friendly replacement for int64 key packing: JAX on TPU runs with x64
disabled by default, so wide packed keys silently truncate. All dedup in the
graph pipeline instead sorts tuples of int32 columns with jnp.lexsort and
marks first occurrences. INT32_MAX doubles as the parked-row sentinel.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp

SENTINEL = jnp.iinfo(jnp.int32).max


def park_invalid(cols: Sequence[jnp.ndarray], valid: jnp.ndarray) -> List[jnp.ndarray]:
    """Replace invalid rows with the sentinel in every column."""
    return [jnp.where(valid, c.astype(jnp.int32), SENTINEL) for c in cols]


def lex_unique(
    cols: Sequence[jnp.ndarray], valid: jnp.ndarray
) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Sort rows lexicographically (cols[0] most significant) and mark the
    first occurrence of each distinct valid row.

    Returns (sorted_cols, unique_mask); parked rows sort to the end and are
    never marked unique.
    """
    parked = park_invalid(cols, valid)
    perm = jnp.lexsort(tuple(parked[::-1]))  # lexsort: last key is primary
    sorted_cols = [c[perm] for c in parked]
    neq = jnp.zeros(sorted_cols[0].shape[0] - 1, dtype=bool)
    for c in sorted_cols:
        neq = neq | (c[1:] != c[:-1])
    first = jnp.concatenate([jnp.array([True]), neq])
    is_valid = sorted_cols[0] != SENTINEL
    return sorted_cols, first & is_valid


def compact_unique(
    cols: Sequence[jnp.ndarray], valid: jnp.ndarray
) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """lex_unique, then push duplicate/parked rows to the tail so distinct
    valid rows form a sorted prefix. Returns (cols, valid_mask)."""
    sorted_cols, uniq = lex_unique(cols, valid)
    compacted = park_invalid(sorted_cols, uniq)
    perm = jnp.lexsort(tuple(compacted[::-1]))
    out = [c[perm] for c in compacted]
    return out, out[0] != SENTINEL
