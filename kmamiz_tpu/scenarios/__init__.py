"""Seeded scenario factory + closed-loop soak runner (docs/SCENARIOS.md).

Compose: :func:`scenario_matrix` samples topologies, traffic curves,
and failure storylines from one integer seed. Run: :func:`run_scenario`
drives each spec against a real in-process ``DataProcessorServer`` /
``TickRouter`` and scores it against its SLO gates. Everything random
happens at compose time; :func:`spec_signature` is the determinism
oracle.
"""
from kmamiz_tpu.scenarios.factory import (
    ARCHETYPES,
    ScenarioSpec,
    TenantPlan,
    build_scenario,
    scenario_matrix,
    spec_signature,
)
from kmamiz_tpu.scenarios.labeled import labeled_windows
from kmamiz_tpu.scenarios.runner import (
    crashed_card,
    recorded_runs,
    run_counterfactual,
    run_matrix,
    run_scenario,
)
from kmamiz_tpu.scenarios.storyline import (
    STORYLINE_KINDS,
    Event,
    enabled_storylines,
)
from kmamiz_tpu.scenarios.topology import TOPOLOGY_KINDS, Topology
from kmamiz_tpu.scenarios.traffic import TRAFFIC_KINDS


def reset_for_tests() -> None:
    """Clear scenario-global state (the completed-run registry)."""
    from kmamiz_tpu.scenarios import runner

    runner.reset_for_tests()


__all__ = [
    "ARCHETYPES",
    "Event",
    "ScenarioSpec",
    "STORYLINE_KINDS",
    "TOPOLOGY_KINDS",
    "TRAFFIC_KINDS",
    "TenantPlan",
    "Topology",
    "build_scenario",
    "enabled_storylines",
    "labeled_windows",
    "recorded_runs",
    "reset_for_tests",
    "run_counterfactual",
    "run_matrix",
    "run_scenario",
    "scenario_matrix",
    "spec_signature",
]
