"""Closed-loop scenario runner: a real server, a storyline, a scorecard.

One :func:`run_scenario` call boots a real in-process
``DataProcessorServer`` (custom ``TickRouter`` mounting every tenant of
the spec with its own controller-driven trace source), replays the
storyline tick by tick over HTTP — ticks through ``POST /`` (or
``/t/<tenant>/``), poison storms through ``POST /ingest``, upstream
flaps through per-tenant circuit breakers wrapping the sources,
tick stalls through the watchdog deadline, kill -9 through a crashed
child process whose ingest WAL the scenario's processor replays — while
concurrent reader workers (the ``tests/test_soak.py`` harness) keep
health/timings pressure on the same server.

The scorecard's lost-span/determinism oracle is a *reference graph*:
every span group the runner hands the live system is also recorded, in
ingest order, and at the end a fresh processor ingests exactly that
sequence — ``resilience.chaos.graph_signature`` equality means the soak
lost nothing and duplicated nothing, whatever degraded serves, breaker
trips, and WAL replays happened along the way. Span content is pure
arithmetic over (tick, trace) — see :mod:`.topology` — so re-posting a
tick during recovery probes cannot change the merged content.

SLO gates per scenario (``scorecard["gates"]``): bit-exact graph +
zero lost spans; zero steady-state recompiles (program-registry
snapshot diff, taken after the terminal-shape warmup); stale serves
present-and-bounded for degrading storylines, zero otherwise; every
poisoned delivery quarantined; recovery-to-fresh after each degrading
fault; child SIGKILL + full WAL replay for kill-9 storylines.
"""
from __future__ import annotations

import contextlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple

from kmamiz_tpu.telemetry.profiling import events as prof_events

from kmamiz_tpu.scenarios.factory import (
    SEED_STRIDE,
    ScenarioSpec,
    build_scenario,
)
from kmamiz_tpu.scenarios.storyline import (
    growth_groups,
    growth_twin_groups,
    poison_payloads_for,
)
from kmamiz_tpu.scenarios.topology import tick_groups, trace_group

#: completed scorecards, newest last (observability + test assertions)
_RUNS_LOCK = threading.Lock()
_RUNS: List[dict] = []

#: wall-clock ceiling per scenario; a wedged scenario fails loudly
#: instead of hanging the matrix
SCENARIO_MAX_WALL_S = 600.0

#: recovery probe loop: attempts x sleep bounds recovery-to-fresh
RECOVERY_ATTEMPTS = 120
RECOVERY_SLEEP_S = 0.05

STALL_DEADLINE_MS = 250
STALL_SLEEP_S = 1.0

#: graftstream freshness SLO: span-arrival -> forecast-visible p99
#: ceiling for the streaming-freshness archetype (matches the bench
#: gate on stream_freshness_ms_p99 in tools/slo_report.py)
FRESHNESS_SLO_MS = 250.0

#: must sit under chaos.mutate_payload's "bomb" size (~4.1 KB) so a
#: poison-storm bomb always trips the ingest cap (chaos_probe's cap)
POISON_SIZE_CAP = 4000

KILL9_WINDOWS = 5


def reset_for_tests() -> None:
    with _RUNS_LOCK:
        _RUNS.clear()


def recorded_runs() -> List[dict]:
    with _RUNS_LOCK:
        return list(_RUNS)


@contextlib.contextmanager
def scoped_env(pairs: Dict[str, Optional[str]]):
    """Set env knobs for one scenario, restoring prior values (None
    removes the key) — scenarios must not leak knobs into each other."""
    saved = {k: os.environ.get(k) for k in pairs}
    try:
        for k, v in pairs.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _soak_harness():
    """The tests/test_soak.py worker harness (guarded loops, shared
    stop, deadline, deadlock-detecting joins); inline fallback when the
    tests tree is not importable (installed-package runs)."""
    try:
        from tests.test_soak import run_soak_workers

        return run_soak_workers
    except ImportError:
        def run_soak_workers(worker_fns, seconds):
            errors: List[str] = []
            stop = threading.Event()
            deadline = time.time() + seconds

            def guard(fn):
                def run():
                    try:
                        while time.time() < deadline and not stop.is_set():
                            fn()
                    except Exception as e:  # noqa: BLE001
                        errors.append(f"{fn.__name__}: {e!r}")
                        stop.set()

                return run

            threads = [
                threading.Thread(target=guard(fn), daemon=True)
                for fn in worker_fns
            ]
            t0 = time.time()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
                if t.is_alive():
                    raise RuntimeError("soak worker failed to stop")
            return errors, time.time() - t0

        return run_soak_workers


class _ScenarioSource:
    """Controller-driven trace source for one tenant, wrapped in that
    tenant's circuit breaker. The driver pushes a tick's groups before
    posting the tick; a flap makes the upstream raise (tripping the
    breaker), a stall makes it hang past the watchdog deadline. Pending
    groups survive failed calls, so recovery probes drain them exactly
    once."""

    def __init__(self, tenant: str) -> None:
        self.tenant = tenant
        self._lock = threading.Lock()
        self._pending: List[List[dict]] = []
        self.fail = False
        self.stall_s = 0.0

    def push(self, groups: List[List[dict]]) -> None:
        with self._lock:
            self._pending.extend(groups)

    def __call__(self, _look_back, _end_ts, _limit):
        from kmamiz_tpu.resilience.breaker import get_breaker

        def upstream():
            if self.fail:
                raise ConnectionError("scenario: upstream flap")
            if self.stall_s:
                time.sleep(self.stall_s)
            with self._lock:
                groups, self._pending = self._pending, []
            return groups

        breaker = get_breaker(
            "scenario-upstream",
            tenant=self.tenant,
            threshold=3,
            cooldown_s=0.25,
        )
        return breaker.call(upstream)


def _tenant_prefix(tenant: str) -> str:
    return "" if tenant == "default" else f"/t/{tenant}"


def _post_tick(
    port: int, tenant: str, unique_id: str, timeout_s: float = 120.0
) -> Tuple[int, dict, float]:
    body = json.dumps(
        {
            "uniqueId": unique_id,
            "lookBack": 30_000,
            # real clock: the processed-trace TTL prunes against ingest
            # time, so a virtual epoch here would strand dedup entries
            "time": int(prof_events.wall_ms()),
        }
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{_tenant_prefix(tenant)}/",
        data=body,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    t0 = prof_events.now_ms()
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        payload = json.loads(resp.read())
        return resp.status, payload, prof_events.now_ms() - t0


def _post_ingest(port: int, tenant: str, raw: bytes) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{_tenant_prefix(tenant)}/ingest",
        data=raw,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())


# -- storyline -> per-tick view ----------------------------------------------


def _deploy_version_fn(plan, tick: int):
    """istio.canonical_revision per service at ``tick`` under any active
    rolling-deploy event: one service of the event's order flips to v2
    per elapsed tick."""
    flipped = set()
    for ev in plan.events:
        if ev.kind == "rolling-deploy" and tick >= ev.at_tick:
            (order,) = ev.params
            flipped.update(order[: tick - ev.at_tick + 1])

    def version_of(svc: str) -> str:
        return "v2" if svc in flipped else "v1"

    return version_of


def _tick_view(plan, tick: int) -> dict:
    """What the storyline does to this tenant at this tick."""
    view = {
        "flap": False,
        "stall": False,
        "drop": set(),
        "error": set(),
        "latency_us": 0,
        "poisons": [],
        "growth": [],
    }
    for ev in plan.events:
        if not ev.active(tick):
            continue
        if ev.kind == "upstream-flap":
            view["flap"] = True
        elif ev.kind == "tick-stall":
            view["stall"] = True
        elif ev.kind == "partial-outage":
            view["drop"].update(ev.params[0])
        elif ev.kind == "cascade":
            view["error"].update(ev.params[0])
            view["latency_us"] = 5_000 * ev.params[1]
        elif ev.kind == "poison-storm":
            view["poisons"].append(ev)
        elif ev.kind == "capacity-growth":
            view["growth"].append(ev)
    return view


def kill9_windows(spec: ScenarioSpec) -> List[bytes]:
    """The deterministic raw windows a kill-9 storyline's crash child
    ingests (and the parent replays): pure spec content, regenerated
    identically on both sides of the process boundary."""
    plan = spec.tenants[0]
    return [
        json.dumps(
            [
                trace_group(plan.topology, f"{spec.name}-wal", 90 + w, i)
                for i in range(2)
            ]
        ).encode()
        for w in range(KILL9_WINDOWS)
    ]


def run_child_kill(
    archetype: str, seed: int, index: int, n_ticks: int
) -> None:
    """Crash-child mode (parent sets KMAMIZ_WAL=1 + the WAL dir): merge
    all kill-9 windows but the last, WAL-append the last, SIGKILL before
    its merge — the exact crash point ingest_raw_window's
    append-before-merge ordering exists for. Never returns."""
    from kmamiz_tpu.server.processor import DataProcessor

    spec = build_scenario(archetype, seed, index, n_ticks)
    windows = kill9_windows(spec)
    dp = DataProcessor(trace_source=lambda *a: [], use_device_stats=False)
    for raw in windows[:-1]:
        dp.ingest_raw_window(raw)
    dp._wal_append(windows[-1])
    os.kill(os.getpid(), signal.SIGKILL)


def _run_kill9_child(spec: ScenarioSpec, wal_dir: str) -> dict:
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    base_seed = (spec.seed - spec.index) // SEED_STRIDE
    child_env = {
        **os.environ,
        "KMAMIZ_WAL": "1",
        "KMAMIZ_WAL_DIR": wal_dir,
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
    }
    child_env.pop("KMAMIZ_INGEST_MAX_BYTES", None)
    child = subprocess.run(
        [
            sys.executable,
            "-m",
            "kmamiz_tpu.scenarios.runner",
            "--child-kill",
            "--archetype",
            spec.archetype,
            "--seed",
            str(base_seed),
            "--index",
            str(spec.index),
            "--ticks",
            str(spec.n_ticks),
        ],
        env=child_env,
        cwd=repo_root,
        capture_output=True,
        timeout=SCENARIO_MAX_WALL_S,
    )
    return {
        "child_sigkilled": child.returncode == -signal.SIGKILL,
        "returncode": child.returncode,
        "stderr_tail": child.stderr.decode(errors="replace")[-400:],
    }


# -- the closed loop ---------------------------------------------------------


def run_scenario(
    spec: ScenarioSpec, tmpdir: Optional[str] = None, verbose: bool = False
) -> dict:
    """Run one scenario against a real server; return its scorecard."""
    from kmamiz_tpu import native

    if not native.available():
        raise RuntimeError("scenario runner requires the native extension")
    with contextlib.ExitStack() as stack:
        if tmpdir is None:
            tmpdir = stack.enter_context(
                tempfile.TemporaryDirectory(prefix="kmamiz-scn-")
            )
        has_poison = spec.has_event("poison-storm")
        has_kill9 = spec.has_event("kill9-replay")
        has_growth = spec.has_event("capacity-growth")
        env: Dict[str, Optional[str]] = {
            "KMAMIZ_TICK_DEADLINE_MS": "0",
            "KMAMIZ_QUARANTINE_DIR": os.path.join(tmpdir, "quarantine"),
            "KMAMIZ_INGEST_MAX_BYTES": str(POISON_SIZE_CAP)
            if has_poison
            else None,
            "KMAMIZ_WAL": "1" if has_kill9 else "0",
            "KMAMIZ_WAL_DIR": os.path.join(tmpdir, "wal"),
            # growth storylines run the cost plane in sync-prewarm mode:
            # the driver drains predictive prewarms between ticks, so
            # the mid-tick compile gate measures the crossing alone
            "KMAMIZ_COST": "1" if has_growth else None,
            "KMAMIZ_COST_PREWARM": "sync" if has_growth else None,
            # the streaming archetype runs every tick through the
            # graftstream micro-tick engine so the soak exercises the
            # freshness SLO and its stale-serve degraded mode; every
            # other archetype pins the serial parity reference
            "KMAMIZ_STREAM": (
                "1" if spec.archetype == "streaming-freshness" else "0"
            ),
            # epoch length 1: the tick-stall storyline flips the
            # deadline env mid-stream and expects it live on the very
            # next micro-tick (the soak exercises the epoch boundary,
            # not the steady cache)
            "KMAMIZ_STREAM_EPOCH_TICKS": (
                "1" if spec.archetype == "streaming-freshness" else None
            ),
        }
        stack.enter_context(scoped_env(env))
        # lock-witness (KMAMIZ_LOCK_WITNESS=1): every lock the scenario
        # constructs from here on records real acquisition orders; the
        # fleet soak cross-checks them against the static graftrace model
        from kmamiz_tpu.analysis.concurrency import witness

        if witness.enabled():
            stack.enter_context(witness.armed())
        _reset_shared_state()
        if spec.archetype == "fleet-migration":
            # archetype 10 runs the graftfleet harness: a 4-worker ring
            # behind one coordinator, with the live WAL-handoff
            # migration fired mid-soak (fleet/soak.py)
            from kmamiz_tpu.fleet.soak import run_fleet_scenario

            card = run_fleet_scenario(spec, tmpdir, verbose)
        elif spec.archetype == "wal-replay":
            # archetype 11 replays a recorded WAL window through the
            # factory harness, gated bit-exact against a reference
            # built from the same records (soak/walreplay.py)
            from kmamiz_tpu.soak.walreplay import run_wal_replay_scenario

            card = run_wal_replay_scenario(spec, tmpdir, verbose)
        else:
            card = _run_scenario_inner(spec, tmpdir, verbose)
    with _RUNS_LOCK:
        _RUNS.append(card)
    return card


def _reset_shared_state() -> None:
    """Per-scenario isolation: fresh breaker budgets, a fresh quarantine
    binding (the default instance caches its directory at first use), a
    fresh tenant arena, a fresh graftpilot controller, a fresh graftcost
    plane."""
    from kmamiz_tpu import control, cost, fleet, tenancy
    from kmamiz_tpu.resilience import breaker, quarantine
    from kmamiz_tpu.server import stream as stream_mod
    from kmamiz_tpu.telemetry import freshness

    breaker.reset_for_tests()
    quarantine.reset_for_tests()
    tenancy.reset_for_tests()
    control.reset_for_tests()
    cost.reset_for_tests()
    stream_mod.reset_for_tests()
    freshness.reset_for_tests()
    fleet.reset_for_tests()


def _run_scenario_inner(spec: ScenarioSpec, tmpdir: str, verbose: bool) -> dict:
    from kmamiz_tpu.core import programs
    from kmamiz_tpu.resilience.chaos import graph_signature
    from kmamiz_tpu.scenarios.factory import spec_signature
    from kmamiz_tpu.server.dp_server import DataProcessorServer, _make_runtime
    from kmamiz_tpu.server.processor import DataProcessor
    from kmamiz_tpu.tenancy.router import TickRouter
    from kmamiz_tpu.telemetry.slo import percentile

    t_start = prof_events.now_ms()
    state: dict = {
        "latencies": [],
        "stale": 0,
        "posts": 0,
        "quarantined": 0,
        "expected_poisons": 0,
        "poison_misses": 0,
        "recoveries": {},
        "recovered_all": True,
        "wal": None,
        "snapshot": None,
        "mid_tick_compiles": 0,
        "pre_caps": {},
        # per-tenant ordered ingest log: ("collect", groups) | ("raw", bytes)
        "expected": {p.tenant: [] for p in spec.tenants},
        "errors": [],
    }

    wal_info = None
    if spec.has_event("kill9-replay"):
        # crash a child mid-ingest BEFORE the server exists; the
        # scenario's own processor then replays the orphaned WAL
        wal_info = _run_kill9_child(spec, os.environ["KMAMIZ_WAL_DIR"])

    sources = {p.tenant: _ScenarioSource(p.tenant) for p in spec.tenants}
    procs = {
        p.tenant: DataProcessor(
            trace_source=sources[p.tenant],
            use_device_stats=False,
            tenant=p.tenant,
        )
        for p in spec.tenants
    }

    if wal_info is not None:
        plan0 = spec.tenants[0]
        replay = procs[plan0.tenant].replay_wal()
        windows = kill9_windows(spec)
        wal_info["replayed"] = replay["replayed"]
        wal_info["windows"] = len(windows)
        wal_info["ok"] = (
            wal_info["child_sigkilled"]
            and replay["replayed"] == len(windows)
        )
        for raw in windows:
            state["expected"][plan0.tenant].append(("raw", raw))
    state["wal"] = wal_info

    def factory(tenant: str):
        return _make_runtime(tenant, procs[tenant])

    router = TickRouter(factory)
    server = DataProcessorServer(
        procs[spec.tenants[0].tenant], host="127.0.0.1", port=0, router=router
    )
    server.start()
    try:
        steps = _drive(spec, state, server.port, sources, procs)

        def driver():
            next(steps)

        def reader():
            # concurrent read pressure on the same server: health +
            # the /timings observability surface
            for path in ("/", "/timings"):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{server.port}{path}"
                )
                with urllib.request.urlopen(req, timeout=60) as resp:
                    resp.read()
            time.sleep(0.02)

        run_soak_workers = _soak_harness()
        errors, wall = run_soak_workers(
            (driver, reader), seconds=SCENARIO_MAX_WALL_S
        )
        # the driver signals completion by exhausting its generator
        real_errors = [e for e in errors if "StopIteration" not in e]
        if real_errors:
            state["errors"].extend(real_errors)
        steady_recompiles = (
            sum(programs.new_compiles_since(state["snapshot"]).values())
            if state["snapshot"] is not None
            else -1
        )
        live_sigs = {
            p.tenant: graph_signature(procs[p.tenant].graph)
            for p in spec.tenants
        }
        end_caps = {
            p.tenant: int(procs[p.tenant].graph.capacity)
            for p in spec.tenants
        }
        lost_spans, missing = _lost_spans(spec, state, procs)
    finally:
        server.stop()

    from kmamiz_tpu.telemetry import freshness as tel_freshness

    ref_sigs = _reference_signatures(spec, state)
    fresh = tel_freshness.snapshot()
    streaming = spec.archetype == "streaming-freshness"
    lat = sorted(state["latencies"])
    recovery_ms = max(state["recoveries"].values(), default=0.0)
    degrading = spec.has_event("upstream-flap") or spec.has_event("tick-stall")
    stale_rate = state["stale"] / max(1, state["posts"])

    has_growth = spec.has_event("capacity-growth")
    growth_tenants = [
        p.tenant
        for p in spec.tenants
        if any(ev.kind == "capacity-growth" for ev in p.events)
    ]
    gates = {
        "no_errors": not state["errors"],
        "bit_exact": all(
            live_sigs[t] == ref_sigs[t] for t in live_sigs
        ),
        "zero_lost_spans": lost_spans == 0,
        # growth storylines cross a capacity bucket mid-soak by design:
        # the recompile gate becomes "no compile inside any measured
        # tick" — between-tick predictive prewarms are the mechanism,
        # not a violation
        "zero_steady_recompiles": (
            state["mid_tick_compiles"] == 0
            if has_growth
            else steady_recompiles == 0
        ),
        "bucket_crossed": all(
            end_caps[t] > state["pre_caps"].get(t, 1 << 62)
            for t in growth_tenants
        )
        if has_growth
        else True,
        "stale_bounded": (
            (state["stale"] >= 1 and stale_rate <= 0.6)
            if degrading
            else state["stale"] == 0
        ),
        "quarantine_exact": (
            state["quarantined"] == state["expected_poisons"]
            and state["poison_misses"] == 0
            and (state["expected_poisons"] > 0 or not spec.has_event("poison-storm"))
        ),
        "recovered_to_fresh": state["recovered_all"],
        "wal_replayed": state["wal"]["ok"] if state["wal"] else True,
        # freshness SLO (graftstream): the streaming soak must either
        # hold the 250 ms arrival->visible p99 or demonstrably take the
        # degraded mode (stale serve) under its mid-stream stall — a
        # silent freshness collapse with fresh 200s is the failure this
        # gate exists to catch. Non-streaming archetypes pass through.
        "freshness_slo": (
            fresh["samples"] > 0
            and (
                fresh["freshness_ms_p99"] < FRESHNESS_SLO_MS
                or state["stale"] >= 1
            )
        )
        if streaming
        else True,
    }
    card = {
        "name": spec.name,
        "archetype": spec.archetype,
        "spec_signature": spec_signature(spec),
        "n_ticks": spec.n_ticks,
        "tenants": [p.tenant for p in spec.tenants],
        "posts": state["posts"],
        "stale_serves": state["stale"],
        "stale_rate": round(stale_rate, 4),
        "p50_tick_ms": round(percentile(lat, 0.50), 2),
        "p95_tick_ms": round(percentile(lat, 0.95), 2),
        "p99_tick_ms": round(percentile(lat, 0.99), 2),
        "lost_spans": lost_spans,
        "missing_traces": missing[:8],
        "quarantined": state["quarantined"],
        "expected_poisons": state["expected_poisons"],
        "recovery_ms": round(recovery_ms, 1),
        "recoveries": {
            k: round(v, 1) for k, v in state["recoveries"].items()
        },
        "steady_recompiles": steady_recompiles,
        "mid_tick_compiles": state["mid_tick_compiles"],
        "mid_tick_detail": state.get("mid_tick_detail", []),
        "capacity": {
            t: [state["pre_caps"].get(t), end_caps.get(t)]
            for t in (growth_tenants or [])
        },
        "signatures": live_sigs,
        "ref_signatures": ref_sigs,
        "freshness": fresh,
        "wal": state["wal"],
        "errors": state["errors"][:4],
        "gates": gates,
        "pass": all(gates.values()),
        "wall_s": round((prof_events.now_ms() - t_start) / 1000, 1),
    }
    if has_growth:
        from kmamiz_tpu import cost

        card["cost"] = cost.snapshot()
    if not card["pass"]:
        # gate failure = reproducible SLO breach under a seeded storyline:
        # freeze the graftprof flight box (force bypasses KMAMIZ_PROF=0
        # and the debounce — a failed scenario always leaves evidence)
        from kmamiz_tpu.telemetry.profiling import recorder

        failed = sorted(g for g, ok in gates.items() if not ok)
        base_seed = (spec.seed - spec.index) // SEED_STRIDE
        card["flight_artifact"] = recorder.record(
            f"scenario-{spec.name}",
            ",".join(failed),
            force=True,
            # per-cell evidence namespace: under a sweep, this cell's
            # retention/debounce never evicts another cell's box
            namespace=f"{spec.archetype}-{base_seed}",
        )
    if verbose:
        print(
            f"{spec.name}: pass={card['pass']} gates={gates}",
            file=sys.stderr,
        )
    return card


def _drive(
    spec: ScenarioSpec,
    state: dict,
    port: int,
    sources: Dict[str, _ScenarioSource],
    procs: Dict[str, object],
) -> Iterator[None]:
    """The storyline as a step generator (one tick-unit of work per
    ``next()``), run as a soak-harness worker alongside the readers.
    Exhaustion (StopIteration) is the completion signal."""
    from kmamiz_tpu.core import programs

    # terminal-shape warmup: every path under every version map the
    # storyline will ever serve, per tenant — capacity growth and its
    # compiles land here, before the steady-state snapshot
    for plan in spec.tenants:
        topo = plan.topology
        warm: List[List[dict]] = []
        stages = {0: _deploy_version_fn(plan, -1)}
        for ev in plan.events:
            if ev.kind == "rolling-deploy":
                for t in range(ev.at_tick, ev.at_tick + ev.duration):
                    stages[len(stages)] = _deploy_version_fn(plan, t)
        for s_i, version_of in stages.items():
            for p_i in range(len(topo.paths)):
                warm.append(
                    trace_group(
                        topo,
                        f"{spec.name}-warm{s_i}",
                        0,
                        p_i,
                        version_of=version_of,
                    )
                )
        sources[plan.tenant].push(warm)
        state["expected"][plan.tenant].append(("collect", warm))
        status, body, _ms = _post_tick(
            port, plan.tenant, f"{spec.name}-warm-{plan.tenant}"
        )
        if status != 200 or body.get("stale"):
            state["errors"].append(f"warmup failed for {plan.tenant}")
        yield

        # window-shape rehearsal: the merge programs bucket on the
        # incoming window's span shape, so replay each distinct tick
        # window (same group structure, warm-prefixed trace ids) once —
        # after this, steady-state ticks hit only compiled buckets
        rehearsed = set()
        for t in range(spec.n_ticks):
            view = _tick_view(plan, t)
            if view["flap"]:
                continue
            groups = tick_groups(
                topo,
                f"{spec.name}-wr{t}",
                t,
                plan.traffic[t],
                drop_services=frozenset(view["drop"]),
                error_services=frozenset(view["error"]),
                version_of=_deploy_version_fn(plan, t),
                latency_boost_us=view["latency_us"],
            )
            for ev in view["growth"]:
                # shape twins: the ramp tick's group-length multiset on
                # one repeated edge — compiles the window bucket here,
                # leaving the capacity ramp itself to the measured soak
                groups = groups + growth_twin_groups(
                    ev, topo, f"{spec.name}-wr{t}", t
                )
            shape_key = tuple(sorted(len(g) for g in groups))
            if not groups or shape_key in rehearsed:
                continue
            rehearsed.add(shape_key)
            sources[plan.tenant].push(groups)
            state["expected"][plan.tenant].append(("collect", groups))
            status, body, _ms = _post_tick(
                port, plan.tenant, f"{spec.name}-wr{t}-{plan.tenant}"
            )
            if status != 200 or body.get("stale"):
                state["errors"].append(
                    f"rehearsal {t} failed for {plan.tenant}"
                )
            yield

    # edge merges apply lazily; force every deferred fit to land (and
    # compile) NOW, so the snapshot below truly marks steady state —
    # otherwise a reader thread finalizing a rehearsal window's pending
    # merge after the snapshot counts as a phantom steady-state compile
    for plan in spec.tenants:
        _ = procs[plan.tenant].graph.capacity
    track_growth = spec.has_event("capacity-growth")
    if track_growth:
        # the ridge-fit program has one fixed padded shape — compile it
        # now so mid-soak retrains (fold hook, prewarm refresh) re-run
        # a warm program instead of compiling inside the gate window
        from kmamiz_tpu import cost

        try:
            cost.refresh()
        except Exception as e:  # noqa: BLE001
            state["errors"].append(f"cost refresh failed: {e!r}")
    state["pre_caps"] = {
        p.tenant: int(procs[p.tenant].graph.capacity) for p in spec.tenants
    }
    state["snapshot"] = programs.snapshot()
    degraded_prev = {p.tenant: False for p in spec.tenants}

    for tick in range(spec.n_ticks):
        for plan in spec.tenants:
            src = sources[plan.tenant]
            view = _tick_view(plan, tick)
            uid = f"{spec.name}-t{tick}-{plan.tenant}"

            def finish_tick(plan=plan):
                """Growth accounting at the tick edge: finalize this
                tick's deferred merges (so a consolidation's compiles —
                if any — land inside the measured window, not under a
                later tick), diff the program registry, then drain any
                armed predictive prewarms BETWEEN ticks (sync mode)."""
                if not track_growth:
                    return
                from kmamiz_tpu import cost
                from kmamiz_tpu.core import programs as _programs

                pre = state.pop("_tick_snap", None)
                if pre is None:
                    return
                _ = procs[plan.tenant].graph.capacity
                grew = {
                    k: v
                    for k, v in _programs.new_compiles_since(pre).items()
                    if v
                }
                if grew:
                    state["mid_tick_compiles"] += sum(grew.values())
                    state.setdefault("mid_tick_detail", []).append(
                        {"tick": tick, **grew}
                    )
                try:
                    cost.run_pending_prewarms()
                except Exception as e:  # noqa: BLE001
                    state["errors"].append(f"prewarm drain failed: {e!r}")

            if track_growth:
                state["_tick_snap"] = programs.snapshot()

            # poison storms ride the raw-ingest path; every delivery
            # must divert to the tenant's quarantine, touching nothing
            for ev in view["poisons"]:
                clean = json.dumps(
                    [trace_group(plan.topology, f"{spec.name}-poison", tick, 0)]
                ).encode()
                for _kind, payload in poison_payloads_for(
                    ev, plan.topology, tick, clean
                ):
                    state["expected_poisons"] += 1
                    summary = _post_ingest(port, plan.tenant, payload)
                    got = summary.get("quarantined", 0)
                    state["quarantined"] += got
                    if got != 1 or summary.get("spans", 0) != 0:
                        state["poison_misses"] += 1

            if view["flap"]:
                # upstream hard-fails: the tenant's breaker trips and
                # the server degrades to its last-good graph
                src.fail = True
                status, body, _ms = _post_tick(port, plan.tenant, uid)
                src.fail = False
                state["posts"] += 1
                if status == 200 and body.get("stale"):
                    state["stale"] += 1
                else:
                    state["errors"].append(
                        f"flap tick {tick} ({plan.tenant}): "
                        f"expected stale, got {status}"
                    )
                degraded_prev[plan.tenant] = True
                finish_tick()
                yield
                continue

            groups = tick_groups(
                plan.topology,
                spec.name,
                tick,
                plan.traffic[tick],
                drop_services=frozenset(view["drop"]),
                error_services=frozenset(view["error"]),
                version_of=_deploy_version_fn(plan, tick),
                latency_boost_us=view["latency_us"],
            )
            for ev in view["growth"]:
                # the measured capacity ramp: per_tick brand-new
                # /grow/<k> endpoints ride the ordinary collect path
                groups = groups + growth_groups(
                    ev, plan.topology, spec.name, tick
                )

            if view["stall"]:
                # the source hangs past the watchdog deadline: stale
                # serve now, the straggler merges the groups late
                src.push(groups)
                state["expected"][plan.tenant].append(("collect", groups))
                src.stall_s = STALL_SLEEP_S
                with scoped_env(
                    {"KMAMIZ_TICK_DEADLINE_MS": str(STALL_DEADLINE_MS)}
                ):
                    status, body, _ms = _post_tick(port, plan.tenant, uid)
                src.stall_s = 0.0
                state["posts"] += 1
                if status == 200 and body.get("stale"):
                    state["stale"] += 1
                else:
                    state["errors"].append(
                        f"stall tick {tick} ({plan.tenant}): "
                        f"expected stale, got {status}"
                    )
                # straggler drain: its late merge must land before the
                # next tick posts (keeps the ingest order deterministic
                # and the in-flight-overlap detector quiet)
                time.sleep(STALL_SLEEP_S + 0.5)
                degraded_prev[plan.tenant] = True
                finish_tick()
                yield
                continue

            if degraded_prev[plan.tenant]:
                # first tick after a degraded window: measure
                # recovery-to-fresh (breaker cooldown + half-open probe)
                src.push(groups)
                state["expected"][plan.tenant].append(("collect", groups))
                t0 = prof_events.now_ms()
                fresh = False
                for _attempt in range(RECOVERY_ATTEMPTS):
                    status, body, ms = _post_tick(port, plan.tenant, uid)
                    state["posts"] += 1
                    if status == 200 and not body.get("stale"):
                        fresh = True
                        break
                    state["stale"] += 1
                    time.sleep(RECOVERY_SLEEP_S)
                recovery_ms = prof_events.now_ms() - t0
                state["recoveries"][f"{plan.tenant}@t{tick}"] = recovery_ms
                if not fresh:
                    state["recovered_all"] = False
                    state["errors"].append(
                        f"no recovery to fresh by tick {tick} ({plan.tenant})"
                    )
                degraded_prev[plan.tenant] = False
                finish_tick()
                yield
                continue

            src.push(groups)
            state["expected"][plan.tenant].append(("collect", groups))
            status, body, ms = _post_tick(port, plan.tenant, uid)
            state["posts"] += 1
            if status != 200:
                state["errors"].append(f"tick {tick} ({plan.tenant}): {status}")
            elif body.get("stale"):
                state["stale"] += 1
                state["errors"].append(
                    f"unexpected stale at tick {tick} ({plan.tenant})"
                )
            else:
                state["latencies"].append(ms)
            finish_tick()
            yield


def _lost_spans(
    spec: ScenarioSpec, state: dict, procs
) -> Tuple[int, List[str]]:
    """Every trace id the runner handed the live system must be in the
    tenant's dedup registry; a missing trace's spans are lost spans."""
    lost = 0
    missing: List[str] = []
    for plan in spec.tenants:
        expected_groups: List[List[dict]] = []
        for kind, payload in state["expected"][plan.tenant]:
            if kind == "raw":
                expected_groups.extend(json.loads(payload))
            else:
                expected_groups.extend(payload)
        dp = procs[plan.tenant]
        with dp._dedup_lock:
            processed = set(dp._processed)
        for group in expected_groups:
            tid = group[0]["traceId"]
            if tid not in processed:
                lost += len(group)
                missing.append(f"{plan.tenant}:{tid}")
    return lost, missing


def _reference_signatures(spec: ScenarioSpec, state: dict) -> Dict[str, str]:
    """Rebuild each tenant's graph from the recorded ingest log on a
    fresh processor, replicating the live paths (collect windows through
    collect, raw windows through raw ingest) in the live order — the
    bit-exactness oracle for the scorecard."""
    from kmamiz_tpu.resilience.chaos import graph_signature
    from kmamiz_tpu.server.processor import DataProcessor

    sigs: Dict[str, str] = {}
    with scoped_env(
        {"KMAMIZ_INGEST_MAX_BYTES": None, "KMAMIZ_WAL": "0"}
    ):
        for plan in spec.tenants:
            pending: List[List[List[dict]]] = []

            def source(_lb, _t, _lim, _pending=pending):
                return _pending.pop(0) if _pending else []

            ref = DataProcessor(trace_source=source, use_device_stats=False)
            for i, (kind, payload) in enumerate(
                state["expected"][plan.tenant]
            ):
                if kind == "raw":
                    ref.ingest_raw_window(payload)
                else:
                    pending.append(payload)
                    ref.collect(
                        {
                            "uniqueId": f"ref-{plan.tenant}-{i}",
                            "lookBack": 30_000,
                            "time": int(prof_events.wall_ms()),
                        }
                    )
            sigs[plan.tenant] = graph_signature(ref.graph)
    return sigs


# -- graftpilot counterfactual (docs/CONTROL.md#counterfactual) --------------

#: span-content SLO for the counterfactual runs: between the baseline
#: window p99 (~1.3 ms: 1_000 + hop*37 µs spans) and the smallest
#: cascade boost (multiplier 2 -> +10 ms), so OFF always violates on
#: cascade ticks and never elsewhere
CF_SLO_MS = 5.0

#: the "all clear" forecast published outside the cascade window
CF_CLEAR_P99_MS = 1.2


def _window_p99_ms(groups: List[List[dict]]) -> float:
    """Span-content p99 of one tick window, in ms (span ``duration`` is
    µs). Pure arithmetic over the composed content — the violation
    oracle both counterfactual runs share."""
    from kmamiz_tpu.telemetry.slo import percentile

    durs = sorted(
        span["duration"] / 1000.0 for group in groups for span in group
    )
    return percentile(durs, 0.99)


def _breach_ticks(plan) -> List[int]:
    """Ticks whose storyline view carries a cascade latency boost — the
    ticks an oracle forecast flags, and (with hysteresis 1) exactly the
    ticks the ON run defers."""
    return [
        t
        for t in range(len(plan.traffic))
        if _tick_view(plan, t)["latency_us"] > 0
    ]


def _counterfactual_run(
    spec: ScenarioSpec,
    control_on: bool,
    forecast_p99_ms: float,
    attributions: Tuple,
    tmpdir: str,
) -> dict:
    """One arm of the counterfactual: the cascade storyline against a
    real server, driven serially, with the control plane ON or OFF. The
    ON arm publishes the oracle forecast through the same
    ``ingest_forecast`` entry the fold hook uses, one evaluation before
    each tick; everything else — spec, windows, seeds — is identical."""
    from kmamiz_tpu import control
    from kmamiz_tpu.core import programs
    from kmamiz_tpu.resilience import breaker as breaker_mod
    from kmamiz_tpu.resilience.chaos import graph_signature
    from kmamiz_tpu.server.dp_server import DataProcessorServer, _make_runtime
    from kmamiz_tpu.server.processor import DataProcessor
    from kmamiz_tpu.tenancy.router import TickRouter

    plan = spec.tenants[0]
    topo = plan.topology
    tenant = plan.tenant
    env: Dict[str, Optional[str]] = {
        "KMAMIZ_TICK_DEADLINE_MS": "0",
        "KMAMIZ_QUARANTINE_DIR": os.path.join(tmpdir, "quarantine"),
        "KMAMIZ_INGEST_MAX_BYTES": None,
        "KMAMIZ_WAL": "0",
        "KMAMIZ_CONTROL": "1" if control_on else "0",
        "KMAMIZ_CONTROL_SLO_MS": str(CF_SLO_MS),
        "KMAMIZ_CONTROL_MODE": "defer",
        # hysteresis 1: the oracle forecast is noise-free, so admission
        # must track the cascade window edge-exactly
        "KMAMIZ_CONTROL_HYSTERESIS": "1",
        "KMAMIZ_CONTROL_WARMUP_GATE": "0.5",
        "KMAMIZ_CONTROL_PROBE_S": "0.05",
    }
    breach = set(_breach_ticks(plan))
    run = {
        "control": control_on,
        "posts": 0,
        "violations": 0,
        "deferred": 0,
        "shed": 0,
        "stale": 0,
        "errors": [],
    }
    state: dict = {"expected": {tenant: []}}
    with contextlib.ExitStack() as stack:
        stack.enter_context(scoped_env(env))
        _reset_shared_state()
        source = _ScenarioSource(tenant)
        procs = {
            tenant: DataProcessor(
                trace_source=source, use_device_stats=False, tenant=tenant
            )
        }
        router = TickRouter(lambda t: _make_runtime(t, procs[t]))
        server = DataProcessorServer(
            procs[tenant], host="127.0.0.1", port=0, router=router
        )
        server.start()
        try:
            # terminal-shape warmup + window-shape rehearsal (the same
            # compile discipline the scenario loop uses)
            version_of = _deploy_version_fn(plan, -1)
            warm = [
                trace_group(topo, f"{spec.name}-cfwarm", 0, p_i)
                for p_i in range(len(topo.paths))
            ]
            source.push(warm)
            state["expected"][tenant].append(("collect", warm))
            status, body, _ms = _post_tick(
                server.port, tenant, f"{spec.name}-cfwarm"
            )
            if status != 200 or body.get("stale"):
                run["errors"].append("counterfactual warmup failed")

            def tick_window(t: int, name: str) -> List[List[dict]]:
                view = _tick_view(plan, t)
                return tick_groups(
                    topo,
                    name,
                    t,
                    plan.traffic[t],
                    drop_services=frozenset(view["drop"]),
                    error_services=frozenset(view["error"]),
                    version_of=version_of,
                    latency_boost_us=view["latency_us"],
                )

            rehearsed = set()
            for t in range(spec.n_ticks):
                groups = tick_window(t, f"{spec.name}-cfwr{t}")
                shape_key = tuple(sorted(len(g) for g in groups))
                if not groups or shape_key in rehearsed:
                    continue
                rehearsed.add(shape_key)
                source.push(groups)
                state["expected"][tenant].append(("collect", groups))
                status, body, _ms = _post_tick(
                    server.port, tenant, f"{spec.name}-cfwr{t}"
                )
                if status != 200 or body.get("stale"):
                    run["errors"].append(f"counterfactual rehearsal {t} failed")

            if control_on and breach:
                # the ON arm's deferred windows all drain in ONE collect
                # at the first clear tick — rehearse that combined window
                # shape too, or the drain would compile in steady state
                drain_tick = max(breach) + 1
                combined: List[List[dict]] = []
                for t in [*sorted(breach), drain_tick]:
                    if t < spec.n_ticks:
                        combined.extend(
                            tick_window(t, f"{spec.name}-cfdrain{t}")
                        )
                if combined:
                    source.push(combined)
                    state["expected"][tenant].append(("collect", combined))
                    status, body, _ms = _post_tick(
                        server.port, tenant, f"{spec.name}-cfdrain"
                    )
                    if status != 200 or body.get("stale"):
                        run["errors"].append(
                            "counterfactual drain rehearsal failed"
                        )

            _ = procs[tenant].graph.capacity
            snapshot = programs.snapshot()

            for t in range(spec.n_ticks):
                if control_on:
                    # the oracle forecast, through the same entry the
                    # processor's fold hook uses
                    if t in breach:
                        control.ingest_forecast(
                            control.ForecastView(
                                tenant=tenant,
                                p99_ms=forecast_p99_ms,
                                cost_ms=forecast_p99_ms * plan.traffic[t],
                                attributions=tuple(attributions),
                            )
                        )
                    else:
                        control.ingest_forecast(
                            control.ForecastView(
                                tenant=tenant,
                                p99_ms=CF_CLEAR_P99_MS,
                                cost_ms=CF_CLEAR_P99_MS * plan.traffic[t],
                            )
                        )
                groups = tick_window(t, spec.name)
                source.push(groups)
                state["expected"][tenant].append(("collect", groups))
                status, body, _ms = _post_tick(
                    server.port, tenant, f"{spec.name}-cf{t}"
                )
                run["posts"] += 1
                if status == 429:
                    run["shed"] += 1
                elif status != 200:
                    run["errors"].append(f"cf tick {t}: {status}")
                elif body.get("deferred"):
                    run["deferred"] += 1
                elif body.get("stale"):
                    run["stale"] += 1
                    run["errors"].append(f"cf tick {t}: unexpected stale")
                elif _window_p99_ms(groups) > CF_SLO_MS:
                    # fresh serve whose own window content breaches the
                    # SLO — the violation the controller exists to defer
                    run["violations"] += 1

            run["steady_recompiles"] = sum(
                programs.new_compiles_since(snapshot).values()
            )
            run["signature"] = graph_signature(procs[tenant].graph)
            lost, missing = _lost_spans(spec, state, procs)
            run["lost_spans"] = lost
            run["missing_traces"] = missing[:8]
            brk = breaker_mod.breakers_for(tenant).get("scenario-upstream")
            brk_snap = brk.snapshot() if brk is not None else {}
            run["breaker_warm_ups"] = int(brk_snap.get("warmUps", 0))
            run["breaker_warmed_at_end"] = bool(brk_snap.get("warmed", False))
            run["control_snapshot"] = control.snapshot()
        finally:
            server.stop()
        run["ref_signature"] = _reference_signatures(spec, state)[tenant]
    return run


def run_counterfactual(
    seed: int = 0,
    index: int = 1,
    n_ticks: int = 10,
    verbose: bool = False,
) -> dict:
    """The graftpilot validation gate: one seeded cascade storyline run
    twice — control plane OFF then ON — with an oracle forecast derived
    from the composed cascade event. Identical spec, identical windows;
    the only difference is whether anyone acts on the forecast. The
    scorecard gates ``slo_violations_prevented >= 1`` with zero lost
    spans, bit-exact reference signatures, and zero steady-state
    recompiles in both arms."""
    from kmamiz_tpu import control, native
    from kmamiz_tpu.scenarios.factory import spec_signature
    from kmamiz_tpu.scenarios.storyline import cascade_forecast

    if not native.available():
        raise RuntimeError("counterfactual runner requires the native extension")
    t_start = time.time()
    spec = build_scenario("cascade-fanout", seed, index, n_ticks)
    plan = spec.tenants[0]
    cascade = next(
        (ev for ev in plan.events if ev.kind == "cascade"), None
    )
    if cascade is None:
        raise RuntimeError(
            "cascade storyline disabled (KMAMIZ_SCENARIO_STORYLINES)"
        )
    forecast_p99_ms, attributions = cascade_forecast(cascade, plan.topology)

    arms = {}
    for label, control_on in (("off", False), ("on", True)):
        with tempfile.TemporaryDirectory(prefix="kmamiz-cf-") as tmp:
            arms[label] = _counterfactual_run(
                spec, control_on, forecast_p99_ms, attributions, tmp
            )
    off, on = arms["off"], arms["on"]

    prevented = off["violations"] - on["violations"]
    control.PREVENTED_VIOLATIONS.set(float(max(0, prevented)))
    gates = {
        "off_violations_present": off["violations"] >= 1,
        "violations_prevented": prevented >= 1,
        "zero_lost_spans": off["lost_spans"] == 0 and on["lost_spans"] == 0,
        "bit_exact": (
            off["signature"] == off["ref_signature"]
            and on["signature"] == on["ref_signature"]
        ),
        "zero_steady_recompiles": (
            off["steady_recompiles"] == 0 and on["steady_recompiles"] == 0
        ),
        "breaker_warmed_and_reverted": (
            on["breaker_warm_ups"] >= 1 and not on["breaker_warmed_at_end"]
        ),
        "no_errors": not off["errors"] and not on["errors"],
    }
    card = {
        "name": f"counterfactual-{spec.name}",
        "archetype": spec.archetype,
        "spec_signature": spec_signature(spec),
        "n_ticks": spec.n_ticks,
        "slo_ms": CF_SLO_MS,
        "forecast_p99_ms": round(forecast_p99_ms, 3),
        "cascade_ticks": _breach_ticks(plan),
        "off": off,
        "on": on,
        "slo_violations_prevented": prevented,
        "gates": gates,
        "pass": all(gates.values()),
        "wall_s": round(time.time() - t_start, 1),
    }
    if verbose:
        print(
            f"{card['name']}: pass={card['pass']} "
            f"prevented={prevented} gates={gates}",
            file=sys.stderr,
        )
    return card


def crashed_card(
    spec: Optional[ScenarioSpec],
    exc: BaseException,
    archetype: Optional[str] = None,
    wall_s: float = 0.0,
) -> dict:
    """A failed scorecard for a scenario that threw instead of scoring:
    gate ``crashed`` False, exception text captured, every headline key
    the table/bench readers expect present. ``spec`` may be None when
    compose itself crashed (pass ``archetype`` so triage can bucket)."""
    import traceback

    from kmamiz_tpu.scenarios.factory import spec_signature
    from kmamiz_tpu.telemetry.profiling import recorder

    name = spec.name if spec is not None else f"{archetype or 'unknown'}-?"
    arch = spec.archetype if spec is not None else (archetype or "unknown")
    base_seed = (
        (spec.seed - spec.index) // SEED_STRIDE if spec is not None else 0
    )
    card = {
        "name": name,
        "archetype": arch,
        "spec_signature": spec_signature(spec) if spec is not None else None,
        "n_ticks": spec.n_ticks if spec is not None else 0,
        "tenants": [p.tenant for p in spec.tenants] if spec is not None else [],
        "posts": 0,
        "stale_serves": 0,
        "stale_rate": 0.0,
        "p50_tick_ms": 0.0,
        "p95_tick_ms": 0.0,
        "p99_tick_ms": 0.0,
        "lost_spans": 0,
        "missing_traces": [],
        "quarantined": 0,
        "expected_poisons": 0,
        "recovery_ms": 0.0,
        "recoveries": {},
        "steady_recompiles": 0,
        "mid_tick_compiles": 0,
        "mid_tick_detail": [],
        "capacity": {},
        "signatures": {},
        "ref_signatures": {},
        "freshness": {},
        "wal": None,
        "errors": [f"{type(exc).__name__}: {exc}"],
        "crash": traceback.format_exception_only(type(exc), exc)[-1].strip(),
        "traceback": traceback.format_exc()[-2000:],
        "gates": {"crashed": False},
        "pass": False,
        "wall_s": round(wall_s, 1),
    }
    card["flight_artifact"] = recorder.record(
        f"scenario-{name}",
        f"crashed: {card['crash']}",
        force=True,
        namespace=f"{arch}-{base_seed}",
    )
    return card


def run_matrix(
    specs, verbose: bool = False
) -> List[dict]:
    """Run every scenario, each inside its own temp sandbox. A scenario
    that throws during its run becomes a ``crashed``-gate failed card —
    one bad cell never aborts the rest of the matrix."""
    results = []
    for spec in specs:
        t0 = time.time()
        with tempfile.TemporaryDirectory(prefix="kmamiz-scn-") as tmp:
            try:
                card = run_scenario(spec, tmpdir=tmp, verbose=verbose)
            except Exception as exc:  # noqa: BLE001 - contained into the scorecard
                card = crashed_card(spec, exc, wall_s=time.time() - t0)
                with _RUNS_LOCK:
                    _RUNS.append(card)
                if verbose:
                    print(
                        f"{spec.name}: CRASHED {card['crash']}",
                        file=sys.stderr,
                    )
        results.append(card)
    return results


def main() -> int:
    """Internal CLI: the kill-9 crash-child entry point (the public
    driver is tools/scenario_soak.py)."""
    import argparse

    parser = argparse.ArgumentParser(description="scenario runner internals")
    parser.add_argument("--child-kill", action="store_true")
    parser.add_argument("--archetype", default="kill9-wal-replay")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--index", type=int, default=0)
    parser.add_argument("--ticks", type=int, default=10)
    args = parser.parse_args()
    if args.child_kill:
        run_child_kill(args.archetype, args.seed, args.index, args.ticks)
        return 1  # unreachable
    parser.error("nothing to do (this entry point only serves --child-kill)")
    return 2


if __name__ == "__main__":
    sys.exit(main())
