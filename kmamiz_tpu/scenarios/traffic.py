"""Seeded traffic-curve sampler: traces-per-tick schedules.

Four curve families map onto production load shapes:

- ``steady``  — flat baseline;
- ``diurnal`` — the MicroViSim daily profile: a daily request total
  split over 24 hourly slots with ±20% random weights by
  ``simulator/load_handler.distribute_daily_request_count`` (the same
  splitter the load simulator uses), compressed onto the tick axis;
- ``burst``   — flat baseline with sampled multi-tick spikes;
- ``ramp``    — linear climb from a low to a high rate.

A curve is a plain ``tuple[int, ...]`` of trace counts, one per tick,
fully determined at compose time — the runner never draws randomness.
Counts are clamped to ``MAX_TRACES_PER_TICK`` so a sampled spike cannot
blow the closed-loop soak's wall-clock budget.
"""
from __future__ import annotations

import random
from typing import Tuple

import numpy as np

from kmamiz_tpu.simulator.load_handler import (
    TIME_SLOTS_PER_DAY,
    distribute_daily_request_count,
)

TRAFFIC_KINDS = ("steady", "diurnal", "burst", "ramp")

MAX_TRACES_PER_TICK = 12


def sample_traffic(
    kind: str, n_ticks: int, rng: random.Random
) -> Tuple[int, ...]:
    """Draw one traces-per-tick schedule of the requested family."""
    if kind not in TRAFFIC_KINDS:
        raise ValueError(f"unknown traffic kind: {kind!r}")
    if kind == "steady":
        base = rng.randint(3, 5)
        curve = [base] * n_ticks
    elif kind == "diurnal":
        # the simulator's own daily splitter, seeded from this curve's
        # stream; tick t reads hourly slot t * 24 // n_ticks
        np_rng = np.random.default_rng(rng.getrandbits(63))
        total = rng.randint(60, 120)
        slots = distribute_daily_request_count(
            total, TIME_SLOTS_PER_DAY, np_rng
        )
        scale = max(1.0, float(slots.max()) / (MAX_TRACES_PER_TICK - 2))
        curve = [
            1 + int(round(float(slots[t * TIME_SLOTS_PER_DAY // n_ticks]) / scale))
            for t in range(n_ticks)
        ]
    elif kind == "burst":
        base = rng.randint(2, 4)
        curve = [base] * n_ticks
        for _ in range(max(1, n_ticks // 5)):
            at = rng.randrange(n_ticks)
            factor = rng.randint(3, 5)
            for j in range(2):
                if at + j < n_ticks:
                    curve[at + j] = base * factor
    else:  # ramp
        low = rng.randint(1, 2)
        high = rng.randint(7, 10)
        span = max(1, n_ticks - 1)
        curve = [
            low + round((high - low) * t / span) for t in range(n_ticks)
        ]
    return tuple(min(MAX_TRACES_PER_TICK, max(1, c)) for c in curve)
