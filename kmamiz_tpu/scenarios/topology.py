"""Seeded mesh-topology sampler for the scenario factory.

Samples one of four production call-graph shapes — deep call chains,
fan-out hubs, cyclic retry loops, and dense random meshes — as a
:class:`Topology`: an immutable set of services, versions, and concrete
call *paths* (sequences of service hops) that every tick's trace groups
walk. Everything derives from the ``random.Random`` the factory hands
in, so the same seed samples the same mesh bit-for-bit.

The canonical serialized form of a sampled topology is the MicroViSim
simulation-config YAML rendered by
``simulator/config_generator.generate_sim_config_from_static_data`` —
the sampler builds the same plain-JSON cache shapes (EndpointDataType /
ReplicaCounts / EndpointDependencies rows) a live system would snapshot,
and the YAML's sha256 is the topology component of the scenario
signature (tests pin that two runs of one seed agree byte-for-byte).

Span emission is pure arithmetic over (tick, trace-index): no RNG is
consumed at run time, so the closed-loop runner's retries and recovery
probes can regenerate a tick's exact content any number of times (the
dedup map makes re-submission idempotent, which is what keeps the
post-soak ``graph_signature`` deterministic under real-clock jitter).
"""
from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from kmamiz_tpu.simulator import naming
from kmamiz_tpu.simulator.config_generator import (
    generate_sim_config_from_static_data,
)

TOPOLOGY_KINDS = ("chain", "fanout", "cycle", "mesh")

#: spans of one trace all land inside this base microsecond epoch; each
#: (tick, trace, hop) offsets deterministically from it
BASE_TIMESTAMP_US = 1_700_000_000_000_000


@dataclass(frozen=True)
class Topology:
    """One sampled service mesh.

    ``paths`` are concrete call chains as tuples of service indices —
    a service may repeat inside one path (cyclic retries). Trace ``i``
    of tick ``t`` walks ``paths[(t * 7 + i) % len(paths)]``.
    """

    kind: str
    namespace: str
    services: Tuple[str, ...]
    replicas: Tuple[int, ...]
    urls_per_service: int
    paths: Tuple[Tuple[int, ...], ...]
    versions: Tuple[str, ...] = ("v1",)

    def path_for(self, tick: int, trace: int) -> Tuple[int, ...]:
        return self.paths[(tick * 7 + trace) % len(self.paths)]


def sample_topology(kind: str, rng: random.Random, namespace: str) -> Topology:
    """Draw one topology of the requested kind from ``rng``."""
    if kind not in TOPOLOGY_KINDS:
        raise ValueError(f"unknown topology kind: {kind!r}")
    if kind == "chain":
        n = rng.randint(6, 9)
        # one full-depth chain plus shallower prefixes: deep call chains
        # with realistic partial traversals
        full = tuple(range(n))
        paths = [full]
        for _ in range(rng.randint(2, 4)):
            depth = rng.randint(3, n)
            paths.append(full[:depth])
    elif kind == "fanout":
        leaves = rng.randint(6, 10)
        n = leaves + 1  # service 0 is the hub
        # each trace fans the hub out to a contiguous leaf band; the
        # union covers every leaf so the mesh shape is a star
        paths = []
        for start in range(1, leaves + 1):
            width = rng.randint(2, 4)
            band = [(start + j - 1) % leaves + 1 for j in range(width)]
            paths.append((0, *band))
    elif kind == "cycle":
        n = rng.randint(4, 6)
        # retry loops: A -> B -> A -> B(..) style revisits
        paths = []
        for a in range(n):
            b = (a + 1) % n
            revisits = rng.randint(1, 2)
            loop: List[int] = [a]
            for _ in range(revisits):
                loop.extend((b, a))
            paths.append(tuple(loop))
    else:  # mesh
        n = rng.randint(8, 12)
        paths = []
        for _ in range(rng.randint(8, 14)):
            length = rng.randint(3, 6)
            walk = [rng.randrange(n)]
            while len(walk) < length:
                step = rng.randrange(n)
                if step != walk[-1]:
                    walk.append(step)
            paths.append(tuple(walk))
    services = tuple(f"{kind[:4]}{i}" for i in range(n))
    replicas = tuple(rng.randint(1, 3) for _ in range(n))
    return Topology(
        kind=kind,
        namespace=namespace,
        services=services,
        replicas=replicas,
        urls_per_service=rng.randint(1, 2),
        paths=tuple(dict.fromkeys(paths)),  # dedup, order-preserving
    )


# -- canonical form (simulator/config_generator.py) --------------------------


def _endpoint_rows(topo: Topology, version: str) -> List[dict]:
    rows = []
    for svc in topo.services:
        for u in range(topo.urls_per_service):
            uep = naming.generate_unique_endpoint_name(
                svc, topo.namespace, version, "GET", f"/api/{u}"
            )
            rows.append(
                {
                    "uniqueEndpointName": uep,
                    "namespace": topo.namespace,
                    "service": svc,
                    "version": version,
                    "method": "GET",
                    "schemas": [
                        {
                            "status": "200",
                            "requestContentType": "",
                            "responseContentType": "",
                        }
                    ],
                }
            )
    return rows


def sim_config_yaml(topo: Topology) -> str:
    """The topology rendered as the editable MicroViSim sim-config YAML
    (SimConfigGenerator shapes) — the canonical, hashable serialization."""
    data_types: List[dict] = []
    replica_counts: List[dict] = []
    for version in topo.versions:
        data_types.extend(_endpoint_rows(topo, version))
        for svc_i, svc in enumerate(topo.services):
            replica_counts.append(
                {
                    "uniqueServiceName": naming.generate_unique_service_name(
                        svc, topo.namespace, version
                    ),
                    "namespace": topo.namespace,
                    "version": version,
                    "replicas": topo.replicas[svc_i],
                }
            )
    deps: Dict[str, List[dict]] = {}
    version = topo.versions[0]
    for path in topo.paths:
        for a, b in zip(path, path[1:]):
            ep_a = naming.generate_unique_endpoint_name(
                topo.services[a], topo.namespace, version, "GET", "/api/0"
            )
            ep_b = naming.generate_unique_endpoint_name(
                topo.services[b], topo.namespace, version, "GET", "/api/0"
            )
            bucket = deps.setdefault(ep_a, [])
            if not any(
                d["endpoint"]["uniqueEndpointName"] == ep_b for d in bucket
            ):
                bucket.append(
                    {"endpoint": {"uniqueEndpointName": ep_b}, "distance": 1}
                )
    endpoint_dependencies = [
        {
            "endpoint": {"uniqueEndpointName": ep},
            "dependingOn": depend_on,
            "isDependedByExternal": True,
        }
        for ep, depend_on in deps.items()
    ]
    return generate_sim_config_from_static_data(
        data_types, replica_counts, endpoint_dependencies
    )


def topology_digest(topo: Topology) -> str:
    """sha256 of the canonical sim-config YAML plus the path table (the
    YAML carries the distance-1 mesh; paths add the walk ordering)."""
    digest = hashlib.sha256(sim_config_yaml(topo).encode("utf-8"))
    digest.update(repr(topo.paths).encode("ascii"))
    return digest.hexdigest()


# -- span emission (pure, no runtime RNG) ------------------------------------


def entry_services(topo: Topology) -> Tuple[str, ...]:
    return tuple(sorted({topo.services[p[0]] for p in topo.paths}))


def downstream_of(topo: Topology, service: str) -> FrozenSet[str]:
    """Services that appear strictly after ``service`` in any path —
    the blast radius of a cascading failure rooted there."""
    out = set()
    for path in topo.paths:
        names = [topo.services[i] for i in path]
        if service in names:
            out.update(names[names.index(service) + 1 :])
    out.discard(service)
    return frozenset(out)


def _span(
    topo: Topology,
    trace_id: str,
    span_id: str,
    parent_id: Optional[str],
    svc: str,
    version: str,
    url_index: int,
    status: str,
    ts_us: int,
    duration_us: int,
) -> dict:
    host = f"{svc}.{topo.namespace}.svc.cluster.local"
    return {
        "traceId": trace_id,
        "id": span_id,
        "parentId": parent_id,
        "kind": "SERVER",
        "name": f"{host}:80/*",
        "timestamp": ts_us,
        "duration": duration_us,
        "tags": {
            "http.method": "GET",
            "http.status_code": status,
            "http.url": f"http://{host}/api/{url_index}",
            "istio.canonical_revision": version,
            "istio.canonical_service": svc,
            "istio.mesh_id": "cluster.local",
            "istio.namespace": topo.namespace,
        },
    }


def trace_group(
    topo: Topology,
    prefix: str,
    tick: int,
    trace: int,
    error_services: FrozenSet[str] = frozenset(),
    version_of: Optional[Callable[[str], str]] = None,
    latency_boost_us: int = 0,
) -> List[dict]:
    """One trace walking ``path_for(tick, trace)``. Status codes are a
    deterministic function of (tick, trace, hop): a small baseline error
    rate everywhere, 503 on every hop at a service in
    ``error_services`` (the cascade/outage storylines)."""
    path = topo.path_for(tick, trace)
    trace_id = f"{prefix}-t{tick}-{trace}"
    group: List[dict] = []
    parent: Optional[str] = None
    for hop, svc_i in enumerate(path):
        svc = topo.services[svc_i]
        version = version_of(svc) if version_of is not None else "v1"
        if svc in error_services:
            status = "503"
        else:
            status = "503" if (tick * 31 + trace * 7 + hop) % 41 == 0 else "200"
        span_id = f"{trace_id}-{hop}"
        group.append(
            _span(
                topo,
                trace_id,
                span_id,
                parent,
                svc,
                version,
                (tick + trace + hop) % topo.urls_per_service,
                status,
                BASE_TIMESTAMP_US + tick * 1_000 + trace * 10 + hop,
                1_000 + hop * 37 + latency_boost_us,
            )
        )
        parent = span_id
    return group


def tick_groups(
    topo: Topology,
    prefix: str,
    tick: int,
    count: int,
    drop_services: FrozenSet[str] = frozenset(),
    error_services: FrozenSet[str] = frozenset(),
    version_of: Optional[Callable[[str], str]] = None,
    latency_boost_us: int = 0,
) -> List[List[dict]]:
    """All trace groups of one tick. Traces whose path crosses a service
    in ``drop_services`` are never emitted (a partial-mesh outage: the
    dead service's sidecar reports nothing), which keeps the merged
    content a pure function of (tick schedule, storyline)."""
    groups = []
    for trace in range(count):
        path = topo.path_for(tick, trace)
        if any(topo.services[i] in drop_services for i in path):
            continue
        groups.append(
            trace_group(
                topo,
                prefix,
                tick,
                trace,
                error_services=error_services,
                version_of=version_of,
                latency_boost_us=latency_boost_us,
            )
        )
    return groups


def warmup_groups(
    topo: Topology,
    prefix: str,
    deployed_versions: Tuple[str, ...] = ("v1",),
) -> List[List[dict]]:
    """The scenario's terminal shape as one warmup window: every path
    under every revision the storyline will ever deploy. Ingesting it
    before the measured phase moves capacity growth — and its one
    legitimate compile — into warmup, which is what makes the
    steady-state zero-recompile gate honest (the PR-3 shape-hint
    prewarm discipline applied to scenarios)."""
    groups = []
    for v_i, version in enumerate(deployed_versions):
        for p_i in range(len(topo.paths)):
            groups.append(
                trace_group(
                    topo,
                    f"{prefix}-warm{v_i}",
                    0,
                    p_i,
                    version_of=lambda _svc, _v=version: _v,
                )
            )
    return groups
