"""Failure storylines: seeded, time-ordered fault events over a soak.

A storyline is a tuple of :class:`Event` records, each active over a
``[at_tick, at_tick + duration)`` window. The vocabulary:

- ``cascade``        — an upstream failure storm: the root service's
  endpoints take an error-rate fault injected through
  ``simulator/faults.inject_faults`` (MicroViSim fault descriptors over
  hourly slots mapped onto ticks) while the induced traffic burst is
  folded through ``simulator/overload.estimate_error_rate_with_overload``
  to decide which *downstream* services saturate and start erroring too
  — the modeled failure cascading through the mesh;
- ``partial-outage`` — a sampled subset of services goes dark: paths
  crossing them emit nothing for the window;
- ``rolling-deploy`` — one service per tick flips ``v1 -> v2`` starting
  at the event tick (canonical-revision change in live windows);
- ``poison-storm``   — poisoned raw-ingest payloads per tick, kinds
  pre-drawn from ``resilience/chaos.FaultPlan``'s payload stream
  (truncate / corrupt / schema / bomb), every delivery expected to land
  in the quarantine;
- ``upstream-flap``  — the tenant's trace source hard-fails for the
  window; the per-tenant circuit breaker trips, ticks degrade to stale
  serves, and recovery-to-fresh is measured after the flap ends;
- ``tick-stall``     — one tick's source hangs past the watchdog
  deadline (stale serve, straggler merges late, recovery measured);
- ``kill9-replay``   — the run crashes (SIGKILL between WAL append and
  merge) at the event tick and restarts, replaying the ingest WAL
  bit-exact before the soak continues;
- ``capacity-growth`` — one tenant's endpoint count ramps linearly
  across its edge store's segment-consolidation threshold mid-soak
  (unique ``/grow/<k>`` endpoints per tick), exercising graftcost's
  predictive prewarm: the gate demands zero mid-tick compiles at the
  crossing with prewarm on;
- ``tenant-migration`` — the fleet coordinator live-migrates the tenant
  to another worker at the event tick (drain -> WAL handoff -> replay
  -> ring flip, fleet/migration.py) while its traffic keeps flowing;
  the gates demand zero lost spans, a bit-exact post-migration
  ``graph_signature`` vs a serial reference replay, and zero
  steady-state recompiles across the handoff.

Events are fully resolved at compose time (all RNG draws happen here),
so a storyline replays identically however the runner's wall clock
behaves. ``KMAMIZ_SCENARIO_STORYLINES`` (comma list, default ``all``)
filters the vocabulary; disabled kinds are dropped from composed
storylines and from the scenario signature alike.
"""
from __future__ import annotations

import os
import random
from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from kmamiz_tpu.resilience.chaos import FaultPlan, mutate_payload
from kmamiz_tpu.scenarios.topology import (
    BASE_TIMESTAMP_US,
    Topology,
    downstream_of,
    entry_services,
)
from kmamiz_tpu.simulator import faults as sim_faults
from kmamiz_tpu.simulator import naming
from kmamiz_tpu.simulator.overload import estimate_error_rate_with_overload
from kmamiz_tpu.simulator.slot_metrics import SlotMetrics, slot_key

STORYLINE_KINDS = (
    "cascade",
    "partial-outage",
    "rolling-deploy",
    "poison-storm",
    "upstream-flap",
    "tick-stall",
    "kill9-replay",
    "capacity-growth",
    "tenant-migration",
)

#: downstream services whose overload-modeled error rate crosses this
#: during a cascade window are treated as erroring too
CASCADE_ERROR_THRESHOLD = 0.30


@dataclass(frozen=True)
class Event:
    """One storyline event; ``params`` is a hashable kind-specific
    payload (service tuples, poison kinds, multipliers)."""

    kind: str
    at_tick: int
    duration: int
    params: Tuple = ()

    def active(self, tick: int) -> bool:
        return self.at_tick <= tick < self.at_tick + self.duration

    def key(self) -> str:
        return f"{self.kind}@{self.at_tick}+{self.duration}:{self.params!r}"


def enabled_storylines() -> Tuple[str, ...]:
    """The storyline vocabulary after the env toggle
    (``KMAMIZ_SCENARIO_STORYLINES``: comma list or ``all``)."""
    raw = os.environ.get("KMAMIZ_SCENARIO_STORYLINES", "all").strip()
    if raw in ("", "all"):
        return STORYLINE_KINDS
    wanted = {p.strip() for p in raw.split(",") if p.strip()}
    return tuple(k for k in STORYLINE_KINDS if k in wanted)


# -- cascade (simulator/faults.py + overload.py) ------------------------------


def compose_cascade(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    """Model an upstream failure cascading through the mesh with the
    simulator's own machinery: a MicroViSim ``increase-error-rate`` +
    ``inject-traffic`` fault pair on the root's endpoints (hourly slots
    = ticks), injected via ``faults.inject_faults``, then the burst
    folded through the overload error model to pick which downstream
    services saturate."""
    roots = [s for s in entry_services(topo) if downstream_of(topo, s)]
    root = rng.choice(roots or list(topo.services))
    at = rng.randint(1, max(1, n_ticks // 3))
    duration = rng.randint(2, max(2, n_ticks // 3))
    duration = min(duration, max(1, n_ticks - at - 2))
    multiplier = rng.randint(2, 4)

    root_ep = naming.generate_unique_endpoint_name(
        root, topo.namespace, "v1", "GET", "/api/0"
    )
    base_rps = 40.0 * multiplier
    fault_descriptors = [
        {
            "type": "increase-error-rate",
            "increaseErrorRatePercent": 75,
            "targets": {"endpoints": [{"uniqueEndpointName": root_ep}]},
            "timePeriods": [
                {
                    "startTime": {"day": 1, "hour": at},
                    "durationHours": duration,
                    "probabilityPercent": 100,
                }
            ],
        },
        {
            "type": "inject-traffic",
            "requestMultiplier": float(multiplier),
            "targets": {"endpoints": [{"uniqueEndpointName": root_ep}]},
            "timePeriods": [
                {
                    "startTime": {"day": 1, "hour": at},
                    "durationHours": duration,
                    "probabilityPercent": 100,
                }
            ],
        },
    ]
    metrics_per_slot: Dict[str, SlotMetrics] = {
        slot_key(0, h): SlotMetrics() for h in range(24)
    }
    for metrics in metrics_per_slot.values():
        metrics.entry_request_counts[root_ep] = base_rps * 3600.0 / multiplier
        metrics.endpoint_error_rate[root_ep] = 0.01
    sim_faults.inject_faults(
        {"faultInjection": fault_descriptors},
        metrics_per_slot,
        np.random.default_rng(rng.getrandbits(63)),
    )
    storm = metrics_per_slot[slot_key(0, at % 24)]
    root_error = storm.get_error_rate(root_ep)

    # the burst's RPS lands on every downstream service; saturation per
    # the overload model decides who joins the error storm
    affected = [root]
    for svc in sorted(downstream_of(topo, root)):
        svc_i = topo.services.index(svc)
        rate = estimate_error_rate_with_overload(
            request_count_per_second=storm.get_entry_request_count(root_ep)
            / 3600.0,
            replica_count=topo.replicas[svc_i],
            replica_max_rps=25.0,
            base_error_rate=0.01,
            overload_factor_k=1.5,
        )
        if rate >= CASCADE_ERROR_THRESHOLD:
            affected.append(svc)
    return Event(
        kind="cascade",
        at_tick=at,
        duration=duration,
        params=(tuple(affected), multiplier, round(root_error, 3)),
    )


def cascade_forecast(
    event: Event, topo: Topology
) -> Tuple[float, Tuple[Tuple[str, str, float], ...]]:
    """The forecast an oracle STLGT would publish ahead of a composed
    cascade: (p99_ms, attribution edges) as pure functions of the event
    params. The p99 mirrors the span arithmetic the cascade injects
    (``topology.trace_group`` boosts span durations by ``5_000 *
    multiplier`` µs), and the attributions blame the edges along the
    affected-service chain — exactly what the neighbor-bias gates learn
    from the storm. The counterfactual harness feeds this to the
    controller, so ON/OFF runs differ only in whether anyone acts."""
    if event.kind != "cascade":
        raise ValueError(f"not a cascade event: {event.kind!r}")
    affected, multiplier, _root_error = event.params
    p99_ms = (1_000 + 5_000 * multiplier) / 1000.0
    edges = [
        (affected[i], affected[i + 1], 0.95)
        for i in range(len(affected) - 1)
    ]
    if not edges:
        # single-service storm: blame the root's first downstream edge
        root = affected[0]
        down = sorted(downstream_of(topo, root))
        edges = [(root, down[0] if down else root, 0.95)]
    return p99_ms, tuple(edges)


# -- the other storyline families --------------------------------------------


def compose_partial_outage(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    # dark services are non-entry hops so some traffic always survives
    entries = set(entry_services(topo))
    candidates = [s for s in topo.services if s not in entries]
    if not candidates:
        candidates = list(topo.services[1:]) or list(topo.services)
    k = min(len(candidates), rng.randint(1, 2))
    down = tuple(sorted(rng.sample(candidates, k)))
    at = rng.randint(1, max(1, n_ticks // 2))
    duration = min(rng.randint(2, 3), max(1, n_ticks - at - 1))
    return Event("partial-outage", at, duration, params=(down,))


def compose_rolling_deploy(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    k = min(len(topo.services), rng.randint(2, 4))
    order = tuple(rng.sample(list(topo.services), k))
    at = rng.randint(1, max(1, n_ticks // 2))
    return Event("rolling-deploy", at, n_ticks - at, params=(order,))


def compose_poison_storm(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    """Pre-draw the storm's poison kinds from a FaultPlan payload stream
    (weights exclude ``none``/``drop`` so every delivery must land in
    the quarantine with a reason code)."""
    plan = FaultPlan(
        rng.getrandbits(31),
        payload_weights={
            "truncate": 0.25,
            "corrupt": 0.25,
            "schema": 0.25,
            "bomb": 0.25,
        },
    )
    at = rng.randint(1, max(1, n_ticks // 2))
    duration = min(rng.randint(2, 4), max(1, n_ticks - at))
    per_tick = rng.randint(1, 2)
    kinds = tuple(plan.payload_faults(duration * per_tick))
    return Event(
        "poison-storm",
        at,
        duration,
        params=(per_tick, kinds, plan.seed),
    )


def compose_upstream_flap(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    at = rng.randint(1, max(1, n_ticks // 2))
    duration = min(rng.randint(3, 5), max(2, n_ticks - at - 2))
    return Event("upstream-flap", at, duration)


def compose_tick_stall(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    at = rng.randint(1, max(1, n_ticks - 2))
    return Event("tick-stall", at, 1)


def compose_kill9(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    at = rng.randint(2, max(2, n_ticks // 2))
    return Event("kill9-replay", at, 1)


def compose_tenant_migration(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    """Fire the live migration strictly mid-soak: at least two warm
    ticks land on the source first (so the handoff ships a non-trivial
    WAL) and at least two more run on the target afterward (so the
    post-flip steady state is measured, recompiles included)."""
    at = rng.randint(2, max(2, n_ticks - 3))
    return Event("tenant-migration", at, 1)


# -- capacity growth (graftcost predictive-prewarm gate) ----------------------

#: unique growth endpoints over the ramp — enough to push the default
#: 1024-main + 256-tail edge store past its consolidation threshold
#: (1280) from a small base mesh, with headroom
GROWTH_TOTAL_ENDPOINTS = 1500


def compose_capacity_growth(
    topo: Topology, rng: random.Random, n_ticks: int
) -> Event:
    """Ramp one tenant across a capacity-bucket boundary mid-soak: every
    ramp tick emits ``per_tick`` 2-span traces entry -> ``/grow/<k>``
    with monotonically increasing ``k`` — each unique k is one new
    endpoint and one new edge. The ramp ends two ticks before the soak
    does, so the post-crossing steady state is measured too."""
    entry = entry_services(topo)[0]
    others = [s for s in topo.services if s != entry]
    grow_svc = rng.choice(others or [entry])
    at = 1
    duration = max(2, n_ticks - 3)
    per_tick = -(-GROWTH_TOTAL_ENDPOINTS // duration)
    return Event(
        "capacity-growth", at, duration, params=(entry, grow_svc, per_tick)
    )


def _growth_span(
    topo: Topology,
    trace_id: str,
    span_id: str,
    parent_id,
    svc: str,
    url_path: str,
    ts_us: int,
) -> dict:
    """topology._span with an explicit URL path — growth endpoints live
    outside the ``/api/<u>`` grid the sampler enumerates."""
    host = f"{svc}.{topo.namespace}.svc.cluster.local"
    return {
        "traceId": trace_id,
        "id": span_id,
        "parentId": parent_id,
        "kind": "SERVER",
        "name": f"{host}:80/*",
        "timestamp": ts_us,
        "duration": 1_000,
        "tags": {
            "http.method": "GET",
            "http.status_code": "200",
            "http.url": f"http://{host}{url_path}",
            "istio.canonical_revision": "v1",
            "istio.canonical_service": svc,
            "istio.mesh_id": "cluster.local",
            "istio.namespace": topo.namespace,
        },
    }


def _growth_pair(
    event: Event, topo: Topology, trace_id: str, url_path: str, ts_us: int
) -> List[dict]:
    entry, grow_svc, _per_tick = event.params
    root = _growth_span(
        topo, trace_id, f"{trace_id}-0", None, entry, "/api/0", ts_us
    )
    leaf = _growth_span(
        topo, trace_id, f"{trace_id}-1", f"{trace_id}-0", grow_svc, url_path,
        ts_us + 1,
    )
    return [root, leaf]


def growth_groups(
    event: Event, topo: Topology, prefix: str, tick: int
) -> List[List[dict]]:
    """The ramp's trace groups at ``tick``: ``per_tick`` 2-span chains
    entry ``/api/0`` -> grow-svc ``/grow/<k>``, ``k`` strictly
    increasing across the ramp. Pure (tick, index) arithmetic — no
    runtime RNG, so recovery re-posts are idempotent like every other
    scenario window."""
    if event.kind != "capacity-growth" or not event.active(tick):
        return []
    _entry, _grow_svc, per_tick = event.params
    base = (tick - event.at_tick) * per_tick
    ts0 = BASE_TIMESTAMP_US + tick * 1_000_000
    return [
        _growth_pair(
            event,
            topo,
            f"{prefix}-g{base + j}",
            f"/grow/{base + j}",
            ts0 + j * 10,
        )
        for j in range(per_tick)
    ]


def growth_twin_groups(
    event: Event, topo: Topology, prefix: str, tick: int
) -> List[List[dict]]:
    """Shape twins for the window rehearsal: the same group-length
    multiset AND the same count of brand-new edges as
    :func:`growth_groups` — the merge kernels bucket on the window's
    new-unique-edge count, not just span shape, so the twins must mint
    ``per_tick`` fresh ``/warm/<tick>-<j>`` endpoints of their own.
    That spends a few hundred capacity rows pre-snapshot (far under the
    consolidation threshold), leaving the measured soak to perform the
    actual crossing against fully compiled buckets."""
    if event.kind != "capacity-growth" or not event.active(tick):
        return []
    _entry, _grow_svc, per_tick = event.params
    ts0 = BASE_TIMESTAMP_US + tick * 1_000_000 + 500_000
    return [
        _growth_pair(
            event,
            topo,
            f"{prefix}-gt{tick}-{j}",
            f"/warm/{tick}-{j}",
            ts0 + j * 10,
        )
        for j in range(per_tick)
    ]


_COMPOSERS = {
    "cascade": compose_cascade,
    "partial-outage": compose_partial_outage,
    "rolling-deploy": compose_rolling_deploy,
    "poison-storm": compose_poison_storm,
    "upstream-flap": compose_upstream_flap,
    "tick-stall": compose_tick_stall,
    "kill9-replay": compose_kill9,
    "capacity-growth": compose_capacity_growth,
    "tenant-migration": compose_tenant_migration,
}


def compose_storyline(
    kinds: Tuple[str, ...],
    topo: Topology,
    rng: random.Random,
    n_ticks: int,
) -> Tuple[Event, ...]:
    """Compose one event per requested kind (env-disabled kinds are
    skipped), sorted by start tick. Every kind consumes its RNG draws
    from a dedicated child stream, so toggling one storyline off never
    reshuffles another's schedule (the FaultPlan two-stream rule)."""
    enabled = set(enabled_storylines())
    events: List[Event] = []
    for kind in kinds:
        if kind not in _COMPOSERS:
            raise ValueError(f"unknown storyline kind: {kind!r}")
        child = random.Random(rng.getrandbits(63))
        if kind not in enabled:
            continue
        events.append(_COMPOSERS[kind](topo, child, n_ticks))
    return tuple(sorted(events, key=lambda e: (e.at_tick, e.kind)))


def poison_payloads_for(
    event: Event, topo: Topology, tick: int, clean_window: bytes
) -> List[Tuple[str, bytes]]:
    """The (kind, poisoned bytes) deliveries of a poison-storm event at
    ``tick``: the pre-drawn kinds applied to a clean window via
    ``chaos.mutate_payload`` under a per-delivery seeded RNG (content is
    a pure function of the event params + tick). Every kind is certainly
    fatal to the parse (``mutate_payload`` guarantees it), so the
    scorecard can require quarantined == delivered exactly."""
    if event.kind != "poison-storm" or not event.active(tick):
        return []
    per_tick, kinds, seed = event.params
    offset = (tick - event.at_tick) * per_tick
    out: List[Tuple[str, bytes]] = []
    for j in range(per_tick):
        kind = kinds[(offset + j) % len(kinds)]
        rng = random.Random((seed << 8) ^ (tick * 131 + j))
        mutated = mutate_payload(clean_window, kind, rng)
        if mutated is not None:
            out.append((kind, mutated))
    return out
