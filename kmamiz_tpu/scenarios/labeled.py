"""Labeled-window export: scenario replay as supervised model data.

The scenario factory composes everything at seed time and span content
is pure arithmetic over (tick, trace, hop) — so a scenario's per-tick
endpoint windows, model features, dependency edges AND the ground truth
(which services the storyline injected faults into, and how hard) can
all be exported WITHOUT running a server. This is the data contract
`tools/eval_stlgt.py` scores against: quantile coverage needs the true
next-window latency per endpoint, attribution hit-rate needs the
injected fault set per tick, and both come straight from the composed
storyline rather than from heuristics over the emitted spans.

One window per tick, every window in the SAME endpoint id space (the
full topology × deployed-versions endpoint set, enumerated up front the
way the interner would converge to after warmup), with:

- ``features``  — the [N, 10] assemble_features layout (the exact
  train/serve column contract, hour_of_day = tick % 24);
- ``latency_ms`` / ``err5_share`` / ``active`` — per-endpoint outcomes;
- ``truth_services`` — services under injected error this tick
  (cascade storm membership: the root plus overload-modeled
  downstream), the attribution target;
- ``latency_boost_us`` — the storyline's injected latency inflation.

Edges are the union of parent->child span pairs over all windows, in
CSR (src, dst, mask) form, matching the live forecast snapshot shape.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from kmamiz_tpu.scenarios.factory import ScenarioSpec
from kmamiz_tpu.scenarios.topology import tick_groups
from kmamiz_tpu.simulator import naming


def _endpoint_name(topo, svc: str, version: str, url_index: int) -> str:
    return naming.generate_unique_endpoint_name(
        svc, topo.namespace, version, "GET", f"/api/{url_index}"
    )


def labeled_windows(spec: ScenarioSpec, tenant_index: int = 0) -> dict:
    """Deterministic labeled replay of one tenant's scenario windows.

    Returns {"names", "src", "dst", "mask", "windows"} where windows is
    a list of per-tick dicts (see module docstring). Same spec -> same
    bytes; the storyline view logic is imported from the runner so the
    export can never skew from what a live soak would ingest."""
    # the runner owns storyline -> per-tick semantics; reusing its view
    # builders keeps this export and the live soak on one source of truth
    from kmamiz_tpu.scenarios.runner import _deploy_version_fn, _tick_view

    plan = spec.tenants[tenant_index]
    topo = plan.topology

    # fixed id space: every (service, version, url) endpoint the
    # storyline can ever emit, enumerated in deterministic order
    names: List[str] = []
    ids: Dict[str, int] = {}
    for version in topo.versions:
        for svc in topo.services:
            for u in range(topo.urls_per_service):
                name = _endpoint_name(topo, svc, version, u)
                if name not in ids:
                    ids[name] = len(names)
                    names.append(name)
    n = len(names)
    svc_of = np.zeros(n, dtype=np.int64)
    for version in topo.versions:
        for svc_i, svc in enumerate(topo.services):
            for u in range(topo.urls_per_service):
                svc_of[ids[_endpoint_name(topo, svc, version, u)]] = svc_i
    replicas = np.asarray(
        [topo.replicas[svc_of[i]] for i in range(n)], dtype=np.float32
    )

    edge_set = set()
    windows = []
    for tick in range(spec.n_ticks):
        view = _tick_view(plan, tick)
        version_of = _deploy_version_fn(plan, tick)
        groups = tick_groups(
            topo,
            spec.name,
            tick,
            plan.traffic[tick],
            drop_services=frozenset(view["drop"]),
            error_services=frozenset(view["error"]),
            version_of=version_of,
            latency_boost_us=view["latency_us"],
        )
        count = np.zeros(n, dtype=np.float64)
        err5 = np.zeros(n, dtype=np.float64)
        lat_sum = np.zeros(n, dtype=np.float64)
        lat_sq = np.zeros(n, dtype=np.float64)
        for group in groups:
            prev_id = None
            for span in group:
                tags = span["tags"]
                svc = tags["istio.canonical_service"]
                url_index = int(tags["http.url"].rsplit("/", 1)[1])
                ep = ids[
                    _endpoint_name(
                        topo, svc, tags["istio.canonical_revision"], url_index
                    )
                ]
                count[ep] += 1
                if tags["http.status_code"] == "503":
                    err5[ep] += 1
                ms = span["duration"] / 1000.0
                lat_sum[ep] += ms
                lat_sq[ep] += ms * ms
                if prev_id is not None and prev_id != ep:
                    edge_set.add((prev_id, ep))
                prev_id = ep
        safe = np.maximum(count, 1.0)
        lat_mean = lat_sum / safe
        var = np.maximum(lat_sq / safe - lat_mean * lat_mean, 0.0)
        cv = np.where(lat_mean > 0, np.sqrt(var) / np.maximum(lat_mean, 1e-9), 0.0)
        active = count > 0
        from kmamiz_tpu.models.graphsage import assemble_features

        features = np.array(  # fresh copy: rows are zeroed in place below
            assemble_features(
                request_rate=count.astype(np.float32),
                err4_share=np.zeros(n, dtype=np.float32),
                err5_share=(err5 / safe).astype(np.float32),
                log_latency=np.log1p(lat_mean).astype(np.float32),
                latency_cv=cv.astype(np.float32),
                replicas=replicas,
                log_volume=np.log1p(count).astype(np.float32),
                active=active.astype(np.float32),
                hour_of_day=float(tick % 24),
            ),
            dtype=np.float32,
        )
        # padded/inactive rows must be all-zero (the STLGT lane-mask
        # contract): an inactive endpoint still gets the hour columns
        # from assemble_features, so zero the dead rows explicitly
        features[~active] = 0.0
        windows.append(
            {
                "tick": tick,
                "features": features,
                "latency_ms": lat_mean.astype(np.float32),
                "err5_share": (err5 / safe).astype(np.float32),
                "active": active,
                "truth_services": sorted(view["error"]),
                "latency_boost_us": int(view["latency_us"]),
            }
        )

    edges = sorted(edge_set)
    src = np.asarray([e[0] for e in edges], dtype=np.int32)
    dst = np.asarray([e[1] for e in edges], dtype=np.int32)
    mask = np.ones(len(edges), dtype=bool)
    return {
        "names": names,
        "services": list(topo.services),
        "service_of": svc_of,
        "src": src,
        "dst": dst,
        "mask": mask,
        "windows": windows,
    }
