"""The scenario factory: seeded archetype matrix.

A *scenario* is one closed-loop soak specification: per-tenant sampled
topologies (:mod:`.topology`), traffic curves (:mod:`.traffic`), and a
failure storyline (:mod:`.storyline`), all drawn from one integer seed.
The ten archetypes cover the production failure space the resilience,
tenancy, cost, streaming, and fleet layers were built for; a matrix of size N
instantiates the first N archetypes (cycling with fresh seeds past the
vocabulary), and the ordering guarantees any matrix of ≥ 4 contains the
cascade, multi-tenant, and kill-9/WAL-replay scenarios the acceptance
gate requires.

Everything random happens here, at compose time. ``spec_signature``
hashes the complete composed content (topology canonical YAML digests,
traffic schedules, storyline event keys), so two calls with one seed
must agree byte-for-byte — the determinism oracle the tests pin.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
import random
from dataclasses import dataclass
from typing import Optional, Tuple

from kmamiz_tpu.scenarios.storyline import Event, compose_storyline
from kmamiz_tpu.scenarios.topology import (
    Topology,
    sample_topology,
    topology_digest,
)
from kmamiz_tpu.scenarios.traffic import sample_traffic

#: (archetype name, ((tenant, topology kind, traffic kind, storyline kinds), ...))
#: Ordered so the always-on bench matrix (first 3) and the acceptance
#: minimum (first 6) both cover cascade + multi-tenant + kill-9.
ARCHETYPES: Tuple[Tuple[str, Tuple[Tuple[str, str, str, Tuple[str, ...]], ...]], ...] = (
    ("steady-chain", (("default", "chain", "steady", ()),)),
    ("cascade-fanout", (("default", "fanout", "burst", ("cascade",)),)),
    (
        "multi-tenant-mix",
        (
            ("alpha", "fanout", "diurnal", ("upstream-flap",)),
            ("beta", "chain", "steady", ("poison-storm",)),
        ),
    ),
    ("kill9-wal-replay", (("default", "chain", "steady", ("kill9-replay",)),)),
    ("poison-storm-mesh", (("default", "mesh", "diurnal", ("poison-storm",)),)),
    ("outage-cycle", (("default", "cycle", "steady", ("partial-outage",)),)),
    (
        "rolling-deploy-mesh",
        (("default", "mesh", "ramp", ("rolling-deploy", "tick-stall")),),
    ),
    # appended (never reordered): the bench matrix (first 3) and the
    # acceptance minimum (first 6) keep their archetype sets
    (
        "capacity-growth-chain",
        (("default", "chain", "steady", ("capacity-growth",)),),
    ),
    # graftstream soak: a bursty fanout under the micro-tick engine with
    # a mid-stream tick stall, so the matrix exercises the freshness SLO
    # AND its degraded mode (watchdog -> last-good stale serve)
    (
        "streaming-freshness",
        (("default", "fanout", "burst", ("tick-stall",)),),
    ),
    # graftfleet soak (docs/FLEET.md): three tenants spread across a
    # 4-worker ring by consistent hash; alpha live-migrates mid-soak
    # (drain -> WAL handoff -> replay -> ring flip) while beta/gamma
    # traffic keeps flowing on their own workers
    (
        "fleet-migration",
        (
            ("alpha", "fanout", "steady", ("tenant-migration",)),
            ("beta", "chain", "steady", ()),
            ("gamma", "mesh", "steady", ()),
        ),
    ),
    # graftsoak production replay (docs/SCENARIOS.md#wal-replay): a
    # recorded WAL v2 window (KMAMIZ_SOAK_BUNDLE, or a bundle
    # synthesized from this composed topology x traffic) replayed
    # through a live server and gated bit-exact against a reference
    # built from the same records (soak/walreplay.py). No storyline:
    # the recording IS the storyline.
    (
        "wal-replay",
        (("default", "fanout", "burst", ()),),
    ),
)

#: per-scenario child-seed stride (prime, far above any matrix size)
SEED_STRIDE = 1_000_003

DEFAULT_TICKS = 10


@dataclass(frozen=True)
class TenantPlan:
    """One tenant's slice of a scenario: its mesh, its traces-per-tick
    schedule, and the storyline events that hit it."""

    tenant: str
    topology: Topology
    traffic: Tuple[int, ...]
    events: Tuple[Event, ...]


@dataclass(frozen=True)
class ScenarioSpec:
    name: str
    archetype: str
    seed: int
    index: int
    n_ticks: int
    tenants: Tuple[TenantPlan, ...]

    def events(self) -> Tuple[Tuple[str, Event], ...]:
        """All (tenant, event) pairs, storyline order."""
        pairs = [
            (plan.tenant, ev) for plan in self.tenants for ev in plan.events
        ]
        return tuple(sorted(pairs, key=lambda p: (p[1].at_tick, p[1].kind, p[0])))

    def has_event(self, kind: str) -> bool:
        return any(ev.kind == kind for _t, ev in self.events())


def default_seed() -> int:
    return int(os.environ.get("KMAMIZ_SCENARIO_SEED", "0"))


def default_matrix_size() -> int:
    return int(os.environ.get("KMAMIZ_SCENARIO_MATRIX", str(len(ARCHETYPES))))


def default_ticks() -> int:
    return int(os.environ.get("KMAMIZ_SCENARIO_TICKS", str(DEFAULT_TICKS)))


def build_scenario(
    archetype: str, seed: int, index: int, n_ticks: int
) -> ScenarioSpec:
    """Compose one scenario. Each tenant consumes topology / traffic /
    storyline draws from dedicated child streams of the scenario's own
    ``random.Random``, so tenants never perturb each other's content."""
    by_name = dict(ARCHETYPES)
    if archetype not in by_name:
        raise ValueError(f"unknown archetype: {archetype!r}")
    scenario_seed = seed * SEED_STRIDE + index
    rng = random.Random(scenario_seed)
    plans = []
    for tenant, topo_kind, traffic_kind, story_kinds in by_name[archetype]:
        topo_rng = random.Random(rng.getrandbits(63))
        traffic_rng = random.Random(rng.getrandbits(63))
        story_rng = random.Random(rng.getrandbits(63))
        topo = sample_topology(topo_kind, topo_rng, f"scn-{tenant}")
        events = compose_storyline(story_kinds, topo, story_rng, n_ticks)
        if any(ev.kind == "rolling-deploy" for ev in events):
            # the storyline will deploy v2 — warmup must carry it
            topo = dataclasses.replace(topo, versions=("v1", "v2"))
        plans.append(
            TenantPlan(
                tenant=tenant,
                topology=topo,
                traffic=sample_traffic(traffic_kind, n_ticks, traffic_rng),
                events=events,
            )
        )
    return ScenarioSpec(
        name=f"{archetype}-s{seed}i{index}",
        archetype=archetype,
        seed=scenario_seed,
        index=index,
        n_ticks=n_ticks,
        tenants=tuple(plans),
    )


def scenario_matrix(
    seed: Optional[int] = None,
    size: Optional[int] = None,
    n_ticks: Optional[int] = None,
) -> Tuple[ScenarioSpec, ...]:
    """The seeded matrix: archetype ``i % len(ARCHETYPES)`` at index
    ``i``. Defaults come from the ``KMAMIZ_SCENARIO_*`` env knobs."""
    seed = default_seed() if seed is None else seed
    size = default_matrix_size() if size is None else size
    n_ticks = default_ticks() if n_ticks is None else n_ticks
    return tuple(
        build_scenario(ARCHETYPES[i % len(ARCHETYPES)][0], seed, i, n_ticks)
        for i in range(size)
    )


def spec_signature(spec: ScenarioSpec) -> str:
    """sha256 over the complete composed content — topology canonical
    digests, traffic schedules, storyline event keys. Bit-identical
    across processes for one seed, and sensitive to every sampled
    choice (the determinism oracle)."""
    digest = hashlib.sha256()
    digest.update(f"{spec.name}|{spec.n_ticks}".encode("ascii"))
    for plan in spec.tenants:
        digest.update(f"|{plan.tenant}|".encode("ascii"))
        digest.update(topology_digest(plan.topology).encode("ascii"))
        digest.update(repr(plan.traffic).encode("ascii"))
        for ev in plan.events:
            digest.update(ev.key().encode("utf-8"))
    return digest.hexdigest()
