"""graftcost scenario plane: predicted wall cost of one soak cell.

The program-level cost model (:mod:`kmamiz_tpu.cost`) prices compiles
and steps from observed timings; a soak sweep needs the same idea one
level up — "how long will this (archetype, seed) cell take end to
end?" — so the scheduler can launch the longest cells first and the
tail of a thousand-cell sweep never straggles behind one slow
scenario (LPT scheduling; tools/graftsoak.py).

Two-tier estimate, deterministic for one spec:

1. **Feature prior**: a linear model over the composed spec — per-tick
   harness overhead, per-trace span volume, per-tenant server cost,
   and a per-storyline-kind surcharge (a tick stall sleeps through the
   watchdog deadline; a kill-9 replay forks a crash child; recovery
   waits burn real wall time). Weights are calibrated from the seed-0
   matrix, not load-bearing: only the ORDERING matters.
2. **Observed correction**: when the sweep manifest already holds
   finished cells, ``fit_observed`` learns a per-archetype ratio of
   measured wall to the prior (median, robust to one outlier cell) and
   ``predicted_scenario_cost_s`` applies it — the second thousand
   cells are ordered by what the first thousand actually cost.
"""
from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional

#: feature weights (seconds) for the prior — per measured tick, per
#: emitted trace, per tenant (server + reference replay), per scenario
PER_TICK_S = 0.12
PER_TRACE_S = 0.004
PER_TENANT_S = 0.35
BASE_S = 0.6

#: storyline surcharges (seconds per event of the kind): wall the
#: harness demonstrably burns beyond span volume
EVENT_COST_S: Dict[str, float] = {
    "tick-stall": 1.2,       # stall sleep + watchdog deadline window
    "upstream-flap": 0.8,    # breaker cooldown + recovery-to-fresh poll
    "partial-outage": 0.6,   # outage window + recovery poll
    "cascade": 0.4,          # error-injection ticks + added latency
    "poison-storm": 0.3,     # quarantine round-trips
    "rolling-deploy": 0.3,   # v2 warmup + flip ticks
    "capacity-growth": 0.9,  # bucket crossing + sync prewarm drains
    "kill9-replay": 9.0,     # forked crash child pays a full interpreter
    "tenant-migration": 12.0,  # 4-worker fleet ring + WAL handoff
}


def predicted_scenario_cost_s(
    spec, observed: Optional[Mapping[str, float]] = None
) -> float:
    """Deterministic cost estimate (seconds) for one composed scenario
    spec. ``observed`` maps archetype -> correction ratio from
    :func:`fit_observed`; absent archetypes fall back to the prior."""
    cost = BASE_S + PER_TICK_S * spec.n_ticks
    for plan in spec.tenants:
        cost += PER_TENANT_S
        cost += PER_TRACE_S * sum(plan.traffic)
        for ev in plan.events:
            cost += EVENT_COST_S.get(ev.kind, 0.2)
    ratio = (observed or {}).get(spec.archetype)
    if ratio is not None and ratio > 0:
        cost *= ratio
    return round(cost, 4)


def fit_observed(records: Iterable[Mapping]) -> Dict[str, float]:
    """Per-archetype correction ratios from finished cell records
    (each carrying ``archetype``, ``wall_s`` and ``predicted_s``).
    Median of wall/predicted per archetype — one straggler cell (page
    cache miss, CI noise) must not reorder the whole sweep."""
    ratios: Dict[str, list] = {}
    for rec in records:
        try:
            wall = float(rec["wall_s"])
            prior = float(rec["predicted_s"])
        except (KeyError, TypeError, ValueError):
            continue
        if wall <= 0 or prior <= 0:
            continue
        ratios.setdefault(str(rec["archetype"]), []).append(wall / prior)
    out: Dict[str, float] = {}
    for archetype, samples in ratios.items():
        samples.sort()
        out[archetype] = round(samples[len(samples) // 2], 4)
    return out
