"""graftcost — learned program-cost model over the registry (docs/COST_MODEL.md).

The program registry continuously generates a TpuGraphs-shaped dataset
(every compile: argument spec + measured wall; every warm call: run
wall). graftcost trains a small ridge regressor over it (cost/model.py
on cost/features.py) and spends the predictions in three places:

- **predictive prewarm**: the per-tenant growth forecaster
  (tenancy/growth.py, fed by the store's own merge finalizes) projects
  the next segment-consolidation crossing; imminent crossings trigger
  spec transposition (cost/prewarm.py) so the post-crossing shapes are
  warm BEFORE the crossing lands — zero mid-tick compiles at a
  capacity doubling (the ROADMAP item-6 gate);
- **boot prewarm ranking**: ``programs.run_prewarm`` orders the hint
  replay longest-predicted-compile-first, so restart readiness is
  bounded by the big programs, not queued behind trivia;
- **cost-aware tick ordering**: per-tenant predicted run cost (by
  arena capacity bucket) folds into the TickRouter's graftpilot batch
  ordering.

Timing contract (the graftpilot posture): training and prewarm planning
run at fold boundaries / between ticks / on the background thread —
never on the warm tick. The store's merge-finalize hook
(``observe_merge``) is one lock-guarded ring append plus integer
arithmetic; the router read is one dict lookup against a table computed
at refresh time.

Gated off by default: KMAMIZ_COST=1 enables the plane.
KMAMIZ_COST_PREWARM: "1" (default) prewarms on a daemon thread when a
crossing is imminent, "sync" defers execution to an explicit
``run_pending_prewarms()`` call (the deterministic harness mode),
"0" forecasts but never prewarms.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from kmamiz_tpu.cost import features, model, prewarm
from kmamiz_tpu.cost.model import CostModel
from kmamiz_tpu.tenancy import growth
from kmamiz_tpu.telemetry.profiling import events as prof_events
from kmamiz_tpu.telemetry.registry import REGISTRY

logger = logging.getLogger("kmamiz_tpu.cost")

# ---------------------------------------------------------------------------
# metrics: handles preallocated at import (observe_merge is reachable
# from the tick's merge finalize — no per-call label formatting there)
# ---------------------------------------------------------------------------
EXAMPLES = REGISTRY.gauge(
    "kmamiz_cost_examples",
    "Labelled (program, spec) rows behind the last cost-model fit",
)
MAE_COMPILE_MS = REGISTRY.gauge(
    "kmamiz_cost_mae_compile_ms",
    "Mean absolute compile-ms prediction error at the last fit",
)
MAE_RUN_MS = REGISTRY.gauge(
    "kmamiz_cost_mae_run_ms",
    "Mean absolute warm-run-ms prediction error at the last fit",
)
PREWARM_HITS = REGISTRY.counter(
    "kmamiz_cost_prewarm_hits_total",
    "Capacity consolidations that landed on a predictively warmed bucket",
)
PREWARM_MISSES = REGISTRY.counter(
    "kmamiz_cost_prewarm_misses_total",
    "Capacity consolidations that landed cold despite graftcost being on",
)
PREDICTIVE_PREWARMS = REGISTRY.counter(
    "kmamiz_cost_predictive_prewarms_total",
    "Predictive prewarm rounds executed ahead of a forecast crossing",
)
PREWARMED_SPECS = REGISTRY.counter(
    "kmamiz_cost_prewarmed_specs_total",
    "Transposed specs warmed by predictive prewarm rounds",
)
TRAIN_MS = REGISTRY.histogram(
    "kmamiz_cost_train_ms",
    "Cost-model refresh latency (fold boundary / prewarm trigger)",
)


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------
def enabled() -> bool:
    """Master gate — graftcost is opt-in (KMAMIZ_COST=1)."""
    return os.environ.get("KMAMIZ_COST", "0") not in ("0", "false", "")


def prewarm_mode() -> str:
    got = os.environ.get("KMAMIZ_COST_PREWARM", "1").strip().lower()
    return got if got in ("0", "1", "sync") else "1"


def horizon_merges() -> int:
    """Crossings projected within this many merges trigger prewarm."""
    try:
        return max(1, int(os.environ.get("KMAMIZ_COST_HORIZON", "3")))
    except ValueError:
        return 3


def _tail_shift() -> int:
    try:
        return int(os.environ.get("KMAMIZ_STORE_TAIL_SHIFT", "3"))
    except ValueError:
        return 3


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------
class GraftCost:
    """Process-wide cost plane: model + growth tracker + prewarm
    bookkeeping. All mutables lock-guarded — observe_merge is called
    from merge finalizes on server threads while the background prewarm
    thread and /timings readers run concurrently."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.model = CostModel()
        self.tracker = growth.GrowthTracker()
        self._warmed: Dict[str, set] = {}  # tenant -> {(main, tail)}
        self._pending: Dict[str, growth.GrowthForecast] = {}
        self._width_costs: Dict[int, float] = {}  # flat width -> run ms
        self._hits = 0
        self._misses = 0
        self._rounds = 0
        self._last_crossing: Optional[dict] = None

    # -- merge-finalize hook (tick-reachable: keep it cheap) ----------------
    def observe_merge(
        self, tenant: str, valid: int, main_cap: int, tail_cap: int
    ) -> None:
        self.tracker.observe(tenant, valid, main_cap, tail_cap)
        fc = self.tracker.forecast(tenant, _tail_shift())
        if fc is None or not fc.imminent(horizon_merges()):
            return
        target = (fc.new_main, fc.new_tail)
        with self._lock:
            if target in self._warmed.get(tenant, ()):
                return
            already = tenant in self._pending
            self._pending[tenant] = fc
        if not already and prewarm_mode() == "1":
            threading.Thread(
                target=self.run_pending_prewarms,
                name="kmamiz-cost-prewarm",
                daemon=True,
            ).start()

    def note_capacity_change(
        self, tenant: str, old_main: int, new_main: int, new_tail: int
    ) -> None:
        """Consolidation accounting: did predictive prewarm get there
        first? (The scorecard floor ``cost_prewarm_hit_rate``.)"""
        with self._lock:
            hit = (new_main, new_tail) in self._warmed.get(tenant, ())
            if hit:
                self._hits += 1
            else:
                self._misses += 1
            self._pending.pop(tenant, None)
            self._last_crossing = {
                "tenant": tenant,
                "fromMain": int(old_main),
                "toMain": int(new_main),
                "toTail": int(new_tail),
                "hit": hit,
            }
        (PREWARM_HITS if hit else PREWARM_MISSES).inc()

    # -- prewarm execution (off the tick) -----------------------------------
    def run_pending_prewarms(self) -> dict:
        """Drain pending crossings: refresh the model, transpose every
        warm spec to the projected (main, tail), replay longest-first.
        Sync-mode harnesses call this between ticks; background mode
        runs it on the daemon thread observe_merge spawned."""
        with self._lock:
            pending, self._pending = dict(self._pending), {}
        if not pending:
            return {"rounds": 0, "warmed": 0, "failed": 0}
        try:
            self.refresh()
        except Exception:  # noqa: BLE001 - ranking degrades, prewarm survives
            logger.exception("cost refresh before prewarm failed")
        warmed_total = failed_total = 0
        for tenant, fc in sorted(pending.items()):
            mapping = prewarm.growth_mapping(
                fc.main, fc.tail, fc.new_main, fc.new_tail
            )
            pairs = prewarm.predictive_pairs(
                mapping,
                delta=(fc.main + fc.tail, fc.new_main + fc.new_tail),
            )
            pairs = prewarm.rank_by_predicted_compile(
                pairs, self.model if self.model.trained() else None
            )
            warmed, failed = prewarm.execute(pairs)
            warmed_total += warmed
            failed_total += failed
            with self._lock:
                self._warmed.setdefault(tenant, set()).add(
                    (fc.new_main, fc.new_tail)
                )
                self._rounds += 1
            PREDICTIVE_PREWARMS.inc()
            if warmed:
                PREWARMED_SPECS.inc(warmed)
            logger.info(
                "predictive prewarm %s: %d->%d (+%d tail), %d warmed %d failed",
                tenant, fc.main, fc.new_main, fc.new_tail, warmed, failed,
            )
        return {
            "rounds": len(pending),
            "warmed": warmed_total,
            "failed": failed_total,
        }

    # -- training -----------------------------------------------------------
    def refresh(self, persisted: Optional[dict] = None) -> dict:
        """Retrain from persisted label history + the live registry and
        recompute the per-width run-cost table the router reads."""
        t0 = prof_events.now_ms()
        if persisted is None:
            from kmamiz_tpu.core import programs as _programs

            persisted = _programs.load_labels()
        rows = model.training_rows(persisted)
        report = self.model.fit(rows)
        EXAMPLES.set(float(report["examples"]))
        MAE_COMPILE_MS.set(report["maeCompileMs"])
        MAE_RUN_MS.set(report["maeRunMs"])
        width_costs = self._compute_width_costs()
        with self._lock:
            self._width_costs = width_costs
        TRAIN_MS.observe(prof_events.now_ms() - t0)
        return report

    def _compute_width_costs(self) -> Dict[int, float]:
        """Predicted per-tick run cost of the store-width-shaped (graph
        family) programs, summed per flat store width — the tenant cost
        is one lookup by its arena bucket's width."""
        from kmamiz_tpu.core import programs as _programs

        pairs: List[Tuple[str, Any]] = []
        widths: List[int] = []
        for name, prog in sorted(_programs.all_programs().items()):
            if not name.startswith("graph."):
                continue
            for spec in prog.specs():
                dims = [
                    d
                    for d in features.spec_dims(spec)
                    if d >= 256 and (d & (d - 1)) == 0
                ]
                if not dims:
                    continue
                pairs.append((name, spec))
                widths.append(max(dims))
        preds = self.model.predict_many(pairs)
        if preds is None:
            return {}
        out: Dict[int, float] = {}
        for width, row in zip(widths, preds):
            out[width] = out.get(width, 0.0) + float(row[1])
        return out

    # -- consumers ----------------------------------------------------------
    def predicted_tenant_costs(self) -> Dict[str, float]:
        with self._lock:
            width_costs = dict(self._width_costs)
        if not width_costs:
            return {}
        try:
            from kmamiz_tpu.tenancy.arena import default_arena

            shift = _tail_shift()
            out: Dict[str, float] = {}
            for cap, tenants in default_arena().buckets().items():
                ms = width_costs.get(int(cap) + growth.tail_rows(int(cap), shift))
                if ms is None:
                    continue
                for t in tenants:
                    out[str(t)] = round(ms, 3)
            return out
        except Exception:  # noqa: BLE001 - ordering is best-effort
            return {}

    def snapshot(self) -> dict:
        with self._lock:
            hits, misses = self._hits, self._misses
            snap = {
                "model": self.model.snapshot(),
                "growth": self.tracker.snapshot(),
                "warmed": {
                    t: sorted(f"{m}+{tl}" for m, tl in caps)
                    for t, caps in sorted(self._warmed.items())
                },
                "pendingTenants": sorted(self._pending),
                "prewarmRounds": self._rounds,
                "prewarmHits": hits,
                "prewarmMisses": misses,
                "hitRate": round(hits / (hits + misses), 3)
                if (hits + misses)
                else None,
                "lastCrossing": self._last_crossing,
                "widthCosts": {
                    str(w): round(ms, 3)
                    for w, ms in sorted(self._width_costs.items())
                },
            }
        return snap


_COST: Optional[GraftCost] = None
_COST_LOCK = threading.Lock()


def get_cost() -> GraftCost:
    global _COST
    with _COST_LOCK:
        if _COST is None:
            _COST = GraftCost()
        return _COST


def reset_for_tests() -> None:
    """Drop the singleton (conftest autouse): fresh model, tracker,
    warmed-bucket bookkeeping."""
    global _COST
    with _COST_LOCK:
        _COST = None


# -- module-level facade (the hook surface the rest of the repo calls) ------
def observe_merge(
    tenant: str, valid: int, main_cap: int, tail_cap: int
) -> None:
    """Merge-finalize hook (graph/store.py): record one observation and
    arm predictive prewarm when a crossing is imminent. One env read;
    everything else is integer arithmetic + one ring append."""
    if not enabled():
        return
    get_cost().observe_merge(tenant or "default", valid, main_cap, tail_cap)


def note_capacity_change(
    tenant: str, old_main: int, new_main: int, new_tail: int
) -> None:
    if not enabled():
        return
    get_cost().note_capacity_change(
        tenant or "default", old_main, new_main, new_tail
    )


def run_pending_prewarms() -> dict:
    if not enabled():
        return {"rounds": 0, "warmed": 0, "failed": 0}
    return get_cost().run_pending_prewarms()


def refresh(persisted: Optional[dict] = None) -> Optional[dict]:
    if not enabled():
        return None
    return get_cost().refresh(persisted)


def on_fold(tenant: Optional[str]) -> Optional[dict]:
    """Fold-boundary hook (server/processor.py): continual retrain from
    the live registry. The fit program has one fixed shape (model.py),
    so steady-state folds re-run a warm program — the trainer can never
    become the stall it predicts."""
    if not enabled():
        return None
    return get_cost().refresh()


def predicted_tenant_costs() -> Dict[str, float]:
    """Per-tenant predicted run-cost table for the TickRouter's batch
    ordering; {} until a refresh has run."""
    if not enabled():
        return {}
    inst = _COST
    return inst.predicted_tenant_costs() if inst is not None else {}


def ranked_prewarm_order(
    pairs: List[Tuple[str, Any]],
    labels: Optional[Dict[str, List[Tuple[Any, float, float]]]] = None,
) -> List[Tuple[str, Any]]:
    """Boot-ranking consumer: longest-predicted-compile-first ordering
    for ``programs.run_prewarm``. Works ungated — with an untrained
    model it falls back to observed compile labels, then name order."""
    inst = _COST
    mdl = inst.model if inst is not None and inst.model.trained() else None
    return prewarm.rank_by_predicted_compile(pairs, mdl, labels)


def snapshot() -> dict:
    """Cost-plane posture for /timings and debugging surfaces."""
    base = {"enabled": enabled(), "prewarm": prewarm_mode()}
    inst = _COST
    if inst is None:
        return {**base, "model": {"trained": False}, "prewarmHits": 0}
    return {**base, **inst.snapshot()}
