"""Capacity-growth stall probe: one consolidation, prewarm ON vs OFF.

The measured claim behind ROADMAP item 6: with graftcost's predictive
prewarm armed, a segment-store consolidation (graph/store.py: ``valid >
main + tail``) dispatches only warm programs, so the crossing merge
costs the same as any steady-state merge; cold, the same merge eats the
multi-program compile wall. This module drives ONE deterministic edge
ramp across the threshold on a bare ``EndpointGraph`` and reports the
crossing batch's wall time, its program-registry compile delta, and the
final graph signature — bench.py runs it twice as subprocesses (compile
caches are process-global; an in-process A/B would leak warmth from the
first arm into the second) and asserts signature equality, so the A/B
compares identical work.

    python -m kmamiz_tpu.cost.growth_probe --prewarm on
    python -m kmamiz_tpu.cost.growth_probe --prewarm off --capacity 256

prints one JSON line: {"stall_ms", "steady_ms", "mid_compiles",
"signature", "crossed", "hit", ...}.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

#: ramp geometry at the default capacity 1024 (+256 tail, threshold
#: 1280): five 300-row batches cross at batch 5 (1500 valid -> 2048
#: main), with the forecast imminent from batch 3 — two clean
#: between-batch prewarm windows before the crossing
DEFAULT_ROWS = 300


def _batches(n_batches: int, rows: int):
    """Globally-distinct (src, dst, dist) int32 triples per batch, so
    the union's dedup never collapses the ramp (bench.py's generator
    idiom). Pure arithmetic — both arms see identical bytes."""
    import numpy as np

    for i in range(n_batches):
        k = np.arange(i * rows, (i + 1) * rows)
        yield (
            (k % 797).astype(np.int32),
            (k // 797).astype(np.int32),
            np.full(rows, 1 + i % 7, dtype=np.int32),
        )


def run_probe(
    prewarm_on: bool,
    capacity: int = 1024,
    rows: Optional[int] = None,
) -> dict:
    """Drive the ramp; return the probe report. Sets the cost-plane env
    knobs for THIS process (the caller isolates arms via subprocesses)."""
    import os

    os.environ["KMAMIZ_COST"] = "1" if prewarm_on else "0"
    os.environ["KMAMIZ_COST_PREWARM"] = "sync"
    from kmamiz_tpu import cost
    from kmamiz_tpu.core import programs
    from kmamiz_tpu.graph.store import EndpointGraph
    from kmamiz_tpu.resilience.chaos import graph_signature

    cost.reset_for_tests()
    gg = EndpointGraph(capacity=capacity, tenant="probe", grow="segment")
    tail = gg.tail_capacity
    threshold = capacity + tail
    rows = rows if rows is not None else max(64, (threshold * 300) // 1280)
    # enough batches to cross once, plus one post-crossing steady batch
    n_batches = threshold // rows + 3

    report = {
        "prewarm": prewarm_on,
        "capacity": capacity,
        "tail": tail,
        "rows": rows,
        "batches": n_batches,
        "stall_ms": None,
        "steady_ms": None,
        "mid_compiles": None,
        "crossed": False,
    }
    walls = []
    for i, (s_b, d_b, ds_b) in enumerate(_batches(n_batches, rows)):
        cap_before = gg.capacity
        snap = programs.snapshot()
        t0 = time.perf_counter()
        gg.merge_edges(s_b, d_b, ds_b)
        cap_after = gg.capacity  # finalize: the consolidation lands here
        wall_ms = (time.perf_counter() - t0) * 1000
        grew = sum(programs.new_compiles_since(snap).values())
        walls.append((wall_ms, grew, cap_before, cap_after))
        if cap_after > cap_before and not report["crossed"]:
            report["crossed"] = True
            report["stall_ms"] = round(wall_ms, 2)
            report["mid_compiles"] = grew
            report["crossing_batch"] = i
            report["to_capacity"] = cap_after
        if prewarm_on:
            cost.run_pending_prewarms()
    # steady cost baseline: the warm batches' median (crossing excluded)
    steady = sorted(
        w for w, _g, cb, ca in walls[1:] if cb == ca
    )
    if steady:
        report["steady_ms"] = round(steady[len(steady) // 2], 2)
    report["n_edges"] = gg.n_edges
    report["signature"] = graph_signature(gg)
    if prewarm_on:
        snap = cost.snapshot()
        report["hit"] = bool((snap.get("lastCrossing") or {}).get("hit"))
        report["prewarm_rounds"] = snap.get("prewarmRounds", 0)
        report["hit_rate"] = snap.get("hitRate")
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--prewarm", choices=("on", "off"), required=True)
    ap.add_argument("--capacity", type=int, default=1024)
    ap.add_argument("--rows", type=int, default=None)
    args = ap.parse_args(argv)
    report = run_probe(
        args.prewarm == "on", capacity=args.capacity, rows=args.rows
    )
    print(json.dumps(report, sort_keys=True))
    return 0 if report["crossed"] else 1


if __name__ == "__main__":
    sys.exit(main())
