"""graftcost predictive prewarm: transpose warm specs to the next bucket.

A capacity consolidation (graph/store.py segment mode: ``valid > main +
tail``) re-runs the store-width-shaped programs at the next pow2 main
capacity — a multi-program compile wall if those shapes are cold. The
growth forecaster (tenancy/growth.py) predicts the crossing a few
merges ahead; this module manufactures the post-crossing argument specs
*from the registry's own warm specs* by dimension transposition:

    mapping = {old_main: new_main, old_tail: new_tail,
               old_main+old_tail: new_main+new_tail}

Three rewrite rules cover the store's actual program shapes across a
crossing:

- **exact dims**: array dims and static ints equal to a mapping key
  rewrite to its value (the flat column width every scorer and merge
  kernel sees, the static ``cap``/``tail_cap`` of split_segments);
- **flat delta** (``graph.`` family only): ``_merge_edges`` outputs are
  exact row *sums* — flat store width + window block — so a dim
  strictly greater than the old flat width shifts by ``new_flat -
  old_flat`` (1280+TL -> 2304+TL). Only the graph family's widths
  compose this way; model/scorer dims past the flat width are
  unrelated and must not shift;
- **statics-only**: the consolidation call itself runs the NEW static
  cap against the OLD merged width (the union that produced it ran
  against the old store), so each spec also transposes with arrays
  untouched and only static scalars mapped.

A transposed spec replays through the ordinary ``Program.prewarm_spec``
zero-fill path, so the dispatch cache holds the post-crossing programs
before the crossing lands. Warming a shape the store never reaches is
harmless (wasted background compile, counted); missing one is a
mid-tick stall — which is why the scenario gate counts per-tick compile
deltas, not intentions.
"""
from __future__ import annotations

import json
import logging
from typing import Any, Dict, List, Optional, Tuple

from kmamiz_tpu.core import programs

logger = logging.getLogger("kmamiz_tpu.cost.prewarm")

#: programs whose argument widths compose additively from the store's
#: flat width (merge-output consumers) — the flat-delta rule's scope
GRAPH_FAMILY = "graph."


def growth_mapping(
    old_main: int, old_tail: int, new_main: int, new_tail: int
) -> Dict[int, int]:
    """The exact-dimension rewrite for one predicted consolidation.
    Identity entries are dropped (a tail that stays 256 wide must not
    rewrite every unrelated 256)."""
    mapping = {
        old_main: new_main,
        old_tail: new_tail,
        old_main + old_tail: new_main + new_tail,
    }
    return {k: v for k, v in mapping.items() if k != v and k > 0}


def transpose_spec(
    spec: Any,
    mapping: Dict[int, int],
    delta: Optional[Tuple[int, int]] = None,
    statics_only: bool = False,
) -> Any:
    """Rewrite one encoded spec (the ``programs._encode`` grammar).
    ``delta=(old_flat, new_flat)`` shifts array dims strictly greater
    than ``old_flat`` by the flat growth (merge-output sums);
    ``statics_only`` leaves arrays untouched and maps static ints only
    (the consolidation-call variant). Pure."""
    old_flat, shift = (delta[0], delta[1] - delta[0]) if delta else (0, 0)

    def dim(d: int) -> int:
        if d in mapping:
            return mapping[d]
        if shift and d > old_flat:
            return d + shift
        return d

    def tr(node: Any) -> Any:
        if isinstance(node, bool) or node is None or isinstance(
            node, (float, str)
        ):
            return node
        if isinstance(node, int):
            return mapping.get(node, node)
        if isinstance(node, list):
            return [tr(v) for v in node]
        if isinstance(node, dict):
            if "__arr__" in node:
                if statics_only:
                    return node
                shape, dtype, weak = node["__arr__"]
                return {
                    "__arr__": [[dim(int(d)) for d in shape], dtype, weak]
                }
            if "__tuple__" in node:
                return {"__tuple__": [tr(v) for v in node["__tuple__"]]}
            if "__nt__" in node:
                return {"__nt__": node["__nt__"], "items": [tr(v) for v in node["items"]]}
            return {k: tr(v) for k, v in node.items()}
        return node

    args, kwargs = spec
    return ([tr(a) for a in args], {k: tr(v) for k, v in kwargs.items()})


def predictive_pairs(
    mapping: Dict[int, int], delta: Optional[Tuple[int, int]] = None
) -> List[Tuple[str, Any]]:
    """Every (program, transposed spec) the rules change, deduped — the
    prewarm plan for one predicted crossing. Graph-family specs yield up
    to two variants each (full transpose for post-crossing steady
    state, statics-only for the consolidation call itself)."""
    if not mapping:
        return []
    out: List[Tuple[str, Any]] = []
    seen = set()

    def add(name: str, warped: Any, original: Any) -> None:
        if warped == original:
            return
        key = (name, json.dumps(warped, sort_keys=True))
        if key in seen:
            return
        seen.add(key)
        out.append((name, warped))

    for name, prog in sorted(programs.all_programs().items()):
        in_family = name.startswith(GRAPH_FAMILY)
        for spec in prog.specs():
            add(
                name,
                transpose_spec(
                    spec, mapping, delta=delta if in_family else None
                ),
                spec,
            )
            if in_family:
                add(
                    name,
                    transpose_spec(spec, mapping, statics_only=True),
                    spec,
                )
    return out


def rank_by_predicted_compile(
    pairs: List[Tuple[str, Any]],
    model,
    labels: Optional[Dict[str, List[Tuple[Any, float, float]]]] = None,
) -> List[Tuple[str, Any]]:
    """Longest-predicted-compile-first ordering (the boot-ranking
    consumer). Falls back to observed compile labels, then to the
    stable name order, so ranking never blocks a cold boot."""
    if not pairs:
        return pairs
    preds = model.predict_many(pairs) if model is not None else None
    by_label: Dict[str, float] = {}
    for name, labelled in (labels or {}).items():
        for _spec, compile_ms, _run_ms in labelled:
            by_label[name] = max(by_label.get(name, 0.0), float(compile_ms))

    def score(i: int) -> float:
        if preds is not None:
            return float(preds[i, 0])
        return by_label.get(pairs[i][0], 0.0)

    order = sorted(
        range(len(pairs)), key=lambda i: (-score(i), pairs[i][0], i)
    )
    return [pairs[i] for i in order]


def execute(pairs: List[Tuple[str, Any]]) -> Tuple[int, int]:
    """Replay the plan through ``Program.prewarm_spec``; returns
    (warmed, failed). Runs off the tick — on the graftcost background
    thread or between harness ticks in sync mode."""
    warmed = failed = 0
    for name, spec in pairs:
        prog = programs.get(name)
        if prog is None:
            failed += 1
            continue
        if prog.prewarm_spec(spec):
            warmed += 1
        else:
            failed += 1
    return warmed, failed
