"""graftcost feature extractor: (program, spec) -> fixed-width vector.

The program registry's shape hints are the TpuGraphs-shaped dataset the
live system generates for free (PAPERS.md): every compiled bucket is a
JSON spec of array shapes, dtypes, and static scalars, and every compile
carries its measured wall. This module turns one (program name, spec)
pair into a deterministic ``DIM``-wide float32 vector the ridge
regressor in :mod:`.model` trains on:

- size terms: log2 total/max array elements, leaf counts, max rank, and
  the log2 of the largest power-of-2 dimension (the capacity-bucket
  proxy — the store pads every growable axis to pow2, so this feature
  IS the bucket the spec compiles for);
- dtype mix: fraction of array leaves that are f32 / integer / bool /
  other (compile cost differs by lowering path);
- static-value buckets: count of static scalars and the log2 of their
  absolute-int mass (``cap=2048`` style static args shift compile cost
  the shape dims alone cannot see);
- program family: an 8-way one-hot over ``zlib.crc32`` of the name's
  family prefix (``graph.``, ``scorers.``, ...). crc32 — never Python
  ``hash()``, which is salted per process and would de-determinize the
  table.

Everything here is pure host arithmetic over already-encoded specs: no
JAX, no clocks, no I/O — callable from any thread at any time.
"""
from __future__ import annotations

import math
import zlib
from typing import Any, List, Tuple

import numpy as np

#: feature vector width (the regressor's input dim)
DIM = 20

#: family one-hot slots (features 12..19)
N_FAMILIES = 8


def _log2p(x: float) -> float:
    return math.log2(1.0 + max(0.0, float(x)))


def _walk(node: Any, arrays: List[Tuple[Tuple[int, ...], str]], scalars: List[Any]) -> None:
    """Collect array leaves ``(shape, dtype)`` and static scalar leaves
    from one encoded spec subtree (the ``programs._encode`` grammar)."""
    if node is None or isinstance(node, (bool, int, float, str)):
        scalars.append(node)
        return
    if isinstance(node, list):
        for v in node:
            _walk(v, arrays, scalars)
        return
    if isinstance(node, dict):
        if "__arr__" in node:
            shape, dtype, _weak = node["__arr__"]
            arrays.append((tuple(int(d) for d in shape), str(dtype)))
            return
        if "__tuple__" in node:
            for v in node["__tuple__"]:
                _walk(v, arrays, scalars)
            return
        if "__nt__" in node:
            for v in node.get("items", ()):
                _walk(v, arrays, scalars)
            return
        for _k, v in sorted(node.items()):
            _walk(v, arrays, scalars)


def family_slot(name: str) -> int:
    """Deterministic family bucket: crc32 of the name's first dotted
    component (``graph.split_segments`` -> ``graph``)."""
    prefix = name.split(".", 1)[0] if name else ""
    return zlib.crc32(prefix.encode("utf-8")) % N_FAMILIES


def spec_dims(spec: Any) -> List[int]:
    """Every array dimension plus every positive static int in the spec
    (the transposition surface predictive prewarm rewrites)."""
    arrays: List[Tuple[Tuple[int, ...], str]] = []
    scalars: List[Any] = []
    args, kwargs = spec
    for a in args:
        _walk(a, arrays, scalars)
    _walk(kwargs, arrays, scalars)
    dims: List[int] = []
    for shape, _dt in arrays:
        dims.extend(shape)
    for s in scalars:
        if isinstance(s, bool):
            continue
        if isinstance(s, int) and s > 0:
            dims.append(s)
    return dims


def feature_vector(name: str, spec: Any) -> np.ndarray:
    """One (program, spec) pair as a ``DIM``-wide float32 vector.
    Deterministic across processes — the table a restarted trainer
    rebuilds from persisted labels is bit-identical."""
    arrays: List[Tuple[Tuple[int, ...], str]] = []
    scalars: List[Any] = []
    args, kwargs = spec
    for a in args:
        _walk(a, arrays, scalars)
    _walk(kwargs, arrays, scalars)

    total_elems = 0
    max_elems = 0
    max_rank = 0
    max_lead = 0
    f32 = ints = bools = other = 0
    for shape, dtype in arrays:
        elems = 1
        for d in shape:
            elems *= max(1, int(d))
        total_elems += elems
        max_elems = max(max_elems, elems)
        max_rank = max(max_rank, len(shape))
        if shape:
            max_lead = max(max_lead, int(shape[0]))
        if dtype.startswith("float32"):
            f32 += 1
        elif dtype.startswith(("int", "uint")):
            ints += 1
        elif dtype.startswith("bool"):
            bools += 1
        else:
            other += 1
    n_arrays = len(arrays)
    static_ints = [
        s for s in scalars if isinstance(s, int) and not isinstance(s, bool)
    ]
    # largest pow2 dim >= 256: the capacity-bucket proxy (0 when none)
    pow2_dims = [
        d for d in spec_dims(spec) if d >= 256 and (d & (d - 1)) == 0
    ]
    vec = np.zeros(DIM, dtype=np.float32)
    vec[0] = 1.0  # bias
    vec[1] = _log2p(total_elems)
    vec[2] = _log2p(max_elems)
    vec[3] = float(n_arrays)
    vec[4] = float(len(scalars))
    vec[5] = float(max_rank)
    vec[6] = _log2p(max_lead)
    denom = float(max(1, n_arrays))
    vec[7] = f32 / denom
    vec[8] = ints / denom
    vec[9] = bools / denom
    vec[10] = _log2p(sum(abs(s) for s in static_ints))
    vec[11] = _log2p(max(pow2_dims) if pow2_dims else 0)
    vec[12 + family_slot(name)] = 1.0
    return vec


def feature_table(rows) -> np.ndarray:
    """Stack ``(name, spec)`` pairs into an ``[N, DIM]`` float32 table."""
    if not rows:
        return np.zeros((0, DIM), dtype=np.float32)
    return np.stack([feature_vector(name, spec) for name, spec in rows])
