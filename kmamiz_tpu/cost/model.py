"""graftcost regressor: a JAX-trained ridge head over the feature table.

Two targets per (program, spec) row — ``log1p(compile_ms)`` and
``log1p(run_ms)`` — fit jointly in closed form:

    W = solve(XᵀX + λI, XᵀY)        X: [CAP, DIM]   Y: [CAP, 2]

The fit is a registered jitted program (``cost.ridge_fit``) dispatched
at a FIXED example capacity: rows are zero-padded (a zero row adds
nothing to XᵀX or XᵀY), so continual retraining from the growing live
registry re-runs one warm program forever — the fit itself can never
become the compile stall it exists to predict. Training runs at fold
boundaries / prewarm triggers, never on the warm tick; predictions are
a host-side numpy dot against the last device-fetched ``W`` so the
serving edge (TickRouter ordering, boot ranking) stays device-free.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from kmamiz_tpu.core import programs
from kmamiz_tpu.cost import features

#: ridge penalty — small: the table is tiny and well-conditioned by the
#: bias + one-hot columns
RIDGE_LAMBDA = 1e-3

_DEFAULT_EXAMPLE_CAP = 256


def example_cap() -> int:
    """Fixed training-table rows (KMAMIZ_COST_EXAMPLES, pow2-clamped).
    One shape forever = one compile forever."""
    try:
        cap = int(os.environ.get("KMAMIZ_COST_EXAMPLES", _DEFAULT_EXAMPLE_CAP))
    except ValueError:
        cap = _DEFAULT_EXAMPLE_CAP
    cap = max(32, min(4096, cap))
    # round up to pow2 so an env tweak still lands on a padded bucket
    p = 32
    while p < cap:
        p <<= 1
    return p


def _build_ridge_fit():
    import jax
    import jax.numpy as jnp

    @programs.register("cost.ridge_fit")
    @jax.jit
    def _ridge_fit(x, y):
        xtx = x.T @ x + RIDGE_LAMBDA * jnp.eye(x.shape[1], dtype=x.dtype)
        return jnp.linalg.solve(xtx, x.T @ y)

    return _ridge_fit


_ridge_fit_prog = _build_ridge_fit()


class CostModel:
    """Thread-safe continual regressor. ``fit`` swaps ``W`` under the
    lock; ``predict*`` reads it with one lock-guarded copy."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._w: Optional[np.ndarray] = None  # [DIM, 2]
        self.version = 0
        self.examples = 0
        self.mae_compile_ms = 0.0
        self.mae_run_ms = 0.0

    # -- training -----------------------------------------------------------
    def fit(self, rows: List[Tuple[str, Any, float, float]]) -> dict:
        """Train from ``(name, spec, compile_ms, run_ms)`` rows. Rows
        beyond the fixed example cap keep the most recent (the registry
        yields them in insertion order). Returns a report dict."""
        import jax

        cap = example_cap()
        rows = rows[-cap:]
        n = len(rows)
        x = np.zeros((cap, features.DIM), dtype=np.float32)
        y = np.zeros((cap, 2), dtype=np.float32)
        for i, (name, spec, compile_ms, run_ms) in enumerate(rows):
            x[i] = features.feature_vector(name, spec)
            y[i, 0] = np.log1p(max(0.0, float(compile_ms)))
            y[i, 1] = np.log1p(max(0.0, float(run_ms)))
        # explicit transfers: the fold path may run under transfer_guard
        w = np.asarray(
            jax.device_get(  # graftlint: disable=host-sync-in-hot-path -- fold-boundary train fetch, off the warm tick
                _ridge_fit_prog(jax.device_put(x), jax.device_put(y))
            ),
            dtype=np.float32,
        )
        pred = np.expm1(np.clip(x[:n] @ w, 0.0, 30.0))
        actual = np.expm1(y[:n])
        mae = (
            np.abs(pred - actual).mean(axis=0)
            if n
            else np.zeros(2, dtype=np.float32)
        )
        with self._lock:
            self._w = w
            self.version += 1
            self.examples = n
            self.mae_compile_ms = float(mae[0])
            self.mae_run_ms = float(mae[1])
            return {
                "version": self.version,
                "examples": n,
                "maeCompileMs": round(self.mae_compile_ms, 3),
                "maeRunMs": round(self.mae_run_ms, 3),
            }

    # -- inference ----------------------------------------------------------
    def _weights(self) -> Optional[np.ndarray]:
        with self._lock:
            return self._w

    def trained(self) -> bool:
        return self._weights() is not None

    def predict(self, name: str, spec: Any) -> Optional[Tuple[float, float]]:
        """(compile_ms, run_ms) prediction, or None before any fit."""
        w = self._weights()
        if w is None:
            return None
        out = np.expm1(
            np.clip(features.feature_vector(name, spec) @ w, 0.0, 30.0)
        )
        return float(out[0]), float(out[1])

    def predict_many(
        self, pairs: List[Tuple[str, Any]]
    ) -> Optional[np.ndarray]:
        """[N, 2] (compile_ms, run_ms) predictions, or None untrained."""
        w = self._weights()
        if w is None or not pairs:
            return None
        x = features.feature_table(pairs)
        return np.expm1(np.clip(x @ w, 0.0, 30.0))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "trained": self._w is not None,
                "version": self.version,
                "examples": self.examples,
                "maeCompileMs": round(self.mae_compile_ms, 3),
                "maeRunMs": round(self.mae_run_ms, 3),
            }


def training_rows(
    persisted: Optional[Dict[str, List[Tuple[Any, float, float]]]] = None,
) -> List[Tuple[str, Any, float, float]]:
    """The union of persisted label history (boot: satellite of the
    shape-hint file) and the live registry's labels, persisted first so
    live observations of the same spec win the recency cut."""
    rows: List[Tuple[str, Any, float, float]] = []
    seen = set()
    live: List[Tuple[str, Any, float, float]] = []
    for name, prog in sorted(programs.all_programs().items()):
        for spec, compile_ms, run_ms in prog.labels():
            live.append((name, spec, compile_ms, run_ms))
    for name, labelled in sorted((persisted or {}).items()):
        for spec, compile_ms, run_ms in labelled:
            key = (name, repr(spec))
            if key in seen:
                continue
            seen.add(key)
            rows.append((name, spec, compile_ms, run_ms))
    for name, spec, compile_ms, run_ms in live:
        key = (name, repr(spec))
        if key in seen:
            continue
        seen.add(key)
        rows.append((name, spec, compile_ms, run_ms))
    return rows
