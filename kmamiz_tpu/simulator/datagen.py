"""Propagation stats -> per-time-slot combined realtime data.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/
LoadSimulation/LoadSimulationDataGenerator.ts: each endpoint's per-slot
stats become up to two TCombinedRealtimeData rows — successes attributed
to the first declared 2xx response (default "200") and errors to the first
5xx (default "500") — stamped with the slot's absolute timestamp in
microseconds (:46-98).
"""
from __future__ import annotations

from typing import Dict, List

from kmamiz_tpu.simulator.slot_metrics import parse_slot_key

DAY_MS = 86_400_000
HOUR_MS = 3_600_000
MINUTE_MS = 60_000


def generate_realtime_data(
    base_data_map: Dict[str, dict],
    propagation_results: Dict[str, Dict[str, dict]],
    simulate_date_ms: float,
) -> Dict[str, List[dict]]:
    """base_data_map: uniqueEndpointName -> {"baseData": ..., "responses": [...]}
    (built by Simulator.collect_sample_data)."""
    out: Dict[str, List[dict]] = {}
    for key, endpoint_stats in propagation_results.items():
        day, hour, minute = parse_slot_key(key)
        timestamp_micro = (
            simulate_date_ms + day * DAY_MS + hour * HOUR_MS + minute * MINUTE_MS
        ) * 1000

        combined: List[dict] = []
        for endpoint, stats in endpoint_stats.items():
            base_with_resp = base_data_map.get(endpoint)
            if not base_with_resp:
                continue
            base = base_with_resp["baseData"]
            responses = base_with_resp.get("responses") or []
            error_count = stats["ownErrorCount"] + stats["downstreamErrorCount"]
            success_count = stats["requestCount"] - error_count
            latency_by_status = stats["latencyStatsByStatus"]
            if success_count > 0:
                resp2xx = next(
                    (r for r in responses if str(r["status"]).startswith("2")), None
                )
                combined.append(
                    {
                        **base,
                        "latestTimestamp": timestamp_micro,
                        "requestSchema": None,
                        "responseSchema": None,
                        "responseBody": resp2xx["responseBody"] if resp2xx else None,
                        "responseContentType": (
                            resp2xx["responseContentType"] if resp2xx else None
                        ),
                        "combined": success_count,
                        "status": resp2xx["status"] if resp2xx else "200",
                        "latency": latency_by_status.get(
                            "200", {"mean": 0.0, "cv": 0.0}
                        ),
                    }
                )
            if error_count > 0:
                resp5xx = next(
                    (r for r in responses if str(r["status"]).startswith("5")), None
                )
                combined.append(
                    {
                        **base,
                        "latestTimestamp": timestamp_micro,
                        "requestSchema": None,
                        "responseSchema": None,
                        "responseBody": resp5xx["responseBody"] if resp5xx else None,
                        "responseContentType": (
                            resp5xx["responseContentType"] if resp5xx else None
                        ),
                        "combined": error_count,
                        "status": resp5xx["status"] if resp5xx else "500",
                        "latency": latency_by_status.get(
                            "500", {"mean": 0.0, "cv": 0.0}
                        ),
                    }
                )
        out[key] = combined
    return out
