"""MicroViSim-equivalent synthetic-mesh simulator (TPU-native rewrite).

Equivalent of the reference's `src/MicroViSim-simulator/`: a YAML-driven
generator that synthesizes a whole service mesh — endpoint dependencies,
datatypes, replica counts, and per-time-slot traffic with faults and
overload — exercising the full framework pipeline without any Kubernetes,
Istio, Zipkin, or Envoy. It doubles as the "multi-node test without a real
cluster" substitute (SURVEY.md §4) and as the 10k-endpoint benchmark mesh
generator.

The hot path — per-request traffic propagation, a recursive DFS in the
reference (LoadSimulationPropagator.ts:89-244) — is re-designed here as
vectorized frontier propagation over the dependency DAG: the request
dimension is an array axis, Bernoulli error draws / dependency-group
selections / critical-path latencies are batched vector ops, and the DAG is
swept once forward (masks + selections) and once backward (status +
latency) in topological order.
"""
from kmamiz_tpu.simulator.simulator import Simulator  # noqa: F401
from kmamiz_tpu.simulator.config import SimulationConfigManager  # noqa: F401
