"""Simulation REST handler.

Equivalent of /root/reference/src/MicroViSim-simulator/handler/
SimulationService.ts: YAML upload -> clear state -> generate simulation
data -> refresh caches and replay per-slot dynamic data; plus the
static-config generator endpoint. Accepts the YAML either as a raw request
body or as a multipart/form-data upload (the reference uses multer).
"""
from __future__ import annotations

import logging
import time

from kmamiz_tpu.api.router import IRequestHandler, Request, Response
from kmamiz_tpu.server.import_export import ImportExportHandler
from kmamiz_tpu.simulator.config_generator import (
    generate_sim_config_from_static_data,
)
from kmamiz_tpu.simulator.simulator import Simulator

logger = logging.getLogger("kmamiz_tpu.simulator")


def _extract_yaml_body(body: bytes) -> str:
    """Raw YAML body, or the first file part of a multipart/form-data
    payload (sniffed from the leading boundary line)."""
    if body.lstrip().startswith(b"--"):
        boundary = body.split(b"\r\n", 1)[0].strip()
        if boundary.startswith(b"--"):
            for part in body.split(boundary):
                part = part.strip(b"\r\n")
                if not part or part == b"--":
                    continue
                header_end = part.find(b"\r\n\r\n")
                if header_end == -1:
                    continue
                headers = part[:header_end].lower()
                if b"filename=" in headers or b"name=\"file\"" in headers:
                    return part[header_end + 4 :].decode("utf-8", "replace")
    return body.decode("utf-8", "replace")


class SimulationHandler(IRequestHandler):
    def __init__(self, ctx) -> None:
        super().__init__("simulation")
        self._ctx = ctx
        self._simulator = Simulator()
        self._import_export = ImportExportHandler(ctx)
        self.add_route("post", "/startSimulation", self._start_simulation)
        self.add_route(
            "get", "/generateStaticSimConfig", self._generate_static_config
        )

    def _start_simulation(self, req: Request) -> Response:
        if not req.body:
            return Response(status=400, payload={"message": "YAML file is missing."})
        yaml_string = _extract_yaml_body(req.body).strip()
        if not yaml_string:
            return Response(
                payload={"message": "Received an empty YAML. Skipping data retrieval."}
            )
        status, message = self._process_simulation(yaml_string)
        return Response(status=status, payload={"message": message})

    def _process_simulation(self, yaml_string: str) -> tuple:
        """SimulationService.ts:61-118."""
        simulate_date_ms = time.time() * 1000
        try:
            self._import_export.clear_data()
            result = self._simulator.generate_simulation_data(
                yaml_string, simulate_date_ms
            )
            if result.validation_error_message:
                return 400, result.validation_error_message
            if result.converting_error_message:
                return 500, result.converting_error_message
            try:
                self._ctx.operator.update_static_simulate_data_to_cache(
                    dependencies=result.endpoint_dependencies,
                    data_types=result.data_types,
                    replica_counts=result.replica_counts,
                )
                self._ctx.operator.update_dynamic_simulate_data(
                    result.realtime_data_per_slot
                )
                return 201, "ok"
            except Exception as err:  # noqa: BLE001
                logger.exception("simulation cache update failed")
                return (
                    500,
                    "Error while caching and creating historical and aggregated "
                    f"data:\n---\n{err}",
                )
        except Exception as err:  # noqa: BLE001
            logger.exception("simulation failed")
            return 500, f"Error simulate retrive data by YAML:\n---\n{err}"

    def _generate_static_config(self, req: Request) -> Response:
        try:
            dep = self._ctx.cache.get("EndpointDependencies").get_data()
            data_types = self._ctx.cache.get("EndpointDataType").get_data() or []
            replicas = self._ctx.cache.get("ReplicaCounts").get_data() or []
            yaml_str = generate_sim_config_from_static_data(
                [dt.to_json() for dt in data_types],
                replicas,
                dep.to_json() if dep else [],
            )
            return Response(payload={"staticYamlStr": yaml_str, "message": "ok"})
        except Exception as err:  # noqa: BLE001
            logger.exception("static sim config generation failed")
            return Response(
                status=500,
                payload={
                    "staticYamlStr": "",
                    "message": (
                        "Error while trying to generate static Simulation "
                        f"Yaml:\n{err}"
                    ),
                },
            )
