"""Live system state -> static simulation-config YAML.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/
SimConfigGenerator.ts: snapshots the EndpointDependencies / ReplicaCounts /
EndpointDataType caches into a servicesInfo + endpointDependencies YAML the
user can edit and re-upload (`GET /simulation/generateStaticSimConfig`).
"""
from __future__ import annotations

import re
from typing import Dict, List

import yaml

from kmamiz_tpu.simulator import naming
from kmamiz_tpu.simulator.bodies import sample_to_user_defined_type

_EMPTY_BODY_RE = re.compile(r"^(\s*)(requestBody|responseBody): '\{\}'", re.M)


def _format_empty_bodies(raw_yaml: str) -> str:
    """Render '{}' bodies as editable multi-line blocks
    (SimConfigGenerator.ts:48-54)."""
    return _EMPTY_BODY_RE.sub(
        lambda m: f"{m.group(1)}{m.group(2)}: |-\n{m.group(1)}  {{\n\n{m.group(1)}  }}",
        raw_yaml,
    )


def generate_sim_config_from_static_data(
    data_types: List[dict],
    replica_counts: List[dict],
    endpoint_dependencies: List[dict],
) -> str:
    """SimConfigGenerator.ts:21-46. Inputs are the plain-JSON cache shapes."""
    services_info, endpoint_id_map = _build_services_info(
        data_types, replica_counts
    )
    dependencies = _build_endpoint_dependencies(
        endpoint_dependencies, endpoint_id_map
    )
    raw = yaml.safe_dump(
        {"servicesInfo": services_info, "endpointDependencies": dependencies},
        sort_keys=False,
        width=10_000,
        allow_unicode=True,
    )
    return _format_empty_bodies(raw)


def _build_services_info(data_types: List[dict], replica_counts: List[dict]):
    namespaces: Dict[str, dict] = {}
    id_counters: Dict[str, int] = {}
    endpoint_id_map: Dict[str, str] = {}

    # merge schemas by endpoint (SimConfigGenerator.ts:67-83)
    endpoint_map: Dict[str, dict] = {}
    for dt in data_types:
        key = dt["uniqueEndpointName"]
        if key not in endpoint_map:
            endpoint_map[key] = {**dt, "schemas": list(dt.get("schemas") or [])}
        else:
            endpoint_map[key]["schemas"].extend(dt.get("schemas") or [])

    for dtype in endpoint_map.values():
        namespace = dtype["namespace"]
        service = dtype["service"]
        version = dtype["version"]
        method = dtype["method"]
        schemas = dtype["schemas"]
        url = dtype["uniqueEndpointName"].split("\t")[4]
        path = naming.get_path_from_url(url)

        ns_yaml = namespaces.setdefault(
            namespace, {"namespace": namespace, "services": []}
        )
        svc_yaml = next(
            (s for s in ns_yaml["services"] if s["serviceName"] == service), None
        )
        if svc_yaml is None:
            svc_yaml = {"serviceName": service, "versions": []}
            ns_yaml["services"].append(svc_yaml)
        ver_yaml = next(
            (v for v in svc_yaml["versions"] if v["version"] == version), None
        )
        if ver_yaml is None:
            ver_yaml = {"version": version, "replica": 1, "endpoints": []}
            svc_yaml["versions"].append(ver_yaml)

        responses = [
            {
                "status": schema["status"],
                "responseContentType": schema.get("responseContentType") or "",
                "responseBody": (
                    sample_to_user_defined_type(schema.get("responseSample") or {})
                    if schema.get("responseContentType") == "application/json"
                    else "{}"
                ),
            }
            for schema in schemas
        ]
        prefix = f"{namespace}-{service}-{version}-{method.lower()}-ep"
        serial = id_counters.get(prefix, 1)
        endpoint_id = f"{prefix}-{serial}"
        id_counters[prefix] = serial + 1
        endpoint_id_map[dtype["uniqueEndpointName"]] = endpoint_id

        first = schemas[0] if schemas else {}
        ver_yaml["endpoints"].append(
            {
                "endpointId": endpoint_id,
                "endpointInfo": {"path": path, "method": method},
                "datatype": {
                    "requestContentType": first.get("requestContentType") or "",
                    "requestBody": (
                        sample_to_user_defined_type(first.get("requestSample") or {})
                        if first.get("requestContentType") == "application/json"
                        else "{}"
                    ),
                    "responses": responses,
                },
            }
        )

    for replica in replica_counts:
        ns_yaml = namespaces.get(replica["namespace"])
        if not ns_yaml:
            continue
        service_name = replica["uniqueServiceName"].split("\t")[0]
        svc_yaml = next(
            (s for s in ns_yaml["services"] if s["serviceName"] == service_name),
            None,
        )
        if not svc_yaml:
            continue
        ver_yaml = next(
            (v for v in svc_yaml["versions"] if v["version"] == replica["version"]),
            None,
        )
        if ver_yaml:
            ver_yaml["replica"] = replica["replicas"]

    return list(namespaces.values()), endpoint_id_map


def _build_endpoint_dependencies(
    endpoint_dependencies: List[dict], endpoint_id_map: Dict[str, str]
) -> List[dict]:
    result = []
    for dep in endpoint_dependencies:
        from_id = endpoint_id_map.get(dep["endpoint"]["uniqueEndpointName"])
        if not from_id:
            continue
        depend_on = [
            {"endpointId": endpoint_id_map[d["endpoint"]["uniqueEndpointName"]]}
            for d in dep.get("dependingOn", [])
            if d.get("distance") == 1
            and d["endpoint"]["uniqueEndpointName"] in endpoint_id_map
        ]
        if not depend_on:
            continue
        result.append(
            {
                "endpointId": from_id,
                "dependOn": depend_on,
                "isExternal": bool(dep.get("isDependedByExternal")),
            }
        )
    return result
