"""Simulation-config parsing, validation, and preprocessing.

Equivalent of the reference's SimulationConfigManager + the three zod
schemas + three validators + three preprocessors
(/root/reference/src/MicroViSim-simulator/classes/SimulationConfigManager.ts,
entities/TSimConfig*.ts, SimConfigValidator/*, SimConfigPreprocessor/*).

The YAML is parsed with pyyaml and checked by a hand-rolled schema walker
(the image has no zod equivalent); semantic validation (duplicates,
undefined ids, cycles, probability sums) and preprocessing (unique-name
assignment, body normalization, fault-target expansion) mirror the
reference checks and their error-message format:

    [Location] <path>  [Error] <message>
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

import yaml

from kmamiz_tpu.simulator import bodies, naming

REQUEST_TYPES = {
    "get", "post", "put", "patch", "delete", "head", "options", "connect", "trace",
}
FALLBACK_STRATEGIES = (
    "failIfAnyDependentFail",
    "failIfAllDependentFail",
    "ignoreDependentFail",
)
MAX_SIMULATION_DAYS = 7

ValidationError = Dict[str, str]  # {"errorLocation": ..., "message": ...}


def _err(location: str, message: str) -> ValidationError:
    return {"errorLocation": location, "message": message}


def _format_errors(header: str, errors: List[ValidationError]) -> str:
    lines = [header]
    for e in errors:
        if e["errorLocation"]:
            lines.append(f"[Location] {e['errorLocation']}  [Error] {e['message']}")
        else:
            lines.append(e["message"])
    return "\n---\n".join(lines)


def _is_number(value) -> bool:
    """Numeric YAML scalar check; bool is an int subclass and must not pass."""
    return isinstance(value, (int, float)) and not isinstance(value, bool)


def _is_int(value) -> bool:
    """Integer YAML scalar check, excluding bool."""
    return isinstance(value, int) and not isinstance(value, bool)



# ---------------------------------------------------------------------------
# schema validation (zod-equivalent structural checks with defaults)
# ---------------------------------------------------------------------------

class _SchemaErrors(Exception):
    def __init__(self, errors: List[ValidationError]) -> None:
        super().__init__("schema validation failed")
        self.errors = errors


class _Walker:
    def __init__(self) -> None:
        self.errors: List[ValidationError] = []

    def fail(self, loc: str, message: str) -> None:
        self.errors.append(_err(loc, message))

    def strict_keys(self, obj: dict, allowed: Set[str], loc: str) -> None:
        for key in obj:
            if key not in allowed:
                self.fail(f"{loc}.{key}", f'Unrecognized key "{key}".')

    def require(self, obj: dict, key: str, kind, loc: str):
        if key not in obj:
            self.fail(f"{loc}.{key}", "Required.")
            return None
        value = obj[key]
        if kind is not None and not isinstance(value, kind):
            self.fail(f"{loc}.{key}", f"Invalid type for {key}.")
            return None
        return value

    def forbid_system_fields(self, obj: dict, loc: str) -> None:
        for field in ("uniqueServiceName", "uniqueEndpointName"):
            if obj.get(field) is not None:
                self.fail(
                    f"{loc}.{field}",
                    f"{field} is a system-generated field. It should not be provided.",
                )


def _norm_endpoint_id(value, walker: _Walker, loc: str) -> Optional[str]:
    if isinstance(value, (int, float)):
        value = str(value)
    if not isinstance(value, str) or not value.strip():
        walker.fail(loc, "endpointId cannot be empty.")
        return None
    return value.strip()


def _norm_version(value) -> str:
    if isinstance(value, (int, float)):
        return str(value)
    if value is None or (isinstance(value, str) and not value.strip()):
        return "latest"
    return str(value).strip()


def _norm_status(value, walker: _Walker, loc: str) -> Optional[str]:
    try:
        num = int(str(value))
    except (TypeError, ValueError):
        num = -1
    if not (100 <= num <= 599):
        walker.fail(loc, "Invalid status. It must be between 100 and 599.")
        return None
    return str(num)


def _walk_services_info(raw, walker: _Walker) -> List[dict]:
    if not isinstance(raw, list):
        walker.fail("servicesInfo", "Expected array.")
        return []
    namespaces = []
    for i, ns in enumerate(raw):
        loc = f"servicesInfo[{i}]"
        if not isinstance(ns, dict):
            walker.fail(loc, "Expected object.")
            continue
        walker.strict_keys(ns, {"namespace", "services"}, loc)
        namespace = walker.require(ns, "namespace", str, loc)
        services_raw = walker.require(ns, "services", list, loc) or []
        services = []
        for j, svc in enumerate(services_raw):
            sloc = f"{loc}.services[{j}]"
            if not isinstance(svc, dict):
                walker.fail(sloc, "Expected object.")
                continue
            walker.strict_keys(svc, {"serviceName", "versions"}, sloc)
            name = walker.require(svc, "serviceName", str, sloc)
            if name is not None and not name:
                walker.fail(f"{sloc}.serviceName", "service name cannot be empty.")
            versions = []
            for k, ver in enumerate(walker.require(svc, "versions", list, sloc) or []):
                vloc = f"{sloc}.versions[{k}]"
                if not isinstance(ver, dict):
                    walker.fail(vloc, "Expected object.")
                    continue
                walker.strict_keys(
                    ver,
                    {"uniqueServiceName", "version", "replica", "endpoints"},
                    vloc,
                )
                walker.forbid_system_fields(ver, vloc)
                replica = ver.get("replica", 1)
                if not _is_int(replica):
                    walker.fail(f"{vloc}.replica", "replica must be an integer.")
                    replica = 1
                elif replica < 0:
                    walker.fail(
                        f"{vloc}.replica",
                        "replica (the number of service instances) must be at "
                        "least 0 to simulate injection.",
                    )
                endpoints = []
                for m, ep in enumerate(
                    walker.require(ver, "endpoints", list, vloc) or []
                ):
                    eloc = f"{vloc}.endpoints[{m}]"
                    if not isinstance(ep, dict):
                        walker.fail(eloc, "Expected object.")
                        continue
                    walker.strict_keys(
                        ep,
                        {"uniqueEndpointName", "endpointId", "endpointInfo", "datatype"},
                        eloc,
                    )
                    walker.forbid_system_fields(ep, eloc)
                    endpoint_id = _norm_endpoint_id(
                        ep.get("endpointId"), walker, f"{eloc}.endpointId"
                    )
                    info_raw = walker.require(ep, "endpointInfo", dict, eloc) or {}
                    walker.strict_keys(info_raw, {"path", "method"}, f"{eloc}.endpointInfo")
                    path = info_raw.get("path")
                    if not isinstance(path, str) or not path:
                        walker.fail(f"{eloc}.endpointInfo.path", "path cannot not be empty.")
                        path = "/"
                    method = info_raw.get("method")
                    if not isinstance(method, str) or method.lower() not in REQUEST_TYPES:
                        walker.fail(f"{eloc}.endpointInfo.method", "Invalid method.")
                        method = "get"
                    datatype = None
                    if ep.get("datatype") is not None:
                        dt = ep["datatype"]
                        dloc = f"{eloc}.datatype"
                        if not isinstance(dt, dict):
                            walker.fail(dloc, "Expected object.")
                            dt = {}
                        walker.strict_keys(
                            dt,
                            {"requestContentType", "requestBody", "responses"},
                            dloc,
                        )
                        responses = []
                        for r, resp in enumerate(
                            walker.require(dt, "responses", list, dloc) or []
                        ):
                            rloc = f"{dloc}.responses[{r}]"
                            if not isinstance(resp, dict):
                                walker.fail(rloc, "Expected object.")
                                continue
                            walker.strict_keys(
                                resp,
                                {"status", "responseContentType", "responseBody"},
                                rloc,
                            )
                            status = _norm_status(
                                resp.get("status"), walker, f"{rloc}.status"
                            )
                            responses.append(
                                {
                                    "status": status,
                                    "responseContentType": resp.get(
                                        "responseContentType", ""
                                    ),
                                    "responseBody": str(
                                        resp.get("responseBody", "")
                                    ),
                                }
                            )
                        datatype = {
                            "requestContentType": walker.require(
                                dt, "requestContentType", str, dloc
                            )
                            or "",
                            "requestBody": str(dt.get("requestBody", "")),
                            "responses": responses,
                        }
                    endpoints.append(
                        {
                            "endpointId": endpoint_id,
                            "endpointInfo": {"path": path, "method": method},
                            "datatype": datatype,
                            "uniqueEndpointName": None,
                        }
                    )
                versions.append(
                    {
                        "version": _norm_version(ver.get("version")),
                        "replica": max(0, replica),
                        "endpoints": endpoints,
                        "uniqueServiceName": None,
                    }
                )
            services.append({"serviceName": name or "", "versions": versions})
        namespaces.append({"namespace": namespace or "", "services": services})
    return namespaces


def _walk_depend_on_entry(dep, walker: _Walker, loc: str) -> Optional[dict]:
    """Normalize one dependOn entry into {"oneOf": [...]} or a plain target."""
    if not isinstance(dep, dict):
        walker.fail(loc, "Expected object.")
        return None
    if "oneOf" in dep:
        walker.strict_keys(dep, {"oneOf"}, loc)
        members = []
        for i, one in enumerate(dep.get("oneOf") or []):
            oloc = f"{loc}.oneOf[{i}]"
            if not isinstance(one, dict):
                walker.fail(oloc, "Expected object.")
                continue
            walker.strict_keys(
                one, {"uniqueEndpointName", "endpointId", "callProbability"}, oloc
            )
            walker.forbid_system_fields(one, oloc)
            prob = one.get("callProbability")
            if not _is_number(prob):
                walker.fail(
                    oloc, "Invalid callProbability. It must be between 0 and 100."
                )
                prob = 0.0
            elif not (0 <= prob <= 100):
                walker.fail(
                    oloc, "Invalid callProbability. It must be between 0 and 100."
                )
                prob = 0.0
            members.append(
                {
                    "endpointId": _norm_endpoint_id(
                        one.get("endpointId"), walker, f"{oloc}.endpointId"
                    ),
                    "callProbability": float(prob),
                    "uniqueEndpointName": None,
                }
            )
        return {"oneOf": members}
    walker.strict_keys(
        dep, {"uniqueEndpointName", "endpointId", "callProbability"}, loc
    )
    walker.forbid_system_fields(dep, loc)
    prob = dep.get("callProbability")
    if prob is not None:
        if (
            not _is_number(prob)
            or not (0 <= prob <= 100)
        ):
            walker.fail(loc, "Invalid callProbability. It must be between 0 and 100.")
            prob = None
    return {
        "endpointId": _norm_endpoint_id(
            dep.get("endpointId"), walker, f"{loc}.endpointId"
        ),
        "callProbability": float(prob) if prob is not None else None,
        "uniqueEndpointName": None,
    }


def _walk_endpoint_dependencies(raw, walker: _Walker) -> List[dict]:
    if not isinstance(raw, list):
        walker.fail("endpointDependencies", "Expected array.")
        return []
    out = []
    for i, dep in enumerate(raw):
        loc = f"endpointDependencies[{i}]"
        if not isinstance(dep, dict):
            walker.fail(loc, "Expected object.")
            continue
        walker.strict_keys(
            dep,
            {"uniqueEndpointName", "isExternal", "endpointId", "dependOn"},
            loc,
        )
        walker.forbid_system_fields(dep, loc)
        depend_on = []
        for j, entry in enumerate(walker.require(dep, "dependOn", list, loc) or []):
            norm = _walk_depend_on_entry(entry, walker, f"{loc}.dependOn[{j}]")
            if norm is not None:
                depend_on.append(norm)
        out.append(
            {
                "endpointId": _norm_endpoint_id(
                    dep.get("endpointId"), walker, f"{loc}.endpointId"
                ),
                "isExternal": bool(dep.get("isExternal", False)),
                "dependOn": depend_on,
                "uniqueEndpointName": None,
            }
        )
    return out


def _walk_fault_targets(
    raw, walker: _Walker, loc: str, allow_endpoints: bool
) -> dict:
    targets = {"services": [], "endpoints": []}
    if not isinstance(raw, dict):
        walker.fail(loc, "Expected object.")
        return targets
    allowed = {"services"} | ({"endpoints"} if allow_endpoints else set())
    walker.strict_keys(raw, allowed, loc)
    for i, svc in enumerate(raw.get("services") or []):
        sloc = f"{loc}.services[{i}]"
        if not isinstance(svc, dict):
            walker.fail(sloc, "Expected object.")
            continue
        walker.strict_keys(
            svc, {"uniqueServiceName", "serviceName", "namespace", "version"}, sloc
        )
        walker.forbid_system_fields(svc, sloc)
        name = walker.require(svc, "serviceName", str, sloc)
        if name is not None and not name:
            walker.fail(f"{sloc}.serviceName", "serviceName cannot be empty.")
        namespace = walker.require(svc, "namespace", str, sloc)
        if namespace is not None and not namespace:
            walker.fail(f"{sloc}.namespace", "namespace cannot be empty.")
        targets["services"].append(
            {
                "serviceName": name or "",
                "namespace": namespace or "",
                "version": _norm_version(svc["version"]) if "version" in svc else None,
                "uniqueServiceName": None,
            }
        )
    for i, ep in enumerate(raw.get("endpoints") or [] if allow_endpoints else []):
        eloc = f"{loc}.endpoints[{i}]"
        if not isinstance(ep, dict):
            walker.fail(eloc, "Expected object.")
            continue
        walker.strict_keys(ep, {"uniqueEndpointName", "endpointId"}, eloc)
        walker.forbid_system_fields(ep, eloc)
        targets["endpoints"].append(
            {
                "endpointId": _norm_endpoint_id(
                    ep.get("endpointId"), walker, f"{eloc}.endpointId"
                ),
                "uniqueEndpointName": None,
            }
        )
    return targets


def _walk_time_periods(raw, walker: _Walker, loc: str) -> List[dict]:
    if not isinstance(raw, list) or not raw:
        walker.fail(loc, "At least one time period is required.")
        return []
    periods = []
    for i, tp in enumerate(raw):
        ploc = f"{loc}[{i}]"
        if not isinstance(tp, dict):
            walker.fail(ploc, "Expected object.")
            continue
        walker.strict_keys(
            tp, {"startTime", "durationHours", "probabilityPercent"}, ploc
        )
        start = tp.get("startTime")
        day, hour = 1, 0
        if not isinstance(start, dict):
            walker.fail(f"{ploc}.startTime", "Expected object.")
        else:
            day = start.get("day")
            hour = start.get("hour")
            if not _is_int(day) or not (1 <= day <= 7):
                walker.fail(f"{ploc}.startTime.day", "day must be an integer in 1..7.")
                day = 1
            if not _is_int(hour) or not (0 <= hour <= 23):
                walker.fail(f"{ploc}.startTime.hour", "hour must be an integer in 0..23.")
                hour = 0
        duration = tp.get("durationHours")
        if not _is_int(duration) or duration < 1:
            walker.fail(f"{ploc}.durationHours", "durationHours must be an integer >= 1.")
            duration = 1
        prob = tp.get("probabilityPercent", 100)
        if not _is_number(prob) or not (0 <= prob <= 100):
            walker.fail(
                f"{ploc}.probabilityPercent",
                "probabilityPercent must be between 0 and 100.",
            )
            prob = 100
        periods.append(
            {
                "startTime": {"day": day, "hour": hour},
                "durationHours": duration,
                "probabilityPercent": float(prob),
            }
        )
    return periods


_FAULT_TYPES = {
    "increase-latency",
    "increase-error-rate",
    "inject-traffic",
    "reduce-instance",
}


def _walk_faults(raw, walker: _Walker) -> List[dict]:
    faults = []
    for i, fault in enumerate(raw or []):
        loc = f"loadSimulation.faultInjection[{i}]"
        if not isinstance(fault, dict):
            walker.fail(loc, "Expected object.")
            continue
        ftype = fault.get("type")
        if ftype not in _FAULT_TYPES:
            walker.fail(f"{loc}.type", f'Invalid fault type "{ftype}".')
            continue
        allow_endpoints = ftype != "reduce-instance"
        base_keys = {"type", "targets", "timePeriods"}
        extra_keys = {
            "increase-latency": {"increaseLatencyMs"},
            "increase-error-rate": {"increaseErrorRatePercent"},
            "inject-traffic": {"increaseRequestCount", "requestMultiplier"},
            "reduce-instance": {"reduceCount"},
        }[ftype]
        walker.strict_keys(fault, base_keys | extra_keys, loc)
        out = {
            "type": ftype,
            "targets": _walk_fault_targets(
                fault.get("targets"), walker, f"{loc}.targets", allow_endpoints
            ),
            "timePeriods": _walk_time_periods(
                fault.get("timePeriods"), walker, f"{loc}.timePeriods"
            ),
        }
        if ftype == "increase-latency":
            v = fault.get("increaseLatencyMs")
            if not _is_number(v) or v < 0:
                walker.fail(f"{loc}.increaseLatencyMs", "increaseLatencyMs must be zero or greater.")
                v = 0
            out["increaseLatencyMs"] = float(v)
        elif ftype == "increase-error-rate":
            v = fault.get("increaseErrorRatePercent")
            if not _is_number(v) or not (0 <= v <= 100):
                walker.fail(
                    f"{loc}.increaseErrorRatePercent",
                    "Invalid increaseErrorRatePercent. It must be between 0 and 100.",
                )
                v = 0
            out["increaseErrorRatePercent"] = float(v)
        elif ftype == "inject-traffic":
            count = fault.get("increaseRequestCount")
            mult = fault.get("requestMultiplier")
            if (count is None) == (mult is None):
                walker.fail(
                    loc,
                    "Exactly one of the fields increaseRequestCount or "
                    "requestMultiplier must be set.",
                )
            if count is not None and (not _is_int(count) or count < 1):
                walker.fail(
                    f"{loc}.increaseRequestCount",
                    "increaseRequestCount must be at least 1.",
                )
                count = None
            if mult is not None and (
                not _is_number(mult) or mult <= 0
            ):
                walker.fail(
                    f"{loc}.requestMultiplier", "requestMultiplier must be greater than 0."
                )
                mult = None
            out["increaseRequestCount"] = count
            out["requestMultiplier"] = float(mult) if mult is not None else None
        elif ftype == "reduce-instance":
            v = fault.get("reduceCount")
            if not _is_int(v) or v < 1:
                walker.fail(f"{loc}.reduceCount", "reduceCount must be an integer >= 1.")
                v = 1
            out["reduceCount"] = v
        faults.append(out)
    return faults


def _walk_load_simulation(raw, walker: _Walker) -> Optional[dict]:
    if raw is None:
        return None
    loc = "loadSimulation"
    if not isinstance(raw, dict):
        walker.fail(loc, "Expected object.")
        return None
    walker.strict_keys(
        raw, {"config", "serviceMetrics", "endpointMetrics", "faultInjection"}, loc
    )

    config_raw = raw.get("config") or {}
    cloc = f"{loc}.config"
    if not isinstance(config_raw, dict):
        walker.fail(cloc, "Expected object.")
        config_raw = {}
    walker.strict_keys(
        config_raw,
        {"simulationDurationInDays", "overloadErrorRateIncreaseFactor"},
        cloc,
    )
    days = config_raw.get("simulationDurationInDays", 1)
    if not _is_int(days):
        walker.fail(f"{cloc}.simulationDurationInDays", "simulationDurationInDays must be an integer.")
        days = 1
    elif days < 1:
        walker.fail(f"{cloc}.simulationDurationInDays", "simulationDurationInDays must be at least 1.")
        days = 1
    elif days > MAX_SIMULATION_DAYS:
        walker.fail(
            f"{cloc}.simulationDurationInDays",
            f"simulationDurationInDays cannot exceed {MAX_SIMULATION_DAYS}.",
        )
        days = MAX_SIMULATION_DAYS
    factor = config_raw.get("overloadErrorRateIncreaseFactor", 3)
    if not _is_number(factor) or not (0 <= factor <= 10):
        walker.fail(
            f"{cloc}.overloadErrorRateIncreaseFactor",
            "Invalid overloadErrorRateIncreaseFactor. It must be between 0 and 10.",
        )
        factor = 3

    service_metrics = []
    for i, ns in enumerate(raw.get("serviceMetrics") or []):
        nloc = f"{loc}.serviceMetrics[{i}]"
        if not isinstance(ns, dict):
            walker.fail(nloc, "Expected object.")
            continue
        walker.strict_keys(ns, {"namespace", "services"}, nloc)
        services = []
        for j, svc in enumerate(ns.get("services") or []):
            sloc = f"{nloc}.services[{j}]"
            if not isinstance(svc, dict):
                walker.fail(sloc, "Expected object.")
                continue
            walker.strict_keys(svc, {"serviceName", "versions"}, sloc)
            name = walker.require(svc, "serviceName", str, sloc)
            if name is not None and not name:
                walker.fail(f"{sloc}.serviceName", "serviceName cannot be empty.")
            versions = []
            for k, ver in enumerate(svc.get("versions") or []):
                vloc = f"{sloc}.versions[{k}]"
                if not isinstance(ver, dict):
                    walker.fail(vloc, "Expected object.")
                    continue
                walker.strict_keys(
                    ver, {"uniqueServiceName", "version", "capacityPerReplica"}, vloc
                )
                walker.forbid_system_fields(ver, vloc)
                cap = ver.get("capacityPerReplica", 1)
                if not _is_number(cap) or cap < 0.01:
                    walker.fail(
                        f"{vloc}.capacityPerReplica",
                        "capacityPerReplica must be at least 0.01.",
                    )
                    cap = 1
                versions.append(
                    {
                        "version": _norm_version(ver.get("version")),
                        "capacityPerReplica": float(cap),
                        "uniqueServiceName": None,
                    }
                )
            services.append({"serviceName": name or "", "versions": versions})
        service_metrics.append({"namespace": ns.get("namespace", ""), "services": services})

    endpoint_metrics = []
    for i, metric in enumerate(raw.get("endpointMetrics") or []):
        mloc = f"{loc}.endpointMetrics[{i}]"
        if not isinstance(metric, dict):
            walker.fail(mloc, "Expected object.")
            continue
        walker.strict_keys(
            metric,
            {
                "uniqueEndpointName",
                "endpointId",
                "delay",
                "errorRatePercent",
                "expectedExternalDailyRequestCount",
                "fallbackStrategy",
            },
            mloc,
        )
        walker.forbid_system_fields(metric, mloc)
        delay_raw = metric.get("delay") or {}
        if not isinstance(delay_raw, dict):
            walker.fail(f"{mloc}.delay", "Expected object.")
            delay_raw = {}
        walker.strict_keys(delay_raw, {"latencyMs", "jitterMs"}, f"{mloc}.delay")
        latency_ms = delay_raw.get("latencyMs", 0)
        if not _is_number(latency_ms) or latency_ms < 0:
            walker.fail(f"{mloc}.delay.latencyMs", "latencyMs must be zero or greater.")
            latency_ms = 0
        jitter_ms = delay_raw.get("jitterMs", 0)
        if not _is_number(jitter_ms) or jitter_ms < 0:
            walker.fail(f"{mloc}.delay.jitterMs", "jitterMs must be zero or greater.")
            jitter_ms = 0
        error_rate = metric.get("errorRatePercent", 0)
        if not _is_number(error_rate) or not (0 <= error_rate <= 100):
            walker.fail(
                f"{mloc}.errorRatePercent",
                "Invalid errorRate. It must be between 0 and 100.",
            )
            error_rate = 0
        daily = metric.get("expectedExternalDailyRequestCount", 0)
        if not _is_int(daily):
            walker.fail(
                f"{mloc}.expectedExternalDailyRequestCount",
                "expectedExternalDailyRequestCount must be an integer.",
            )
            daily = 0
        elif daily < 0:
            walker.fail(
                f"{mloc}.expectedExternalDailyRequestCount",
                "expectedExternalDailyRequestCount cannot be negative.",
            )
            daily = 0
        fallback = metric.get("fallbackStrategy", FALLBACK_STRATEGIES[0])
        if fallback not in FALLBACK_STRATEGIES:
            walker.fail(f"{mloc}.fallbackStrategy", f'Invalid fallbackStrategy "{fallback}".')
            fallback = FALLBACK_STRATEGIES[0]
        endpoint_metrics.append(
            {
                "endpointId": _norm_endpoint_id(
                    metric.get("endpointId"), walker, f"{mloc}.endpointId"
                ),
                "delay": {"latencyMs": float(latency_ms), "jitterMs": float(jitter_ms)},
                "errorRatePercent": float(error_rate),
                "expectedExternalDailyRequestCount": daily,
                "fallbackStrategy": fallback,
                "uniqueEndpointName": None,
            }
        )

    return {
        "config": {
            "simulationDurationInDays": days,
            "overloadErrorRateIncreaseFactor": float(factor),
        },
        "serviceMetrics": service_metrics,
        "endpointMetrics": endpoint_metrics,
        "faultInjection": _walk_faults(raw.get("faultInjection"), walker),
    }


def validate_schema(raw: Any) -> Tuple[List[ValidationError], Optional[dict]]:
    """Structural validation + normalization of the parsed YAML document."""
    walker = _Walker()
    if not isinstance(raw, dict):
        return [_err("", "Top-level YAML document must be a mapping.")], None
    walker.strict_keys(
        raw, {"servicesInfo", "endpointDependencies", "loadSimulation"}, "config"
    )
    config = {
        "servicesInfo": _walk_services_info(raw.get("servicesInfo"), walker),
        "endpointDependencies": _walk_endpoint_dependencies(
            raw.get("endpointDependencies"), walker
        ),
        "loadSimulation": _walk_load_simulation(raw.get("loadSimulation"), walker),
    }
    if "servicesInfo" not in raw:
        walker.fail("servicesInfo", "Required.")
    if "endpointDependencies" not in raw:
        walker.fail("endpointDependencies", "Required.")
    if walker.errors:
        return walker.errors, None
    return [], config


# ---------------------------------------------------------------------------
# semantic validators (SimConfigValidator/*)
# ---------------------------------------------------------------------------

def validate_services_info(services_info: List[dict]) -> List[ValidationError]:
    """Duplicate service / endpointId / endpoint-path checks
    (SimConfigServicesInfoValidator.ts)."""
    errors: List[ValidationError] = []
    seen_services: Set[str] = set()
    for ns in services_info:
        for svc in ns["services"]:
            for ver in svc["versions"]:
                usn = naming.generate_unique_service_name(
                    svc["serviceName"], ns["namespace"], ver["version"]
                )
                if usn in seen_services:
                    errors.append(
                        _err(
                            f"servicesInfo > namespace: {ns['namespace']} > "
                            f"serviceName: {svc['serviceName']} > version: {ver['version']}",
                            "Duplicate service found.",
                        )
                    )
                else:
                    seen_services.add(usn)
    if errors:
        return errors

    seen_ids: Set[str] = set()
    seen_endpoint_names: Set[str] = set()
    for ns in services_info:
        for svc in ns["services"]:
            for ver in svc["versions"]:
                for ep in ver["endpoints"]:
                    loc = (
                        f"servicesInfo > namespace: {ns['namespace']} > "
                        f"serviceName: {svc['serviceName']} > version: {ver['version']} > "
                        f"endpointId: {ep['endpointId']}"
                    )
                    if ep["endpointId"] in seen_ids:
                        errors.append(_err(loc, "Duplicate endpointId found."))
                    else:
                        seen_ids.add(ep["endpointId"])
                    uen = naming.generate_unique_endpoint_name(
                        svc["serviceName"],
                        ns["namespace"],
                        ver["version"],
                        ep["endpointInfo"]["method"].upper(),
                        ep["endpointInfo"]["path"],
                    )
                    if uen in seen_endpoint_names:
                        errors.append(
                            _err(
                                loc,
                                f'The endpoint with method "{ep["endpointInfo"]["method"].upper()}" '
                                f'and path "{ep["endpointInfo"]["path"]}" has already been defined.',
                            )
                        )
                    else:
                        seen_endpoint_names.add(uen)
    return errors


def _depend_on_id_map(dependencies: List[dict]) -> Dict[str, Set[str]]:
    """endpointId -> set of target endpointIds (flattening oneOf groups)."""
    out: Dict[str, Set[str]] = {}
    for dep in dependencies:
        targets = out.setdefault(dep["endpointId"], set())
        for entry in dep["dependOn"]:
            if "oneOf" in entry:
                targets.update(one["endpointId"] for one in entry["oneOf"])
            else:
                targets.add(entry["endpointId"])
    return out


def validate_endpoint_dependencies(
    dependencies: List[dict], defined_ids: Set[str]
) -> List[ValidationError]:
    """Undefined ids, duplicates, cycles, oneOf probability sums
    (SimConfigEndpointDependenciesValidator.ts)."""
    errors: List[ValidationError] = []
    for i, dep in enumerate(dependencies):
        loc = f"endpointDependencies[{i}]"
        if dep["endpointId"] not in defined_ids:
            errors.append(
                _err(loc, f'Source endpointId "{dep["endpointId"]}" is not defined in servicesInfo.')
            )
        for j, entry in enumerate(dep["dependOn"]):
            dloc = f"{loc}.dependOn[{j}]"
            members = entry["oneOf"] if "oneOf" in entry else [entry]
            for k, one in enumerate(members):
                mloc = f"{dloc}.oneOf[{k}]" if "oneOf" in entry else dloc
                if one["endpointId"] not in defined_ids:
                    errors.append(
                        _err(
                            mloc,
                            f'Target endpointId "{one["endpointId"]}" is not defined in servicesInfo.',
                        )
                    )
    if errors:
        return errors

    seen_sources: Set[str] = set()
    for i, dep in enumerate(dependencies):
        loc = f"endpointDependencies[{i}]"
        if dep["endpointId"] in seen_sources:
            errors.append(
                _err(loc, f'Duplicate source endpointId "{dep["endpointId"]}" found.')
            )
            continue
        seen_sources.add(dep["endpointId"])
        seen_targets: Set[str] = set()
        for entry in dep["dependOn"]:
            members = entry["oneOf"] if "oneOf" in entry else [entry]
            for one in members:
                if one["endpointId"] in seen_targets:
                    errors.append(
                        _err(
                            f"{loc}.dependOn",
                            f'Duplicate endpointId "{one["endpointId"]}" found in the '
                            f'dependOn list for "{dep["endpointId"]}".',
                        )
                    )
                else:
                    seen_targets.add(one["endpointId"])
    if errors:
        return errors

    errors.extend(_check_cycles(dependencies))
    if errors:
        return errors

    for i, dep in enumerate(dependencies):
        for j, entry in enumerate(dep["dependOn"]):
            if "oneOf" in entry:
                total = sum(one["callProbability"] for one in entry["oneOf"])
                if total > 100:
                    errors.append(
                        _err(
                            f"endpointDependencies[{i}].dependOn[{j}]",
                            f'Total callProbability of oneOf group exceeds 100 for source '
                            f'endpoint "{dep["endpointId"]}". The current total is {total:g}.',
                        )
                    )
    return errors


def _check_cycles(dependencies: List[dict]) -> List[ValidationError]:
    """Cycle detection (incl. self-loops) on the id-level dependOn graph
    (SimConfigEndpointDependenciesValidator.ts checkCyclicEndpointDependencies),
    implemented iteratively so deep chains can't blow the Python stack."""
    graph = _depend_on_id_map(dependencies)
    errors: List[ValidationError] = []
    reported: Set[str] = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    parent: Dict[str, Optional[str]] = {}

    for root in graph:
        if color.get(root, WHITE) != WHITE:
            continue
        stack: List[Tuple[str, any]] = [(root, iter(sorted(graph.get(root, ()))))]
        color[root] = GRAY
        parent[root] = None
        while stack:
            node, neighbors = stack[-1]
            advanced = False
            for nxt in neighbors:
                if color.get(nxt, WHITE) == WHITE:
                    color[nxt] = GRAY
                    parent[nxt] = node
                    stack.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if color.get(nxt) == GRAY:
                    cycle = [nxt]
                    cur = node
                    while cur is not None and cur != nxt:
                        cycle.append(cur)
                        cur = parent.get(cur)
                    cycle.append(nxt)
                    cycle.reverse()
                    normalized = "->".join(sorted(set(cycle)))
                    if normalized not in reported:
                        reported.add(normalized)
                        errors.append(
                            _err(
                                "endpointDependencies",
                                "Cyclic dependency detected: " + " -> ".join(cycle),
                            )
                        )
            if not advanced:
                color[node] = BLACK
                stack.pop()
    return errors


def validate_load_simulation(
    load: dict,
    defined_ids: Set[str],
    defined_service_names: Set[str],
) -> List[ValidationError]:
    """serviceMetrics / endpointMetrics / fault-target reference checks
    (SimConfigLoadSimulationValidator.ts)."""
    errors: List[ValidationError] = []
    seen_services: Set[str] = set()
    for ns in load["serviceMetrics"]:
        for svc in ns["services"]:
            for ver in svc["versions"]:
                usn = naming.generate_unique_service_name(
                    svc["serviceName"], ns["namespace"], ver["version"]
                )
                loc = "loadSimulation.serviceMetrics"
                if usn not in defined_service_names:
                    errors.append(
                        _err(
                            loc,
                            f'service "{svc["serviceName"]}" in namespace '
                            f'"{ns["namespace"]}" with version "{ver["version"]}" is '
                            "not defined in servicesInfo.",
                        )
                    )
                elif usn in seen_services:
                    errors.append(
                        _err(
                            loc,
                            f'Duplicate service "{svc["serviceName"]}" in namespace '
                            f'"{ns["namespace"]}" with version "{ver["version"]}" found '
                            "in serviceMetrics.",
                        )
                    )
                else:
                    seen_services.add(usn)

    seen_metrics: Set[str] = set()
    for metric in load["endpointMetrics"]:
        loc = "loadSimulation.endpointMetrics"
        if metric["endpointId"] not in defined_ids:
            errors.append(
                _err(loc, f'EndpointId "{metric["endpointId"]}" is not defined in servicesInfo.')
            )
        elif metric["endpointId"] in seen_metrics:
            errors.append(
                _err(loc, f'Duplicate endpointId "{metric["endpointId"]}" found in endpointMetrics.')
            )
        else:
            seen_metrics.add(metric["endpointId"])

    for i, fault in enumerate(load["faultInjection"]):
        loc = f"loadSimulation.faultInjection[{i}]"
        for svc in fault["targets"]["services"]:
            if svc["version"] is not None:
                usn = naming.generate_unique_service_name(
                    svc["serviceName"], svc["namespace"], svc["version"]
                )
                if usn not in defined_service_names:
                    errors.append(
                        _err(
                            loc,
                            f'Service "{svc["serviceName"]}" in namespace '
                            f'"{svc["namespace"]}" with version "{svc["version"]}" is '
                            "not defined in servicesInfo.",
                        )
                    )
            else:
                prefix = naming.generate_unique_service_name_without_version(
                    svc["serviceName"], svc["namespace"]
                ) + "\t"
                if not any(name.startswith(prefix) for name in defined_service_names):
                    errors.append(
                        _err(
                            loc,
                            f'Service "{svc["serviceName"]}" in namespace '
                            f'"{svc["namespace"]}" is not defined in servicesInfo.',
                        )
                    )
        for ep in fault["targets"]["endpoints"]:
            if ep["endpointId"] not in defined_ids:
                errors.append(
                    _err(loc, f'EndpointId "{ep["endpointId"]}" is not defined in servicesInfo.')
                )
    return errors


# ---------------------------------------------------------------------------
# preprocessors (SimConfigPreprocessor/*)
# ---------------------------------------------------------------------------

def preprocess_services_info(services_info: List[dict]) -> List[ValidationError]:
    """Assign unique names and normalize JSON bodies in place
    (SimConfigServicesInfoPreprocessor.ts)."""
    errors: List[ValidationError] = []
    for ni, ns in enumerate(services_info):
        for si, svc in enumerate(ns["services"]):
            for vi, ver in enumerate(svc["versions"]):
                ver["uniqueServiceName"] = naming.generate_unique_service_name(
                    svc["serviceName"], ns["namespace"], ver["version"]
                )
                for ei, ep in enumerate(ver["endpoints"]):
                    ep["uniqueEndpointName"] = naming.generate_unique_endpoint_name(
                        svc["serviceName"],
                        ns["namespace"],
                        ver["version"],
                        ep["endpointInfo"]["method"].upper(),
                        ep["endpointInfo"]["path"],
                    )
                    dt = ep.get("datatype")
                    if not dt:
                        continue
                    loc = (
                        f"servicesInfo[{ni}].services[{si}].versions[{vi}]"
                        f".endpoints[{ei}]"
                    )
                    if dt["requestContentType"] == "application/json":
                        ok, processed, warning = bodies.preprocess_json_body(
                            dt["requestBody"]
                        )
                        if not ok:
                            errors.append(
                                _err(
                                    loc,
                                    f'Unacceptable format in requestBody of endpoint '
                                    f'"{ep["endpointId"]}": {warning}',
                                )
                            )
                        else:
                            dt["requestBody"] = processed
                    for resp in dt["responses"]:
                        if resp["responseContentType"] == "application/json":
                            ok, processed, warning = bodies.preprocess_json_body(
                                resp["responseBody"]
                            )
                            if not ok:
                                errors.append(
                                    _err(
                                        loc,
                                        f'Unacceptable format in responseBody (status: '
                                        f'{resp["status"]}) of endpoint '
                                        f'"{ep["endpointId"]}": {warning}',
                                    )
                                )
                            else:
                                resp["responseBody"] = processed
    return errors


def preprocess_endpoint_dependencies(
    dependencies: List[dict], id_to_name: Dict[str, str]
) -> List[ValidationError]:
    """Fill uniqueEndpointName on every dependency entry in place
    (SimConfigEndpointDependenciesPreprocessor.ts)."""
    errors: List[ValidationError] = []

    def assign(obj: dict, loc: str) -> None:
        obj["uniqueEndpointName"] = id_to_name.get(obj["endpointId"])
        if not obj["uniqueEndpointName"]:
            errors.append(
                _err(
                    loc,
                    f'Failed to assign uniqueEndpointName: endpointId '
                    f'"{obj["endpointId"]}" does not exist in the mapping. '
                    "(This is unexpected system error!!)",
                )
            )

    for i, dep in enumerate(dependencies):
        loc = f"endpointDependencies[{i}]"
        assign(dep, loc)
        for j, entry in enumerate(dep["dependOn"]):
            if "oneOf" in entry:
                for k, one in enumerate(entry["oneOf"]):
                    assign(one, f"{loc}.dependOn[{j}].oneOf[{k}]")
            else:
                assign(entry, f"{loc}.dependOn[{j}]")
    return errors


def preprocess_load_simulation(
    load: dict,
    id_to_name: Dict[str, str],
    service_to_endpoint_ids: Dict[str, Set[str]],
) -> List[ValidationError]:
    """Fill unique names; expand version-less fault service targets to all
    matching versions; convert fault service targets to endpoint targets
    (SimConfigLoadSimulationPreprocessor.ts)."""
    errors: List[ValidationError] = []
    for i, metric in enumerate(load["endpointMetrics"]):
        metric["uniqueEndpointName"] = id_to_name.get(metric["endpointId"])
        if not metric["uniqueEndpointName"]:
            errors.append(
                _err(
                    f"loadSimulation.endpointMetrics[{i}]",
                    f'Failed to assign uniqueEndpointName: endpointId '
                    f'"{metric["endpointId"]}" does not exist in the mapping. '
                    "(This is unexpected system error!!)",
                )
            )
    for ns in load["serviceMetrics"]:
        for svc in ns["services"]:
            for ver in svc["versions"]:
                ver["uniqueServiceName"] = naming.generate_unique_service_name(
                    svc["serviceName"], ns["namespace"], ver["version"]
                )

    for fault in load["faultInjection"]:
        # expand version-less service targets to every defined version
        expanded: List[str] = []
        seen: Set[str] = set()
        for svc in fault["targets"]["services"]:
            if svc["version"] is not None:
                usn = naming.generate_unique_service_name(
                    svc["serviceName"], svc["namespace"], svc["version"]
                )
                if usn not in seen:
                    seen.add(usn)
                    expanded.append(usn)
            else:
                prefix = naming.generate_unique_service_name_without_version(
                    svc["serviceName"], svc["namespace"]
                ) + "\t"
                for name in sorted(service_to_endpoint_ids):
                    if name.startswith(prefix) and name not in seen:
                        seen.add(name)
                        expanded.append(name)
        fault["targets"]["services"] = []
        for usn in expanded:
            service, namespace, version = naming.split_unique_service_name(usn)
            fault["targets"]["services"].append(
                {
                    "serviceName": service,
                    "namespace": namespace,
                    "version": version,
                    "uniqueServiceName": usn,
                }
            )

        # endpoint-level faults targeting services apply to every endpoint of
        # the service (SimConfigLoadSimulationPreprocessor.ts:117-140)
        if fault["type"] != "reduce-instance":
            endpoint_ids = {ep["endpointId"] for ep in fault["targets"]["endpoints"]}
            for svc in fault["targets"]["services"]:
                for endpoint_id in service_to_endpoint_ids.get(
                    svc["uniqueServiceName"], ()
                ):
                    endpoint_ids.add(endpoint_id)
            fault["targets"]["endpoints"] = [
                {"endpointId": eid, "uniqueEndpointName": id_to_name.get(eid)}
                for eid in sorted(endpoint_ids)
            ]
    return errors


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------

class SimulationConfigManager:
    """YAML-string -> validated+preprocessed config, or an error message
    (SimulationConfigManager.ts:52-107)."""

    def handle_sim_config(self, yaml_string: str) -> Tuple[str, Optional[dict]]:
        if not yaml_string.strip():
            return "", None
        try:
            raw = yaml.safe_load(yaml_string)
        except yaml.YAMLError as err:
            return (
                "Failed to handle simulation configuration file"
                f"(Unexpected error occurred):\n---\n{err}",
                None,
            )

        errors, config = validate_schema(raw)
        if errors:
            return (
                _format_errors(
                    "Failed to parse simulation configuration file:", errors
                ),
                None,
            )

        errors = self._validate_and_preprocess(config)
        if errors:
            return (
                _format_errors(
                    "Failed to validate and preprocess simulation configuration file:",
                    errors,
                ),
                None,
            )
        return "", config

    def _validate_and_preprocess(self, config: dict) -> List[ValidationError]:
        errors = validate_services_info(config["servicesInfo"])
        if errors:
            return errors
        errors = preprocess_services_info(config["servicesInfo"])
        if errors:
            return errors

        id_to_name = endpoint_id_to_unique_name_map(config["servicesInfo"])
        service_to_endpoint_ids = service_name_to_endpoint_ids_map(
            config["servicesInfo"]
        )

        errors = validate_endpoint_dependencies(
            config["endpointDependencies"], set(id_to_name)
        )
        if errors:
            return errors
        errors = preprocess_endpoint_dependencies(
            config["endpointDependencies"], id_to_name
        )
        if errors:
            return errors

        if config["loadSimulation"] is not None:
            errors = validate_load_simulation(
                config["loadSimulation"],
                set(id_to_name),
                set(service_to_endpoint_ids),
            )
            if errors:
                return errors
            errors = preprocess_load_simulation(
                config["loadSimulation"], id_to_name, service_to_endpoint_ids
            )
            if errors:
                return errors
        return []


def endpoint_id_to_unique_name_map(services_info: List[dict]) -> Dict[str, str]:
    """endpointId -> uniqueEndpointName (first definition wins,
    SimulationConfigManager.ts:159-175)."""
    out: Dict[str, str] = {}
    for ns in services_info:
        for svc in ns["services"]:
            for ver in svc["versions"]:
                for ep in ver["endpoints"]:
                    out.setdefault(ep["endpointId"], ep["uniqueEndpointName"])
    return out


def service_name_to_endpoint_ids_map(
    services_info: List[dict],
) -> Dict[str, Set[str]]:
    """uniqueServiceName -> set of endpointIds (SimulationConfigManager.ts:177-192)."""
    out: Dict[str, Set[str]] = {}
    for ns in services_info:
        for svc in ns["services"]:
            for ver in svc["versions"]:
                out[ver["uniqueServiceName"]] = {
                    ep["endpointId"] for ep in ver["endpoints"]
                }
    return out
