"""Load-simulation orchestration.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/
LoadSimulation/LoadSimulationHandler.ts: build per-slot base metrics from
the config (daily request counts distributed over 24 hourly slots with
±20% random weights, :240-302), inject faults, propagate once with base
error rates, adjust error rates for overload, propagate again with
latency, and emit per-slot combined realtime data.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from kmamiz_tpu.simulator import datagen, faults, overload, propagator
from kmamiz_tpu.simulator.dependency_builder import ProbabilityGroups
from kmamiz_tpu.simulator.slot_metrics import SlotMetrics, slot_key

TIME_SLOTS_PER_DAY = 24


def distribute_daily_request_count(
    total: int, slots: int, rng: np.random.Generator
) -> np.ndarray:
    """Split a daily total over `slots` with ±20% random weights; floors are
    topped back up to the exact total in descending-weight order
    (LoadSimulationHandler.ts:260-302)."""
    weights = 1.0 + (rng.random(slots) * 0.4 - 0.2)
    normalized = weights / weights.sum()
    counts = np.floor(normalized * total).astype(np.int64)
    diff = int(total - counts.sum())
    if diff >= 1:
        order = np.argsort(-normalized, kind="stable")
        for i in range(diff):
            counts[order[i % slots]] += 1
    return counts


def build_base_metrics_per_slot(
    load: dict,
    base_replica_counts: List[dict],
    rng: np.random.Generator,
) -> Dict[str, SlotMetrics]:
    """slotKey ("day-hour-0") -> SlotMetrics (LoadSimulationHandler.ts:133-238)."""
    days = load["config"]["simulationDurationInDays"]
    metrics_per_slot = {
        slot_key(day, hour): SlotMetrics()
        for day in range(days)
        for hour in range(TIME_SLOTS_PER_DAY)
    }
    if not load["endpointMetrics"]:
        return metrics_per_slot

    replica_map = {
        r["uniqueServiceName"]: r["replicas"] for r in base_replica_counts
    }
    capacity_map: Dict[str, float] = {}
    for ns in load["serviceMetrics"]:
        for svc in ns["services"]:
            for ver in svc["versions"]:
                if ver["uniqueServiceName"]:
                    capacity_map[ver["uniqueServiceName"]] = ver["capacityPerReplica"]

    delay_map = {
        m["uniqueEndpointName"]: (m["delay"]["latencyMs"], m["delay"]["jitterMs"])
        for m in load["endpointMetrics"]
    }
    error_map = {
        m["uniqueEndpointName"]: m["errorRatePercent"] / 100.0
        for m in load["endpointMetrics"]
    }
    counts_map = {
        m["uniqueEndpointName"]: [
            distribute_daily_request_count(
                m["expectedExternalDailyRequestCount"], TIME_SLOTS_PER_DAY, rng
            )
            for _ in range(days)
        ]
        for m in load["endpointMetrics"]
    }

    for day in range(days):
        for hour in range(TIME_SLOTS_PER_DAY):
            metrics = metrics_per_slot[slot_key(day, hour)]
            metrics.endpoint_delay = dict(delay_map)
            metrics.endpoint_error_rate = dict(error_map)
            metrics.entry_request_counts = {
                endpoint: int(day_counts[day][hour])
                for endpoint, day_counts in counts_map.items()
            }
            metrics.service_replicas = dict(replica_map)
            metrics.service_capacity_per_replica = dict(capacity_map)
    return metrics_per_slot


def generate_combined_realtime_data_map(
    load: dict,
    depend_on_groups: Dict[str, ProbabilityGroups],
    base_replica_counts: List[dict],
    base_data_map: Dict[str, dict],
    simulate_date_ms: float,
    rng: Optional[np.random.Generator] = None,
) -> Dict[str, List[dict]]:
    """Full load-simulation pipeline (LoadSimulationHandler.ts:37-131)."""
    rng = rng if rng is not None else np.random.default_rng()

    metrics_per_slot = build_base_metrics_per_slot(load, base_replica_counts, rng)

    # faults first so both propagation passes see identical conditions
    faults.inject_faults(load, metrics_per_slot, rng)

    # pass 1: expected traffic under base error rates (no latency)
    base_results = propagator.simulate_propagation(
        load["endpointMetrics"],
        depend_on_groups,
        metrics_per_slot,
        compute_latency=False,
        rng=rng,
    )

    # overload model folds measured traffic back into error rates
    overload.adjust_error_rates_by_overload(
        load["config"]["overloadErrorRateIncreaseFactor"],
        base_results,
        metrics_per_slot,
    )

    # pass 2: actual traffic with overload-adjusted errors + latency stats
    final_results = propagator.simulate_propagation(
        load["endpointMetrics"],
        depend_on_groups,
        metrics_per_slot,
        compute_latency=True,
        rng=rng,
    )

    return datagen.generate_realtime_data(
        base_data_map, final_results, simulate_date_ms
    )
