"""Vectorized traffic propagation over the dependency DAG.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/
LoadSimulation/LoadSimulationPropagator.ts, re-designed array-first: the
reference walks a recursive DFS per request id (:89-244); here the request
dimension is a vector axis and the DAG is swept twice per entry point —

  forward (topological order): per-endpoint request masks, Bernoulli
    own-error draws, and per-group dependency selection by cumulative call
    probability (one uniform draw per request per group);
  backward (reverse topological order): final success per fallback
    strategy and critical-path latency (own jittered latency + max over
    called children, LoadSimulationPropagator.ts:236-243).

Requests are processed in fixed-size chunks so memory stays bounded at
(subgraph size x chunk); statistics accumulate as (count, sum, sum-of-
squares) and finalize to the same sample mean / CV the reference computes
with Welford (:76-83,300-309).

Documented divergences from the reference (both intentional):
- A request reaching an endpoint through two parents (diamond) sees the
  endpoint's actual outcome on both paths; the reference's visited-set
  returns "assume success" to the second caller (:220-227).
- Endpoints are processed in deterministic topological order rather than
  JS Map insertion order; with seeded RNG this makes runs reproducible.
"""
from __future__ import annotations

import math
from typing import Dict, List, Sequence, Tuple

import numpy as np

from kmamiz_tpu.simulator import naming
from kmamiz_tpu.simulator.dependency_builder import ProbabilityGroups
from kmamiz_tpu.simulator.slot_metrics import SlotMetrics

FALLBACK_ANY = 0  # failIfAnyDependentFail (default)
FALLBACK_ALL = 1  # failIfAllDependentFail
FALLBACK_IGNORE = 2  # ignoreDependentFail

_FALLBACK_CODES = {
    "failIfAnyDependentFail": FALLBACK_ANY,
    "failIfAllDependentFail": FALLBACK_ALL,
    "ignoreDependentFail": FALLBACK_IGNORE,
}

DEFAULT_CHUNK = 1 << 16


class _StatsAccumulator:
    """Per-endpoint counters plus per-(endpoint, status) latency moments."""

    def __init__(self) -> None:
        self.request_count: Dict[str, int] = {}
        self.own_error: Dict[str, int] = {}
        self.downstream_error: Dict[str, int] = {}
        # (endpoint, status) -> [count, sum, sumsq]
        self.latency: Dict[Tuple[str, str], List[float]] = {}

    def add_counts(self, endpoint: str, requests: int, own: int, downstream: int) -> None:
        self.request_count[endpoint] = self.request_count.get(endpoint, 0) + requests
        self.own_error[endpoint] = self.own_error.get(endpoint, 0) + own
        self.downstream_error[endpoint] = (
            self.downstream_error.get(endpoint, 0) + downstream
        )

    def add_latency(self, endpoint: str, status: str, values: np.ndarray) -> None:
        entry = self.latency.setdefault((endpoint, status), [0, 0.0, 0.0])
        entry[0] += int(values.size)
        entry[1] += float(values.sum())
        entry[2] += float(np.square(values, dtype=np.float64).sum())

    def add_status_count(self, endpoint: str, status: str, count: int) -> None:
        if count > 0:
            entry = self.latency.setdefault((endpoint, status), [0, 0.0, 0.0])
            entry[0] += count

    def finalize(self, compute_latency: bool) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for endpoint, requests in self.request_count.items():
            out[endpoint] = {
                "requestCount": requests,
                "ownErrorCount": self.own_error.get(endpoint, 0),
                "downstreamErrorCount": self.downstream_error.get(endpoint, 0),
                "latencyStatsByStatus": {},
            }
        for (endpoint, status), (count, total, sumsq) in self.latency.items():
            stats = out.setdefault(
                endpoint,
                {
                    "requestCount": 0,
                    "ownErrorCount": 0,
                    "downstreamErrorCount": 0,
                    "latencyStatsByStatus": {},
                },
            )
            if compute_latency and count > 0:
                mean = total / count
                variance = (
                    max(0.0, (sumsq - count * mean * mean) / (count - 1))
                    if count > 1
                    else 0.0
                )
                std = math.sqrt(variance)
                cv = std / mean if mean != 0 else 0.0
                stats["latencyStatsByStatus"][status] = {"mean": mean, "cv": cv}
            else:
                stats["latencyStatsByStatus"][status] = {"mean": 0.0, "cv": 0.0}
        return out


def _reachable_topo_order(
    entry: str, groups: Dict[str, ProbabilityGroups]
) -> List[str]:
    """Topological order of the subgraph reachable from `entry` (DFS
    postorder reversed; the config validator guarantees acyclicity)."""
    order: List[str] = []
    state: Dict[str, int] = {}  # 1 = on stack, 2 = done
    stack: List[Tuple[str, int]] = [(entry, 0)]
    while stack:
        node, phase = stack.pop()
        if phase == 1:
            state[node] = 2
            order.append(node)
            continue
        if state.get(node):
            continue
        state[node] = 1
        stack.append((node, 1))
        for group in groups.get(node, ()):  # pragma: no branch
            for target, _prob in group:
                if not state.get(target):
                    stack.append((target, 0))
    order.reverse()
    return order


def _propagate_entry_chunk(
    topo: Sequence[str],
    entry: str,
    n: int,
    groups: Dict[str, ProbabilityGroups],
    error_rate: Dict[str, float],
    delay: Dict[str, Tuple[float, float]],
    replica_zero: Dict[str, bool],
    fallback: Dict[str, int],
    compute_latency: bool,
    rng: np.random.Generator,
    acc: _StatsAccumulator,
) -> None:
    """One chunk of `n` requests entering at `entry`, vectorized over the
    request axis."""
    mask: Dict[str, np.ndarray] = {name: np.zeros(n, dtype=bool) for name in topo}
    mask[entry][:] = True
    own_ok: Dict[str, np.ndarray] = {}
    own_lat: Dict[str, np.ndarray] = {}
    selections: Dict[str, List[np.ndarray]] = {}

    # forward sweep: masks, own-error draws, dependency selection
    for name in topo:
        m = mask[name]
        if replica_zero[name] or not m.any():
            continue
        ok = rng.random(n) >= error_rate[name]
        own_ok[name] = ok
        if compute_latency:
            base, jitter = delay[name]
            lat = base + (rng.random(n) * 2.0 - 1.0) * jitter
            own_lat[name] = np.maximum(0.0, lat).astype(np.float64)
        node_groups = groups.get(name, [])
        sels: List[np.ndarray] = []
        active = m & ok
        for group in node_groups:
            cum = np.cumsum([prob for _t, prob in group])
            draw = rng.random(n) * 100.0
            sel = np.searchsorted(cum, draw, side="right").astype(np.int32)
            sel[sel >= len(group)] = -1
            sel[~active] = -1  # failed/absent requests call nothing
            sels.append(sel)
            for idx, (target, _prob) in enumerate(group):
                mask[target] |= sel == idx
        selections[name] = sels

    # backward sweep: final status + critical-path latency
    final_ok: Dict[str, np.ndarray] = {}
    total_lat: Dict[str, np.ndarray] = {}
    for name in reversed(topo):
        m = mask[name]
        if replica_zero[name]:
            # reports failure upstream, latency 0, no propagation, no stats
            # (LoadSimulationPropagator.ts:112-123)
            final_ok[name] = np.zeros(n, dtype=bool)
            total_lat[name] = np.zeros(n, dtype=np.float64)
            continue
        if not m.any():
            final_ok[name] = np.zeros(n, dtype=bool)
            total_lat[name] = np.zeros(n, dtype=np.float64)
            continue
        ok = own_ok[name]
        node_groups = groups.get(name, [])
        sels = selections.get(name, [])
        strategy = fallback[name]

        if node_groups and strategy != FALLBACK_IGNORE:
            deps_ok = (
                np.ones(n, dtype=bool)
                if strategy == FALLBACK_ANY
                else np.zeros(n, dtype=bool)
            )
            for group, sel in zip(node_groups, sels):
                group_ok = np.ones(n, dtype=bool)  # NO_DEPENDENT_CALL => success
                for idx, (target, _prob) in enumerate(group):
                    chosen = sel == idx
                    if chosen.any():
                        group_ok[chosen] = final_ok[target][chosen]
                if strategy == FALLBACK_ANY:
                    deps_ok &= group_ok
                else:
                    deps_ok |= group_ok
            fin = ok & deps_ok
        else:
            fin = ok.copy()
        final_ok[name] = fin

        if compute_latency:
            lat = own_lat[name].copy()
            if node_groups:
                max_child = np.zeros(n, dtype=np.float64)
                for group, sel in zip(node_groups, sels):
                    group_lat = np.zeros(n, dtype=np.float64)
                    for idx, (target, _prob) in enumerate(group):
                        chosen = sel == idx
                        if chosen.any():
                            group_lat[chosen] = total_lat[target][chosen]
                    np.maximum(max_child, group_lat, out=max_child)
                lat[ok] += max_child[ok]  # children only called on own success
            total_lat[name] = lat

        # stats (only under the request mask)
        requests = int(m.sum())
        own_err = int((m & ~ok).sum())
        ds_err = int((m & ok & ~fin).sum())
        acc.add_counts(name, requests, own_err, ds_err)
        ok_mask = m & fin
        err_mask = m & ~fin
        if compute_latency:
            if ok_mask.any():
                acc.add_latency(name, "200", total_lat[name][ok_mask])
            if err_mask.any():
                acc.add_latency(name, "500", total_lat[name][err_mask])
        else:
            acc.add_status_count(name, "200", int(ok_mask.sum()))
            acc.add_status_count(name, "500", int(err_mask.sum()))


def simulate_propagation(
    endpoint_metrics: List[dict],
    depend_on_groups: Dict[str, ProbabilityGroups],
    metrics_per_slot: Dict[str, SlotMetrics],
    compute_latency: bool,
    rng: np.random.Generator,
    chunk_size: int = DEFAULT_CHUNK,
) -> Dict[str, Dict[str, dict]]:
    """-> slotKey -> uniqueEndpointName -> propagation stats
    (LoadSimulationPropagator.ts:32-63)."""
    fallback_by_endpoint = {
        m["uniqueEndpointName"]: _FALLBACK_CODES[m["fallbackStrategy"]]
        for m in endpoint_metrics
    }
    topo_cache: Dict[str, List[str]] = {}
    results: Dict[str, Dict[str, dict]] = {}

    for key in metrics_per_slot:
        metrics = metrics_per_slot[key]
        acc = _StatsAccumulator()
        for entry in sorted(metrics.entry_request_counts):
            # the reference's `for (i = 0; i < count; i++)` runs
            # ceil(count) times for fractional counts (traffic
            # multipliers make them common); int() truncated one
            # request off every such slot (review r5)
            count = math.ceil(metrics.get_entry_request_count(entry))
            if count <= 0:
                continue
            if entry not in topo_cache:
                topo_cache[entry] = _reachable_topo_order(entry, depend_on_groups)
            topo = topo_cache[entry]
            error_rate = {n: metrics.get_error_rate(n) for n in topo}
            delay = {n: metrics.get_delay(n) for n in topo}
            replica_zero = {
                n: metrics.get_replicas(naming.extract_unique_service_name(n)) == 0
                for n in topo
            }
            fallback = {n: fallback_by_endpoint.get(n, FALLBACK_ANY) for n in topo}
            remaining = count
            while remaining > 0:
                n = min(remaining, chunk_size)
                _propagate_entry_chunk(
                    topo,
                    entry,
                    n,
                    depend_on_groups,
                    error_rate,
                    delay,
                    replica_zero,
                    fallback,
                    compute_latency,
                    rng,
                    acc,
                )
                remaining -= n
        results[key] = acc.finalize(compute_latency)
    return results
