"""Per-time-slot dynamic metrics container.

Equivalent of TCMetricsPerTimeSlot
(/root/reference/src/MicroViSim-simulator/entities/TLoadSimulation.ts:59-206):
per-slot entry-point request counts, endpoint delay/error-rate, service
replica counts and per-replica capacity, with clamped mutators used by the
fault injector.
"""
from __future__ import annotations

from typing import Dict, Tuple


class SlotMetrics:
    def __init__(self) -> None:
        self.entry_request_counts: Dict[str, float] = {}
        self.endpoint_delay: Dict[str, Tuple[float, float]] = {}  # (latencyMs, jitterMs)
        self.endpoint_error_rate: Dict[str, float] = {}
        self.service_replicas: Dict[str, int] = {}
        self.service_capacity_per_replica: Dict[str, float] = {}

    # defaults mirror TLoadSimulation.ts:132-147
    def get_entry_request_count(self, endpoint: str) -> float:
        return self.entry_request_counts.get(endpoint, 0)

    def get_delay(self, endpoint: str) -> Tuple[float, float]:
        return self.endpoint_delay.get(endpoint, (0.0, 0.0))

    def get_error_rate(self, endpoint: str) -> float:
        return self.endpoint_error_rate.get(endpoint, 0.0)

    def get_replicas(self, service: str) -> int:
        return self.service_replicas.get(service, 1)

    def get_capacity_per_replica(self, service: str) -> float:
        return self.service_capacity_per_replica.get(service, 1.0)

    # fault-injection mutators (clamped like the reference setters)
    def add_delay(self, endpoint: str, latency_ms: float, jitter_ms: float) -> None:
        base_lat, base_jit = self.get_delay(endpoint)
        self.endpoint_delay[endpoint] = (
            max(0.0, base_lat + latency_ms),
            max(0.0, base_jit + jitter_ms),
        )

    def add_error_rate(self, endpoint: str, delta: float) -> None:
        self.endpoint_error_rate[endpoint] = max(
            0.0, self.get_error_rate(endpoint) + delta
        )

    def set_error_rate(self, endpoint: str, rate: float) -> None:
        self.endpoint_error_rate[endpoint] = max(0.0, rate)

    def add_entry_request_count(self, endpoint: str, delta: float) -> None:
        self.entry_request_counts[endpoint] = max(
            0, self.get_entry_request_count(endpoint) + delta
        )

    def multiply_entry_request_count(self, endpoint: str, factor: float) -> None:
        self.entry_request_counts[endpoint] = max(
            0, self.get_entry_request_count(endpoint) * factor
        )

    def subtract_replicas(self, service: str, count: int) -> None:
        self.service_replicas[service] = max(0, self.get_replicas(service) - count)


def slot_key(day: int, hour: int, minute: int = 0) -> str:
    return f"{day}-{hour}-{minute}"


def parse_slot_key(key: str) -> Tuple[int, int, int]:
    day, hour, minute = (int(x) for x in key.split("-"))
    return day, hour, minute
