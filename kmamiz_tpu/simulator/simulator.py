"""Top-level simulator: YAML config -> full synthetic-system dataset.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/Simulator.ts:
validates/preprocesses the config, collects sample realtime data + replica
counts per declared endpoint (so datatypes exist even with zero traffic,
:149-238), builds the endpoint-dependency records, and — when the config
declares traffic — runs the load simulation to produce per-time-slot
combined realtime data.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from kmamiz_tpu.domain.endpoint_data_type import EndpointDataType
from kmamiz_tpu.domain.endpoint_dependencies import EndpointDependencies
from kmamiz_tpu.domain.realtime import RealtimeDataList
from kmamiz_tpu.simulator import dependency_builder, load_handler
from kmamiz_tpu.simulator.config import SimulationConfigManager


@dataclass
class SimulationResult:
    validation_error_message: str = ""
    converting_error_message: str = ""
    endpoint_dependencies: List[dict] = field(default_factory=list)
    data_types: List[EndpointDataType] = field(default_factory=list)
    replica_counts: List[dict] = field(default_factory=list)
    realtime_data_per_slot: Dict[str, List[dict]] = field(default_factory=dict)


class Simulator:
    def __init__(
        self, config_manager: Optional[SimulationConfigManager] = None
    ) -> None:
        self._config_manager = config_manager or SimulationConfigManager()

    def generate_simulation_data(
        self,
        config_yaml: str,
        simulate_date_ms: float,
        existing_dependencies: Optional[List[dict]] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> SimulationResult:
        """Simulator.ts:39-127. `existing_dependencies` (if any) are merged
        into the generated dependency records like the reference merges the
        EndpointDependencies cache (:135-141)."""
        error_message, config = self._config_manager.handle_sim_config(config_yaml)
        if config is None:
            return SimulationResult(validation_error_message=error_message)

        sample = self.collect_sample_data(config["servicesInfo"], simulate_date_ms)

        dependencies, depend_on_groups = dependency_builder.build_endpoint_dependencies(
            config, simulate_date_ms
        )

        realtime_per_slot: Dict[str, List[dict]] = {}
        load = config.get("loadSimulation")
        if load and load["endpointMetrics"]:
            realtime_per_slot = load_handler.generate_combined_realtime_data_map(
                load,
                depend_on_groups,
                sample["replicaCounts"],
                sample["baseDataMap"],
                simulate_date_ms,
                rng=rng,
            )

        try:
            combined = RealtimeDataList(
                sample["sampleRealtimeData"]
            ).to_combined_realtime_data()
            data_types = combined.extract_endpoint_data_type()
            dep = EndpointDependencies(dependencies)
            if existing_dependencies:
                dep = EndpointDependencies(existing_dependencies).combine_with(dep)
            return SimulationResult(
                endpoint_dependencies=dep.to_json(),
                data_types=data_types,
                replica_counts=sample["replicaCounts"],
                realtime_data_per_slot=realtime_per_slot,
            )
        except Exception as err:  # noqa: BLE001 - Simulator.ts:113-126
            return SimulationResult(
                converting_error_message=(
                    "Failed to convert simulationRawData to simulation data:\n "
                    f"{err}"
                )
            )

    @staticmethod
    def collect_sample_data(
        services_info: List[dict], simulate_date_ms: float
    ) -> dict:
        """Per declared endpoint: replica counts, base realtime-data fields,
        and one fake realtime row per declared response status so schemas
        can be inferred without traffic (Simulator.ts:149-238)."""
        sample_rows: List[dict] = []
        replica_counts: List[dict] = []
        base_data_map: Dict[str, dict] = {}
        seen_services = set()

        for ns in services_info:
            for svc in ns["services"]:
                for ver in svc["versions"]:
                    usn = ver["uniqueServiceName"]
                    if usn in seen_services:
                        continue
                    seen_services.add(usn)
                    replica_counts.append(
                        {
                            "uniqueServiceName": usn,
                            "service": svc["serviceName"],
                            "namespace": ns["namespace"],
                            "version": ver["version"],
                            "replicas": ver["replica"],
                        }
                    )
                    for ep in ver["endpoints"]:
                        datatype = ep.get("datatype") or {}
                        base_data = {
                            "uniqueServiceName": usn,
                            "uniqueEndpointName": ep["uniqueEndpointName"],
                            "method": ep["endpointInfo"]["method"].upper(),
                            "service": svc["serviceName"],
                            "namespace": ns["namespace"],
                            "version": ver["version"],
                            "requestBody": datatype.get("requestBody"),
                            "requestContentType": datatype.get("requestContentType"),
                        }
                        responses = datatype.get("responses") or []
                        base_data_map[ep["uniqueEndpointName"]] = {
                            "baseData": base_data,
                            "responses": responses,
                        }
                        by_status = {}
                        for resp in responses:
                            # Map.set overwrites: LAST declaration of a
                            # duplicated status wins (review r5)
                            by_status[str(resp["status"])] = resp
                        for status, resp in by_status.items():
                            sample_rows.append(
                                {
                                    **base_data,
                                    "latency": 0,
                                    "timestamp": simulate_date_ms * 1000,
                                    "status": status,
                                    "responseBody": resp["responseBody"],
                                    "responseContentType": resp["responseContentType"],
                                }
                            )

        return {
            "sampleRealtimeData": sample_rows,
            "replicaCounts": replica_counts,
            "baseDataMap": base_data_map,
        }
