"""Endpoint body preprocessing for the simulation config.

Equivalent of the body half of the reference's servicesInfo preprocessor
(/root/reference/src/MicroViSim-simulator/classes/SimConfigPreprocessor/
SimConfigServicesInfoPreprocessor.ts:49-284): users may provide a request/
response body either as a JSON sample or as a TypeScript-like type
definition (`{ name: string, age: number }`); both are normalized to a
de-identified JSON sample string that the realtime pipeline can infer
schemas from.
"""
from __future__ import annotations

import json
import re
from typing import Tuple

from kmamiz_tpu.core.desensitize import (
    deidentify_sample,
    deidentify_type_definition,
)

_TYPE_DEF_RE = re.compile(r":\s*(string|number|boolean|null|any|\{|\[)", re.I)


def classify_body(body: str) -> str:
    """-> "sample" | "typeDefinition" | "empty" | "unknown"
    (SimConfigServicesInfoPreprocessor.ts:134-151)."""
    if _is_json_sample(body):
        return "sample"
    if _TYPE_DEF_RE.search(body.strip()):
        return "typeDefinition"
    if not body.strip():
        return "empty"
    return "unknown"


def _is_json_sample(body: str) -> bool:
    try:
        parsed = json.loads(body)
    except (json.JSONDecodeError, TypeError):
        return False
    return isinstance(parsed, (dict, list))


def type_definition_to_json(text: str) -> str:
    """Convert a TypeScript-like type definition into a JSON string whose
    leaves are the type names (SimConfigServicesInfoPreprocessor.ts:153-252)."""
    text = re.sub(r"\s+", " ", text).strip()
    if text.startswith("{") and text.endswith("}"):
        return "{" + _parse_properties(text[1:-1].strip()) + "}"
    return text


def _parse_properties(text: str) -> str:
    properties = []
    current = ""
    depth = 0
    for ch in text:
        if ch in "{[":
            depth += 1
        elif ch in "}]":
            depth -= 1
        if ch == "," and depth == 0:
            if current.strip():
                properties.append(_parse_property(current.strip()))
            current = ""
        else:
            current += ch
    if current.strip():
        properties.append(_parse_property(current.strip()))
    return ", ".join(properties)


def _parse_property(text: str) -> str:
    colon = text.find(":")
    if colon == -1:
        return text
    name = text[:colon].strip()
    return f'"{name}": {_parse_type(text[colon + 1:].strip())}'


def _parse_type(type_text: str) -> str:
    array_depth = 0
    base = type_text
    while base.endswith("[]"):
        array_depth += 1
        base = base[:-2]

    if base == "any" and array_depth:
        result = "[]"
        for _ in range(array_depth - 1):
            result = f"[{result}]"
        return result

    if base.startswith("{") and base.endswith("}"):
        result = type_definition_to_json(base)
    elif array_depth:
        result = f'"{base}"'
    else:
        return f'"{type_text}"'
    for _ in range(array_depth):
        result = f"[{result}]"
    return result


def preprocess_json_body(body: str) -> Tuple[bool, str, str]:
    """-> (ok, processed_body_string, warning). Normalizes a user-provided
    JSON body (sample or type definition) to a de-identified sample string
    (SimConfigServicesInfoPreprocessor.ts:91-133)."""
    kind = classify_body(body)
    try:
        if kind == "sample":
            processed = deidentify_sample(json.loads(body))
        elif kind == "typeDefinition":
            processed = deidentify_type_definition(
                json.loads(type_definition_to_json(body))
            )
        elif kind == "empty":
            processed = {}
        else:
            return (
                False,
                "",
                "Unrecognized format. Please provide a valid JSON sample or a "
                "type definition using only primitive types like string, "
                "number, or boolean (e.g., { name: string, age: number }).",
            )
        return True, json.dumps(processed, separators=(",", ":")), ""
    except (json.JSONDecodeError, ValueError) as err:
        return (
            False,
            "",
            "Failed to process input. Make sure it is valid JSON or a type "
            "definition using only primitive types like string, number, or "
            f"boolean (e.g., {{ name: string, age: number }}). err: {err}",
        )


def sample_to_user_defined_type(obj, indent_level: int = 0) -> str:
    """Inverse direction, used when exporting the live system back to a sim
    YAML: JSON sample -> type definition string (SimConfigGenerator.ts:227-264)."""
    if obj == {}:
        return "{}"
    indent = "  " * indent_level
    next_indent = "  " * (indent_level + 1)
    if isinstance(obj, list):
        if obj:
            return f"{sample_to_user_defined_type(obj[0], indent_level)}[]"
        return "any[]"
    if isinstance(obj, dict):  # non-empty: the {} case returned above
        lines = [
            f"{next_indent}{key}: {sample_to_user_defined_type(obj[key], indent_level + 1)}"
            for key in sorted(obj.keys())
        ]
        return "{\n" + ",\n".join(lines) + f"\n{indent}}}"
    if isinstance(obj, bool):
        return "boolean"
    if isinstance(obj, str):
        return "string"
    if isinstance(obj, (int, float)):
        return "number"
    return "null"
