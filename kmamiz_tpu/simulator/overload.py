"""Overload-dependent error-rate model.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/
LoadSimulation/OverloadErrorRateEstimator.ts: after the first propagation
pass measures expected per-service traffic, utilization u = RPS /
(replicas x capacityPerReplica); when u > 1 an exponential overload error
E_overload = 1 - exp(-k(u-1)) composes with the base error as
E = E_base + (1 - E_base) * E_overload (:101-142).
"""
from __future__ import annotations

import math
from typing import Dict

from kmamiz_tpu.simulator import naming
from kmamiz_tpu.simulator.slot_metrics import SlotMetrics


def estimate_error_rate_with_overload(
    request_count_per_second: float,
    replica_count: float,
    replica_max_rps: float,
    base_error_rate: float,
    overload_factor_k: float,
) -> float:
    capacity = replica_count * replica_max_rps
    if capacity == 0:
        return 1.0
    utilization = request_count_per_second / capacity
    if utilization <= 1:
        return base_error_rate
    overload = utilization - 1.0
    overload_error = 1.0 - math.exp(-overload_factor_k * overload)
    return min(1.0, base_error_rate + (1.0 - base_error_rate) * overload_error)


def adjust_error_rates_by_overload(
    overload_factor_k: float,
    propagation_results: Dict[str, Dict[str, dict]],
    metrics_per_slot: Dict[str, SlotMetrics],
) -> None:
    """Fold per-service measured traffic back into per-endpoint error rates
    in place (OverloadErrorRateEstimator.ts:8-55)."""
    for key, endpoint_stats in propagation_results.items():
        metrics = metrics_per_slot.get(key)
        if metrics is None:
            continue
        service_counts: Dict[str, float] = {}
        for endpoint, stats in endpoint_stats.items():
            service = naming.extract_unique_service_name(endpoint)
            service_counts[service] = (
                service_counts.get(service, 0.0) + stats["requestCount"]
            )
        for endpoint, base_error_rate in list(metrics.endpoint_error_rate.items()):
            service = naming.extract_unique_service_name(endpoint)
            request_count_per_second = service_counts.get(service, 0.0) / 3600.0
            metrics.set_error_rate(
                endpoint,
                estimate_error_rate_with_overload(
                    request_count_per_second,
                    metrics.get_replicas(service),
                    metrics.get_capacity_per_replica(service),
                    base_error_rate,
                    overload_factor_k,
                ),
            )
