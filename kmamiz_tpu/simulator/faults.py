"""Time-windowed fault injection.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/
LoadSimulation/FaultInjector.ts: latency-increase, error-rate-increase,
traffic-burst (add or multiply), and replica-reduction faults, each active
in one or more (day, hour) windows with an occurrence probability; windows
of the same fault that overlap combine as the union of independent events
(1 - prod(1 - p), FaultInjector.ts:108-139).
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from kmamiz_tpu.simulator.slot_metrics import SlotMetrics, slot_key


class _EndpointFault:
    __slots__ = ("latency_ms", "error_rate_percent", "request_count", "request_multiplier")

    def __init__(self) -> None:
        self.latency_ms = 0.0
        self.error_rate_percent = 0.0
        self.request_count = 0
        self.request_multiplier = 0.0


class _ServiceFault:
    __slots__ = ("reduced_replicas",)

    def __init__(self) -> None:
        self.reduced_replicas = 0


def _fault_probability_per_slot(fault: dict) -> Dict[str, float]:
    """slotKey -> occurrence probability, overlapping windows unioned."""
    grouped: Dict[str, list] = {}
    for period in fault["timePeriods"]:
        percent = period["probabilityPercent"] / 100.0
        for h in range(period["durationHours"]):
            current_hour = period["startTime"]["hour"] + h
            actual_day = period["startTime"]["day"] + current_hour // 24 - 1
            key = slot_key(actual_day, current_hour % 24)
            grouped.setdefault(key, []).append(percent)
    return {
        key: 1.0 - float(np.prod([1.0 - p for p in probs]))
        for key, probs in grouped.items()
    }


def inject_faults(
    load: dict,
    metrics_per_slot: Dict[str, SlotMetrics],
    rng: np.random.Generator,
) -> None:
    """Draw fault occurrences per slot and apply them to the slot metrics in
    place (FaultInjector.ts:5-68). Faults are injected before propagation so
    both propagation passes see identical conditions."""
    endpoint_faults: Dict[str, Dict[str, _EndpointFault]] = {
        key: {} for key in metrics_per_slot
    }
    service_faults: Dict[str, Dict[str, _ServiceFault]] = {
        key: {} for key in metrics_per_slot
    }

    for fault in load.get("faultInjection") or []:
        for key, prob in _fault_probability_per_slot(fault).items():
            if key not in metrics_per_slot or rng.random() > prob:
                continue
            if fault["type"] == "reduce-instance":
                for svc in fault["targets"]["services"]:
                    record = service_faults[key].setdefault(
                        svc["uniqueServiceName"], _ServiceFault()
                    )
                    record.reduced_replicas = max(0, fault["reduceCount"])
            else:
                for ep in fault["targets"]["endpoints"]:
                    record = endpoint_faults[key].setdefault(
                        ep["uniqueEndpointName"], _EndpointFault()
                    )
                    # later faults of the same slot overwrite, matching the
                    # reference's setter behavior (FaultInjector.ts:163-178)
                    record.latency_ms = (
                        fault["increaseLatencyMs"]
                        if fault["type"] == "increase-latency"
                        else 0.0
                    )
                    record.error_rate_percent = (
                        fault["increaseErrorRatePercent"]
                        if fault["type"] == "increase-error-rate"
                        else 0.0
                    )
                    if fault["type"] == "inject-traffic":
                        if fault.get("increaseRequestCount"):
                            record.request_count = fault["increaseRequestCount"]
                        if fault.get("requestMultiplier"):
                            record.request_multiplier = fault["requestMultiplier"]

    for key, metrics in metrics_per_slot.items():
        for endpoint, record in endpoint_faults[key].items():
            if record.latency_ms > 0:
                metrics.add_delay(endpoint, record.latency_ms, 0.0)
            if record.error_rate_percent > 0:
                metrics.add_error_rate(endpoint, record.error_rate_percent / 100.0)
            if record.request_count > 0:
                metrics.add_entry_request_count(endpoint, record.request_count)
            elif record.request_multiplier > 0:
                metrics.multiply_entry_request_count(
                    endpoint, record.request_multiplier
                )
        for service, record in service_faults[key].items():
            if record.reduced_replicas > 0:
                metrics.subtract_replicas(service, record.reduced_replicas)
