"""Simulation-config -> endpoint-dependency records + propagation maps.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/
SimEndpointDependencyBuilder.ts: builds the dependOn / dependBy adjacency,
the per-group call-probability structure used by the load propagator, and
the framework-shaped TEndpointDependency records (BFS closure over both
directions with distances, :218-288).
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Set, Tuple

from kmamiz_tpu.simulator import naming

# Probability groups: per source endpoint, a list of groups; each group is a
# list of (target uniqueEndpointName, call probability in percent). Groups
# are mutually exclusive choices; probability mass left under 100 in a group
# means "call nothing" (LoadSimulationPropagator.ts:13-29).
ProbabilityGroups = List[List[Tuple[str, float]]]


def build_dependency_maps(dependencies: List[dict]) -> dict:
    """-> {dependOnMap, dependByMap, dependOnGroups, externalIds}
    keyed by uniqueEndpointName (SimEndpointDependencyBuilder.ts:82-166)."""
    depend_on: Dict[str, Set[str]] = {}
    depend_by: Dict[str, Set[str]] = {}
    groups: Dict[str, ProbabilityGroups] = {}
    external: Set[str] = set()

    for dep in dependencies:
        source = dep["uniqueEndpointName"]
        if dep.get("isExternal"):
            external.add(source)
        on_set = depend_on.setdefault(source, set())
        group_list: ProbabilityGroups = []
        for entry in dep["dependOn"]:
            if "oneOf" in entry:
                group = []
                for one in entry["oneOf"]:
                    target = one["uniqueEndpointName"]
                    on_set.add(target)
                    depend_by.setdefault(target, set()).add(source)
                    group.append((target, float(one["callProbability"])))
                group_list.append(group)
            else:
                target = entry["uniqueEndpointName"]
                on_set.add(target)
                depend_by.setdefault(target, set()).add(source)
                prob = entry.get("callProbability")
                group_list.append([(target, 100.0 if prob is None else float(prob))])
        groups[source] = group_list

    return {
        "dependOnMap": depend_on,
        "dependByMap": depend_by,
        "dependOnGroups": groups,
        "externalIds": external,
    }


def extract_endpoint_infos(
    services_info: List[dict], timestamp_ms: float
) -> Dict[str, dict]:
    """uniqueEndpointName -> TEndpointInfo record
    (SimEndpointDependencyBuilder.ts:170-216)."""
    infos: Dict[str, dict] = {}
    seen_services: Set[str] = set()
    for ns in services_info:
        for svc in ns["services"]:
            for ver in svc["versions"]:
                usn = ver["uniqueServiceName"]
                if usn in seen_services:
                    continue
                seen_services.add(usn)
                for ep in ver["endpoints"]:
                    path = ep["endpointInfo"]["path"]
                    infos[ep["uniqueEndpointName"]] = {
                        "uniqueServiceName": usn,
                        "uniqueEndpointName": ep["uniqueEndpointName"],
                        "service": svc["serviceName"],
                        "namespace": ns["namespace"],
                        "version": ver["version"],
                        "labelName": path,
                        "url": "",
                        "host": "",
                        "path": path,
                        "port": "",
                        "method": ep["endpointInfo"]["method"].upper(),
                        "clusterName": "cluster.local",
                        "timestamp": timestamp_ms,
                    }
    return infos


def _bfs(
    start: str, graph: Dict[str, Set[str]], infos: Dict[str, dict], kind: str
) -> List[dict]:
    visited: Set[str] = {start}
    queue = deque([(start, 0)])
    result = []
    while queue:
        current, distance = queue.popleft()
        if current != start and current in infos:
            result.append(
                {"endpoint": infos[current], "distance": distance, "type": kind}
            )
        for nxt in sorted(graph.get(current, ())):
            if nxt not in visited:
                visited.add(nxt)
                queue.append((nxt, distance + 1))
    return result


def build_endpoint_dependencies(
    config: dict, timestamp_ms: float
) -> Tuple[List[dict], Dict[str, ProbabilityGroups]]:
    """-> (TEndpointDependency records, per-endpoint probability groups)
    (SimEndpointDependencyBuilder.ts:19-52)."""
    infos = extract_endpoint_infos(config["servicesInfo"], timestamp_ms)
    maps = build_dependency_maps(config["endpointDependencies"])

    records = []
    for name, info in infos.items():
        records.append(
            {
                "endpoint": info,
                "lastUsageTimestamp": timestamp_ms,
                "isDependedByExternal": name in maps["externalIds"],
                "dependingOn": _bfs(name, maps["dependOnMap"], infos, "SERVER"),
                "dependingBy": _bfs(name, maps["dependByMap"], infos, "CLIENT"),
            }
        )
    return records, maps["dependOnGroups"]
