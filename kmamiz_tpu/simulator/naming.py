"""Unique-name helpers for simulator-generated services/endpoints.

Equivalent of /root/reference/src/MicroViSim-simulator/classes/SimulatorUtils.ts:
tab-joined unique names with the simulator's fake host convention
(`http://<svc>.<ns>.svc.cluster.local<path>`, SimulatorUtils.ts:29-32).
"""
from __future__ import annotations

from typing import Tuple
from urllib.parse import urlsplit


def generate_unique_service_name(service: str, namespace: str, version: str) -> str:
    return f"{service.strip()}\t{namespace.strip()}\t{version.strip()}"


def generate_unique_service_name_without_version(service: str, namespace: str) -> str:
    return f"{service.strip()}\t{namespace.strip()}"


def split_unique_service_name(unique_service_name: str) -> Tuple[str, str, str]:
    service, namespace, version = unique_service_name.split("\t")
    return service.strip(), namespace.strip(), version.strip()


def generate_unique_endpoint_name(
    service: str, namespace: str, version: str, method_upper: str, path: str
) -> str:
    service = service.strip()
    namespace = namespace.strip()
    url = f"http://{service}.{namespace}.svc.cluster.local{path.strip()}"
    return (
        f"{service}\t{namespace}\t{version.strip()}\t{method_upper.strip()}\t{url}"
    )


def extract_unique_service_name(unique_endpoint_name: str) -> str:
    return "\t".join(unique_endpoint_name.split("\t")[:3])


def get_path_from_url(url: str) -> str:
    try:
        path = urlsplit(url).path
        return path if path else "/"
    except ValueError:
        return "/"
