"""Synthetic raw-Zipkin workload generation.

One generator shared by the bench headline (bench.py), the driver's
multi-chip dryrun (__graft_entry__.dryrun_multichip), and the parallel
tests: Istio-sidecar-shaped span groups serialized exactly like a Zipkin
`GET /api/v2/traces` response body, so the native SoA loader
(native/kmamiz_spans.cpp) and the deployed streaming route
(server/processor.DataProcessor.ingest_raw_stream) run the same code
they run in production.

Diversity is configurable because throughput claims depend on it
(VERDICT r4): `n_services`/`urls_per_service` set the intern-table and
edge cardinality the window carries. The BASELINE.json mesh shape is
1k services x 10 urls each = 10k distinct endpoints; the legacy bench
shape (200 services / 50 shared url templates) is kept for continuity.
"""
from __future__ import annotations

import json


def make_raw_window(
    n_traces: int,
    spans_per: int,
    t_start: int = 0,
    n_services: int = 200,
    n_namespaces: int = 8,
    urls_per_service: int = 0,
    n_url_templates: int = 50,
    trace_prefix: str = "w",
) -> bytes:
    """Serialized trace groups: `n_traces` chains of `spans_per` spans.

    With urls_per_service == 0 (legacy shape), every service shares the
    same `n_url_templates` url pool — endpoint diversity collapses to
    the template count. With urls_per_service > 0 (BASELINE shape),
    each service owns its own url set (distinct endpoints =
    n_services * urls_per_service) and traces walk a STRUCTURED call
    mesh: the entry service comes from the trace id and each hop calls
    one of ~32 fixed callees of the current service — per-service
    fan-out like a real mesh, not random adjacency. At the bench's
    1k-svc/10-url config and ~150k traces this yields the full 10k
    endpoints and >=100k distinct (ancestor, descendant, distance)
    edges (production cardinality for the interner, shape tables, and
    union sort).

    `trace_prefix` varies the trace ids without changing the naming
    shapes: steady-state benchmarking feeds a persistent processor
    fresh windows that dedup as new traces while every naming shape
    hits the warm interner — exactly like production windows after
    boot.
    """
    groups = []
    for t in range(t_start, t_start + n_traces):
        group = []
        svc_chain = t % n_services
        for j in range(spans_per):
            if urls_per_service:
                svc = svc_chain
                ep = (t // 7 + 3 * j) % urls_per_service
                svc_chain = (svc_chain * 31 + (t + j) % 32 + 1) % n_services
                # a service lives in ONE namespace (real meshes pin a
                # workload to its namespace); a per-hop namespace would
                # silently multiply the distinct service count
                ns = svc % n_namespaces
            else:
                svc = (t + j) % n_services
                ep = (t * 7 + j) % n_url_templates
                ns = j % n_namespaces
            group.append(
                {
                    "traceId": f"{trace_prefix}{t}",
                    "id": f"{t}-{j}",
                    "parentId": f"{t}-{j-1}" if j else None,
                    "kind": "SERVER" if j % 2 == 0 else "CLIENT",
                    "name": f"svc{svc}.ns{ns}.svc.cluster.local:80/*",
                    "timestamp": 1_700_000_000_000_000 + t * 900 + j,
                    "duration": 1000 + (t + j) % 5000,
                    "localEndpoint": {"serviceName": f"svc{svc}"},
                    "tags": {
                        "component": "proxy",
                        "http.method": "GET",
                        "http.protocol": "HTTP/1.1",
                        "http.status_code": "503" if t % 50 == 0 else "200",
                        "http.url": (
                            f"http://svc{svc}.ns{ns}"
                            f".svc.cluster.local/api/v1/ep{ep}"
                        ),
                        "istio.canonical_revision": "latest",
                        "istio.canonical_service": f"svc{svc}",
                        "istio.mesh_id": "cluster.local",
                        "istio.namespace": f"ns{ns}",
                        "response_flags": "-",
                        "upstream_cluster": "inbound|9080||",
                    },
                }
            )
        groups.append(group)
    return json.dumps(groups).encode()


def make_raw_chunks(
    n_traces: int, spans_per: int, chunks: int, **shape_kw
) -> list:
    """The same window split into `chunks` serialized pages (whole traces
    per page), the layout ingest_raw_stream consumes."""
    per = n_traces // chunks
    out = []
    start = 0
    for c in range(chunks):
        n = per if c < chunks - 1 else n_traces - start
        out.append(
            make_raw_window(n, spans_per, t_start=start, **shape_kw)
        )
        start += n
    return out
