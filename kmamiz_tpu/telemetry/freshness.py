"""Freshness plane: span-arrival -> forecast-visible latency.

Every tick stamps its window with an arrival watermark at native parse
time (processor.prepare_tick) and observes the elapsed wall when the
tick's response — the forecast-visible state — is assembled
(processor.finish_tick). The plane therefore measures end-to-end
freshness through parse/upload -> merge -> score regardless of whether
the serial tick or the graftstream micro-tick engine
(server/stream.py) drove the window; the stream engine's overlap shows
up here as the p99 dropping toward single-stage cost.

Surfaces:

- rolling percentile snapshot (`snapshot()`) — `/timings` "freshness"
  key, the scenario runner's freshness gate, and bench.py's
  `stream_freshness_ms_p99` headline;
- Prometheus: `kmamiz_freshness_ms` histogram + observation counter,
  plus scrape-time p50/p95/p99 gauges refreshed via the registry's
  callback hook (same pull-gauge idiom as telemetry/device.py).
"""
import threading
from collections import deque

from .registry import REGISTRY
from .slo import percentile

#: rolling sample window — sized like the SLO scorecard's tick window:
#: big enough for stable tails over a bench curve, small enough that a
#: burst's degradation ages out within one curve
WINDOW = 4096

_lock = threading.Lock()
_samples: deque = deque(maxlen=WINDOW)

_HIST = REGISTRY.histogram(
    "kmamiz_freshness_ms",
    "span-arrival to forecast-visible latency per tick (ms)",
)
_OBSERVED = REGISTRY.counter(
    "kmamiz_freshness_observations_total",
    "ticks that carried an arrival watermark",
)
_P50 = REGISTRY.gauge(
    "kmamiz_freshness_ms_p50", "rolling freshness p50 (ms)"
)
_P95 = REGISTRY.gauge(
    "kmamiz_freshness_ms_p95", "rolling freshness p95 (ms)"
)
_P99 = REGISTRY.gauge(
    "kmamiz_freshness_ms_p99", "rolling freshness p99 (ms)"
)


def observe(freshness_ms: float) -> None:
    """Record one tick's arrival->visible latency."""
    with _lock:
        _samples.append(float(freshness_ms))
    _HIST.observe(freshness_ms)
    _OBSERVED.inc()


def snapshot() -> dict:
    """Rolling-window percentile summary (the /timings payload shape)."""
    with _lock:
        vals = sorted(_samples)
    return {
        "samples": len(vals),
        "freshness_ms_p50": round(percentile(vals, 0.50), 3),
        "freshness_ms_p95": round(percentile(vals, 0.95), 3),
        "freshness_ms_p99": round(percentile(vals, 0.99), 3),
        "freshness_ms_max": round(vals[-1], 3) if vals else 0.0,
    }


def _refresh_gauges() -> None:
    snap = snapshot()
    _P50.set(snap["freshness_ms_p50"])
    _P95.set(snap["freshness_ms_p95"])
    _P99.set(snap["freshness_ms_p99"])


REGISTRY.register_callback(_refresh_gauges)


def reset_for_tests() -> None:
    with _lock:
        _samples.clear()
