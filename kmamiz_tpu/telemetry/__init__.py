"""graftscope: the self-tracing telemetry layer (docs/OBSERVABILITY.md).

Four parts behind one package:

- `registry`  — unified metrics registry (counters / gauges /
  fixed-bucket histograms, preallocated handles, Prometheus text
  exposition at `GET /metrics`).
- `tracing`   — per-tick span traces in a ring, exported as Zipkin v2
  JSON at `GET /debug/traces`; the processor can re-ingest its own
  export (self-trace).
- `device`    — HBM/arena residency gauges and the on-demand
  `POST /debug/profile` jax.profiler capture.
- `slo`       — the rolling SLO scorecard bench.py emits as headline
  keys and `tools/slo_report.py` gates on.
- `profiling` — graftprof: the lock-free host event ring, native
  parse/merge contention counters, device attribution, and the
  SLO-breach flight recorder (`GET /debug/graftprof`,
  tools/graftprof.py).

`KMAMIZ_TELEMETRY=0` disables span capture; the metrics registry stays
live regardless (the resilience counters and `/timings` ride on it).
"""
from .registry import REGISTRY, MetricsRegistry  # noqa: F401
from .tracing import TRACER, phase_span, telemetry_enabled  # noqa: F401
from .slo import SCORECARD, TENANTS  # noqa: F401
from . import device  # noqa: F401  (registers its scrape callback)
from . import freshness  # noqa: F401  (registers its scrape callback)
from . import profiling  # noqa: F401  (registers its scrape callback + hooks)


def reset_for_tests() -> None:
    """Zero all metric values (keeping registered handles live), drop
    buffered traces, clear the scorecard windows (process-wide and
    per-tenant, including the tenant-label slug table), and empty the
    graftprof planes (event ring, native deltas, device logs)."""
    REGISTRY.reset_for_tests()
    TRACER.reset_for_tests()
    SCORECARD.reset_for_tests()
    TENANTS.reset_for_tests()
    freshness.reset_for_tests()
    profiling.reset_for_tests()
