"""Device-plane attribution: kernel costs joined back to named programs.

Three sources, all cold-path:

- **Compile-cause log** — `core/programs.py` reports every cache-entry
  growth (a real XLA compile) via `note_compile`; the ring here keeps
  the last N causes with program name, wall stamp, and compile ms, so
  "what recompiled and when" is answerable after the fact.
- **HBM watermark timeline** — a per-tick sample of the existing device
  gauges (`telemetry/device.device_memory_stats`), ring-buffered as
  ``(tick_id, bytes_in_use, peak_bytes)`` — the flight recorder freezes
  it next to the host events.
- **jax.profiler trace join** — `parse_profile_dir` walks a capture
  directory (the `POST /debug/profile` output), aggregates device-op
  durations from the Chrome-trace/`.trace.json(.gz)` files, and joins
  ``jit_<name>`` kernels back to `core/programs.py` registry entries,
  mirrored as `kmamiz_prof_program_device_ms` gauges.
"""
from __future__ import annotations

import gzip
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..registry import REGISTRY
from . import events

_COMPILE_LOG_MAX = 256
_HBM_MAX = 1024

_lock = threading.Lock()
_compile_log: deque = deque(maxlen=_COMPILE_LOG_MAX)
_hbm: deque = deque(maxlen=_HBM_MAX)

_COMPILE_EVENTS = REGISTRY.counter(
    "kmamiz_prof_compile_events_total",
    "Compile-cause log entries recorded (program cache growth)",
)
_PROG_DEVICE_MS = REGISTRY.gauge_family(
    "kmamiz_prof_program_device_ms",
    "Per-program device time from the last joined jax.profiler capture",
    ("program",),
)


def note_compile(program: str, compiles: int, elapsed_ms: float) -> None:
    """Compile-cause hook (called by core/programs.Program.__call__ when
    the jit cache grew). Compiles are cold by definition — the wall
    stamp is fine here."""
    entry = {
        "program": program,
        "compiles": int(compiles),
        "ms": round(float(elapsed_ms), 3),
        "wall_s": round(time.time(), 3),
        "tick": events._cur_tick,
    }
    with _lock:
        _compile_log.append(entry)
    _COMPILE_EVENTS.inc()
    events.emit("compile", int(elapsed_ms * 1e6))


def compile_log() -> List[dict]:
    with _lock:
        return list(_compile_log)


def _sample_hbm(tick_id: int) -> None:
    """Per-tick HBM watermark sample (events.on_tick_end hook)."""
    from ..device import device_memory_stats

    stats = device_memory_stats()
    if not stats:
        return
    with _lock:
        _hbm.append(
            (
                int(tick_id),
                int(stats.get("bytes_in_use", 0) or 0),
                int(stats.get("peak_bytes_in_use", 0) or 0),
            )
        )


events.on_tick_end(_sample_hbm)


def hbm_timeline() -> List[List[int]]:
    """(tick_id, bytes_in_use, peak_bytes) rows, oldest first."""
    with _lock:
        return [list(row) for row in _hbm]


# -- jax.profiler trace join -------------------------------------------------


def _iter_trace_files(root: str) -> List[str]:
    """All .trace.json(.gz) files under a profiler capture directory
    (jax writes plugins/profile/<ts>/<host>.trace.json.gz)."""
    found: List[str] = []
    for dirpath, _dirs, files in os.walk(root):
        for fname in files:
            if fname.endswith(".trace.json") or fname.endswith(
                ".trace.json.gz"
            ):
                found.append(os.path.join(dirpath, fname))
    return sorted(found)


def _load_trace_events(path: str) -> List[dict]:
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8", errors="replace") as f:
                doc = json.load(f)
        else:
            with open(path, encoding="utf-8", errors="replace") as f:
                doc = json.load(f)
    except (OSError, ValueError):
        return []
    if isinstance(doc, dict):
        evs = doc.get("traceEvents", [])
        return evs if isinstance(evs, list) else []
    return doc if isinstance(doc, list) else []


def _program_names() -> List[str]:
    try:
        from kmamiz_tpu.core import programs

        return sorted(programs.all_programs().keys(), key=len, reverse=True)
    except Exception:  # noqa: BLE001 - attribution without a registry
        return []


def join_kernels_to_programs(
    kernel_us: Dict[str, float], names: Optional[List[str]] = None
) -> Dict[str, float]:
    """Fold per-kernel device microseconds onto registry program names:
    a kernel named `jit_<prog>...` (or containing `<prog>`) credits
    `<prog>`; the rest lands under `__unattributed__`. Longest program
    name wins, so `forecast_forward_v2` never miscredits
    `forecast_forward`."""
    if names is None:
        names = _program_names()
    out: Dict[str, float] = {}
    for kernel, us in kernel_us.items():
        base = kernel[4:] if kernel.startswith("jit_") else kernel
        target = "__unattributed__"
        for name in names:
            if base == name or base.startswith(name) or name in base:
                target = name
                break
        out[target] = out.get(target, 0.0) + float(us)
    return out


def parse_profile_dir(root: str) -> dict:
    """Aggregate a jax.profiler capture directory into per-program
    device ms. Tolerant of partial/foreign captures: unparseable files
    skip, unmatched kernels report as `__unattributed__`."""
    files = _iter_trace_files(root)
    kernel_us: Dict[str, float] = {}
    n_events = 0
    for path in files:
        for ev in _load_trace_events(path):
            if not isinstance(ev, dict) or ev.get("ph") != "X":
                continue
            name = ev.get("name")
            dur = ev.get("dur")
            if not name or not isinstance(dur, (int, float)):
                continue
            kernel_us[name] = kernel_us.get(name, 0.0) + float(dur)
            n_events += 1
    programs_us = join_kernels_to_programs(kernel_us)
    programs_ms = {
        name: round(us / 1000.0, 3) for name, us in sorted(programs_us.items())
    }
    for name, ms in programs_ms.items():
        if name != "__unattributed__":
            _PROG_DEVICE_MS.handle(name).set(ms)
    total_ms = round(sum(programs_us.values()) / 1000.0, 3)
    return {
        "files": len(files),
        "events": n_events,
        "total_device_ms": total_ms,
        "unattributed_ms": programs_ms.get("__unattributed__", 0.0),
        "programs": {
            k: v for k, v in programs_ms.items() if k != "__unattributed__"
        },
    }


def reset_for_tests() -> None:
    with _lock:
        _compile_log.clear()
        _hbm.clear()
