"""Native-plane attribution: the C++ parse/merge counters as telemetry.

`native/kmamiz_spans.cpp` keeps cumulative graftprof counters (per-shard
parse ns, merge lock-wait ns — the barrier skew behind the t2 merge
wall — merge queue depth, span-id claim contention, intern-table probe
stats). This module is their Python face:

- `counters()` — the raw snapshot via `native.prof_counters()` (zeros,
  never raises, when the library or symbols are absent).
- scrape-time mirror into the `kmamiz_prof_native*` registry families
  (a `register_callback` collector: the hot path never touches it).
- `poll(tick_id)` — the per-tick delta hook (events.on_tick_end): when
  parses happened since the last tick, the merge-time and lock-wait
  deltas land in the host event ring as `native-merge` /
  `native-merge-lockwait` events, making the contention wall visible in
  the same per-tick stream as the host phases.
"""
from __future__ import annotations

import threading
from typing import Dict

from ..registry import REGISTRY
from . import events

_SCALARS = (
    "parses",
    "spans",
    "merge_ns",
    "merge_lock_wait_ns",
    "merge_queue_depth_peak",
    "claim_contended",
    "intern_probes",
    "intern_hits",
)

_NATIVE = REGISTRY.gauge_family(
    "kmamiz_prof_native",
    "graftprof native parse/merge counters (cumulative)",
    ("counter",),
)
_SCALAR_HANDLES = {k: _NATIVE.handle(k) for k in _SCALARS}
_AVAILABLE = REGISTRY.gauge(
    "kmamiz_prof_native_available",
    "1 when libkmamiz_native exports the graftprof counters",
)
_SHARD = REGISTRY.gauge_family(
    "kmamiz_prof_native_shard",
    "graftprof per-shard stats of the last native parse",
    ("shard", "field"),
)

_lock = threading.Lock()
_last: Dict[str, int] = {}


def counters() -> dict:
    """Cumulative native counter snapshot; the zero snapshot (with
    available=False) when the native layer cannot serve it."""
    from kmamiz_tpu import native

    return native.prof_counters()


def _collect() -> None:
    """Scrape-time mirror into the registry (render() callback)."""
    snap = counters()
    _AVAILABLE.set(1.0 if snap.get("available") else 0.0)
    for key, handle in _SCALAR_HANDLES.items():
        handle.set(float(snap.get(key, 0)))
    for i, sh in enumerate(snap.get("shards", ())):
        for field in ("parse_ns", "wait_ns", "spans"):
            _SHARD.handle(str(i), field).set(float(sh.get(field, 0)))


REGISTRY.register_callback(_collect)


def poll(tick_id: int = 0) -> None:
    """Per-tick delta poll: emit native merge/lock-wait deltas into the
    host event ring. One ctypes snapshot per tick, nothing per span."""
    snap = counters()
    if not snap.get("available"):
        return
    with _lock:
        prev = dict(_last)
        for key in ("parses", "merge_ns", "merge_lock_wait_ns"):
            _last[key] = int(snap.get(key, 0))
    d_parses = int(snap.get("parses", 0)) - prev.get("parses", 0)
    if d_parses <= 0:
        return
    d_merge = int(snap.get("merge_ns", 0)) - prev.get("merge_ns", 0)
    d_wait = int(snap.get("merge_lock_wait_ns", 0)) - prev.get(
        "merge_lock_wait_ns", 0
    )
    if d_merge >= 0:
        events.emit("native-merge", d_merge)
    if d_wait >= 0:
        events.emit("native-merge-lockwait", d_wait)


events.on_tick_end(poll)


def reset_for_tests() -> None:
    from kmamiz_tpu import native

    with _lock:
        _last.clear()
    native.prof_reset()
