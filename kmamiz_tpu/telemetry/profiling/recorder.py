"""SLO-breach flight recorder: freeze the evidence when serving degrades.

When the tick watchdog fires, an upstream circuit breaker opens, or a
scenario gate fails, `record(trigger)` snapshots the last-N-ticks of
host events, the span-trace ring, the SLO scorecard rows (process-wide
and per-tenant), the native graftprof counters, the compile-cause log,
and the HBM watermark timeline into one JSON artifact under
``KMAMIZ_PROF_FLIGHT_DIR`` — the crash-box an operator (or the scenario
runner's stderr table) opens *after* the incident, instead of trying to
reproduce it.

Discipline: `record` never raises, debounces trigger storms
(``KMAMIZ_PROF_FLIGHT_DEBOUNCE_S``, breaker flaps would otherwise write
hundreds of artifacts), keeps bounded retention
(``KMAMIZ_PROF_FLIGHT_MAX`` newest artifacts survive), and writes
atomically (tmp + rename) so a reader never sees a torn file. Trigger
sites import this module lazily — the resilience layer must not pay for
profiling at import time.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import re
import threading
import time
from typing import Optional

from . import events

logger = logging.getLogger("kmamiz_tpu.telemetry.profiling")

ARTIFACT_KIND = "kmamiz-flight"
ARTIFACT_VERSION = 1

_lock = threading.Lock()
_last_dump_monotonic = 0.0
_seq = itertools.count(1)

_SAFE_TRIGGER = re.compile(r"[^A-Za-z0-9_.-]+")


def flight_dir() -> str:
    return os.environ.get("KMAMIZ_PROF_FLIGHT_DIR") or os.path.join(
        "kmamiz-data", "flight"
    )


def flight_ticks() -> int:
    try:
        return max(1, int(os.environ.get("KMAMIZ_PROF_FLIGHT_TICKS", "64")))
    except ValueError:
        return 64


def flight_max() -> int:
    try:
        return max(1, int(os.environ.get("KMAMIZ_PROF_FLIGHT_MAX", "16")))
    except ValueError:
        return 16


def _debounce_s() -> float:
    try:
        return max(
            0.0, float(os.environ.get("KMAMIZ_PROF_FLIGHT_DEBOUNCE_S", "5"))
        )
    except ValueError:
        return 5.0


def build_artifact(trigger: str, detail: str = "") -> dict:
    """The flight artifact dict (separate from I/O so tests and
    /debug/graftprof can inspect it without touching disk)."""
    from .. import slo, tracing
    from . import device_attr, native_counters

    keep = flight_ticks()
    return {
        "kind": ARTIFACT_KIND,
        "version": ARTIFACT_VERSION,
        "trigger": trigger,
        "detail": detail,
        "wall_s": round(time.time(), 3),
        "flight_ticks": keep,
        "events": [list(e) for e in events.snapshot(last_ticks=keep)],
        "traces": [
            {
                "traceId": tb.trace_id,
                "wallUs": tb.wall_us,
                "status": tb.status,
                "spans": [list(s) for s in tb.spans],
            }
            for tb in tracing.TRACER.traces()[-keep:]
        ],
        "scorecard": slo.SCORECARD.snapshot(),
        "tenants": slo.TENANTS.snapshot(),
        "native": native_counters.counters(),
        "compileLog": device_attr.compile_log(),
        "hbmTimeline": device_attr.hbm_timeline(),
    }


def record(
    trigger: str, detail: str = "", force: bool = False
) -> Optional[str]:
    """Dump a flight artifact; returns its path, or None when skipped
    (profiling off, debounced) or failed. NEVER raises — the trigger
    sites are the resilience layer's own failure paths."""
    try:
        return _record(trigger, detail, force)
    except Exception as exc:  # noqa: BLE001 - recorder must not re-fail a failure path
        logger.warning("flight recorder dump failed: %s", exc)
        return None


def _record(trigger: str, detail: str, force: bool) -> Optional[str]:
    global _last_dump_monotonic
    events.refresh_from_env()
    if not events.prof_enabled() and not force:
        return None
    now = time.monotonic()
    with _lock:
        if not force and (now - _last_dump_monotonic) < _debounce_s():
            return None
        _last_dump_monotonic = now
        seq = next(_seq)
    artifact = build_artifact(trigger, detail)
    out_dir = flight_dir()
    os.makedirs(out_dir, exist_ok=True)
    slug = _SAFE_TRIGGER.sub("-", trigger) or "trigger"
    fname = f"flight-{int(time.time() * 1000):013d}-{seq:04d}-{slug}.json"
    path = os.path.join(out_dir, fname)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(artifact, f, separators=(",", ":"))
    os.replace(tmp, path)
    _prune(out_dir)
    return path


def _prune(out_dir: str) -> None:
    """Bounded retention: keep the newest flight_max() artifacts (the
    timestamped names sort chronologically)."""
    try:
        names = sorted(
            n
            for n in os.listdir(out_dir)
            if n.startswith("flight-") and n.endswith(".json")
        )
    except OSError:
        return
    for stale in names[: -flight_max()] if len(names) > flight_max() else []:
        try:
            os.remove(os.path.join(out_dir, stale))
        except OSError:
            pass


def reset_for_tests() -> None:
    global _last_dump_monotonic, _seq
    with _lock:
        _last_dump_monotonic = 0.0
        _seq = itertools.count(1)
