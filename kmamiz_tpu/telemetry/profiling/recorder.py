"""SLO-breach flight recorder: freeze the evidence when serving degrades.

When the tick watchdog fires, an upstream circuit breaker opens, or a
scenario gate fails, `record(trigger)` snapshots the last-N-ticks of
host events, the span-trace ring, the SLO scorecard rows (process-wide
and per-tenant), the native graftprof counters, the compile-cause log,
and the HBM watermark timeline into one JSON artifact under
``KMAMIZ_PROF_FLIGHT_DIR`` — the crash-box an operator (or the scenario
runner's stderr table) opens *after* the incident, instead of trying to
reproduce it.

Discipline: `record` never raises, debounces trigger storms
(``KMAMIZ_PROF_FLIGHT_DEBOUNCE_S``, breaker flaps would otherwise write
hundreds of artifacts), keeps bounded retention
(``KMAMIZ_PROF_FLIGHT_MAX`` newest artifacts survive), and writes
atomically (tmp + rename) so a reader never sees a torn file. Trigger
sites import this module lazily — the resilience layer must not pay for
profiling at import time.

Sweep safety: a caller may pass ``namespace`` (the soak runner uses
``<archetype>-<seed>``) to get ``flight-<namespace>-*.json`` names with
retention AND debounce applied per namespace — two scenario cells
failing back-to-back can never evict or suppress each other's evidence
box (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import re
import threading
import time
from typing import Optional

from . import events

logger = logging.getLogger("kmamiz_tpu.telemetry.profiling")

ARTIFACT_KIND = "kmamiz-flight"
ARTIFACT_VERSION = 1

_lock = threading.Lock()
_last_dump_by_ns: dict = {}
_seq = itertools.count(1)

_SAFE_TRIGGER = re.compile(r"[^A-Za-z0-9_.-]+")
#: a legacy (un-namespaced) artifact: flight-<epoch ms>-<seq>-<slug>.json
_LEGACY_NAME = re.compile(r"^flight-\d{13}-")


def flight_dir() -> str:
    return os.environ.get("KMAMIZ_PROF_FLIGHT_DIR") or os.path.join(
        "kmamiz-data", "flight"
    )


def flight_ticks() -> int:
    try:
        return max(1, int(os.environ.get("KMAMIZ_PROF_FLIGHT_TICKS", "64")))
    except ValueError:
        return 64


def flight_max() -> int:
    try:
        return max(1, int(os.environ.get("KMAMIZ_PROF_FLIGHT_MAX", "16")))
    except ValueError:
        return 16


def _debounce_s() -> float:
    try:
        return max(
            0.0, float(os.environ.get("KMAMIZ_PROF_FLIGHT_DEBOUNCE_S", "5"))
        )
    except ValueError:
        return 5.0


def build_artifact(trigger: str, detail: str = "") -> dict:
    """The flight artifact dict (separate from I/O so tests and
    /debug/graftprof can inspect it without touching disk)."""
    from .. import slo, tracing
    from . import device_attr, native_counters

    keep = flight_ticks()
    return {
        "kind": ARTIFACT_KIND,
        "version": ARTIFACT_VERSION,
        "trigger": trigger,
        "detail": detail,
        "wall_s": round(time.time(), 3),
        "flight_ticks": keep,
        "events": [list(e) for e in events.snapshot(last_ticks=keep)],
        "traces": [
            {
                "traceId": tb.trace_id,
                "wallUs": tb.wall_us,
                "status": tb.status,
                "spans": [list(s) for s in tb.spans],
            }
            for tb in tracing.TRACER.traces()[-keep:]
        ],
        "scorecard": slo.SCORECARD.snapshot(),
        "tenants": slo.TENANTS.snapshot(),
        "native": native_counters.counters(),
        "compileLog": device_attr.compile_log(),
        "hbmTimeline": device_attr.hbm_timeline(),
    }


def record(
    trigger: str,
    detail: str = "",
    force: bool = False,
    namespace: Optional[str] = None,
) -> Optional[str]:
    """Dump a flight artifact; returns its path, or None when skipped
    (profiling off, debounced) or failed. NEVER raises — the trigger
    sites are the resilience layer's own failure paths. ``namespace``
    isolates a scenario cell's evidence: its own filename prefix, its
    own debounce clock, its own retention budget."""
    try:
        return _record(trigger, detail, force, namespace)
    except Exception as exc:  # noqa: BLE001 - recorder must not re-fail a failure path
        logger.warning("flight recorder dump failed: %s", exc)
        return None


def _safe_namespace(namespace: Optional[str]) -> Optional[str]:
    if namespace is None:
        return None
    ns = _SAFE_TRIGGER.sub("-", str(namespace)).strip("-")
    # a purely-numeric namespace could collide with the legacy
    # epoch-ms name pattern; anchor it with a letter
    return f"ns-{ns}" if not ns or ns.isdigit() else ns


def _record(
    trigger: str, detail: str, force: bool, namespace: Optional[str]
) -> Optional[str]:
    events.refresh_from_env()
    if not events.prof_enabled() and not force:
        return None
    ns = _safe_namespace(namespace)
    now = time.monotonic()
    with _lock:
        last = _last_dump_by_ns.get(ns, 0.0)
        if not force and (now - last) < _debounce_s():
            return None
        _last_dump_by_ns[ns] = now
        seq = next(_seq)
    artifact = build_artifact(trigger, detail)
    if ns is not None:
        artifact["namespace"] = ns
    out_dir = flight_dir()
    os.makedirs(out_dir, exist_ok=True)
    slug = _SAFE_TRIGGER.sub("-", trigger) or "trigger"
    stamp = f"{int(time.time() * 1000):013d}-{seq:04d}-{slug}.json"
    fname = f"flight-{ns}-{stamp}" if ns is not None else f"flight-{stamp}"
    path = os.path.join(out_dir, fname)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(artifact, f, separators=(",", ":"))
    os.replace(tmp, path)
    _prune(out_dir, ns)
    return path


def _prune(out_dir: str, namespace: Optional[str] = None) -> None:
    """Bounded retention PER NAMESPACE: keep the newest flight_max()
    artifacts of this record's namespace (timestamped names sort
    chronologically within one namespace). Legacy un-namespaced
    artifacts form their own retention group, so a sweep's per-cell
    evidence never evicts an operator's ad-hoc dumps (or vice versa)."""
    if namespace is None:
        def mine(name: str) -> bool:
            return bool(_LEGACY_NAME.match(name))
    else:
        prefix = f"flight-{namespace}-"

        def mine(name: str) -> bool:
            return name.startswith(prefix) and bool(
                _LEGACY_NAME.match("flight-" + name[len(prefix):])
            )

    try:
        names = sorted(
            n
            for n in os.listdir(out_dir)
            if n.startswith("flight-") and n.endswith(".json") and mine(n)
        )
    except OSError:
        return
    for stale in names[: -flight_max()] if len(names) > flight_max() else []:
        try:
            os.remove(os.path.join(out_dir, stale))
        except OSError:
            pass


def reset_for_tests() -> None:
    global _seq
    with _lock:
        _last_dump_by_ns.clear()
        _seq = itertools.count(1)
