"""graftprof: continuous hot-path profiling for the serving plane.

Three attribution planes plus a crash-box, all bounded and all
off-hot-path:

- `events` — the lock-free host event ring (per-phase 4-tuples) and the
  sanctioned hot-path clocks.
- `native_counters` — the C++ parse/merge contention counters
  (per-shard parse ns, merge lock-wait ns, claim contention, intern
  probe stats) surfaced as registry families and per-tick ring deltas.
- `device_attr` — compile-cause log, HBM watermark timeline, and the
  jax.profiler capture join back to named programs.
- `recorder` — the SLO-breach flight recorder (watchdog trip, breaker
  open, scenario gate failure freeze the last-N-ticks of evidence).
- `report` — profile condensation, text rendering, and per-phase
  regression diffing (tools/graftprof.py, /debug/graftprof).
"""
from __future__ import annotations

from . import device_attr, events, native_counters, recorder, report

__all__ = [
    "device_attr",
    "events",
    "native_counters",
    "recorder",
    "report",
    "reset_for_tests",
]


def reset_for_tests() -> None:
    """Clear every graftprof plane (wired into telemetry.reset_for_tests)."""
    events.reset_for_tests()
    native_counters.reset_for_tests()
    device_attr.reset_for_tests()
    recorder.reset_for_tests()
