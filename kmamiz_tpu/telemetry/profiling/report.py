"""graftprof profiles: build, render, and diff per-phase attributions.

A *profile* is the analysis-ready condensation of the raw planes (host
event ring, native counters, device logs): per-phase latency stats,
the tick-wall attribution ratio (how much of dp_tick wall time the
named phases explain), the native shard table, and the device plane.
`tools/graftprof.py` renders one as text and `diff`s two with per-phase
regression thresholds; `GET /debug/graftprof` serves the live one.

Accepted inputs everywhere: a profile dict (kind "kmamiz-graftprof")
or a flight-recorder artifact (kind "kmamiz-flight") — the latter is
condensed on the fly, so the crash-box and the profiler share one
report path.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..slo import percentile
from . import events as events_mod
from .events import NATIVE_EVENTS, ROOT_EVENTS
from .recorder import ARTIFACT_KIND

PROFILE_KIND = "kmamiz-graftprof"
PROFILE_VERSION = 1

# events that overlap host phases (native deltas ride inside the parse/
# merge spans; compiles ride inside whatever phase triggered them; the
# freshness watermark spans the whole arrival->visible window) — they
# inform but must not double-count in the attribution sum
_NON_ATTRIBUTED = set(NATIVE_EVENTS) | {"compile", "freshness"}

#: per-phase relative regression thresholds for diff(); phases not
#: listed use "default". merge/lock-wait get headroom — they are the
#: quantities under active rework (ROADMAP item 1) and jitter most.
DEFAULT_THRESHOLDS: Dict[str, float] = {
    "default": 0.25,
    "merge": 0.35,
    "native-merge": 0.35,
    "native-merge-lockwait": 0.50,
    # graftpilot's fold-boundary decision recompute: tiny host-side
    # work whose absolute cost jitters, so a looser relative bar
    "control-decide": 0.50,
}
_DIFF_ABS_SLACK_MS = 0.5


def _phase_stats(durs_ms: List[float]) -> dict:
    vals = sorted(durs_ms)
    return {
        "count": len(vals),
        "total_ms": round(sum(vals), 3),
        "p50_ms": round(percentile(vals, 0.50), 3),
        "p95_ms": round(percentile(vals, 0.95), 3),
        "max_ms": round(vals[-1] if vals else 0.0, 3),
    }


def _native_section(native: dict) -> dict:
    shards = []
    for i, sh in enumerate(native.get("shards", ())):
        shards.append(
            {
                "shard": i,
                "parse_ms": round(sh.get("parse_ns", 0) / 1e6, 3),
                "lock_wait_ms": round(sh.get("wait_ns", 0) / 1e6, 3),
                "spans": int(sh.get("spans", 0)),
            }
        )
    probes = int(native.get("intern_probes", 0))
    hits = int(native.get("intern_hits", 0))
    return {
        "available": bool(native.get("available")),
        "parses": int(native.get("parses", 0)),
        "spans": int(native.get("spans", 0)),
        "merge_ms": round(native.get("merge_ns", 0) / 1e6, 3),
        "merge_lock_wait_ms": round(
            native.get("merge_lock_wait_ns", 0) / 1e6, 3
        ),
        "merge_queue_depth_peak": int(
            native.get("merge_queue_depth_peak", 0)
        ),
        "claim_contended": int(native.get("claim_contended", 0)),
        "intern_probes": probes,
        "intern_hits": hits,
        "intern_hit_rate": round(hits / probes, 4) if probes else 0.0,
        "shards": shards,
    }


def build_profile(
    event_rows: Optional[List[Tuple[str, int, int, int]]] = None,
    native: Optional[dict] = None,
    compile_log: Optional[List[dict]] = None,
    hbm_timeline: Optional[List[List[int]]] = None,
) -> dict:
    """Condense raw planes into a profile. With no arguments, reads the
    live process state (the /debug/graftprof payload)."""
    if event_rows is None:
        event_rows = events_mod.snapshot()
    if native is None:
        from . import native_counters

        native = native_counters.counters()
    if compile_log is None or hbm_timeline is None:
        from . import device_attr

        if compile_log is None:
            compile_log = device_attr.compile_log()
        if hbm_timeline is None:
            hbm_timeline = device_attr.hbm_timeline()

    # per-tick attribution: root events carry the tick wall; phase
    # events of the same tick id explain it (capped at the root — nested
    # spans must not push a tick past 100%)
    root_by_tick: Dict[int, float] = {}
    phases_by_tick: Dict[int, float] = {}
    phase_durs: Dict[str, List[float]] = {}
    for name, tick, _end_ns, dur_ns in event_rows:
        ms = dur_ns / 1e6
        phase_durs.setdefault(name, []).append(ms)
        if name in ROOT_EVENTS:
            root_by_tick[tick] = root_by_tick.get(tick, 0.0) + ms
        elif name not in _NON_ATTRIBUTED:
            phases_by_tick[tick] = phases_by_tick.get(tick, 0.0) + ms
    wall_ms = sum(root_by_tick.values())
    attributed_ms = sum(
        min(root, phases_by_tick.get(tick, 0.0))
        for tick, root in root_by_tick.items()
    )
    return {
        "kind": PROFILE_KIND,
        "version": PROFILE_VERSION,
        "ticks": len(root_by_tick),
        "wall_ms": round(wall_ms, 3),
        "attributed_ms": round(attributed_ms, 3),
        "attribution_ratio": (
            round(attributed_ms / wall_ms, 4) if wall_ms > 0 else 0.0
        ),
        "phases": {
            name: _phase_stats(durs)
            for name, durs in sorted(phase_durs.items())
        },
        "native": _native_section(native),
        "device": {
            "compileLog": compile_log,
            "hbmTimeline": hbm_timeline,
        },
    }


def from_any(doc: dict) -> dict:
    """A profile from either artifact kind (profile pass-through,
    flight-recorder condensation)."""
    if not isinstance(doc, dict):
        raise ValueError("not a graftprof artifact (expected a JSON object)")
    kind = doc.get("kind")
    if kind == PROFILE_KIND:
        return doc
    if kind == ARTIFACT_KIND:
        return build_profile(
            event_rows=[tuple(e) for e in doc.get("events", [])],
            native=doc.get("native", {}),
            compile_log=doc.get("compileLog", []),
            hbm_timeline=doc.get("hbmTimeline", []),
        )
    raise ValueError(f"unrecognized artifact kind: {kind!r}")


def render(profile: dict) -> str:
    """Per-phase text report (tools/graftprof.py)."""
    p = profile
    lines = [
        f"graftprof — {p.get('ticks', 0)} tick(s), "
        f"{p.get('wall_ms', 0.0):.1f} ms wall, "
        f"{p.get('attribution_ratio', 0.0) * 100:.1f}% attributed "
        f"({p.get('attributed_ms', 0.0):.1f} ms in named phases)",
        "",
        f"  {'phase':<24} {'count':>6} {'total_ms':>10} {'p50_ms':>9} "
        f"{'p95_ms':>9} {'max_ms':>9}",
    ]
    for name, st in sorted(
        p.get("phases", {}).items(),
        key=lambda kv: -kv[1].get("total_ms", 0.0),
    ):
        lines.append(
            f"  {name:<24} {st.get('count', 0):>6} "
            f"{st.get('total_ms', 0.0):>10.2f} {st.get('p50_ms', 0.0):>9.2f} "
            f"{st.get('p95_ms', 0.0):>9.2f} {st.get('max_ms', 0.0):>9.2f}"
        )
    nat = p.get("native", {})
    lines.append("")
    if nat.get("available"):
        lines.append(
            f"native: {nat.get('parses', 0)} parse(s), "
            f"{nat.get('spans', 0)} spans, merge {nat.get('merge_ms', 0.0)} ms, "
            f"lock-wait {nat.get('merge_lock_wait_ms', 0.0)} ms, "
            f"queue-depth peak {nat.get('merge_queue_depth_peak', 0)}, "
            f"claim contended {nat.get('claim_contended', 0)}, "
            f"intern hit-rate {nat.get('intern_hit_rate', 0.0)}"
        )
        for sh in nat.get("shards", ()):
            lines.append(
                f"  shard {sh['shard']}: parse {sh['parse_ms']:.2f} ms, "
                f"lock-wait {sh['lock_wait_ms']:.2f} ms, "
                f"{sh['spans']} spans"
            )
    else:
        lines.append("native: counters unavailable (pure-Python fallback)")
    dev = p.get("device", {})
    clog = dev.get("compileLog", [])
    lines.append(
        f"device: {len(clog)} compile cause(s), "
        f"{len(dev.get('hbmTimeline', []))} HBM watermark sample(s)"
    )
    for entry in clog[-5:]:
        lines.append(
            f"  compile {entry.get('program')} x{entry.get('compiles')} "
            f"({entry.get('ms')} ms, tick {entry.get('tick')})"
        )
    return "\n".join(lines)


def diff(
    baseline: dict,
    candidate: dict,
    thresholds: Optional[Dict[str, float]] = None,
    abs_slack_ms: float = _DIFF_ABS_SLACK_MS,
) -> List[dict]:
    """Per-phase p95 regressions of candidate vs baseline: one row per
    phase whose candidate p95 exceeds baseline p95 by more than the
    phase's relative threshold plus the absolute slack."""
    thresholds = dict(DEFAULT_THRESHOLDS, **(thresholds or {}))
    base = from_any(baseline).get("phases", {})
    cand = from_any(candidate).get("phases", {})
    regressions: List[dict] = []
    for name in sorted(set(base) & set(cand)):
        old = float(base[name].get("p95_ms", 0.0))
        new = float(cand[name].get("p95_ms", 0.0))
        rel = thresholds.get(name, thresholds["default"])
        if new > old * (1.0 + rel) + abs_slack_ms:
            regressions.append(
                {
                    "phase": name,
                    "baseline_p95_ms": old,
                    "candidate_p95_ms": new,
                    "threshold": rel,
                    "ratio": round(new / old, 3) if old > 0 else float("inf"),
                }
            )
    return regressions
