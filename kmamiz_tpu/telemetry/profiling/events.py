"""graftprof host event ring: lock-free per-phase time attribution.

The continuous profiler's first plane: every tick phase (and every
native parse/merge delta, via the per-tick hooks) appends one 4-tuple
event — ``(name, tick_id, end_ns, dur_ns)`` — into a PREALLOCATED ring,
mirroring the tracing.py builder discipline. An append is one
``itertools.count`` bump (GIL-atomic) plus one slot store; there is no
lock, no allocation beyond the tuple, and no formatting on the hot
path. Readers (`snapshot`, the flight recorder, `/debug/graftprof`)
tolerate in-flight overwrites — an event ring is telemetry, not a WAL.

Gate: ``KMAMIZ_PROF`` (default ON), re-read once per tick by
`note_tick_start` — never per event — so tests and operators flip it
without a restart and the disabled cost is one module-bool check.
Ring capacity: ``KMAMIZ_PROF_RING`` (default 4096 events).

This module also exports the sanctioned hot-path clocks `now_ns` /
`now_ms` / `wall_ms`: the graftlint rule `hot-path-clock` flags raw
``time.time()`` / ``time.perf_counter()`` reads in hot functions, and
these helpers are the one blessed detour (every hot clock read stays
greppable and swappable in one place).
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..slo import percentile

Event = Tuple[str, int, int, int]  # (name, tick_id, end_ns, dur_ns)

_DEFAULT_RING = 4096

# root-event names: the per-tick wall-clock denominators of the
# attribution report (report.py) — everything else is an attributed phase
ROOT_EVENTS = ("dp-tick", "dp-ingest")
# native counter-delta events (native_counters.poll): they overlap the
# host phase spans that contain them, so attribution must NOT sum them
NATIVE_EVENTS = ("native-merge", "native-merge-lockwait")


# -- sanctioned hot-path clocks ---------------------------------------------


def now_ns() -> int:
    """Monotonic ns — THE hot-path clock (graftlint: hot-path-clock)."""
    return time.perf_counter_ns()


def now_ms() -> float:
    """Monotonic ms for hot-path wall accounting."""
    return time.perf_counter() * 1000.0


def wall_ms() -> float:
    """Epoch ms for hot-path domain stamps (dedup windows, stale age)."""
    return time.time() * 1000.0


# -- the ring ----------------------------------------------------------------


def _ring_size() -> int:
    try:
        return max(64, int(os.environ.get("KMAMIZ_PROF_RING", str(_DEFAULT_RING))))
    except ValueError:
        return _DEFAULT_RING


_enabled = os.environ.get("KMAMIZ_PROF", "1") not in ("0", "false", "")
_ring: List[Optional[Event]] = [None] * _ring_size()
_idx = itertools.count()
_tick_seq = itertools.count(1)
_cur_tick = 0

_hook_lock = threading.Lock()
_tick_end_hooks: List[Callable[[int], None]] = []


def prof_enabled() -> bool:
    """The cached KMAMIZ_PROF gate (refreshed per tick, default ON)."""
    return _enabled


def refresh_from_env() -> None:
    """Re-read KMAMIZ_PROF. Called once per tick by note_tick_start."""
    global _enabled
    _enabled = os.environ.get("KMAMIZ_PROF", "1") not in ("0", "false", "")


def emit(name: str, dur_ns: int) -> None:
    """Append one event (hot path: one counter bump + one slot store)."""
    if not _enabled:
        return
    ring = _ring
    ring[next(_idx) % len(ring)] = (
        name,
        _cur_tick,
        time.perf_counter_ns(),
        int(dur_ns),
    )


def on_tick_end(fn: Callable[[int], None]) -> None:
    """Register a per-tick hook (native counter poll, HBM sample). Runs
    at tick close only — never per event."""
    with _hook_lock:
        if fn not in _tick_end_hooks:
            _tick_end_hooks.append(fn)


def note_tick_start() -> int:
    """Open a tick: refresh the env gate, advance the tick id."""
    global _cur_tick
    refresh_from_env()
    if _enabled:
        _cur_tick = next(_tick_seq)
    return _cur_tick


def note_tick_end(root_name: str, dur_ns: int) -> None:
    """Close a tick: emit its root event, run the per-tick hooks."""
    if not _enabled:
        return
    emit(root_name, dur_ns)
    with _hook_lock:
        hooks = list(_tick_end_hooks)
    for fn in hooks:
        try:
            fn(_cur_tick)
        except Exception:  # noqa: BLE001 - a broken hook must not break ticks
            pass


# -- cold-path readers -------------------------------------------------------


def snapshot(last_ticks: Optional[int] = None) -> List[Event]:
    """The ring's events, oldest first; optionally only the last N tick
    ids (the flight recorder's freeze window)."""
    evs = [e for e in list(_ring) if e is not None]
    evs.sort(key=lambda e: e[2])
    if last_ticks and evs:
        hi = max(e[1] for e in evs)
        lo = hi - int(last_ticks) + 1
        evs = [e for e in evs if e[1] >= lo]
    return evs


def phase_durations_ms(
    events: Optional[List[Event]] = None,
) -> Dict[str, List[float]]:
    """Per-name duration samples (ms) from the ring (or a given list)."""
    out: Dict[str, List[float]] = {}
    for name, _tick, _end, dur_ns in (
        events if events is not None else snapshot()
    ):
        out.setdefault(name, []).append(dur_ns / 1e6)
    return out


def phase_p95_ms(name: str) -> float:
    """p95 of one phase's ring samples (0.0 when absent) — the bench's
    always-present `prof_*_ms_p95` keys read this."""
    durs = sorted(phase_durations_ms().get(name, []))
    return round(percentile(durs, 0.95), 3)


def phase_percentile_ms(name: str, q: float) -> float:
    """Arbitrary-quantile variant of phase_p95_ms — the freshness plane
    gates on p99 (`prof_freshness_ms_p99`), not the per-phase p95."""
    durs = sorted(phase_durations_ms().get(name, []))
    return round(percentile(durs, q), 3)


def reset_for_tests() -> None:
    global _ring, _idx, _tick_seq, _cur_tick
    _ring = [None] * _ring_size()
    _idx = itertools.count()
    _tick_seq = itertools.count(1)
    _cur_tick = 0
    refresh_from_env()
