"""Fleet scrape aggregation: N worker /metrics pages -> one exposition.

Each fleet worker process runs its own unified registry and serves its
own Prometheus text page; a fleet deployment wants ONE scrape target.
This module merges worker pages sample-by-sample — counters and sums
add, every series also re-emits per worker under a ``worker`` label so
the grafana fleet row can chart per-worker spans/s next to the fleet
total — without importing any worker state: input is the exposition
text itself, so the aggregator works identically over HTTP-scraped
subprocess workers and in-process test fixtures.

Histogram series aggregate soundly under addition (bucket counts, sums,
and counts are all counters); gauges add too, which is the correct
fleet semantics for the occupancy-style gauges the registry exports
(queue depths, arena buckets) — a fleet-wide depth IS the sum.
"""
from __future__ import annotations

import re
from typing import Dict, List, Tuple

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)\s*$"
)


def parse_exposition(text: str) -> List[Tuple[str, str, float]]:
    """(metric name, label body, value) samples from one exposition
    page. Comment/HELP/TYPE lines and malformed samples are skipped —
    the aggregator must survive a worker mid-restart serving a torn
    page."""
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        samples.append((m.group("name"), m.group("labels") or "", value))
    return samples


def _with_worker_label(labels: str, worker: str) -> str:
    tag = f'worker="{worker}"'
    return f"{labels},{tag}" if labels else tag


def aggregate(pages: Dict[str, str]) -> Dict[str, Dict[str, float]]:
    """{metric: {label body: value}} summed across worker pages, plus
    the per-worker breakdown under an added ``worker`` label. ``pages``
    maps worker id -> that worker's exposition text."""
    merged: Dict[str, Dict[str, float]] = {}
    for worker in sorted(pages):
        for name, labels, value in parse_exposition(pages[worker]):
            series = merged.setdefault(name, {})
            series[labels] = series.get(labels, 0.0) + value
            per_worker = _with_worker_label(labels, worker)
            series[per_worker] = series.get(per_worker, 0.0) + value
    return merged


def render(pages: Dict[str, str]) -> str:
    """One merged exposition page (fleet totals + per-worker series).
    HELP/TYPE metadata is intentionally dropped: the upstream pages
    disagree on nothing but sample values, and a scraper that wants
    metadata reads any single worker."""
    merged = aggregate(pages)
    out: List[str] = []
    for name in sorted(merged):
        for labels in sorted(merged[name]):
            suffix = f"{{{labels}}}" if labels else ""
            value = merged[name][labels]
            rendered = repr(value) if value != int(value) else str(int(value))
            out.append(f"{name}{suffix} {rendered}")
    return "\n".join(out) + ("\n" if out else "")


def spans_per_worker(
    pages: Dict[str, str], metric: str = "kmamiz_ingest_payloads_total"
) -> Dict[str, float]:
    """Per-worker total of one counter family (label-summed) — the
    grafana fleet row's per-worker spans/s series feed."""
    totals = {}
    for worker, text in pages.items():
        totals[worker] = sum(
            value
            for name, _labels, value in parse_exposition(text)
            if name == metric
        )
    return totals


def scrape_workers(
    endpoints: Dict[str, str], timeout_s: float = 10.0
) -> Dict[str, str]:
    """Fetch every worker's /metrics page; a dead worker contributes an
    empty page (scrapes must not fail fleet-wide on one kill -9)."""
    import urllib.error
    import urllib.request

    pages = {}
    for worker, base in endpoints.items():
        url = f"{base.rstrip('/')}/metrics"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                pages[worker] = resp.read().decode("utf-8", "replace")
        except (urllib.error.URLError, OSError, TimeoutError):
            pages[worker] = ""
    return pages
