"""Per-tick span tracing: the collect tick as a trace of its own phases.

Each DP collect tick (and each raw-ingest window) opens a trace; the
pipeline phases — parse / quarantine / WAL append / merge / pack /
host→device transfer / walk / scorers / encode-serve — record spans into
a preallocated builder. Device phases take their span boundaries at
points the tick ALREADY synchronizes (`block_until_ready` fences that
exist for correctness), so tracing adds zero host syncs and zero device
round-trips; span timing is host `perf_counter_ns` only.

Finished traces land in a ring (`KMAMIZ_TRACE_RING` traces, default
256) and export as Zipkin v2 JSON trace groups at `GET /debug/traces` —
in exactly the Istio-sidecar span shape the ingest path parses
(`synth.make_raw_window`), so the processor can re-ingest its own
export and build a dependency graph of its own pipeline (dogfooding:
the self-trace round-trip test).

Overhead: when disabled (`KMAMIZ_TELEMETRY=0`) `tick()`/`span()` yield
immediately with no allocation. When enabled, a span is one list append
of a 4-tuple; Zipkin formatting happens only at export time, never on
the tick.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import List, Optional, Tuple

from .profiling import events as prof_events
from .registry import REGISTRY

# span taxonomy: canonical phase names (docs/OBSERVABILITY.md)
PHASES = (
    "parse",
    "quarantine",
    "wal-append",
    "merge",
    "assemble",
    "pack",
    "host-transfer",
    "walk",
    # same tick stage as "walk" but under the KMAMIZ_SPARSE flat-gather
    # walk dispatch (graph/store._sparse_walk_default) — a distinct name
    # so graftprof --diff can compare walk backends instead of folding
    # both into one phase
    "walk_sparse",
    "scorers",
    "encode-serve",
    # STLGT continual-training refresh (models/stlgt/trainer.py): a
    # first-class tick phase so online training shows up in warm tick
    # attribution instead of hiding in the unattributed residue
    "stlgt-refresh",
    # graftpilot decision recompute (control/, docs/CONTROL.md): runs
    # at the fold boundary (forecast forward + admission/warm-up/
    # scheduling decisions), a first-class phase so controller cost is
    # attributable and gated like any other
    "control-decide",
)

_SELFTRACE_NAMESPACE = "graftscope"
_ROOT_SERVICE = "dp-tick"


def _ring_size() -> int:
    try:
        return max(1, int(os.environ.get("KMAMIZ_TRACE_RING", "256")))
    except ValueError:
        return 256


def telemetry_enabled() -> bool:
    """KMAMIZ_TELEMETRY gate, default ON. Re-read per tick (not per
    span) so tests and operators can flip it without a restart."""
    return os.environ.get("KMAMIZ_TELEMETRY", "1") not in ("0", "false", "")


class _TraceBuilder:
    """One in-flight trace: spans as (name, start_ns, dur_ns, parent_idx).

    Built once per tick; appends are the only hot-path operation.
    """

    __slots__ = ("trace_id", "wall_us", "t0_ns", "spans", "_stack", "status")

    def __init__(self, trace_id: str, root_name: str) -> None:
        self.trace_id = trace_id
        self.wall_us = time.time_ns() // 1000
        self.t0_ns = time.perf_counter_ns()
        # span 0 is the root; dur filled at close
        self.spans: List[Tuple[str, int, int, int]] = [(root_name, 0, -1, -1)]
        self._stack = [0]
        self.status = "200"

    def open_span(self, name: str) -> int:
        idx = len(self.spans)
        self.spans.append(
            (name, time.perf_counter_ns() - self.t0_ns, -1, self._stack[-1])
        )
        self._stack.append(idx)
        return idx

    def close_span(self, idx: int) -> None:
        name, start, _, parent = self.spans[idx]
        self.spans[idx] = (
            name,
            start,
            time.perf_counter_ns() - self.t0_ns - start,
            parent,
        )
        if self._stack and self._stack[-1] == idx:
            self._stack.pop()

    def close(self) -> None:
        name, start, _, parent = self.spans[0]
        self.spans[0] = (
            name,
            start,
            time.perf_counter_ns() - self.t0_ns,
            parent,
        )


class TickTracer:
    """Ring of finished tick traces + the per-thread open builder."""

    def __init__(self) -> None:
        self._ring: deque = deque(maxlen=_ring_size())
        self._lock = threading.Lock()
        self._seq = 0
        self._tls = threading.local()

    # -- hot path --------------------------------------------------------
    def current(self) -> Optional[_TraceBuilder]:
        return getattr(self._tls, "builder", None)

    @contextmanager
    def tick(self, root_name: str = _ROOT_SERVICE):
        """Open a trace for one tick. No-op (yields None) when telemetry
        is off or a trace is already open on this thread (re-entrancy:
        ingest-inside-collect keeps one trace)."""
        if not telemetry_enabled() or self.current() is not None:
            yield None
            return
        with self._lock:
            self._seq += 1
            trace_id = f"graftscope-{self._seq}"
        builder = _TraceBuilder(trace_id, root_name)
        self._tls.builder = builder
        prof_events.note_tick_start()
        try:
            yield builder
        finally:
            self._tls.builder = None
            builder.close()
            with self._lock:
                self._ring.append(builder)
            prof_events.note_tick_end(root_name, builder.spans[0][2])

    @contextmanager
    def span(self, name: str):
        """Record one phase span on the current trace (no-op outside a
        tick or with telemetry off)."""
        builder = self.current()
        if builder is None:
            yield
            return
        idx = builder.open_span(name)
        try:
            yield
        finally:
            builder.close_span(idx)

    def annotate_last(self, name: str, dur_ms: float) -> None:
        """Append a post-tick span (e.g. encode-serve, which happens
        after the tick's trace closed — possibly on a different thread
        when the watchdog ran the tick on a worker) to the most recent
        trace in the ring, parented on its root."""
        if not telemetry_enabled():
            return
        with self._lock:
            if not self._ring:
                return
            tb = self._ring[-1]
            _rn, rstart, rdur, _rp = tb.spans[0]
            start = rstart + (rdur if rdur >= 0 else 0)
            tb.spans.append((name, start, max(0, int(dur_ms * 1e6)), 0))
        prof_events.emit(name, max(0, int(dur_ms * 1e6)))
        h = SPAN_HANDLES.get(name)
        if h is not None:
            h.observe(dur_ms)

    # -- export (cold path) ----------------------------------------------
    def traces(self) -> List[_TraceBuilder]:
        with self._lock:
            return list(self._ring)

    def export_zipkin(self) -> List[List[dict]]:
        """Ring contents as Zipkin v2 JSON trace groups, in the
        Istio-sidecar span shape the raw-ingest path parses — feeding
        this back into `ingest_raw_window` yields the pipeline's own
        dependency graph."""
        groups = []
        for tb in self.traces():
            group = []
            for i, (name, start_ns, dur_ns, parent) in enumerate(tb.spans):
                svc = name.replace("_", "-").replace(".", "-")
                ns = _SELFTRACE_NAMESPACE
                url = f"http://{svc}.{ns}.svc.cluster.local/tick/{svc}"
                group.append(
                    {
                        "traceId": tb.trace_id,
                        "id": f"{tb.trace_id}-{i}",
                        "parentId": f"{tb.trace_id}-{parent}" if parent >= 0 else None,
                        "kind": "SERVER",
                        "name": f"{svc}.{ns}.svc.cluster.local:80/*",
                        "timestamp": tb.wall_us + start_ns // 1000,
                        "duration": max(1, dur_ns // 1000),
                        "localEndpoint": {"serviceName": svc},
                        "tags": {
                            "component": "proxy",
                            "http.method": "POST",
                            "http.protocol": "HTTP/1.1",
                            "http.status_code": tb.status,
                            "http.url": url,
                            "istio.canonical_revision": "latest",
                            "istio.canonical_service": svc,
                            "istio.mesh_id": "cluster.local",
                            "istio.namespace": ns,
                            "response_flags": "-",
                            "upstream_cluster": "inbound|9080||",
                        },
                    }
                )
            if group:
                groups.append(group)
        return groups

    def reset_for_tests(self) -> None:
        with self._lock:
            self._ring = deque(maxlen=_ring_size())
            self._seq = 0
        self._tls = threading.local()


# the process-wide tracer (mirrors REGISTRY's singleton pattern)
TRACER = TickTracer()

# span-latency histogram: one preallocated handle per canonical phase —
# the tick looks handles up by identity, never by formatted label
_SPAN_MS = REGISTRY.histogram_family(
    "kmamiz_tick_span_ms",
    "Per-phase span latency within one collect tick (ms)",
    ("phase",),
)
SPAN_HANDLES = {p: _SPAN_MS.handle(p) for p in PHASES}


@contextmanager
def phase_span(name: str):
    """Span + histogram observation for one canonical phase. The handle
    dict is module-scope; unknown names trace but skip the histogram."""
    builder = TRACER.current()
    if builder is None:
        yield
        return
    h = SPAN_HANDLES.get(name)
    idx = builder.open_span(name)
    try:
        yield
    finally:
        builder.close_span(idx)
        _n, _s, dur_ns, _p = builder.spans[idx]
        prof_events.emit(name, dur_ns)
        if h is not None:
            h.observe(dur_ns / 1e6)
