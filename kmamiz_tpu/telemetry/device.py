"""Device telemetry: HBM residency gauges and on-demand profiler capture.

Two sources, merged at scrape time (never on the tick):

- `memory_stats()` from the first addressable device, where the backend
  supports it (TPU does; CPU returns None) — bytes_in_use / peak /
  limit as `kmamiz_device_*` gauges.
- Tracked arena sizes: device-resident subsystems (graph-store edge
  arena, endpoint metadata, staged streaming buffers, scorer caches)
  report their allocation sizes via `track_arena`, exported per-arena
  as `kmamiz_arena_bytes{arena=...}`. This is the fallback accounting
  when `memory_stats()` is unavailable, and the per-subsystem breakdown
  when it is.

Profiling: `capture_profile(duration_ms)` wraps `jax.profiler`
start/stop for `POST /debug/profile` — one capture at a time, written
under `KMAMIZ_PROFILE_DIR` (or an explicit directory).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from .registry import REGISTRY

_ARENA_BYTES = REGISTRY.gauge_family(
    "kmamiz_arena_bytes",
    "Tracked device-resident allocation bytes per arena",
    ("arena",),
)
_DEV_IN_USE = REGISTRY.gauge(
    "kmamiz_device_bytes_in_use", "Device bytes in use (memory_stats)"
)
_DEV_PEAK = REGISTRY.gauge(
    "kmamiz_device_bytes_peak", "Peak device bytes in use (memory_stats)"
)
_DEV_LIMIT = REGISTRY.gauge(
    "kmamiz_device_bytes_limit", "Device memory limit (memory_stats)"
)

_arena_sources: Dict[str, Callable[[], float]] = {}
_arena_handles: Dict[str, object] = {}
_arena_lock = threading.Lock()


def track_arena(name: str, size_fn: Callable[[], float]) -> None:
    """Register a pull source for one arena's byte size. Called at init
    scope by the owning subsystem; `size_fn` runs only at scrape time."""
    with _arena_lock:
        _arena_sources[name] = size_fn
        if name not in _arena_handles:
            _arena_handles[name] = _ARENA_BYTES.handle(name)


def device_memory_stats() -> Optional[dict]:
    try:
        import jax

        devs = jax.local_devices()
        if not devs:
            return None
        return devs[0].memory_stats()
    except Exception:
        return None


def _collect() -> None:
    with _arena_lock:
        items = list(_arena_sources.items())
    for name, fn in items:
        try:
            _arena_handles[name].set(float(fn()))
        except Exception:
            pass
    stats = device_memory_stats()
    if stats:
        _DEV_IN_USE.set(float(stats.get("bytes_in_use", 0) or 0))
        _DEV_PEAK.set(float(stats.get("peak_bytes_in_use", 0) or 0))
        _DEV_LIMIT.set(float(stats.get("bytes_limit", 0) or 0))


REGISTRY.register_callback(_collect)


# -- on-demand profiler capture (POST /debug/profile) --------------------

_PROFILES = REGISTRY.counter(
    "kmamiz_profile_captures_total", "On-demand jax.profiler captures"
)


def profile_max_s() -> float:
    """KMAMIZ_PROFILE_MAX_S: the hard bound on one on-demand capture
    window (default 10 s) — a fat durationMs must not hold the profiler
    guard (and the capture thread) for a minute."""
    try:
        return max(0.001, float(os.environ.get("KMAMIZ_PROFILE_MAX_S", "10")))
    except ValueError:
        return 10.0


def capture_profile(duration_ms: int, out_dir: Optional[str] = None) -> dict:
    """Capture a jax.profiler trace for `duration_ms` to `out_dir`
    (default `KMAMIZ_PROFILE_DIR`, else ./kmamiz-data/profiles). Blocks
    the caller for the capture window, clamped to ``KMAMIZ_PROFILE_MAX_S``.

    One profiler session at a time, PROCESS-wide: the guard is shared
    with `core.profiling.trace` (jax.profiler cannot nest sessions, so a
    tick-scoped trace and an on-demand capture stacking would raise from
    inside the tick). A busy guard answers ``busy: True`` — the server
    maps it to 409."""
    from kmamiz_tpu.core import profiling as core_profiling

    target = out_dir or os.environ.get("KMAMIZ_PROFILE_DIR") or os.path.join(
        "kmamiz-data", "profiles"
    )
    duration_ms = max(1, min(int(duration_ms), int(profile_max_s() * 1000)))
    if not core_profiling._trace_guard.acquire(blocking=False):
        return {
            "ok": False,
            "busy": True,
            "error": "capture already in progress",
        }
    try:
        os.makedirs(target, exist_ok=True)
        import jax

        jax.profiler.start_trace(target)
        try:
            time.sleep(duration_ms / 1000.0)
        finally:
            jax.profiler.stop_trace()
        _PROFILES.inc()
        return {"ok": True, "dir": target, "duration_ms": duration_ms}
    except Exception as exc:  # profiler unavailable on some backends
        return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
    finally:
        core_profiling._trace_guard.release()
