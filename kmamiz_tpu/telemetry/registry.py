"""Unified metrics registry: counters, gauges, fixed-bucket histograms.

One process-wide registry absorbs the ad-hoc counters that used to live
in three places (core/profiling.StepTimer stats, core/programs compile
counters, resilience/metrics._COUNTERS) behind a single API, and renders
the whole set as Prometheus text exposition format for `GET /metrics`.

Hot-path contract (enforced by the graftlint rule
`hot-path-metric-label`): handles are PREALLOCATED at module or init
scope — `REGISTRY.counter(...)` / `family.handle(...)` are
registration-time calls. The per-call operations (`inc`, `set`,
`observe`) touch one lock and a few floats; they never format a label
string, never build a dict key, never allocate a handle.

Pull-side collection: modules that already keep their own structured
state (program registry, graph store arenas, device memory_stats) hook
`register_callback` — the callback runs at scrape/render time only, so
mirroring their numbers into gauges costs the hot path nothing.
"""
from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, List, Sequence, Tuple

# default latency buckets (ms): tick phases span ~0.1 ms device walks to
# multi-second capacity-growth merges
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
)


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integers render bare, floats as repr."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _fmt_le(bound: float) -> str:
    if bound == float("inf"):
        return "+Inf"
    return _fmt_value(bound)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter:
    """Monotonic counter handle. `inc` is the only hot-path operation."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, by: float = 1.0) -> None:
        with self._lock:
            self._value += by

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge:
    """Settable gauge handle."""

    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, by: float = 1.0) -> None:
        with self._lock:
            self._value += by

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Fixed-bucket histogram handle.

    Buckets are upper bounds fixed at registration; `observe` does one
    bisect into a preallocated count array — no per-call allocation.
    """

    __slots__ = ("buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, buckets: Sequence[float]) -> None:
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl +Inf, sum, count)."""
        with self._lock:
            raw = list(self._counts)
            s, c = self._sum, self._count
        cum, acc = [], 0
        for n in raw:
            acc += n
            cum.append(acc)
        return cum, s, c

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0


_KINDS = {"counter": Counter, "gauge": Gauge}


class Family:
    """One named metric with a fixed label schema.

    `handle(*label_values)` allocates (or returns) the child for one
    label combination — call it at init scope, keep the handle, and use
    only the handle on the hot path.
    """

    __slots__ = ("name", "help", "kind", "label_names", "buckets", "_children", "_lock")

    def __init__(self, name, help_text, kind, label_names, buckets=None):
        self.name = name
        self.help = help_text
        self.kind = kind
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets) if buckets else None
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def handle(self, *label_values: str):
        vals = tuple(str(v) for v in label_values)
        if len(vals) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} label values, "
                f"got {len(vals)}"
            )
        with self._lock:
            child = self._children.get(vals)
            if child is None:
                if self.kind == "histogram":
                    child = Histogram(self.buckets)
                else:
                    child = _KINDS[self.kind]()
                self._children[vals] = child
            return child

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class MetricsRegistry:
    """Process-wide named-metric store with Prometheus text rendering."""

    def __init__(self) -> None:
        self._families: Dict[str, Family] = {}
        self._callbacks: List[Callable[[], None]] = []
        self._lock = threading.Lock()

    # -- registration (init scope only) ---------------------------------
    def _family(self, name, help_text, kind, label_names, buckets=None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, help_text, kind, label_names, buckets)
                self._families[name] = fam
            elif fam.kind != kind or fam.label_names != tuple(label_names):
                raise ValueError(
                    f"metric {name} re-registered with a different schema"
                )
            return fam

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._family(name, help_text, "counter", ()).handle()

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._family(name, help_text, "gauge", ()).handle()

    def histogram(
        self, name: str, help_text: str = "", buckets: Sequence[float] = DEFAULT_MS_BUCKETS
    ) -> Histogram:
        return self._family(name, help_text, "histogram", (), buckets).handle()

    def counter_family(self, name, help_text="", label_names=()) -> Family:
        return self._family(name, help_text, "counter", label_names)

    def gauge_family(self, name, help_text="", label_names=()) -> Family:
        return self._family(name, help_text, "gauge", label_names)

    def histogram_family(
        self, name, help_text="", label_names=(), buckets=DEFAULT_MS_BUCKETS
    ) -> Family:
        return self._family(name, help_text, "histogram", label_names, buckets)

    def register_callback(self, fn: Callable[[], None]) -> None:
        """Scrape-time collector: `fn` runs at render() to refresh pull
        gauges from structured sources (program registry, arenas, HBM)."""
        with self._lock:
            if fn not in self._callbacks:
                self._callbacks.append(fn)

    # -- introspection ---------------------------------------------------
    def get_value(self, name: str, label_values: Tuple[str, ...] = ()) -> float:
        fam = self._families.get(name)
        if fam is None:
            return 0.0
        child = fam._children.get(tuple(str(v) for v in label_values))
        if child is None:
            return 0.0
        if isinstance(child, Histogram):
            return float(child._count)
        return child.value

    def families(self) -> List[Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # -- exposition ------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            callbacks = list(self._callbacks)
        for cb in callbacks:
            try:
                cb()
            except Exception:
                pass  # a broken collector must not poison the scrape
        out: List[str] = []
        for fam in self.families():
            out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for vals, child in fam.children():
                labels = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in zip(fam.label_names, vals)
                )
                if isinstance(child, Histogram):
                    cum, total, count = child.snapshot()
                    bounds = list(child.buckets) + [float("inf")]
                    for bound, c in zip(bounds, cum):
                        le = f'le="{_fmt_le(bound)}"'
                        lb = f"{labels},{le}" if labels else le
                        out.append(f"{fam.name}_bucket{{{lb}}} {c}")
                    suffix = f"{{{labels}}}" if labels else ""
                    out.append(f"{fam.name}_sum{suffix} {_fmt_value(total)}")
                    out.append(f"{fam.name}_count{suffix} {count}")
                else:
                    suffix = f"{{{labels}}}" if labels else ""
                    out.append(f"{fam.name}{suffix} {_fmt_value(child.value)}")
        return "\n".join(out) + "\n"

    def reset_for_tests(self) -> None:
        """Zero every value but KEEP families and handles registered —
        module-scope handles captured at import time stay live."""
        for fam in self.families():
            for _vals, child in fam.children():
                child._reset()


# the process-wide registry: all modules register against this instance
REGISTRY = MetricsRegistry()
