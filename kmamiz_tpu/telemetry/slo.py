"""SLO scorecard: the handful of numbers that say whether serving is OK.

Rolling tick-latency percentiles (p50/p95/p99 over the last
`KMAMIZ_SLO_WINDOW` ticks) plus rates derived from registry counters:
stale-serve rate, ingest-drop rate, quarantine rate, and the process
recompile count from the program registry. `bench.py` emits the
scorecard as headline keys; `tools/slo_report.py --check` gates
regressions against the last recorded BENCH_r*.json.
"""
from __future__ import annotations

import os
import threading
from collections import deque
from typing import Dict, List

from .registry import REGISTRY

# scorecard counters: single source of truth shared with the resilience
# summary (resilience/metrics.py increments these same handles)
TICKS = REGISTRY.counter("kmamiz_ticks_total", "Collect ticks attempted")
STALE_SERVES = REGISTRY.counter(
    "kmamiz_stale_serves_total", "Ticks answered from the last-good graph"
)
INGEST_PAYLOADS = REGISTRY.counter(
    "kmamiz_ingest_payloads_total", "Raw ingest payloads accepted for parse"
)
INGEST_DROPPED = REGISTRY.counter(
    "kmamiz_ingest_dropped_total", "Ingest chunks dropped under backpressure"
)
QUARANTINED = REGISTRY.counter(
    "kmamiz_quarantined_total", "Payloads diverted to the quarantine"
)


def _window() -> int:
    try:
        return max(8, int(os.environ.get("KMAMIZ_SLO_WINDOW", "512")))
    except ValueError:
        return 512


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted sample."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class Scorecard:
    """Rolling tick-latency window + counter-derived rates."""

    def __init__(self) -> None:
        self._ticks_ms: deque = deque(maxlen=_window())
        self._lock = threading.Lock()

    def observe_tick(self, ms: float) -> None:
        with self._lock:
            self._ticks_ms.append(float(ms))

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._ticks_ms)
        ticks = TICKS.value
        payloads = INGEST_PAYLOADS.value
        recompiles = 0.0
        try:
            from ..core import programs

            recompiles = float(programs.summary().get("totalCompiles", 0))
        except Exception:
            pass
        return {
            "tick_p50_ms": round(percentile(vals, 0.50), 3),
            "tick_p95_ms": round(percentile(vals, 0.95), 3),
            "tick_p99_ms": round(percentile(vals, 0.99), 3),
            "stale_serve_rate": round(STALE_SERVES.value / max(1.0, ticks), 6),
            "ingest_drop_rate": round(
                INGEST_DROPPED.value / max(1.0, payloads), 6
            ),
            "quarantine_rate": round(QUARANTINED.value / max(1.0, payloads), 6),
            "recompile_count": recompiles,
        }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._ticks_ms = deque(maxlen=_window())


SCORECARD = Scorecard()

# the keys bench.py promotes to headline level, and the direction in
# which each regresses (for tools/slo_report.py --check)
SLO_KEYS_HIGHER_IS_WORSE = (
    "tick_p50_ms",
    "tick_p95_ms",
    "tick_p99_ms",
    "stale_serve_rate",
    "ingest_drop_rate",
    "quarantine_rate",
    "recompile_count",
)


# -- per-tenant SLO (tenancy layer) ------------------------------------------

#: per-tenant tick/stale counter families; the tenant label value is
#: ALWAYS routed through tenant_label() so cardinality stays bounded
TENANT_TICKS = REGISTRY.counter_family(
    "kmamiz_tenant_ticks_total", "Collect ticks attempted, per tenant", ("tenant",)
)
TENANT_STALE_SERVES = REGISTRY.counter_family(
    "kmamiz_tenant_stale_serves_total",
    "Ticks answered from the tenant's last-good graph",
    ("tenant",),
)

_TENANT_SERIES_LOCK = threading.Lock()
# first-seen order of distinct tenant slugs; index < max_tenant_series()
# keeps its own label, the tail folds into "__other__"
_TENANT_SLUGS: Dict[str, int] = {}

OTHER_TENANT_LABEL = "__other__"


def max_tenant_series() -> int:
    try:
        return max(1, int(os.environ.get("KMAMIZ_MAX_TENANT_SERIES", "32")))
    except ValueError:
        return 32


def tenant_label(tenant: str) -> str:
    """The metric label value for a tenant: itself for the first
    KMAMIZ_MAX_TENANT_SERIES distinct tenants this process has seen,
    "__other__" for the tail. Every tenant-labelled family routes its
    label through here, so a tenant flood cannot blow up scrape-side
    cardinality."""
    with _TENANT_SERIES_LOCK:
        idx = _TENANT_SLUGS.get(tenant)
        if idx is None:
            idx = len(_TENANT_SLUGS)
            _TENANT_SLUGS[tenant] = idx
    return tenant if idx < max_tenant_series() else OTHER_TENANT_LABEL


class TenantScorecards:
    """Per-tenant rolling scorecards + counter handles.

    Handles are acquired once per tenant label (cold path, under the
    lock) and cached — the per-tick observe is a dict hit plus a deque
    append, so the hot path never formats a label (the
    hot-path-metric-label discipline; telemetry/ is the one layer
    allowed to touch handles)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cards: Dict[str, Scorecard] = {}
        self._ticks: Dict[str, object] = {}
        self._stales: Dict[str, object] = {}

    def _slot(self, tenant: str):
        label = tenant_label(tenant)
        with self._lock:
            card = self._cards.get(label)
            if card is None:
                card = Scorecard()
                self._cards[label] = card
                self._ticks[label] = TENANT_TICKS.handle(label)
                self._stales[label] = TENANT_STALE_SERVES.handle(label)
            return label, card

    def observe_tick(self, tenant: str, ms: float) -> None:
        label, card = self._slot(tenant)
        card.observe_tick(ms)
        self._ticks[label].inc()

    def note_stale(self, tenant: str) -> None:
        label, _card = self._slot(tenant)
        self._stales[label].inc()

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant-label scorecard rows: tick percentiles + tick /
        stale-serve counts + stale rate."""
        with self._lock:
            cards = dict(self._cards)
        rows: Dict[str, Dict[str, float]] = {}
        for label, card in sorted(cards.items()):
            with card._lock:
                vals = sorted(card._ticks_ms)
            ticks = self._ticks[label].value
            stales = self._stales[label].value
            rows[label] = {
                "tick_p50_ms": round(percentile(vals, 0.50), 3),
                "tick_p95_ms": round(percentile(vals, 0.95), 3),
                "tick_p99_ms": round(percentile(vals, 0.99), 3),
                "ticks": ticks,
                "stale_serves": stales,
                "stale_serve_rate": round(stales / max(1.0, ticks), 6),
            }
        return rows

    def reset_for_tests(self) -> None:
        with self._lock:
            self._cards.clear()
            self._ticks.clear()
            self._stales.clear()
        with _TENANT_SERIES_LOCK:
            _TENANT_SLUGS.clear()


TENANTS = TenantScorecards()
