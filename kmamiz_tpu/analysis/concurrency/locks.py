"""graftrace lock model: lock inventory, held-set flow, order graph.

Static, best-effort, and biased the same way callgraph.py is — toward
*coverage*. The model keeps two precisions side by side:

- **confident** resolution (self-methods, module-local names, direct
  imports, ``self.<attr>`` whose type is pinned by an ``__init__``
  constructor assignment) drives the rules that accuse code of a bug:
  ``lock-order-cycle`` edges and the held-set context used by
  ``blocking-call-under-lock`` / ``inconsistent-guard``. A false edge
  here would fabricate a deadlock report, so no guessing.
- **wide** resolution additionally takes callgraph.py's receiver-blind
  fallback. It only feeds the *coverage universe* the runtime witness
  compares against: a witnessed edge outside even the wide model means
  the extractor has a real blind spot, not that resolution was shy.

Held sets propagate interprocedurally with matching bias: a *may*-held
union feeds the order graph (missing an edge hides a deadlock), while
the accusing rules only trust locally-held locks plus a *must*-held
intersection for underscore-private helpers (public entry points can be
called lock-free from anywhere, including tests we cannot see).

Dynamic dispatch through callable objects (e.g. a registered Program
instance invoked under a store lock) is invisible to any AST pass; the
``DECLARED_EDGES`` table below names those edges explicitly, the same
guard-table pattern core/programs.py uses for jit sites. Stale entries
(naming unknown locks) are themselves findings.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from kmamiz_tpu.analysis.framework import LintContext, ModuleInfo
from kmamiz_tpu.analysis.callgraph import _ModuleIndex, _module_to_rel
from kmamiz_tpu.analysis.rules import (
    _MUTABLE_CTORS,
    _attr_chain,
    _chain_str,
    _module_mutables,
)

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

# Acquisition-order edges taken through dynamic dispatch the AST cannot
# see (callable objects, registry indirection). Each entry is
# (src lock id, dst lock id, reason) and is merged into BOTH edge sets;
# entries naming a lock the extractor does not know are reported stale.
DECLARED_EDGES: Tuple[Tuple[str, str, str], ...] = (
    (
        "kmamiz_tpu/graph/store.py:EndpointGraph._lock",
        "kmamiz_tpu/core/programs.py:Program._lock",
        "jitted Program handles are callable objects: the store's "
        "`self._programs[...](...)` dispatch is a __call__ the resolver "
        "cannot name, and Program.__call__ takes its telemetry lock",
    ),
)


@dataclass(frozen=True)
class LockSite:
    lock_id: str  # "rel/path.py:Class.attr" | "rel/path.py:name" | fn-local
    rel_path: str
    line: int  # creation line (the threading.Lock() call)
    kind: str  # Lock | RLock | Condition
    alias_of: Optional[str] = None  # Condition(lock) -> underlying lock id


@dataclass(frozen=True)
class Acquisition:
    fn: str  # "rel/path.py:Qual.name"
    lock_id: str  # canonical
    line: int
    held_before: Tuple[str, ...]  # canonical, locally-held only
    blocking: bool  # False for acquire(blocking=False) try-locks


@dataclass(frozen=True)
class CallRec:
    fn: str
    line: int
    held: Tuple[str, ...]  # locally-held at the call
    chain: Tuple[str, ...]  # attr chain of the callee expr (may be 1-long)
    nonblocking_kw: bool  # block=False / blocking=False / timeout=0
    thread_join: bool  # .join() on a local threading.Thread
    recv_lock: Optional[str]  # receiver resolves to a known lock/condition
    confident: Tuple[str, ...]  # confident callee qualnames
    wide: Tuple[str, ...]  # wide callee qualnames (superset)


@dataclass(frozen=True)
class Access:
    fn: str
    line: int
    held: Tuple[str, ...]  # locally-held
    key: Tuple[str, ...]  # ("rel", name) module var | ("rel", cls, attr)


@dataclass(frozen=True)
class OrderEdge:
    src: str
    dst: str
    rel_path: str
    line: int
    fn: str
    blocking: bool


@dataclass
class LockModel:
    locks: Dict[str, LockSite] = field(default_factory=dict)
    acquisitions: List[Acquisition] = field(default_factory=list)
    calls: List[CallRec] = field(default_factory=list)
    accesses: List[Access] = field(default_factory=list)
    # fn qual -> held-at-entry sets under the three propagation modes
    entry_may: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    entry_may_wide: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    entry_must: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    edges: List[OrderEdge] = field(default_factory=list)  # confident
    wide_edge_pairs: Set[Tuple[str, str]] = field(default_factory=set)
    # (rel, cls) -> mutable attrs assigned in __init__ (lock-owning classes)
    mutable_attrs: Dict[Tuple[str, str], Set[str]] = field(default_factory=dict)
    stale_declared: List[Tuple[str, str, str]] = field(default_factory=list)
    # locks only ever acquired with blocking=False (nobody can stall on them)
    trylock_only: Set[str] = field(default_factory=set)

    def canon(self, lock_id: str) -> str:
        seen = set()
        while lock_id in self.locks and self.locks[lock_id].alias_of:
            if lock_id in seen:  # defensive: alias cycles
                break
            seen.add(lock_id)
            lock_id = self.locks[lock_id].alias_of
        return lock_id

    def creation_site(self, lock_id: str) -> Optional[Tuple[str, int]]:
        site = self.locks.get(lock_id)
        return (site.rel_path, site.line) if site else None

    def edge_pairs(self) -> Set[Tuple[str, str]]:
        return {(e.src, e.dst) for e in self.edges}


def _lock_ctor_kind(call: ast.AST, idx: _ModuleIndex) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    chain = _attr_chain(call.func)
    if not chain:
        return None
    if len(chain) == 2 and chain[0] == "threading" and chain[1] in _LOCK_CTORS:
        return chain[1]
    if len(chain) == 1 and chain[0] in _LOCK_CTORS:
        if idx.from_symbols.get(chain[0]) == ("threading", chain[0]):
            return chain[0]
    return None


def _mutable_value(v: ast.AST) -> bool:
    return isinstance(
        v, (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp)
    ) or (
        isinstance(v, ast.Call)
        and _chain_str(v.func).split(".")[-1] in _MUTABLE_CTORS
    )


class _ClassInfo:
    def __init__(self) -> None:
        self.lock_attrs: Set[str] = set()
        self.mutable_attrs: Set[str] = set()
        # attr -> (target_rel, ClassName) when __init__ pins the type
        self.attr_types: Dict[str, Tuple[str, str]] = {}


class _ModScan:
    """Per-module extraction state shared by both passes."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.rel = mod.rel_path
        self.idx = _ModuleIndex(mod)
        self.classes: Dict[str, _ClassInfo] = {}
        self.class_defs: Dict[str, ast.ClassDef] = {}
        self.shared_vars: Set[str] = _module_mutables(mod)
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self.class_defs[node.name] = node


def _module_rels(dotted: str) -> Tuple[str, str]:
    """Candidate rel paths for a dotted module: the plain module file and
    the package ``__init__``.  callgraph's ``_module_to_rel`` only knows
    the former, which would lose every lock edge into a package's own
    ``__init__.py`` (e.g. the fleet counters behind ``fleet_mod.incr``)."""
    return _module_to_rel(dotted), dotted.replace(".", "/") + "/__init__.py"


def _scan_for_module(
    dotted: str, scans: Dict[str, "_ModScan"]
) -> Tuple[Optional[str], Optional["_ModScan"]]:
    for rel in _module_rels(dotted):
        tgt = scans.get(rel)
        if tgt is not None:
            return rel, tgt
    return None, None


def _resolve_class(
    name: str, scan: _ModScan, scans: Dict[str, "_ModScan"]
) -> Optional[Tuple[str, str]]:
    """Resolve a constructor name to (rel_path, ClassName)."""
    if name in scan.class_defs:
        return (scan.rel, name)
    sym = scan.idx.from_symbols.get(name)
    if sym:
        target_rel, tgt = _scan_for_module(sym[0], scans)
        if tgt and sym[1] in tgt.class_defs:
            return (target_rel, sym[1])
    return None


def _collect_sites(scans: Dict[str, _ModScan], model: LockModel) -> None:
    """Pass A: lock sites, Condition aliases, class attr inventories."""
    pending_aliases: List[Tuple[str, str, Optional[str], ast.Call]] = []
    for rel, scan in scans.items():
        # module-level locks
        for stmt in scan.mod.tree.body:
            if isinstance(stmt, ast.Assign):
                kind = _lock_ctor_kind(stmt.value, scan.idx)
                if kind:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            lid = f"{rel}:{t.id}"
                            model.locks[lid] = LockSite(
                                lid, rel, stmt.value.lineno, kind
                            )
                            if kind == "Condition":
                                pending_aliases.append(
                                    (lid, rel, None, stmt.value)
                                )
        # class-attr locks + mutable attrs + attr types
        for cls_name, cls_node in scan.class_defs.items():
            info = scan.classes.setdefault(cls_name, _ClassInfo())
            for meth in cls_node.body:
                if not isinstance(
                    meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                for node in ast.walk(meth):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        chain = _attr_chain(t)
                        if not (
                            chain and len(chain) == 2 and chain[0] == "self"
                        ):
                            continue
                        attr = chain[1]
                        kind = _lock_ctor_kind(node.value, scan.idx)
                        if kind:
                            lid = f"{rel}:{cls_name}.{attr}"
                            if lid not in model.locks:
                                model.locks[lid] = LockSite(
                                    lid, rel, node.value.lineno, kind
                                )
                            info.lock_attrs.add(attr)
                            if kind == "Condition":
                                pending_aliases.append(
                                    (lid, rel, cls_name, node.value)
                                )
                        elif meth.name == "__init__":
                            if _mutable_value(node.value):
                                info.mutable_attrs.add(attr)
                            elif isinstance(node.value, ast.Call):
                                fchain = _attr_chain(node.value.func)
                                if fchain and len(fchain) == 1:
                                    tgt = _resolve_class(
                                        fchain[0], scan, scans
                                    )
                                    if tgt:
                                        info.attr_types[attr] = tgt
        # function-local locks (closures: per enclosing-def qualname)
        for suffix, fn_node in scan.idx.defs.items():
            for node in ast.walk(fn_node):
                if isinstance(node, ast.Assign):
                    kind = _lock_ctor_kind(node.value, scan.idx)
                    if kind:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                lid = f"{rel}:{suffix}.{t.id}"
                                if lid not in model.locks:
                                    model.locks[lid] = LockSite(
                                        lid, rel, node.value.lineno, kind
                                    )
    # resolve Condition(underlying) aliases now that all sites exist
    for lid, rel, cls_name, call in pending_aliases:
        if not call.args:
            continue
        chain = _attr_chain(call.args[0])
        target: Optional[str] = None
        if chain and chain[0] == "self" and len(chain) == 2 and cls_name:
            target = f"{rel}:{cls_name}.{chain[1]}"
        elif chain and len(chain) == 1:
            target = f"{rel}:{chain[0]}"
        if target and target in model.locks:
            old = model.locks[lid]
            model.locks[lid] = LockSite(
                lid, old.rel_path, old.line, old.kind, alias_of=target
            )


class _FnScanner:
    """Pass B: walk one function body tracking the locally-held set."""

    def __init__(
        self,
        scan: _ModScan,
        scans: Dict[str, _ModScan],
        suffix: str,
        fn_node: ast.AST,
        model: LockModel,
    ):
        self.scan = scan
        self.scans = scans
        self.rel = scan.rel
        self.suffix = suffix
        self.fn = f"{scan.rel}:{suffix}"
        self.fn_node = fn_node
        self.model = model
        parts = suffix.split(".")
        self.cls = (
            parts[-2]
            if len(parts) >= 2 and parts[-2] in scan.class_defs
            else None
        )
        self.local_threads: Set[str] = set()
        # parameter name -> annotated class name, so `with session.lock:`
        # resolves when the signature says `session: RawIngestSession`
        self.param_types: Dict[str, str] = {}
        if isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = fn_node.args
            for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
                ann = arg.annotation
                if isinstance(ann, ast.Name):
                    self.param_types[arg.arg] = ann.id
                elif isinstance(ann, ast.Constant) and isinstance(
                    ann.value, str
                ):
                    self.param_types[arg.arg] = ann.value
        self.acquisitions: List[Acquisition] = []
        self.calls: List[CallRec] = []
        self.accesses: List[Access] = []

    # -- resolution -----------------------------------------------------

    def resolve_lock(self, expr: ast.AST) -> Optional[str]:
        chain = _attr_chain(expr)
        if not chain:
            return None
        locks = self.model.locks
        if chain[0] == "self" and len(chain) == 2 and self.cls:
            cand = f"{self.rel}:{self.cls}.{chain[1]}"
            if cand in locks:
                return self.model.canon(cand)
        if len(chain) == 1:
            # fn-local (walk enclosing-def prefixes), then module-level
            parts = self.suffix.split(".")
            for i in range(len(parts), 0, -1):
                cand = f"{self.rel}:{'.'.join(parts[:i])}.{chain[0]}"
                if cand in locks:
                    return self.model.canon(cand)
            cand = f"{self.rel}:{chain[0]}"
            if cand in locks:
                return self.model.canon(cand)
        if len(chain) == 2:
            dotted = self.scan.idx.import_aliases.get(chain[0])
            if dotted is None and chain[0] in self.scan.idx.from_symbols:
                base, sym_name = self.scan.idx.from_symbols[chain[0]]
                dotted = f"{base}.{sym_name}"
            if dotted:
                for rel in _module_rels(dotted):
                    cand = f"{rel}:{chain[1]}"
                    if cand in locks:
                        return self.model.canon(cand)
            ann = self.param_types.get(chain[0])
            if ann:
                cls = _resolve_class(ann, self.scan, self.scans)
                if cls:
                    cand = f"{cls[0]}:{cls[1]}.{chain[1]}"
                    if cand in locks:
                        return self.model.canon(cand)
        return None

    def _resolve_callees(
        self, call: ast.Call
    ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
        idx = self.scan.idx
        confident: Set[str] = set()
        wide: Set[str] = set()
        chain = _attr_chain(call.func)
        if chain is None:
            return (), ()
        if len(chain) == 1:
            name = chain[0]
            for cand in idx.by_basename.get(name, []):
                confident.add(f"{self.rel}:{cand}")
            sym = idx.from_symbols.get(name)
            if sym:
                target_rel, tgt = _scan_for_module(sym[0], self.scans)
                if tgt:
                    for cand in tgt.idx.by_basename.get(sym[1], []):
                        confident.add(f"{target_rel}:{cand}")
            cls = _resolve_class(name, self.scan, self.scans)
            if cls and f"{cls[1]}.__init__" in self.scans[cls[0]].idx.defs:
                confident.add(f"{cls[0]}:{cls[1]}.__init__")
            return tuple(sorted(confident)), tuple(sorted(confident))
        meth = chain[-1]
        if chain[0] == "self" and len(chain) == 2 and self.cls:
            cand = f"{self.cls}.{meth}"
            if cand in idx.defs:
                confident.add(f"{self.rel}:{cand}")
                return tuple(sorted(confident)), tuple(sorted(confident))
        if chain[0] == "self" and len(chain) == 3 and self.cls:
            info = self.scan.classes.get(self.cls)
            typed = info.attr_types.get(chain[1]) if info else None
            if typed:
                target_rel, target_cls = typed
                cand = f"{target_cls}.{meth}"
                if cand in self.scans[target_rel].idx.defs:
                    confident.add(f"{target_rel}:{cand}")
                    return tuple(sorted(confident)), tuple(sorted(confident))
        if len(chain) == 2:
            dotted = idx.import_aliases.get(chain[0])
            if dotted is None and chain[0] in idx.from_symbols:
                base, sym_name = idx.from_symbols[chain[0]]
                dotted = f"{base}.{sym_name}"
            if dotted:
                target_rel, tgt = _scan_for_module(dotted, self.scans)
                if tgt:
                    for cand in tgt.idx.by_basename.get(meth, []):
                        confident.add(f"{target_rel}:{cand}")
                    if confident:
                        return (
                            tuple(sorted(confident)),
                            tuple(sorted(confident)),
                        )
        # receiver-blind fallback (wide only), mirroring callgraph.py
        for cand in idx.by_basename.get(meth, []):
            wide.add(f"{self.rel}:{cand}")
        for target_rel in idx.imported_rels:
            tgt = self.scans.get(target_rel)
            if tgt is None and target_rel.endswith(".py"):
                # imported_rels carries the dotted-path rel; packages
                # actually live in <pkg>/__init__.py
                target_rel = target_rel[:-3] + "/__init__.py"
                tgt = self.scans.get(target_rel)
            if not tgt:
                continue
            for cand in tgt.idx.by_basename.get(meth, []):
                wide.add(f"{target_rel}:{cand}")
        return tuple(sorted(confident)), tuple(sorted(wide | confident))

    # -- recording ------------------------------------------------------

    def _record_acq(
        self, lid: str, line: int, held: Tuple[str, ...], blocking: bool
    ) -> None:
        self.acquisitions.append(
            Acquisition(self.fn, lid, line, tuple(held), blocking)
        )

    def _record_call(self, call: ast.Call, held: Tuple[str, ...]) -> None:
        chain = _attr_chain(call.func)
        if chain is None:
            chain_t: Tuple[str, ...] = ()
        else:
            chain_t = tuple(chain)
        nonblocking = False
        for kw in call.keywords:
            if kw.arg in ("block", "blocking") and (
                isinstance(kw.value, ast.Constant) and kw.value.value is False
            ):
                nonblocking = True
            if kw.arg == "timeout" and (
                isinstance(kw.value, ast.Constant) and kw.value.value == 0
            ):
                nonblocking = True
        thread_join = bool(
            chain_t
            and chain_t[-1] == "join"
            and len(chain_t) >= 2
            and chain_t[0] in self.local_threads
        )
        recv_lock = None
        if isinstance(call.func, ast.Attribute):
            recv = call.func.value
            recv_chain = _attr_chain(recv)
            if recv_chain:
                lid = self.resolve_lock(recv)
                if lid is None and recv_chain[0] == "self" and self.cls:
                    # `self._barrier.wait()` resolves through the alias id
                    cand = f"{self.rel}:{self.cls}.{recv_chain[-1]}"
                    if cand in self.model.locks:
                        lid = self.model.canon(cand)
                recv_lock = lid
        confident, wide = self._resolve_callees(call)
        self.calls.append(
            CallRec(
                self.fn,
                call.lineno,
                tuple(held),
                chain_t,
                nonblocking,
                thread_join,
                recv_lock,
                confident,
                wide,
            )
        )

    def _record_accesses(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.Name):
            if node.id in self.scan.shared_vars:
                self.accesses.append(
                    Access(self.fn, node.lineno, tuple(held), (self.rel, node.id))
                )
        elif isinstance(node, ast.Attribute):
            chain = _attr_chain(node)
            if not chain:
                return
            if chain[0] == "self" and len(chain) == 2 and self.cls:
                info = self.scan.classes.get(self.cls)
                if info and chain[1] in info.mutable_attrs:
                    self.accesses.append(
                        Access(
                            self.fn,
                            node.lineno,
                            tuple(held),
                            (self.rel, self.cls, chain[1]),
                        )
                    )
            elif len(chain) == 2:
                dotted = self.scan.idx.import_aliases.get(chain[0])
                if dotted:
                    target_rel, tgt = _scan_for_module(dotted, self.scans)
                    if tgt and chain[1] in tgt.shared_vars:
                        self.accesses.append(
                            Access(
                                self.fn,
                                node.lineno,
                                tuple(held),
                                (target_rel, chain[1]),
                            )
                        )

    # -- expression / statement walks -----------------------------------

    def scan_expr(self, expr: Optional[ast.AST], held: Tuple[str, ...]) -> None:
        if expr is None:
            return
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue  # runs later, under whoever calls it
            if isinstance(node, ast.Call):
                acq = self._acquire_release(node)
                if acq is None:
                    self._record_call(node, held)
                elif acq[1] == "acquire":
                    # acquire inside an expression: handled by the
                    # statement-level walkers when it affects flow; still
                    # record the event so the order graph sees it
                    self._record_acq(acq[0], node.lineno, held, acq[2])
            self._record_accesses(node, held)
            stack.extend(ast.iter_child_nodes(node))

    def _acquire_release(
        self, call: ast.Call
    ) -> Optional[Tuple[str, str, bool]]:
        """(lock_id, 'acquire'|'release', blocking) for lock method calls."""
        if not isinstance(call.func, ast.Attribute):
            return None
        verb = call.func.attr
        if verb not in ("acquire", "release"):
            return None
        lid = self.resolve_lock(call.func.value)
        if lid is None:
            return None
        blocking = True
        if call.args and isinstance(call.args[0], ast.Constant):
            if call.args[0].value in (False, 0):
                blocking = False
        for kw in call.keywords:
            if kw.arg == "blocking" and (
                isinstance(kw.value, ast.Constant)
                and kw.value.value in (False, 0)
            ):
                blocking = False
        return (lid, verb, blocking)

    def _trylock_in_test(
        self, test: ast.AST
    ) -> Optional[Tuple[str, bool, int]]:
        """(lock_id, negated, line) for `[not] X.acquire(blocking=False)`."""
        negated = False
        node = test
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            negated = True
            node = node.operand
        if isinstance(node, ast.Call):
            acq = self._acquire_release(node)
            if acq and acq[1] == "acquire" and not acq[2]:
                return (acq[0], negated, node.lineno)
        return None

    @staticmethod
    def _terminates(body: Sequence[ast.stmt]) -> bool:
        return bool(body) and isinstance(
            body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
        )

    def scan_stmts(
        self, stmts: Sequence[ast.stmt], held: Tuple[str, ...]
    ) -> Tuple[str, ...]:
        for st in stmts:
            held = self.scan_stmt(st, held)
        return held

    def scan_stmt(self, st: ast.stmt, held: Tuple[str, ...]) -> Tuple[str, ...]:
        if isinstance(
            st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            # nested defs run later under their own qualname; a held lock
            # here does not extend into their call time
            for dec in getattr(st, "decorator_list", []):
                self.scan_expr(dec, held)
            return held
        if isinstance(st, (ast.With, ast.AsyncWith)):
            inner = held
            for item in st.items:
                lid = self.resolve_lock(item.context_expr)
                if lid is not None:
                    self._record_acq(
                        lid, item.context_expr.lineno, inner, True
                    )
                    if lid not in inner:
                        inner = inner + (lid,)
                else:
                    self.scan_expr(item.context_expr, inner)
            self.scan_stmts(st.body, inner)
            return held
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            acq = self._acquire_release(st.value)
            if acq is not None:
                lid, verb, blocking = acq
                if verb == "acquire":
                    self._record_acq(lid, st.value.lineno, held, blocking)
                    if blocking and lid not in held:
                        held = held + (lid,)
                else:
                    held = tuple(h for h in held if h != lid)
                return held
            self._track_thread_assign(st)
            self.scan_expr(st.value, held)
            return held
        if isinstance(st, ast.If):
            tl = self._trylock_in_test(st.test)
            if tl is not None:
                lid, negated, line = tl
                self._record_acq(lid, line, held, False)
                with_lock = held + ((lid,) if lid not in held else ())
                if negated:
                    self.scan_stmts(st.body, held)
                    self.scan_stmts(st.orelse, with_lock)
                    if self._terminates(st.body):
                        return with_lock
                    return held
                self.scan_stmts(st.body, with_lock)
                self.scan_stmts(st.orelse, held)
                return held
            self.scan_expr(st.test, held)
            self.scan_stmts(st.body, held)
            self.scan_stmts(st.orelse, held)
            return held
        if isinstance(st, (ast.While,)):
            self.scan_expr(st.test, held)
            self.scan_stmts(st.body, held)
            self.scan_stmts(st.orelse, held)
            return held
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self.scan_expr(st.iter, held)
            self.scan_expr(st.target, held)
            self.scan_stmts(st.body, held)
            self.scan_stmts(st.orelse, held)
            return held
        if isinstance(st, ast.Try):
            after_body = self.scan_stmts(st.body, held)
            for handler in st.handlers:
                self.scan_stmts(handler.body, held)
            after_else = self.scan_stmts(st.orelse, after_body)
            return self.scan_stmts(st.finalbody, after_else)
        if isinstance(st, ast.Assign):
            self._track_thread_assign(st)
            self.scan_expr(st.value, held)
            for t in st.targets:
                self.scan_expr(t, held)
            return held
        # everything else: walk child expressions with the current held set
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.stmt):
                held = self.scan_stmt(child, held)
            else:
                self.scan_expr(child, held)
        return held

    def _track_thread_assign(self, st: ast.stmt) -> None:
        if not isinstance(st, ast.Assign):
            return
        v = st.value
        if isinstance(v, ast.Call) and _chain_str(v.func) in (
            "threading.Thread",
            "Thread",
        ):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self.local_threads.add(t.id)

    def run(self) -> None:
        body = getattr(self.fn_node, "body", [])
        self.scan_stmts(body, ())
        self.model.acquisitions.extend(self.acquisitions)
        self.model.calls.extend(self.calls)
        seen = set()
        for a in self.accesses:
            k = (a.fn, a.key, a.line)
            if k not in seen:
                seen.add(k)
                self.model.accesses.append(a)


def _propagate(
    calls: List[CallRec],
    mode: str,
) -> Dict[str, FrozenSet[str]]:
    """Fixpoint held-at-entry propagation.

    mode 'may'      union over confident call sites
    mode 'may_wide' union over confident+wide call sites
    mode 'must'     intersection over confident call sites, and only for
                    underscore-private basenames (public fns may be
                    called lock-free from outside the repo's own code)
    """
    entry: Dict[str, FrozenSet[str]] = {}
    by_caller: Dict[str, List[CallRec]] = {}
    for c in calls:
        by_caller.setdefault(c.fn, []).append(c)

    if mode == "must":
        seen_vals: Dict[str, Optional[FrozenSet[str]]] = {}
        changed = True
        while changed:
            changed = False
            for c in calls:
                ctx_held = (entry.get(c.fn) or frozenset()) | frozenset(c.held)
                for callee in c.confident:
                    base = callee.rsplit(".", 1)[-1]
                    if not base.startswith("_") or base.startswith("__"):
                        continue
                    prev = seen_vals.get(callee, None)
                    new = ctx_held if prev is None else (prev & ctx_held)
                    if new != prev:
                        seen_vals[callee] = new
                        entry[callee] = new
                        changed = True
        return {k: v for k, v in entry.items() if v}

    changed = True
    while changed:
        changed = False
        for c in calls:
            ctx_held = (entry.get(c.fn) or frozenset()) | frozenset(c.held)
            if not ctx_held:
                continue
            targets = c.confident if mode == "may" else c.wide
            for callee in targets:
                prev = entry.get(callee, frozenset())
                new = prev | ctx_held
                if new != prev:
                    entry[callee] = new
                    changed = True
    return entry


def build_model(ctx: LintContext) -> LockModel:
    """Build (and cache on the context) the repo-wide lock model."""
    cached = getattr(ctx, "_graftrace_model", None)
    if cached is not None:
        return cached
    model = LockModel()
    scans = {rel: _ModScan(m) for rel, m in ctx.modules.items()}
    _collect_sites(scans, model)
    for rel, scan in scans.items():
        for cls_name, info in scan.classes.items():
            if info.lock_attrs and info.mutable_attrs:
                model.mutable_attrs[(rel, cls_name)] = set(info.mutable_attrs)
        for suffix, fn_node in scan.idx.defs.items():
            _FnScanner(scan, scans, suffix, fn_node, model).run()

    model.entry_may = _propagate(model.calls, "may")
    model.entry_may_wide = _propagate(model.calls, "may_wide")
    model.entry_must = _propagate(model.calls, "must")

    blocking_by_lock: Dict[str, bool] = {}
    for acq in model.acquisitions:
        blocking_by_lock[acq.lock_id] = (
            blocking_by_lock.get(acq.lock_id, False) or acq.blocking
        )
    model.trylock_only = {
        lid for lid, any_blocking in blocking_by_lock.items() if not any_blocking
    }

    seen_edges: Set[Tuple[str, str, str, int]] = set()
    for acq in model.acquisitions:
        rel = acq.fn.split(":", 1)[0]
        dst = acq.lock_id
        for entry_map, wide in (
            (model.entry_may, False),
            (model.entry_may_wide, True),
        ):
            held = set(acq.held_before) | entry_map.get(acq.fn, frozenset())
            for src in held:
                if src == dst:
                    continue  # reentrant re-acquire, not an order edge
                model.wide_edge_pairs.add((src, dst))
                if not wide:
                    key = (src, dst, rel, acq.line)
                    if key not in seen_edges:
                        seen_edges.add(key)
                        model.edges.append(
                            OrderEdge(
                                src, dst, rel, acq.line, acq.fn, acq.blocking
                            )
                        )
    for src, dst, reason in DECLARED_EDGES:
        if src not in model.locks or dst not in model.locks:
            model.stale_declared.append((src, dst, reason))
            continue
        csrc, cdst = model.canon(src), model.canon(dst)
        model.wide_edge_pairs.add((csrc, cdst))
        site = model.locks[csrc]
        model.edges.append(
            OrderEdge(
                csrc, cdst, site.rel_path, site.line, "<declared>", True
            )
        )

    model.edges.sort(key=lambda e: (e.rel_path, e.line, e.src, e.dst))
    ctx._graftrace_model = model
    return model


def repo_model() -> LockModel:
    """The lock model for the in-repo kmamiz_tpu package — parsing only
    (no hot-set, no jit tables), so the runtime witness can cross-check
    without paying a full lint context."""
    from kmamiz_tpu.analysis import framework

    root = framework.repo_root()
    ctx = LintContext(root=root)
    for rel in framework._iter_py_files(root, None):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                ctx.modules[rel.replace("\\", "/")] = ModuleInfo(
                    rel, fh.read()
                )
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    return build_model(ctx)


def find_cycles(model: LockModel) -> List[List[OrderEdge]]:
    """Cycles in the blocking confident order graph, one per SCC.

    Try-lock edges (acquire(blocking=False)) cannot stall a thread, so
    they are excluded; so are edges *into* locks that are only ever
    try-acquired (nobody can block on them).
    """
    edges = [
        e
        for e in model.edges
        if e.blocking and e.dst not in model.trylock_only
    ]
    adj: Dict[str, Set[str]] = {}
    for e in edges:
        adj.setdefault(e.src, set()).add(e.dst)

    index_counter = [0]
    stack: List[str] = []
    lowlink: Dict[str, int] = {}
    index: Dict[str, int] = {}
    on_stack: Set[str] = set()
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = lowlink[v] = index_counter[0]
        index_counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = lowlink[w] = index_counter[0]
                    index_counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    lowlink[node] = min(lowlink[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    cycles: List[List[OrderEdge]] = []
    for comp in sccs:
        comp_set = set(comp)
        cyc = sorted(
            (
                e
                for e in edges
                if e.src in comp_set and e.dst in comp_set
            ),
            key=lambda e: (e.rel_path, e.line, e.src, e.dst),
        )
        if cyc:
            cycles.append(cyc)
    return cycles
