"""The three graftrace rules, registered into the graftlint framework.

They share one LockModel per lint context (built lazily, cached), and
they are deliberately NOT hot-path gated: a deadlock on a cold admin
route hangs the process just as hard as one on the tick path.

Suppression uses the same `# graftlint: disable=<rule> -- reason`
comments as every other rule; `--strict` enforces the reason.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from kmamiz_tpu.analysis.framework import (
    Finding,
    LintContext,
    ModuleInfo,
    rule,
)
from kmamiz_tpu.analysis.concurrency import locks as _locks
from kmamiz_tpu.analysis.concurrency.locks import CallRec, LockModel

# ---------------------------------------------------------------------------
# rule 10: lock-order-cycle
# ---------------------------------------------------------------------------


@rule(
    "lock-order-cycle",
    "the interprocedural lock-acquisition-order graph must stay acyclic; "
    "a cycle is a potential deadlock, reported as the full cycle path",
)
def check_lock_order_cycle(
    mod: ModuleInfo, ctx: LintContext
) -> List[Finding]:
    model = _locks.build_model(ctx)
    findings: List[Finding] = []
    for cyc in _locks.find_cycles(model):
        anchor = cyc[0]  # edges are sorted; first is the smallest site
        if anchor.rel_path != mod.rel_path:
            continue
        path = "; ".join(
            f"{e.src} -> {e.dst} at {e.rel_path}:{e.line} in {e.fn.split(':', 1)[1]}"
            for e in cyc
        )
        findings.append(
            Finding(
                "lock-order-cycle",
                mod.rel_path,
                anchor.line,
                f"lock acquisition order cycle (potential deadlock): {path}",
            )
        )
    for src, dst, reason in model.stale_declared:
        if src.split(":", 1)[0] != mod.rel_path:
            continue
        findings.append(
            Finding(
                "lock-order-cycle",
                mod.rel_path,
                1,
                f"DECLARED_EDGES entry {src} -> {dst} ({reason}) names a "
                "lock the extractor does not know — stale declaration",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# rule 11: blocking-call-under-lock
# ---------------------------------------------------------------------------

_BLOCKING_CHAINS: Dict[Tuple[str, ...], str] = {
    ("time", "sleep"): "time.sleep",
    ("os", "fsync"): "os.fsync",
    ("os", "fdatasync"): "os.fdatasync",
    ("socket", "create_connection"): "socket connect",
    ("urllib", "request", "urlopen"): "HTTP request",
}
_SUBPROCESS_CALLS = {"run", "call", "check_call", "check_output", "Popen"}
_BLOCKING_BASENAMES = {
    "fsync": "os.fsync",
    "fdatasync": "os.fdatasync",
    "urlopen": "HTTP request",
    "create_connection": "socket connect",
    "block_until_ready": "device sync",
}
_QUEUE_VERBS = {"get", "put"}

# Single-writer design table: these locks exist precisely to serialize
# device mutation, so jitted dispatch while holding ONLY them is the
# module's documented contract (EndpointGraph is a single-writer store;
# every merge/fold/score dispatch runs under its RLock by design).
# Dispatch while holding any OTHER lock on top still reports.
_OWN_LOCK_DISPATCH_OK = frozenset(
    {
        "kmamiz_tpu/graph/store.py:EndpointGraph._lock",
    }
)


def _receiver_segments(chain: Tuple[str, ...]) -> Tuple[str, ...]:
    return chain[:-1] if len(chain) > 1 else ()


def _blocking_reason(
    call: CallRec, model: LockModel, ctx: LintContext
) -> Optional[str]:
    chain = call.chain
    if not chain:
        return None
    base = chain[-1]
    if chain in _BLOCKING_CHAINS:
        return _BLOCKING_CHAINS[chain]
    if len(chain) == 2 and chain[0] == "subprocess" and base in _SUBPROCESS_CALLS:
        return f"subprocess.{base}"
    if base in _BLOCKING_BASENAMES and len(chain) > 1:
        return _BLOCKING_BASENAMES[base]
    if base in ("wait", "wait_for"):
        # Condition.wait releases its own lock while waiting — only the
        # *other* held locks stall anyone (the caller filters for that)
        return "blocking wait"
    if base in _QUEUE_VERBS and not call.nonblocking_kw:
        recv = _receiver_segments(chain)
        if recv and ("queue" in recv[-1].lower() or recv[-1] == "q"):
            return f"queue.{base}"
    if call.thread_join or (
        base == "join"
        and any("thread" in s.lower() for s in _receiver_segments(chain))
    ):
        return "thread join"
    if any("transport" in s.lower() for s in _receiver_segments(chain)):
        return "transport send"
    if base == "call" and any(
        "breaker" in s.lower() for s in _receiver_segments(chain)
    ):
        return "breaker-wrapped I/O"
    if len(chain) == 1 and base in ctx.jit_bound_names:
        return "jitted-program dispatch"
    return None


@rule(
    "blocking-call-under-lock",
    "transport/HTTP sends, fsync, queue waits, jitted dispatch, sleeps, "
    "subprocess and breaker-wrapped I/O must not run while a lock is held",
)
def check_blocking_call_under_lock(
    mod: ModuleInfo, ctx: LintContext
) -> List[Finding]:
    model = _locks.build_model(ctx)
    findings: List[Finding] = []
    seen: Set[Tuple[int, str]] = set()
    for call in model.calls:
        if call.fn.split(":", 1)[0] != mod.rel_path:
            continue
        held = set(call.held) | model.entry_must.get(call.fn, frozenset())
        # locks nobody ever blocks on (try-lock-only) cannot stall a peer
        held -= model.trylock_only
        if not held:
            continue
        reason = _blocking_reason(call, model, ctx)
        if reason is None:
            continue
        if reason == "jitted-program dispatch" and held <= _OWN_LOCK_DISPATCH_OK:
            continue
        if reason == "blocking wait" and call.recv_lock is not None:
            # waiting on a condition releases its underlying lock
            held = held - {call.recv_lock}
            if not held:
                continue
        held_s = ", ".join(sorted(held))
        key = (call.line, reason)
        if key in seen:
            continue
        seen.add(key)
        findings.append(
            Finding(
                "blocking-call-under-lock",
                mod.rel_path,
                call.line,
                f"{reason} while holding {held_s} — move the blocking "
                "call outside the lock (snapshot under the lock, act after)",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# rule 12: inconsistent-guard
# ---------------------------------------------------------------------------


@rule(
    "inconsistent-guard",
    "a shared mutable guarded by one lock at most access sites must not "
    "be touched under a different lock or none (guarded-by inference)",
)
def check_inconsistent_guard(
    mod: ModuleInfo, ctx: LintContext
) -> List[Finding]:
    model = _locks.build_model(ctx)
    by_key: Dict[Tuple[str, ...], List] = {}
    for acc in model.accesses:
        by_key.setdefault(acc.key, []).append(acc)
    findings: List[Finding] = []
    for key, sites in sorted(by_key.items()):
        if key[0] != mod.rel_path:
            continue
        counted = []
        for acc in sites:
            fn_base = acc.fn.rsplit(".", 1)[-1]
            if fn_base.endswith("_locked") or fn_base == "__init__":
                continue  # trusted helper / single-threaded construction
            held = set(acc.held) | model.entry_must.get(acc.fn, frozenset())
            counted.append((acc, held))
        total = len(counted)
        if total < 2:
            continue
        votes: Dict[str, int] = {}
        for _, held in counted:
            for lid in held:
                votes[lid] = votes.get(lid, 0) + 1
        if not votes:
            continue
        guard = max(sorted(votes), key=lambda lid: votes[lid])
        n = votes[guard]
        if n < 2 or 2 * n <= total:
            continue  # no majority guard — unguarded-shared-state's turf
        name = key[-1] if len(key) == 2 else f"{key[1]}.{key[2]}"
        for acc, held in counted:
            if guard in held:
                continue
            others = ", ".join(sorted(held)) or "no lock"
            findings.append(
                Finding(
                    "inconsistent-guard",
                    mod.rel_path,
                    acc.line,
                    f"'{name}' is guarded by {guard} at {n}/{total} access "
                    f"sites but this access holds {others}",
                )
            )
    findings.sort(key=lambda f: (f.line, f.message))
    # one finding per line: several mentions of the same name on a line
    # collapse (dict/loop expressions mention a var more than once)
    out: List[Finding] = []
    seen: Set[Tuple[int, str]] = set()
    for f in findings:
        if (f.line, f.message) not in seen:
            seen.add((f.line, f.message))
            out.append(f)
    return out
