"""graftrace: whole-repo concurrency analysis on the graftlint engine.

Two layers, same split as graftlint + analysis/guards.py:

- ``locks.py``    static lock model — every threading.Lock/RLock/Condition
                  site, held-set propagation through the call graph, the
                  interprocedural acquisition-order graph.
- ``rules.py``    three graftlint rules over that model (lock-order-cycle,
                  blocking-call-under-lock, inconsistent-guard).
- ``witness.py``  runtime lock-witness (KMAMIZ_LOCK_WITNESS=1): records
                  actual acquisition orders during soaks and cross-checks
                  them against the static model.

Deliberately jax-free, like the rest of ``analysis/``.
"""
