"""Runtime lock-witness: record real acquisition orders, cross-check
the static model (KMAMIZ_LOCK_WITNESS=1).

Same two-layer shape as analysis/guards.py's transfer guard: graftrace's
static rules catch the *causes* (a cyclic order graph, a blocking call
under a lock) while this witness catches the *symptoms* during tests
and scenario soaks — and closes the loop: a witnessed edge the static
extractor missed is itself a finding (the extractor has a blind spot),
not a pass.

Mechanics: ``install()`` patches the ``threading.Lock`` / ``RLock``
factories so locks **created afterwards from repo code** return a
recording proxy named by its creation site (``rel/path.py:line`` — the
same site the static model keys on). The proxy keeps a thread-local
held stack; each first-depth acquire records one order edge per held
lock plus per-site acquire counts and held-duration maxima. Locks
created before arming (module-level registries) stay raw — the soak
constructs its fleet after arming, which is where the nests live.

``check()`` asserts the witnessed order graph is acyclic AND a subgraph
of the static model's wide (coverage-biased) edge set. Same-site pairs
— two *instances* from one creation site nesting — are reported
informationally: a per-instance hierarchy is real but inexpressible in
a site-keyed static model.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from kmamiz_tpu.telemetry.registry import REGISTRY

ENV_WITNESS = "KMAMIZ_LOCK_WITNESS"

# the meta lock is created from the REAL factory before any patching
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_meta = _REAL_LOCK()

_installed = False
_edges: Dict[Tuple[Tuple[str, int], Tuple[str, int]], int] = {}
_acquires: Dict[Tuple[str, int], int] = {}
_max_hold_ms: Dict[Tuple[str, int], float] = {}
_total_hold_ms: Dict[Tuple[str, int], float] = {}

_PKG_ROOT = os.path.dirname(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
)

_ACQUIRES_TOTAL = REGISTRY.counter(
    "kmamiz_lock_witness_acquires_total",
    "first-depth lock acquisitions recorded by the lock witness",
)
_EDGES_GAUGE = REGISTRY.gauge(
    "kmamiz_lock_witness_edges",
    "distinct witnessed lock-order edges (by creation site)",
)
_CYCLES_GAUGE = REGISTRY.gauge(
    "kmamiz_lock_witness_cycles",
    "cycles in the witnessed lock-order graph (must stay 0)",
)
_UNCOVERED_GAUGE = REGISTRY.gauge(
    "kmamiz_lock_witness_uncovered_edges",
    "witnessed order edges missing from the static graftrace model",
)
_MAX_HOLD_GAUGE = REGISTRY.gauge(
    "kmamiz_lock_witness_max_hold_ms",
    "longest witnessed single hold of any repo lock, ms",
)


def enabled() -> bool:
    return os.environ.get(ENV_WITNESS, "0") not in ("0", "false", "")


def installed() -> bool:
    return _installed


def _repo_rel(filename: str) -> Optional[str]:
    try:
        rel = os.path.relpath(os.path.abspath(filename), _PKG_ROOT)
    except ValueError:
        return None
    rel = rel.replace(os.sep, "/")
    if rel.startswith("kmamiz_tpu/") and rel.endswith(".py"):
        return rel
    return None


class _TLS(threading.local):
    def __init__(self) -> None:
        self.stack: List[List] = []  # [proxy_id, site, t0]
        self.counts: Dict[int, int] = {}


_tls = _TLS()


def _record_first_acquire(site: Tuple[str, int]) -> None:
    _ACQUIRES_TOTAL.inc()
    with _meta:
        _acquires[site] = _acquires.get(site, 0) + 1
        seen: Set[Tuple[str, int]] = set()
        for _pid, src, _t0 in _tls.stack:
            if src == site or src in seen:
                continue
            seen.add(src)
            key = (src, site)
            _edges[key] = _edges.get(key, 0) + 1


def _record_hold(site: Tuple[str, int], dur_ms: float) -> None:
    with _meta:
        if dur_ms > _max_hold_ms.get(site, 0.0):
            _max_hold_ms[site] = dur_ms
        _total_hold_ms[site] = _total_hold_ms.get(site, 0.0) + dur_ms


class _WitnessLock:
    """Recording proxy around one Lock/RLock instance."""

    __slots__ = ("_inner", "_site", "_kind")

    def __init__(self, inner, site: Tuple[str, int], kind: str) -> None:
        self._inner = inner
        self._site = site
        self._kind = kind

    # -- core protocol --------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            return ok
        pid = id(self)
        depth = _tls.counts.get(pid, 0)
        _tls.counts[pid] = depth + 1
        if depth == 0:
            _record_first_acquire(self._site)
            _tls.stack.append([pid, self._site, time.perf_counter()])
        return ok

    def release(self) -> None:
        pid = id(self)
        depth = _tls.counts.get(pid, 0)
        if depth == 1:
            for i in range(len(_tls.stack) - 1, -1, -1):
                if _tls.stack[i][0] == pid:
                    t0 = _tls.stack[i][2]
                    del _tls.stack[i]
                    _record_hold(
                        self._site, (time.perf_counter() - t0) * 1000.0
                    )
                    break
            _tls.counts.pop(pid, None)
        elif depth > 1:
            _tls.counts[pid] = depth - 1
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition integration (threading.Condition delegates these) ----

    def _release_save(self):
        pid = id(self)
        depth = _tls.counts.pop(pid, 0)
        if depth:
            for i in range(len(_tls.stack) - 1, -1, -1):
                if _tls.stack[i][0] == pid:
                    t0 = _tls.stack[i][2]
                    del _tls.stack[i]
                    _record_hold(
                        self._site, (time.perf_counter() - t0) * 1000.0
                    )
                    break
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        if inner_state is not None and hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        if depth:
            _tls.counts[id(self)] = depth
            _record_first_acquire(self._site)
            _tls.stack.append([id(self), self._site, time.perf_counter()])

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<WitnessLock {self._kind} {self._site[0]}:{self._site[1]}>"


def _factory(kind: str, real):
    def make():
        inner = real()
        if not _installed:
            return inner
        frame = sys._getframe(1)
        rel = _repo_rel(frame.f_code.co_filename)
        if rel is None:
            return inner
        return _WitnessLock(inner, (rel, frame.f_lineno), kind)

    make.__name__ = kind
    return make


def install() -> None:
    """Patch the lock factories; repo locks created from here on record."""
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = _factory("Lock", _REAL_LOCK)
    threading.RLock = _factory("RLock", _REAL_RLOCK)


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    _installed = False
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _publish()


@contextmanager
def armed():
    """Install for the duration of a scenario/test body."""
    install()
    try:
        yield
    finally:
        uninstall()


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------


def _site_str(site: Tuple[str, int]) -> str:
    return f"{site[0]}:{site[1]}"


def _witnessed_edges() -> Dict[Tuple[Tuple[str, int], Tuple[str, int]], int]:
    with _meta:
        return dict(_edges)


def _find_cycles(
    pairs: Set[Tuple[Tuple[str, int], Tuple[str, int]]]
) -> List[List[str]]:
    adj: Dict[Tuple[str, int], Set[Tuple[str, int]]] = {}
    for src, dst in pairs:
        adj.setdefault(src, set()).add(dst)
    cycles: List[List[str]] = []
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[Tuple[str, int], int] = {}
    path: List[Tuple[str, int]] = []

    def dfs(v: Tuple[str, int]) -> None:
        color[v] = GRAY
        path.append(v)
        for w in sorted(adj.get(v, ())):
            c = color.get(w, WHITE)
            if c == GRAY:
                i = path.index(w)
                cycles.append([_site_str(s) for s in path[i:]] + [_site_str(w)])
            elif c == WHITE:
                dfs(w)
        path.pop()
        color[v] = BLACK

    for v in sorted(adj):
        if color.get(v, WHITE) == WHITE:
            dfs(v)
    return cycles


@dataclass
class WitnessReport:
    cycles: List[List[str]] = field(default_factory=list)
    uncovered: List[Tuple[str, str]] = field(default_factory=list)
    unknown_sites: List[str] = field(default_factory=list)
    peer_edges: List[str] = field(default_factory=list)  # informational
    edge_count: int = 0
    acquire_count: int = 0

    @property
    def acyclic(self) -> bool:
        return not self.cycles

    @property
    def ok(self) -> bool:
        return self.acyclic and not self.uncovered and not self.unknown_sites


_static_cache: Optional[Tuple[Set[Tuple[str, int]], Set[tuple]]] = None


def _static_sites_and_pairs() -> Tuple[Set[Tuple[str, int]], Set[tuple]]:
    """(known creation sites, wide coverage edge set) from the static
    model — built once per process, pure-ast, no jax."""
    global _static_cache
    if _static_cache is None:
        from kmamiz_tpu.analysis.concurrency import locks as _locks

        model = _locks.repo_model()
        sites = {
            (s.rel_path, s.line) for s in model.locks.values()
        }
        pairs = set()
        for src, dst in model.wide_edge_pairs:
            a = model.creation_site(src)
            b = model.creation_site(dst)
            if a and b:
                pairs.add((a, b))
        _static_cache = (sites, pairs)
    return _static_cache


def check(static: Optional[Tuple[Set, Set]] = None) -> WitnessReport:
    """Cross-check the witnessed order graph against the static model."""
    known_sites, static_pairs = (
        static if static is not None else _static_sites_and_pairs()
    )
    edges = _witnessed_edges()
    report = WitnessReport()
    pairs: Set[Tuple[Tuple[str, int], Tuple[str, int]]] = set()
    for (src, dst), _count in edges.items():
        if src == dst:
            report.peer_edges.append(_site_str(src))
            continue
        pairs.add((src, dst))
    report.edge_count = len(pairs)
    with _meta:
        report.acquire_count = sum(_acquires.values())
    report.cycles = _find_cycles(pairs)
    for src, dst in sorted(pairs):
        for site in (src, dst):
            s = _site_str(site)
            if site not in known_sites and s not in report.unknown_sites:
                report.unknown_sites.append(s)
        if (src, dst) not in static_pairs:
            report.uncovered.append((_site_str(src), _site_str(dst)))
    _publish(report)
    return report


def _publish(report: Optional[WitnessReport] = None) -> None:
    with _meta:
        distinct = len({(s, d) for (s, d) in _edges if s != d})
        max_hold = max(_max_hold_ms.values(), default=0.0)
    _EDGES_GAUGE.set(distinct)
    _MAX_HOLD_GAUGE.set(max_hold)
    if report is not None:
        _CYCLES_GAUGE.set(len(report.cycles))
        _UNCOVERED_GAUGE.set(len(report.uncovered))


def snapshot() -> dict:
    """JSON-shaped state for /timings."""
    with _meta:
        sites = sorted(_acquires)
        out_sites = {
            _site_str(s): {
                "acquires": _acquires.get(s, 0),
                "maxHoldMs": round(_max_hold_ms.get(s, 0.0), 3),
                "totalHoldMs": round(_total_hold_ms.get(s, 0.0), 3),
            }
            for s in sites
        }
        out_edges = [
            {"src": _site_str(s), "dst": _site_str(d), "count": c}
            for (s, d), c in sorted(_edges.items())
        ]
    _publish()
    return {
        "enabled": enabled(),
        "installed": _installed,
        "locks": out_sites,
        "edges": out_edges,
    }


def reset_for_tests() -> None:
    uninstall()
    global _static_cache
    with _meta:
        _edges.clear()
        _acquires.clear()
        _max_hold_ms.clear()
        _total_hold_ms.clear()
    _static_cache = None
    _tls.stack.clear()
    _tls.counts.clear()
    for g in (_EDGES_GAUGE, _CYCLES_GAUGE, _UNCOVERED_GAUGE, _MAX_HOLD_GAUGE):
        g.set(0.0)
