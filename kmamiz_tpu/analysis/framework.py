"""graftlint engine: modules, suppressions, rule registry, reporters.

Deliberately jax-free — linting is pure ``ast`` work so the CLI and the
tier-1 repo-clean test never pay a jax import (or an accelerator init)
just to read source files.
"""
from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set

# `# graftlint: disable=rule-a,rule-b -- reason` (reason optional unless
# strict mode; `--` separator optional)
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=(?P<rules>[\w,-]+)(?:\s*(?:--)?\s*(?P<reason>\S.*))?"
)


@dataclass(frozen=True)
class Suppression:
    line: int
    rules: frozenset
    reason: str = ""


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class ModuleInfo:
    """One parsed source file: AST, source lines, suppression comments."""

    def __init__(self, rel_path: str, source: str):
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel_path)
        self.suppressions: Dict[int, Suppression] = {}
        for tok in tokenize.generate_tokens(StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = frozenset(
                r.strip() for r in m.group("rules").split(",") if r.strip()
            )
            self.suppressions[tok.start[0]] = Suppression(
                line=tok.start[0], rules=rules, reason=(m.group("reason") or "").strip()
            )

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        """A finding at `line` is suppressed by a disable comment for its
        rule on the same physical line, or on the line directly above
        (comment-above style, for lines formatters keep full)."""
        for ln in (line, line - 1):
            sup = self.suppressions.get(ln)
            if sup and rule in sup.rules:
                return sup
        return None


@dataclass
class LintContext:
    """Everything rules may consult beyond their own module's AST."""

    root: str
    modules: Dict[str, ModuleInfo] = field(default_factory=dict)
    # qualnames ("pkg/mod.py:Class.fn") reachable from the tick/serve
    # entry points; None => hot-path-gated rules treat every fn as hot
    # (fixture mode), computed lazily otherwise
    hot: Optional[Set[str]] = None
    # repo-wide names bound to jitted callables (for shape-hazard's
    # "passed into a jitted call" check); filled by the engine
    jit_bound_names: Set[str] = field(default_factory=set)
    # jit-site coverage tables; default to core.programs' live tables
    registered_sites: Optional[Dict[str, set]] = None
    allowlisted_sites: Optional[Dict[str, set]] = None

    def is_hot(self, qualname: str) -> bool:
        return self.hot is None or qualname in self.hot


@dataclass(frozen=True)
class Rule:
    name: str
    doc: str
    check: Callable[[ModuleInfo, LintContext], List[Finding]]


_RULES: Dict[str, Rule] = {}


def rule(name: str, doc: str):
    """Register a rule checker: fn(module, context) -> [Finding]."""

    def deco(fn):
        _RULES[name] = Rule(name=name, doc=doc, check=fn)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    from kmamiz_tpu.analysis import rules as _  # noqa: F401  (registers)
    from kmamiz_tpu.analysis.concurrency import rules as _c  # noqa: F401

    return dict(_RULES)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", "tests", "docs"}


def _iter_py_files(root: str, paths: Optional[Sequence[str]]) -> List[str]:
    if paths:
        out = []
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isdir(ap):
                out.extend(_iter_py_files(root, _walk(ap, root)))
            else:
                out.append(os.path.relpath(ap, root))
        return sorted(set(out))
    return _walk(os.path.join(root, "kmamiz_tpu"), root)


def _walk(top: str, root: str) -> List[str]:
    found = []
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for f in filenames:
            if f.endswith(".py"):
                found.append(os.path.relpath(os.path.join(dirpath, f), root))
    return sorted(found)


def build_context(
    root: str,
    paths: Optional[Sequence[str]] = None,
    *,
    seeds: Optional[Sequence[str]] = None,
    hot_all: bool = False,
    tables: Optional[tuple] = None,
) -> LintContext:
    """tables: optional (registered_sites, allowlisted_sites) override for
    the unregistered-jit rule — fixture corpora must not inherit the live
    core/programs tables, whose paths can collide with fixture paths."""
    ctx = LintContext(root=root)
    if tables is not None:
        ctx.registered_sites, ctx.allowlisted_sites = tables
    for rel in _iter_py_files(root, paths):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                ctx.modules[rel.replace(os.sep, "/")] = ModuleInfo(rel, fh.read())
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue  # non-parseable files are out of scope, not findings

    from kmamiz_tpu.analysis import callgraph, rules as _rules

    ctx.jit_bound_names = _rules.collect_jit_bound_names(ctx)
    if hot_all:
        ctx.hot = None
    else:
        ctx.hot = callgraph.hot_functions(ctx, seeds=seeds)
    if ctx.registered_sites is None or ctx.allowlisted_sites is None:
        from kmamiz_tpu.core import programs

        ctx.registered_sites = {
            k: set(v) for k, v in programs.REGISTERED_JIT_SITES.items()
        }
        ctx.allowlisted_sites = {
            k: set(v) for k, v in programs.ALLOWLISTED_JIT_SITES.items()
        }
    return ctx


@dataclass
class LintResult:
    findings: List[Finding]  # unsuppressed
    suppressed: List[Finding]
    suppressions_used: List[tuple]  # (rel_path, Suppression)

    def missing_reasons(self) -> List[tuple]:
        return [(p, s) for p, s in self.suppressions_used if not s.reason]


def run_rules(
    ctx: LintContext, rule_names: Optional[Iterable[str]] = None
) -> LintResult:
    registry = all_rules()
    names = list(rule_names) if rule_names else sorted(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
    live: List[Finding] = []
    suppressed: List[Finding] = []
    used: List[tuple] = []
    for rel in sorted(ctx.modules):
        mod = ctx.modules[rel]
        for name in names:
            for f in registry[name].check(mod, ctx):
                sup = mod.suppression_for(f.rule, f.line)
                if sup is not None:
                    suppressed.append(f)
                    used.append((mod.rel_path, sup))
                else:
                    live.append(f)
    live.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings=live, suppressed=suppressed, suppressions_used=used)


def lint_paths(
    root: str,
    paths: Optional[Sequence[str]] = None,
    rules: Optional[Iterable[str]] = None,
    *,
    seeds: Optional[Sequence[str]] = None,
    hot_all: bool = False,
    tables: Optional[tuple] = None,
) -> LintResult:
    ctx = build_context(root, paths, seeds=seeds, hot_all=hot_all, tables=tables)
    return run_rules(ctx, rules)


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def lint_repo(rules: Optional[Iterable[str]] = None) -> LintResult:
    """Lint the kmamiz_tpu package in-repo (what --strict CI runs)."""
    return lint_paths(repo_root(), None, rules)


# ---------------------------------------------------------------------------
# reporters
# ---------------------------------------------------------------------------


def render_text(result: LintResult, *, verbose: bool = False) -> str:
    out = [f.render() for f in result.findings]
    if verbose and result.suppressed:
        out.append("")
        out.extend(f"suppressed: {f.render()}" for f in result.suppressed)
    out.append(
        f"graftlint: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed"
    )
    return "\n".join(out)


def render_json(result: LintResult) -> str:
    return json.dumps(
        {
            "findings": [vars(f) for f in result.findings],
            "suppressed": [vars(f) for f in result.suppressed],
            "counts": {
                "findings": len(result.findings),
                "suppressed": len(result.suppressed),
            },
        },
        indent=2,
        sort_keys=True,
    )
