"""graftlint: JAX-aware static analysis enforcing the hot-path invariants.

PRs 1-3 made the tick and training paths fast by hand-establishing a set
of invariants — every hot jit registered in core/programs.py, statics
pow2-bucketed, buffers donated, the tick free of implicit host<->device
transfers. This package is the mechanical guard that keeps refactors
from silently regressing them:

- :mod:`framework` — AST lint engine: rule registry, per-line
  suppressions (``# graftlint: disable=<rule> -- reason``), text/JSON
  reporters;
- :mod:`callgraph` — lightweight import+call-graph walk that decides
  which functions are reachable from the tick/serve entry points;
- :mod:`rules` — the six shipped rules (unregistered-jit,
  host-sync-in-hot-path, shape-hazard, dtype-drift, donation-miss,
  unguarded-shared-state);
- :mod:`guards` — the RUNTIME enforcement layer: a context manager
  wrapping a dp tick in ``jax.transfer_guard("disallow")`` plus the
  program-registry recompile counters (KMAMIZ_TRANSFER_GUARD=1 turns it
  on in the serving process).

Run it via ``python tools/graftlint.py [--strict]``; docs in
docs/STATIC_ANALYSIS.md. This module deliberately never imports jax —
the CLI lints the repo without paying a jax import.
"""
from kmamiz_tpu.analysis.framework import (  # noqa: F401
    Finding,
    LintContext,
    Rule,
    all_rules,
    lint_paths,
    lint_repo,
    render_json,
    render_text,
)
