"""The nine shipped graftlint rules.

Each rule is a function (module, context) -> [Finding] registered via
framework.rule(). Shared AST plumbing (jit-site extraction, parent maps,
taint walks) lives at the top; the rules themselves stay short.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set

from kmamiz_tpu.analysis.framework import (
    Finding,
    LintContext,
    ModuleInfo,
    rule,
)

# ---------------------------------------------------------------------------
# shared AST plumbing
# ---------------------------------------------------------------------------


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    return {
        child: parent
        for parent in ast.walk(tree)
        for child in ast.iter_child_nodes(parent)
    }


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _chain_str(node: ast.AST) -> str:
    chain = _attr_chain(node)
    return ".".join(chain) if chain else ""


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _is_jit_callable(node: ast.AST) -> bool:
    """True when `node` denotes jax.jit/pjit itself (Name or Attribute)."""
    return _chain_str(node) in _JIT_NAMES


@dataclass
class JitSite:
    name: str  # function name the site binds to (old-scanner semantics)
    line: int
    keywords: Set[str]  # kwargs passed to jit/partial(jit, ...)
    fn_node: Optional[ast.AST]  # wrapped FunctionDef when resolvable
    registered_by_construction: bool  # under @programs.register / register_instance


def _jit_decorator(dec: ast.AST) -> Optional[Set[str]]:
    """If `dec` applies jax.jit, return its kwarg names; else None."""
    if _is_jit_callable(dec):
        return set()
    if isinstance(dec, ast.Call):
        if _is_jit_callable(dec.func):
            return {k.arg for k in dec.keywords if k.arg}
        # partial(jax.jit, static_argnames=...)
        if _chain_str(dec.func) in {"partial", "functools.partial"} and dec.args:
            if _is_jit_callable(dec.args[0]):
                return {k.arg for k in dec.keywords if k.arg}
    return None


def _is_register_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    chain = _attr_chain(dec)
    return bool(chain) and chain[-1] in {"register", "register_instance"}


def _enclosing_defs(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> List[ast.FunctionDef]:
    out = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(cur)
        cur = parents.get(cur)
    return out


def _under_register_call(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call) and _is_register_decorator(cur.func):
            return True
        cur = parents.get(cur)
    return False


def jit_sites(mod: ModuleInfo) -> List[JitSite]:
    """Every jax.jit/pjit application in the module, bound to a function
    name the way core/programs' guard tables expect: decorators bind to
    the decorated def; `jax.jit(f)` binds to f (if local) else the
    assignment target else the nearest enclosing def."""
    parents = _parents(mod.tree)
    local_defs: Dict[str, ast.AST] = {
        n.name: n
        for n in ast.walk(mod.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    sites: List[JitSite] = []
    seen_calls: Set[int] = set()

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            kw: Optional[Set[str]] = None
            registered = False
            for dec in node.decorator_list:
                got = _jit_decorator(dec)
                if got is not None:
                    kw = got
                if _is_register_decorator(dec):
                    registered = True
            if kw is not None:
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and _jit_decorator(dec) is not None:
                        seen_calls.add(id(dec))
                sites.append(
                    JitSite(node.name, node.lineno, kw, node, registered)
                )

    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _is_jit_callable(node.func)):
            continue
        if id(node) in seen_calls:
            continue
        kw = {k.arg for k in node.keywords if k.arg}
        name = None
        fn_node = None
        if node.args and isinstance(node.args[0], ast.Name):
            name = node.args[0].id
            fn_node = local_defs.get(name)
        if name is None:
            parent = parents.get(node)
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                tgt = parent.targets[0]
                if isinstance(tgt, ast.Name):
                    name = tgt.id
        if name is None:
            enc = _enclosing_defs(node, parents)
            name = enc[0].name if enc else "<module>"
        registered = _under_register_call(node, parents)
        if fn_node is not None:
            for dec in getattr(fn_node, "decorator_list", []):
                if _is_register_decorator(dec):
                    registered = True
        sites.append(JitSite(name, node.lineno, kw, fn_node, registered))
    return sites


def collect_jit_bound_names(ctx: LintContext) -> Set[str]:
    names = set()
    for mod in ctx.modules.values():
        for site in jit_sites(mod):
            if site.name != "<module>":
                names.add(site.name)
    return names


def _walk_own(fn_node: ast.AST):
    """Walk a function body without descending into nested defs (they
    lint under their own qualname)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _functions(mod: ModuleInfo):
    """(qualname-suffix, node) for every def, class-qualified."""

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                yield qn, child
                yield from visit(child, f"{qn}.")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")

    yield from visit(mod.tree, "")


# ---------------------------------------------------------------------------
# rule 1: unregistered-jit
# ---------------------------------------------------------------------------


@rule(
    "unregistered-jit",
    "every jax.jit/pjit/lax.scan entry point must be wrapped in the "
    "core/programs registry or listed in its guard tables",
)
def check_unregistered_jit(mod: ModuleInfo, ctx: LintContext) -> List[Finding]:
    registered = (ctx.registered_sites or {}).get(mod.rel_path, set())
    allowlisted = (ctx.allowlisted_sites or {}).get(mod.rel_path, set())
    sites = jit_sites(mod)
    findings: List[Finding] = []
    covered_names: Set[str] = set()
    for site in sites:
        covered = (
            site.registered_by_construction
            or site.name in registered
            or site.name in allowlisted
        )
        if covered:
            covered_names.add(site.name)
        else:
            findings.append(
                Finding(
                    "unregistered-jit",
                    mod.rel_path,
                    site.line,
                    f"jit site '{site.name}' is not wrapped in the program "
                    "registry and not listed in REGISTERED_JIT_SITES/"
                    "ALLOWLISTED_JIT_SITES (core/programs.py)",
                )
            )
    # stale guard entries: table names with no site in the file at all
    site_names = {s.name for s in sites}
    for name in sorted((registered | allowlisted) - site_names):
        findings.append(
            Finding(
                "unregistered-jit",
                mod.rel_path,
                1,
                f"stale guard entry: '{name}' is listed for this file in "
                "core/programs.py but no jit site binds to it",
            )
        )
    # bare lax.scan outside any covered jit: a compiled loop the registry
    # cannot see (prewarm/recompile counters miss it)
    parents = _parents(mod.tree)
    for node in ast.walk(mod.tree):
        if not (
            isinstance(node, ast.Call)
            and _chain_str(node.func) in {"lax.scan", "jax.lax.scan"}
        ):
            continue
        chain = _enclosing_defs(node, parents)
        names_in_chain = {fn.name for fn in chain}
        if names_in_chain & (covered_names | registered | allowlisted):
            continue
        if any(
            _is_register_decorator(d)
            for fn in chain
            for d in fn.decorator_list
        ):
            continue
        findings.append(
            Finding(
                "unregistered-jit",
                mod.rel_path,
                node.lineno,
                "lax.scan outside any registered jit site: this compiled "
                "loop is invisible to the program registry",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# rule 2: host-sync-in-hot-path
# ---------------------------------------------------------------------------

_HOST_SYNC_CALLS = {
    "jax.device_get": "explicit device->host fetch",
    "jax.block_until_ready": "blocks the host on device work",
    "np.asarray": "device->host copy when fed a device array",
    "numpy.asarray": "device->host copy when fed a device array",
}

_DEVICE_PRODUCERS = ("jnp.", "jax.")
_HOST_PRODUCERS = {
    "jax.device_get",
    "np.asarray",
    "numpy.asarray",
    "jax.devices",
    "jax.local_devices",
    "jax.device_count",
}
# attribute reads that return host metadata, not device data
_METADATA_ATTRS = {"shape", "size", "ndim", "dtype"}


def _device_taint(fn_node: ast.AST, ctx: LintContext) -> Set[str]:
    """Names in this function assigned from jnp./jax. calls or calls to
    known jitted callables — i.e. likely device arrays."""
    taint: Set[str] = set()
    for node in _walk_own(fn_node):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        cs = _chain_str(node.value.func)
        produces_device = (
            cs.startswith(_DEVICE_PRODUCERS) or cs.split(".")[-1] in ctx.jit_bound_names
        ) and cs not in _HOST_PRODUCERS
        if not produces_device:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                taint.add(tgt.id)
            elif isinstance(tgt, ast.Tuple):
                taint.update(
                    e.id for e in tgt.elts if isinstance(e, ast.Name)
                )
    return taint


def _mentions_taint(node: ast.AST, taint: Set[str]) -> bool:
    """Does the expression read device DATA (not host metadata like
    .shape/.size, and not through a host producer like device_get)?"""
    stack = [node]
    while stack:
        sub = stack.pop()
        if isinstance(sub, ast.Attribute) and sub.attr in _METADATA_ATTRS:
            continue  # x.shape[...] etc. never touch device data
        if isinstance(sub, ast.Call):
            cs = _chain_str(sub.func)
            if cs in _HOST_PRODUCERS:
                continue  # returns a host value; the sync is its own finding
            if cs.startswith(_DEVICE_PRODUCERS):
                return True
        if isinstance(sub, ast.Name) and sub.id in taint:
            return True
        stack.extend(ast.iter_child_nodes(sub))
    return False


@rule(
    "host-sync-in-hot-path",
    "no device->host synchronization (.item(), float()/int() on device "
    "values, np.asarray/jax.device_get/block_until_ready) in functions "
    "reachable from the tick/serve entry points",
)
def check_host_sync(mod: ModuleInfo, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for suffix, fn_node in _functions(mod):
        if not ctx.is_hot(f"{mod.rel_path}:{suffix}"):
            continue
        taint = _device_taint(fn_node, ctx)
        for node in _walk_own(fn_node):
            if not isinstance(node, ast.Call):
                continue
            cs = _chain_str(node.func)
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "item" and not node.args:
                    findings.append(
                        Finding(
                            "host-sync-in-hot-path",
                            mod.rel_path,
                            node.lineno,
                            ".item() forces a device->host sync on the hot path",
                        )
                    )
                    continue
                if node.func.attr == "block_until_ready":
                    findings.append(
                        Finding(
                            "host-sync-in-hot-path",
                            mod.rel_path,
                            node.lineno,
                            ".block_until_ready() stalls the hot path on device work",
                        )
                    )
                    continue
            if cs in _HOST_SYNC_CALLS:
                if cs in {"np.asarray", "numpy.asarray"} and not (
                    node.args and _mentions_taint(node.args[0], taint)
                ):
                    continue  # asarray of host data is free
                findings.append(
                    Finding(
                        "host-sync-in-hot-path",
                        mod.rel_path,
                        node.lineno,
                        f"{cs}() on the hot path: {_HOST_SYNC_CALLS[cs]}",
                    )
                )
                continue
            if cs in {"float", "int", "bool"} and node.args:
                if _mentions_taint(node.args[0], taint):
                    findings.append(
                        Finding(
                            "host-sync-in-hot-path",
                            mod.rel_path,
                            node.lineno,
                            f"{cs}() of a device value forces a device->host "
                            "sync on the hot path",
                        )
                    )
    return findings


# ---------------------------------------------------------------------------
# rule 3: shape-hazard
# ---------------------------------------------------------------------------

_BUCKET_FNS = ("pad", "pow2", "bucket")


def _raw_shape_expr(node: ast.AST, taint: Set[str]) -> bool:
    """Structural test: is this expression a RAW shape scalar — x.shape,
    x.shape[i], int() of one, arithmetic over them, or a name carrying
    one? Any other call launders the value (in particular anything
    routed through a *pad*/*pow2*/*bucket* helper)."""
    if isinstance(node, ast.Attribute):
        return node.attr == "shape"
    if isinstance(node, ast.Subscript):
        return _raw_shape_expr(node.value, taint)
    if isinstance(node, ast.Name):
        return node.id in taint
    if isinstance(node, ast.Call):
        if _chain_str(node.func) == "int" and node.args:
            return _raw_shape_expr(node.args[0], taint)
        return False
    if isinstance(node, ast.BinOp):
        return _raw_shape_expr(node.left, taint) or _raw_shape_expr(
            node.right, taint
        )
    if isinstance(node, ast.UnaryOp):
        return _raw_shape_expr(node.operand, taint)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_raw_shape_expr(e, taint) for e in node.elts)
    if isinstance(node, ast.Starred):
        return _raw_shape_expr(node.value, taint)
    return False


def _shape_taint(fn_node: ast.AST) -> Set[str]:
    """Names assigned a raw (unbucketed) Python shape scalar."""
    taint: Set[str] = set()
    for node in _walk_own(fn_node):
        if not isinstance(node, ast.Assign):
            continue
        values = (
            node.value.elts
            if isinstance(node.value, ast.Tuple)
            else [node.value]
        )
        for tgt in node.targets:
            names = (
                [e for e in tgt.elts if isinstance(e, ast.Name)]
                if isinstance(tgt, ast.Tuple)
                else ([tgt] if isinstance(tgt, ast.Name) else [])
            )
            if isinstance(tgt, ast.Tuple) and len(values) == len(tgt.elts):
                for e, v in zip(tgt.elts, values):
                    if isinstance(e, ast.Name) and _raw_shape_expr(v, taint):
                        taint.add(e.id)
            elif isinstance(tgt, ast.Tuple):
                # n, f = x.shape: unpacking a shape taints every target
                if _raw_shape_expr(node.value, taint):
                    taint.update(e.id for e in names)
            else:
                if names and _raw_shape_expr(node.value, taint):
                    taint.add(names[0].id)
    return taint


def _arg_is_raw_shape(arg: ast.AST, taint: Set[str]) -> bool:
    return _raw_shape_expr(arg, taint)


@rule(
    "shape-hazard",
    "raw Python scalars from arr.shape must pass through pow2 bucketing "
    "(_pad_size/_pow2) before reaching jitted calls, f-strings or cache keys",
)
def check_shape_hazard(mod: ModuleInfo, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for suffix, fn_node in _functions(mod):
        taint = _shape_taint(fn_node)
        # shapes interpolated into raised error messages are diagnostics,
        # not cache keys — skip f-strings under a raise
        in_raise = {
            id(sub)
            for n in _walk_own(fn_node)
            if isinstance(n, ast.Raise)
            for sub in ast.walk(n)
            if isinstance(sub, ast.JoinedStr)
        }
        for node in _walk_own(fn_node):
            if isinstance(node, ast.JoinedStr) and id(node) in in_raise:
                continue
            if isinstance(node, ast.Call):
                callee = _chain_str(node.func).split(".")[-1]
                if callee in ctx.jit_bound_names:
                    for arg in list(node.args) + [k.value for k in node.keywords]:
                        if _arg_is_raw_shape(arg, taint):
                            findings.append(
                                Finding(
                                    "shape-hazard",
                                    mod.rel_path,
                                    node.lineno,
                                    f"raw shape scalar passed to jitted "
                                    f"'{callee}' without pow2 bucketing: "
                                    "every new shape is a recompile",
                                )
                            )
                            break
            elif isinstance(node, ast.JoinedStr):
                for part in node.values:
                    if isinstance(part, ast.FormattedValue) and _arg_is_raw_shape(
                        part.value, taint
                    ):
                        findings.append(
                            Finding(
                                "shape-hazard",
                                mod.rel_path,
                                node.lineno,
                                "f-string built from a raw array shape "
                                "(unbounded-cardinality key/label)",
                            )
                        )
                        break
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Store
            ):
                if _arg_is_raw_shape(node.slice, taint):
                    findings.append(
                        Finding(
                            "shape-hazard",
                            mod.rel_path,
                            node.lineno,
                            "cache/dict keyed on a raw array shape: "
                            "unbounded key cardinality (bucket it first)",
                        )
                    )
            elif isinstance(node, ast.Dict):
                for key in node.keys:
                    if key is not None and _arg_is_raw_shape(key, taint):
                        findings.append(
                            Finding(
                                "shape-hazard",
                                mod.rel_path,
                                node.lineno,
                                "dict literal keyed on a raw array shape "
                                "(bucket it first)",
                            )
                        )
                        break
    return findings


# ---------------------------------------------------------------------------
# rule 4: dtype-drift
# ---------------------------------------------------------------------------

_JNP_CTORS = {"zeros", "ones", "full", "empty", "arange", "linspace", "eye"}


@rule(
    "dtype-drift",
    "no float64 on the hot path (TPUs emulate it in software) and no "
    "jnp constructors relying on the ambient default dtype",
)
def check_dtype_drift(mod: ModuleInfo, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for suffix, fn_node in _functions(mod):
        hot = ctx.is_hot(f"{mod.rel_path}:{suffix}")
        for node in _walk_own(fn_node):
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if (
                    chain
                    and len(chain) == 2
                    and chain[0] == "jnp"
                    and chain[1] in _JNP_CTORS
                ):
                    # dtype may ride positionally: zeros/ones/empty take
                    # it 2nd, full 3rd (after the fill value)
                    pos_ok = len(node.args) >= (
                        3 if chain[1] == "full" else 2
                    ) and chain[1] not in {"arange", "linspace"}
                    if not any(k.arg == "dtype" for k in node.keywords) and not pos_ok:
                        findings.append(
                            Finding(
                                "dtype-drift",
                                mod.rel_path,
                                node.lineno,
                                f"jnp.{chain[1]} without dtype=: inherits the "
                                "ambient default and drifts across x64 configs",
                            )
                        )
                if hot and isinstance(node.func, ast.Attribute):
                    if node.func.attr == "astype" and node.args:
                        a = node.args[0]
                        is64 = (
                            isinstance(a, ast.Constant) and a.value == "float64"
                        ) or _chain_str(a) in {"np.float64", "jnp.float64"}
                        if is64:
                            findings.append(
                                Finding(
                                    "dtype-drift",
                                    mod.rel_path,
                                    node.lineno,
                                    "astype(float64) on the hot path: TPUs "
                                    "emulate f64 in software",
                                )
                            )
            elif hot and isinstance(node, ast.Attribute):
                if _chain_str(node) in {"np.float64", "jnp.float64"}:
                    findings.append(
                        Finding(
                            "dtype-drift",
                            mod.rel_path,
                            node.lineno,
                            "float64 dtype on the hot path: TPUs emulate "
                            "f64 in software (use f32 + compensated sums)",
                        )
                    )
    return findings


# ---------------------------------------------------------------------------
# rule 5: donation-miss
# ---------------------------------------------------------------------------

_CARRY_PARAMS = {"params", "opt_state", "state", "carry", "buffers"}
_DONATE_KW = {"donate_argnums", "donate_argnames"}


@rule(
    "donation-miss",
    "jit sites that thread large carries (params/opt_state) through a "
    "lax.scan or update step must donate them to avoid double-buffering",
)
def check_donation_miss(mod: ModuleInfo, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for site in jit_sites(mod):
        if site.fn_node is None or site.keywords & _DONATE_KW:
            continue
        args = getattr(site.fn_node, "args", None)
        if args is None:
            continue
        param_names = {a.arg for a in args.args + args.kwonlyargs}
        carries = param_names & _CARRY_PARAMS
        if not carries:
            continue
        has_scan = any(
            isinstance(n, ast.Call)
            and _chain_str(n.func) in {"lax.scan", "jax.lax.scan"}
            for n in ast.walk(site.fn_node)
        )
        has_update = any(
            isinstance(n, ast.Call)
            and _chain_str(n.func).split(".")[-1] == "apply_updates"
            for n in ast.walk(site.fn_node)
        )
        if has_scan or has_update:
            findings.append(
                Finding(
                    "donation-miss",
                    mod.rel_path,
                    site.line,
                    f"jit site '{site.name}' threads {sorted(carries)} "
                    "through a scan/update without donate_argnums: the "
                    "carry is double-buffered on device",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# rule 6: unguarded-shared-state
# ---------------------------------------------------------------------------

_MUTABLE_CTORS = {
    "dict",
    "list",
    "set",
    "defaultdict",
    "deque",
    "OrderedDict",
    "Counter",
    "bytearray",
}
_MUTATORS = {
    "append",
    "add",
    "update",
    "clear",
    "pop",
    "popitem",
    "extend",
    "setdefault",
    "remove",
    "discard",
    "insert",
    "appendleft",
}
_STATE_SCOPES = (
    "kmamiz_tpu/server/",
    "kmamiz_tpu/core/",
    # the resilience registries (breakers, counters, quarantine default)
    # are written from scheduler threads, server threads, AND the ingest
    # producer at once — exactly the state this rule exists for
    "kmamiz_tpu/resilience/",
    # the tenancy layer's process-wide registries (arena, per-tenant
    # runtimes, micro-batch queue) take writes from every server thread
    "kmamiz_tpu/tenancy/",
    # the scenario runner's shared mutables (the completed-run registry,
    # per-tenant source queues) are written from the driving thread, the
    # reader thread, and HTTP handler threads of the live soak server
    "kmamiz_tpu/scenarios/",
    # the fleet coordinator's routing state (overrides, drain flags,
    # queues) is written by request threads AND the migration driver;
    # the module counters take increments from every worker thread
    "kmamiz_tpu/fleet/",
    # the STLGT continual trainer's ring/stale/params state is written
    # from the processor's fold path while /model/forecast and
    # /model/stlgt read it from server threads
    "kmamiz_tpu/models/stlgt/",
    # the graftpilot controller's decision stores (admission states,
    # cost table, warmed-breaker sets) are swapped from the fold path
    # while every serving thread reads verdicts per tick
    "kmamiz_tpu/control/",
    # the graftcost plane's model weights, growth tracker, and
    # warmed/pending bookkeeping take writes from merge finalizes on
    # server threads while the background prewarm thread and /timings
    # readers run concurrently
    "kmamiz_tpu/cost/",
    # the graftsoak engine's completed-sweep registry is appended from
    # whichever thread drove run_sweep while tests and observability
    # readers snapshot it; the manifest layer itself is cross-PROCESS
    # shared state (O_EXCL claims + atomic renames stand in for locks,
    # but any in-process mutable module state still needs one)
    "kmamiz_tpu/soak/",
)


def _module_mutables(mod: ModuleInfo) -> Set[str]:
    names: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.If):
            stmts = list(node.body) + list(node.orelse)
        else:
            stmts = [node]
        for stmt in stmts:
            if not isinstance(stmt, ast.Assign):
                continue
            v = stmt.value
            mutable = isinstance(
                v, (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp)
            ) or (
                isinstance(v, ast.Call)
                and _chain_str(v.func).split(".")[-1] in _MUTABLE_CTORS
            )
            if not mutable:
                continue
            names.update(
                t.id for t in stmt.targets if isinstance(t, ast.Name)
            )
    return names


def _lockish(expr: ast.AST) -> bool:
    return "lock" in _chain_str(expr).lower() or (
        isinstance(expr, ast.Call) and "lock" in _chain_str(expr.func).lower()
    )


@rule(
    "unguarded-shared-state",
    "module-level mutable containers in server/, core/ and resilience/ "
    "may only be written under a lock (or inside *_locked helpers)",
)
def check_unguarded_shared_state(
    mod: ModuleInfo, ctx: LintContext
) -> List[Finding]:
    if not mod.rel_path.startswith(_STATE_SCOPES):
        return []
    shared = _module_mutables(mod)
    if not shared:
        return []
    findings: List[Finding] = []

    def visit(node, fn_stack, lock_depth):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_stack = fn_stack + [node.name]
            # a lock held when the closure was entered does not extend
            # into the nested def's own call time
            lock_depth = 0
        if isinstance(node, ast.With) and any(
            _lockish(item.context_expr) for item in node.items
        ):
            lock_depth += 1
        if fn_stack and lock_depth == 0 and not fn_stack[-1].endswith("_locked"):
            hit = _write_to_shared(node, shared)
            if hit:
                findings.append(
                    Finding(
                        "unguarded-shared-state",
                        mod.rel_path,
                        node.lineno,
                        f"module-level '{hit}' written outside a lock "
                        "(wrap in `with <lock>:` or a *_locked helper)",
                    )
                )
        for child in ast.iter_child_nodes(node):
            visit(child, fn_stack, lock_depth)

    visit(mod.tree, [], 0)
    return findings


def _write_to_shared(node: ast.AST, shared: Set[str]) -> Optional[str]:
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id in shared
            ):
                return t.value.id
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id in shared
            ):
                return t.value.id
    elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        f = node.value.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _MUTATORS
            and isinstance(f.value, ast.Name)
            and f.value.id in shared
        ):
            return f.value.id
    elif isinstance(node, ast.Global):
        hit = [n for n in node.names if n in shared]
        if hit:
            return hit[0]
    return None


# ---------------------------------------------------------------------------
# rule 7: hot-path-metric-label
# ---------------------------------------------------------------------------

# methods that mint a new metric child / family: calling one per tick
# means a dict lookup + possible allocation under the registry lock on
# every increment, instead of a one-time lookup at import
_HANDLE_ACQUIRERS = {
    "handle",
    "labels",
    "counter",
    "gauge",
    "histogram",
    "counter_family",
    "gauge_family",
    "histogram_family",
}
# metric write methods whose first argument names the counter/series
_METRIC_WRITERS = {"incr", "inc", "observe"}
# the registry implementation itself necessarily calls these
_METRIC_IMPL_PATHS = ("kmamiz_tpu/telemetry/",)


def _is_stringy(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Constant) and isinstance(node.value, str)
    ) or isinstance(node, ast.JoinedStr)


def _formatted_name(node: ast.AST) -> bool:
    """Is this expression a metric name/label built per call — f-string
    with interpolation, str.format(), %-format, or string concatenation?"""
    if isinstance(node, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in node.values)
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "format"
        and _is_stringy(node.func.value)
    ):
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Mod, ast.Add)):
        return _is_stringy(node.left) or _is_stringy(node.right)
    return False


@rule(
    "hot-path-metric-label",
    "hot-path metric writes must go through handles preallocated at "
    "import time: no handle/family acquisition and no per-call label "
    "formatting (f-string/.format/%/concat names) in functions reachable "
    "from the tick/serve entry points",
)
def check_hot_path_metric_label(
    mod: ModuleInfo, ctx: LintContext
) -> List[Finding]:
    if mod.rel_path.startswith(_METRIC_IMPL_PATHS):
        return []
    findings: List[Finding] = []
    for suffix, fn_node in _functions(mod):
        if not ctx.is_hot(f"{mod.rel_path}:{suffix}"):
            continue
        for node in _walk_own(fn_node):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            attr = node.func.attr
            if attr in _HANDLE_ACQUIRERS:
                findings.append(
                    Finding(
                        "hot-path-metric-label",
                        mod.rel_path,
                        node.lineno,
                        f"metric handle acquisition '.{attr}(...)' on the "
                        "hot path: look the handle up once at import time "
                        "and write through it",
                    )
                )
                continue
            if (
                attr in _METRIC_WRITERS
                and node.args
                and _formatted_name(node.args[0])
            ):
                findings.append(
                    Finding(
                        "hot-path-metric-label",
                        mod.rel_path,
                        node.lineno,
                        f"per-call label formatting in '.{attr}(...)' on "
                        "the hot path: a formatted metric name allocates "
                        "every call and has unbounded cardinality — use a "
                        "preallocated handle",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# rule 8: hot-path-clock
# ---------------------------------------------------------------------------

# raw clock reads: each is a syscall-backed read the profiler cannot see.
# Hot code routes through telemetry/profiling/events.now_ns/now_ms/wall_ms
# — one blessed, greppable detour that keeps every hot clock swappable
# (and lets graftprof account for the reads it makes itself).
_RAW_CLOCKS = {
    "time.time",
    "time.time_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.monotonic",
    "time.monotonic_ns",
}
# bare names from `from time import perf_counter` style imports
_RAW_CLOCK_BASENAMES = {c.split(".", 1)[1] for c in _RAW_CLOCKS}
# the sanctioned clock helpers (and the step timer that predates them)
# necessarily read the raw clocks
_CLOCK_IMPL_PATHS = (
    "kmamiz_tpu/telemetry/",
    "kmamiz_tpu/core/profiling.py",
)


@rule(
    "hot-path-clock",
    "hot-path code must read clocks through the graftprof helpers "
    "(telemetry.profiling.events.now_ns/now_ms/wall_ms), not raw "
    "time.time()/perf_counter(): raw reads scatter unaccountable timing "
    "syscalls through the tick and dodge the host event ring",
)
def check_hot_path_clock(mod: ModuleInfo, ctx: LintContext) -> List[Finding]:
    if mod.rel_path.startswith(_CLOCK_IMPL_PATHS):
        return []
    # a module that imports the time module under a different alias is
    # out of scope for the chain match; the common idioms are covered
    findings: List[Finding] = []
    bare_clock_imports: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in _RAW_CLOCK_BASENAMES:
                    bare_clock_imports.add(alias.asname or alias.name)
    for suffix, fn_node in _functions(mod):
        if not ctx.is_hot(f"{mod.rel_path}:{suffix}"):
            continue
        for node in _walk_own(fn_node):
            if not isinstance(node, ast.Call):
                continue
            chain = _chain_str(node.func)
            if chain in _RAW_CLOCKS or (
                chain in bare_clock_imports and "." not in chain
            ):
                findings.append(
                    Finding(
                        "hot-path-clock",
                        mod.rel_path,
                        node.lineno,
                        f"raw clock read '{chain}()' on the hot path: "
                        "route it through the graftprof clock helpers "
                        "(telemetry.profiling.events.now_ns/now_ms/"
                        "wall_ms) so tick timing stays attributable",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# rule: prof-counter-wire
# ---------------------------------------------------------------------------

# the ctypes decoder module whose _PROF_SCALARS tuples name the wire
_PROF_DECODER = "kmamiz_tpu/native/__init__.py"
_PROF_CPP_REL = os.path.join("native", "kmamiz_spans.cpp")
_PROF_TUPLE_NAMES = {"_PROF_SCALARS", "_PROF_SCALARS_V1"}
# a cumulative scalar in the ProfCounters struct: `uint64_t name = 0;`
# (the per-shard arrays initialize with `= {0}` and never match)
_PROF_SCALAR_RE = re.compile(r"^\s*uint64_t\s+(\w+)\s*=\s*0\s*;")
_PROF_STRUCT_RE = re.compile(r"struct\s+ProfCounters\s*\{(.*?)\n\};", re.S)


def _cpp_prof_scalars(root: str) -> Optional[List[str]]:
    """Scalar counter names in native/kmamiz_spans.cpp's ProfCounters
    struct, declaration (= wire) order; None when the source or struct
    is absent (fixture repos without a native tree)."""
    try:
        with open(os.path.join(root, _PROF_CPP_REL), encoding="utf-8") as fh:
            source = fh.read()
    except OSError:
        return None
    m = _PROF_STRUCT_RE.search(source)
    if not m:
        return None
    return [
        sm.group(1)
        for line in m.group(1).splitlines()
        if (sm := _PROF_SCALAR_RE.match(line))
    ]


@rule(
    "prof-counter-wire",
    "every cumulative uint64 scalar in native ProfCounters "
    "(native/kmamiz_spans.cpp) must be named in _PROF_SCALARS in "
    "kmamiz_tpu/native/__init__.py, and vice versa: the snapshot wire "
    "serializes the struct in declaration order, so an unlisted scalar "
    "silently shifts every later field the Python decoder reads",
)
def check_prof_counter_wire(mod: ModuleInfo, ctx: LintContext) -> List[Finding]:
    if mod.rel_path != _PROF_DECODER:
        return []
    cpp_scalars = _cpp_prof_scalars(ctx.root)
    if cpp_scalars is None:
        return []
    declared: Set[str] = set()
    anchor = 1
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if not targets & _PROF_TUPLE_NAMES:
            continue
        anchor = max(anchor, node.lineno)
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                declared.add(sub.value)
    findings: List[Finding] = []
    for name in cpp_scalars:
        if name not in declared:
            findings.append(
                Finding(
                    "prof-counter-wire",
                    mod.rel_path,
                    anchor,
                    f"native ProfCounters scalar '{name}' is not listed in "
                    "_PROF_SCALARS: the snapshot wire serializes struct "
                    "declaration order, so the decoder misreads every "
                    "field after it (bump kProfWireVersion and append the "
                    "name)",
                )
            )
    for name in sorted(declared - set(cpp_scalars)):
        findings.append(
            Finding(
                "prof-counter-wire",
                mod.rel_path,
                anchor,
                f"_PROF_SCALARS entry '{name}' has no matching uint64_t "
                "scalar in the native ProfCounters struct: a stale "
                "decoder entry misaligns the counter wire",
            )
        )
    return findings
