"""Runtime enforcement layer for the invariants graftlint checks statically.

The static rules catch the patterns that CAUSE hot-path stalls; this
module catches the stalls themselves:

- :func:`hot_path_guard` wraps a full dp tick (or any hot section) in
  ``jax.transfer_guard(...)`` so any IMPLICIT host<->device transfer —
  an eager op baking a host constant, a jit dispatch on a raw numpy
  array — raises instead of silently stalling, and diffs the program
  registry's compile counters across the section so steady-state
  recompiles surface as well.
- ``KMAMIZ_TRANSFER_GUARD`` turns it on in the serving process
  (server/dp_server.py wraps each collect tick): ``1``/``disallow``
  raises on implicit transfers, ``log`` only logs them (jax emits the
  transfer stack), ``0``/unset leaves the tick unguarded.

Note on CPU vs TPU: with the CPU backend, device_get and same-process
numpy views are zero-copy so only host->device constant uploads trip the
guard; on a real TPU every implicit direction trips. The tier-1 test
(tests/test_transfer_guard.py) runs on CPU and still catches the h2d
class — the one PRs keep reintroducing via bare ``arr != CONST`` eager
ops.
"""
from __future__ import annotations

import logging
import os
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Optional

log = logging.getLogger("kmamiz.guards")

_LEVELS = {"allow", "log", "disallow", "log_explicit", "disallow_explicit"}


def transfer_guard_level(default: Optional[str] = None) -> Optional[str]:
    """Map KMAMIZ_TRANSFER_GUARD to a jax transfer-guard level (or None
    when guarding is off)."""
    raw = os.environ.get("KMAMIZ_TRANSFER_GUARD", "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return default
    if raw in ("1", "on", "true"):
        return "disallow"
    if raw in _LEVELS:
        return raw
    log.warning("unrecognized KMAMIZ_TRANSFER_GUARD=%r; guarding off", raw)
    return default


class RecompileInGuardedSection(RuntimeError):
    """A registered program recompiled inside a guarded hot section."""


@dataclass
class GuardReport:
    level: str
    new_compiles: Dict[str, int] = field(default_factory=dict)

    @property
    def recompiled(self) -> bool:
        return bool(self.new_compiles)


@contextmanager
def hot_path_guard(
    level: Optional[str] = None, *, require_no_recompile: bool = False
):
    """Run a hot section under jax.transfer_guard + registry recompile
    accounting.

    Yields a :class:`GuardReport`; after the block exits,
    ``report.new_compiles`` maps program name -> compiles that happened
    inside the section (steady state must be {}). With
    ``require_no_recompile=True`` a non-empty diff raises
    :class:`RecompileInGuardedSection` — what the tier-1 steady-state
    test asserts.
    """
    import jax

    from kmamiz_tpu.core import programs

    resolved = level or transfer_guard_level("disallow") or "disallow"
    snap = programs.snapshot()
    report = GuardReport(level=resolved)
    try:
        with jax.transfer_guard(resolved):
            yield report
    finally:
        report.new_compiles = programs.new_compiles_since(snap)
    if report.recompiled:
        if require_no_recompile:
            raise RecompileInGuardedSection(
                f"programs recompiled under guard: {report.new_compiles}"
            )
        log.warning(
            "programs recompiled inside guarded section: %s",
            report.new_compiles,
        )


@contextmanager
def maybe_guarded_tick():
    """The serving-process form: guard the tick only when
    KMAMIZ_TRANSFER_GUARD asks for it, otherwise run unwrapped."""
    lvl = transfer_guard_level()
    if lvl is None:
        yield None
        return
    with hot_path_guard(lvl) as report:
        yield report
