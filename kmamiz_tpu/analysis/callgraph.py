"""Import+call-graph reachability for the hot-path-gated rules.

Static, best-effort, and deliberately over-approximate: a function is
"hot" when it is reachable from the tick/serve entry modules
(server/processor.py, server/dp_server.py, models/serving.py) through

- direct calls to names defined or imported in the caller's module,
- ``self.method()`` calls within a class,
- bare references to local functions (callbacks: scan bodies, jit
  arguments, thread targets), and
- a receiver-blind fallback: ``obj.method()`` on an unresolvable
  receiver links to any ``method`` defined in a module the caller
  imports (so ``self.traces.ingest()`` reaches core/spans.py).

Over-approximation errs toward more functions being checked by the
host-sync/dtype rules — a false "hot" costs a suppression comment, a
false "cold" hides a tick stall.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kmamiz_tpu.analysis.framework import LintContext, ModuleInfo

DEFAULT_SEED_MODULES = (
    "kmamiz_tpu/server/processor.py",
    "kmamiz_tpu/server/dp_server.py",
    "kmamiz_tpu/models/serving.py",
    # the STLGT continual trainer runs inside the tick's fold path and
    # its quantile forward inside the forecast route — both hot
    "kmamiz_tpu/models/stlgt/trainer.py",
    "kmamiz_tpu/models/stlgt/serving.py",
    # graftpilot: admission_verdict runs on the serving edge and the
    # decision recompute inside the tick's fold path — hot by seed so
    # the hot-path rules cover the whole control plane
    "kmamiz_tpu/control/__init__.py",
    "kmamiz_tpu/control/admission.py",
    "kmamiz_tpu/control/policy.py",
    "kmamiz_tpu/control/warmup.py",
    # the fused SDDMM/SpMM kernels sit under every sparse-backend
    # consumer (scorers, packed walk, graphsage, stlgt bias) — seed the
    # module itself so the hot-path rules see its helpers even when the
    # consumer dispatch is behind the KMAMIZ_SPARSE knob
    "kmamiz_tpu/ops/sparse.py",
    # graftstream: the micro-tick engine's produce/consume loops run
    # every prepared window through prepare/merge/finish — hot by seed
    # so the hot-path rules reach it even though the dispatch sits
    # behind the KMAMIZ_STREAM knob
    "kmamiz_tpu/server/stream.py",
    # graftfleet: route_ingest sits on every frame's path and the
    # worker's ingest/drain/replay verbs ARE the DP hot loop when the
    # fleet fronts it — hot by seed so the rules reach them even though
    # fleet mode hides behind KMAMIZ_FLEET_SIZE
    "kmamiz_tpu/fleet/coordinator.py",
    "kmamiz_tpu/fleet/worker.py",
    # the placement scorer, the migration protocol, and the soak driver
    # run inside the archetype-10 scenario's tick loop — seed them so the
    # hot-path rules see the whole fleet subsystem, not just the two
    # verbs the coordinator/worker seeds happen to reach
    "kmamiz_tpu/fleet/placement.py",
    "kmamiz_tpu/fleet/migration.py",
    "kmamiz_tpu/fleet/soak.py",
    # graftsoak: the WAL-replay scenario's ingest loop drives the DP
    # ingest hot path record by record, and the sweep worker's
    # claim/run/record cycle wraps every scenario the sweep executes —
    # seed both so the hot-path rules cover the soak plane
    "kmamiz_tpu/soak/walreplay.py",
    "kmamiz_tpu/soak/worker.py",
)


def _module_to_rel(dotted: str) -> str:
    return dotted.replace(".", "/") + ".py"


class _ModuleIndex:
    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.defs: Dict[str, ast.AST] = {}  # qualname suffix -> node
        self.by_basename: Dict[str, List[str]] = {}
        self.import_aliases: Dict[str, str] = {}  # alias -> dotted module
        self.from_symbols: Dict[str, Tuple[str, str]] = {}  # name -> (mod, sym)
        self.imported_rels: Set[str] = set()
        self._collect()

    def _pkg(self, level: int) -> str:
        parts = self.mod.rel_path[:-3].split("/")
        return ".".join(parts[: len(parts) - level])

    def _collect(self) -> None:
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
                    if a.asname:
                        self.import_aliases[a.asname] = a.name
                    self.imported_rels.add(_module_to_rel(a.name))
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg = self._pkg(node.level)
                    base = f"{pkg}.{base}" if base else pkg
                for a in node.names:
                    name = a.asname or a.name
                    # `from pkg import mod` may bind a submodule
                    sub_rel = _module_to_rel(f"{base}.{a.name}")
                    self.from_symbols[name] = (base, a.name)
                    self.imported_rels.add(_module_to_rel(base))
                    self.imported_rels.add(sub_rel)
        # defs with class-qualified names
        def visit(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    self.defs[qn] = child
                    self.by_basename.setdefault(child.name, []).append(qn)
                    visit(child, f"{qn}.")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")

        visit(self.mod.tree, "")


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def build_edges(ctx: LintContext) -> Dict[str, Set[str]]:
    """qualname ('rel/path.py:Class.fn') -> callee qualnames."""
    indexes = {rel: _ModuleIndex(m) for rel, m in ctx.modules.items()}
    edges: Dict[str, Set[str]] = {}

    def qual(rel: str, suffix: str) -> str:
        return f"{rel}:{suffix}"

    for rel, idx in indexes.items():
        for suffix, fn_node in idx.defs.items():
            out: Set[str] = set()
            cls_prefix = suffix.rsplit(".", 1)[0] + "." if "." in suffix else ""
            for node in ast.walk(fn_node):
                if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    # bare reference: local function used as a callback
                    for cand in idx.by_basename.get(node.id, []):
                        out.add(qual(rel, cand))
                    sym = idx.from_symbols.get(node.id)
                    if sym:
                        target_rel = _module_to_rel(sym[0])
                        tgt = indexes.get(target_rel)
                        if tgt:
                            for cand in tgt.by_basename.get(sym[1], []):
                                out.add(qual(target_rel, cand))
                elif isinstance(node, ast.Call):
                    chain = _attr_chain(node.func)
                    if not chain or len(chain) == 1:
                        continue
                    head, meth = chain[0], chain[-1]
                    if head == "self" and len(chain) == 2:
                        cand = f"{cls_prefix}{meth}"
                        if cand in idx.defs:
                            out.add(qual(rel, cand))
                            continue
                    resolved = False
                    dotted = idx.import_aliases.get(head)
                    if dotted is None and head in idx.from_symbols:
                        base, sym_name = idx.from_symbols[head]
                        dotted = f"{base}.{sym_name}"
                    if dotted and len(chain) == 2:
                        target_rel = _module_to_rel(dotted)
                        tgt = indexes.get(target_rel)
                        if tgt:
                            resolved = True
                            for cand in tgt.by_basename.get(meth, []):
                                out.add(qual(target_rel, cand))
                    if not resolved:
                        # receiver-blind fallback: any `meth` in this
                        # module or a directly-imported one
                        for cand in idx.by_basename.get(meth, []):
                            out.add(qual(rel, cand))
                        for target_rel in idx.imported_rels:
                            tgt = indexes.get(target_rel)
                            if not tgt:
                                continue
                            for cand in tgt.by_basename.get(meth, []):
                                out.add(qual(target_rel, cand))
            edges[qual(rel, suffix)] = out
    return edges


def hot_functions(
    ctx: LintContext, seeds: Optional[Sequence[str]] = None
) -> Set[str]:
    """Qualnames reachable from the seed entry points. Seeds may be
    module rel-paths (every function in the module seeds) or explicit
    'rel/path.py:fn' qualnames."""
    edges = build_edges(ctx)
    seed_set: Set[str] = set()
    for s in seeds if seeds is not None else DEFAULT_SEED_MODULES:
        if ":" in s:
            if s in edges:
                seed_set.add(s)
        else:
            prefix = s.replace("\\", "/") + ":"
            seed_set.update(q for q in edges if q.startswith(prefix))
    hot = set(seed_set)
    frontier = list(seed_set)
    while frontier:
        cur = frontier.pop()
        for nxt in edges.get(cur, ()):
            if nxt not in hot:
                hot.add(nxt)
                frontier.append(nxt)
    return hot
