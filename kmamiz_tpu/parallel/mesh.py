"""Device-mesh sharding of the span-window pipeline.

The scaling axis of this system is spans-per-window and
endpoints-per-graph (SURVEY.md §5): the reference caps ingestion at 2,500
traces per 5 s tick because a single Node/Rust process walks every span.
Here the window is sharded across a `jax.sharding.Mesh`:

- span rows are split over the `spans` axis (the host packs whole traces
  per shard so parent chains stay shard-local);
- each device computes its local segment statistics (dense
  [endpoints x statuses] lanes);
- a `psum` over ICI merges the partial sums — count/error/latency-sum
  reductions are associative, and CV recombines exactly via the
  sum/sum-of-squares form (the same pooled-variance identity the
  reference applies when merging windows,
  /root/reference/src/classes/CombinedRealtimeDataList.ts:278-315).

Multi-host later rides the same code: a Mesh spanning hosts puts the
psum on DCN instead of ICI with no code change.
"""
from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # jax<0.5 keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

# jax renamed the replication/varying-axes check kwarg (check_rep ->
# check_vma around 0.6); dispatch to whichever this jax understands
_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else "check_rep"
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )

from kmamiz_tpu.core.spans import KIND_SERVER, SpanBatch, spans_to_batch
from kmamiz_tpu.ops import window as window_ops


def make_mesh(n_devices: int = 0, axis: str = "spans") -> Mesh:
    devices = jax.devices()
    if n_devices:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


# ---------------------------------------------------------------------------
# deployed-path activation (VERDICT r4 #1)
#
# The serving components (graph/store.py window merges,
# server/processor.py device stats) consult active_mesh() on every
# window: with more than one addressable device the window's walk and
# stats shard across the full device mesh automatically — on a v5e-8 the
# deployed DataProcessor uses all eight chips, not one. A single chip
# (the common dev case, and the driver's bench harness) returns None and
# the single-device kernels run unchanged.
# ---------------------------------------------------------------------------

import os as _os
from functools import lru_cache as _lru_cache


@_lru_cache(maxsize=8)
def _mesh_for(n: int, axis: str) -> Mesh:
    return make_mesh(n, axis)


def active_mesh(axis: str = "spans") -> Optional[Mesh]:
    """The mesh the deployed ingest path shards over, or None.

    Env knobs (read per call so tests can flip them):
      KMAMIZ_MESH=0          force single-device even with many chips
      KMAMIZ_MESH_DEVICES=N  cap the mesh at the first N devices
    """
    if _os.environ.get("KMAMIZ_MESH", "1") in ("0", "off", "false"):
        return None
    n = len(jax.devices())
    limit = int(_os.environ.get("KMAMIZ_MESH_DEVICES", "0") or 0)
    if limit:
        n = min(n, limit)
    if n < 2:
        return None
    return _mesh_for(n, axis)


# ---------------------------------------------------------------------------
# ring collectives (explicit ppermute over ICI)
#
# The ICI topology is a ring/torus; these are the classic ring algorithms
# (reduce-scatter then all-gather) written against jax.lax.ppermute instead
# of the opaque psum, so cross-shard merges can (a) overlap chunk transfers
# with adds step by step and (b) leave the result SEGMENT-SHARDED — each
# device ends up owning S/n of the merged segment statistics, which is the
# right layout when the next stage (scorer segment reductions, top-k) is
# itself sharded over segments. This is the span-window analogue of ring
# attention's sequence parallelism: spans are the "sequence", per-segment
# partial sums are the rotating state.
# ---------------------------------------------------------------------------


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def ring_reduce_scatter(x, axis: str, n: int, op: str = "add"):
    """Inside shard_map: reduce x (replicated-shape [n*c, ...] partials,
    one copy per device) so device i returns the fully merged chunk i.

    n-1 ppermute steps, each overlapping one chunk transfer with one
    combine; a final rotation lands chunk i on device i. x's leading dim
    must divide evenly into n chunks (pad first — sharded_window_stats
    does)."""
    if x.shape[0] % n:
        raise ValueError(
            f"ring_reduce_scatter needs len divisible by {n}, got {x.shape[0]}"
        )
    idx = jax.lax.axis_index(axis)
    chunk_len = x.shape[0] // n

    def chunk(i):
        start = (jnp.mod(i, n)) * chunk_len
        return jax.lax.dynamic_slice_in_dim(x, start, chunk_len)

    combine = jnp.maximum if op == "max" else jnp.add
    carry = chunk(idx)
    for k in range(n - 1):
        carry = jax.lax.ppermute(carry, axis, _ring_perm(n))
        carry = combine(carry, chunk(idx - 1 - k))
    # device i now holds merged chunk (i+1); rotate once so i owns chunk i
    return jax.lax.ppermute(carry, axis, _ring_perm(n))


def ring_all_gather(chunk, axis: str, n: int):
    """Inside shard_map: device-owned chunks [c, ...] -> replicated
    [n*c, ...] via n-1 ring hops."""
    idx = jax.lax.axis_index(axis)
    chunk_len = chunk.shape[0]
    out = jnp.zeros((n * chunk_len,) + chunk.shape[1:], chunk.dtype)
    rolling = chunk
    for k in range(n):
        src = jnp.mod(idx - k, n)  # whose chunk we hold at step k
        out = jax.lax.dynamic_update_slice_in_dim(out, rolling, src * chunk_len, 0)
        if k != n - 1:
            rolling = jax.lax.ppermute(rolling, axis, _ring_perm(n))
    return out


def ring_all_reduce(x, axis: str, n: int, op: str = "add"):
    """psum/pmax equivalent built from ring reduce-scatter + all-gather."""
    return ring_all_gather(ring_reduce_scatter(x, axis, n, op), axis, n)


def hierarchical_all_reduce(
    x, chip_axis: str, n_chip: int, host_axis: str, op: str = "add"
):
    """Bandwidth-optimal multi-host all-reduce: ring reduce-scatter within
    the host (ICI), ONE cross-host reduction of the 1/n_chip-sized owned
    chunk (DCN — the slow wire carries only chunk-sized traffic), then
    ring all-gather back over ICI. The merge shape for meshes whose
    `host` axis spans DCN (SURVEY.md §5 distributed-communication
    mapping)."""
    chunk = ring_reduce_scatter(x, chip_axis, n_chip, op)
    if op == "max":
        chunk = jax.lax.pmax(chunk, host_axis)
    else:
        chunk = jax.lax.psum(chunk, host_axis)
    return ring_all_gather(chunk, chip_axis, n_chip)


class ShardedWindow(NamedTuple):
    """One window of spans laid out for an n-way mesh.

    Every array is [n_shards * per_shard]; rows are grouped so each shard's
    parent indices are shard-local (whole traces per shard)."""

    valid: np.ndarray
    kind: np.ndarray
    parent_idx: np.ndarray  # local to the shard slice
    endpoint_id: np.ndarray
    rt_endpoint_id: np.ndarray
    status_id: np.ndarray
    status_class: np.ndarray
    latency_ms: np.ndarray
    timestamp_rel: np.ndarray
    per_shard: int
    ts_base_us: int
    batches: List[SpanBatch]


def shard_window(
    trace_groups: Sequence[Sequence[dict]],
    n_shards: int,
    interner=None,
    statuses=None,
) -> ShardedWindow:
    """Pack whole trace groups into n_shards per-device batches sharing one
    intern table, then concatenate to a single global array layout."""
    from kmamiz_tpu.core.interning import EndpointInterner, StringInterner

    interner = interner or EndpointInterner()
    statuses = statuses or StringInterner()

    # round-robin whole traces so parent chains never cross shards
    per_shard_groups: List[List[Sequence[dict]]] = [[] for _ in range(n_shards)]
    for i, group in enumerate(trace_groups):
        per_shard_groups[i % n_shards].append(group)

    # one window-wide timestamp base: per-shard rel offsets must be
    # comparable under the cross-shard pmax merge
    all_ts = [
        s.get("timestamp", 0) for g in trace_groups for s in g
    ]
    ts_base = min(all_ts) if all_ts else 0

    batches = [
        spans_to_batch(
            groups,
            interner=interner,
            statuses=statuses,
            pad=False,
            ts_base_us=ts_base,
        )
        for groups in per_shard_groups
    ]
    per_shard = max(max(b.capacity for b in batches), 8)

    def pad_to(arr, fill=0):
        out = np.full((n_shards, per_shard), fill, dtype=arr[0].dtype)
        for s, a in enumerate(arr):
            out[s, : len(a)] = a
        return out.reshape(-1)

    return ShardedWindow(
        valid=pad_to([b.valid for b in batches], False),
        kind=pad_to([b.kind for b in batches]),
        parent_idx=pad_to([b.parent_idx for b in batches], -1),
        endpoint_id=pad_to([b.endpoint_id for b in batches]),
        rt_endpoint_id=pad_to([b.rt_endpoint_id for b in batches]),
        status_id=pad_to([b.status_id for b in batches]),
        status_class=pad_to([b.status_class for b in batches]),
        latency_ms=pad_to([b.latency_ms.astype(np.float32) for b in batches]),
        timestamp_rel=pad_to([b.timestamp_rel for b in batches]),
        per_shard=per_shard,
        ts_base_us=ts_base,
        batches=batches,
    )


@partial(
    jax.jit,
    static_argnames=(
        "mesh",
        "num_endpoints",
        "num_statuses",
        "axis",
        "merge",
        "backend",
    ),
)
def sharded_window_stats(
    mesh: Mesh,
    rt_endpoint_id: jnp.ndarray,
    status_id: jnp.ndarray,
    status_class: jnp.ndarray,
    latency_ms: jnp.ndarray,
    timestamp_rel: jnp.ndarray,
    valid_server: jnp.ndarray,
    num_endpoints: int,
    num_statuses: int,
    axis: str = "spans",
    merge: str = "psum",
    backend: str = "xla",
) -> window_ops.WindowStats:
    """Per-shard segment stats + cross-shard merge over the mesh axis.

    Input arrays are sharded on their leading (span) dimension; the output
    is the fully merged dense per-(endpoint,status) statistics, replicated.

    merge: 'psum' lets XLA pick the all-reduce; 'ring' runs the explicit
    ppermute ring (reduce-scatter + all-gather) — same result, but the
    merge is expressed as n-1 chunk hops over ICI, the layout ring/Ulysses
    sequence parallelism uses, and the reduce-scatter half can serve
    segment-sharded consumers without ever replicating. 'hierarchical'
    (for a 2-D ('host', axis) mesh, spans sharded over BOTH axes) ring-
    reduces within each host over ICI and crosses hosts (DCN) with only
    chunk-sized traffic.

    backend: same contract as ops.window.window_stats — 'xla' scatters,
    'pallas'/'pallas_interpret' run each shard's local segment sums as
    the one-hot MXU matmul kernel (KMAMIZ_SEGMENT_BACKEND honors the
    same override on the mesh as on one chip).
    """
    hierarchical = merge == "hierarchical"
    host_axis = "host"
    spec = P((host_axis, axis)) if hierarchical else P(axis)
    n_shards = mesh.shape[axis]

    def local_stats(eid, sid, scl, lat, ts, vs):
        num_segments = num_endpoints * num_statuses
        seg = eid * num_statuses + sid
        seg = jnp.where(vs, seg, num_segments)
        w = vs.astype(lat.dtype)

        if hierarchical:
            reduce_fn = partial(
                hierarchical_all_reduce,
                chip_axis=axis,
                n_chip=n_shards,
                host_axis=host_axis,
            )
        elif merge == "ring":
            reduce_fn = partial(ring_all_reduce, axis=axis, n=n_shards)
        else:
            reduce_fn = None
        pad = -num_segments % n_shards

        def merged(x, op="add"):
            if reduce_fn is None:
                return jax.lax.pmax(x, axis) if op == "max" else jax.lax.psum(x, axis)
            padding = ((0, pad),) + ((0, 0),) * (x.ndim - 1)
            return reduce_fn(jnp.pad(x, padding), op=op)[:num_segments]

        if backend.startswith("pallas"):
            from kmamiz_tpu.ops.pallas_kernels import segment_stats_matmul

            interpret = backend == "pallas_interpret"
            lat_f = lat.astype(jnp.float32)
            values = jnp.stack(
                [
                    w.astype(jnp.float32),
                    (w * (scl == 4)).astype(jnp.float32),
                    (w * (scl == 5)).astype(jnp.float32),
                    lat_f * w,
                    lat_f * lat_f * w,
                ]
            )
            local_sums, local_ts = segment_stats_matmul(
                values,
                seg,
                jnp.where(vs, ts, 0),
                num_segments,
                interpret=interpret,
            )
            sums = merged(local_sums.T)
            ts_max = merged(local_ts.astype(jnp.int32), op="max")
        else:
            # one vector-valued scatter for the five sums (window_stats)
            data = jnp.stack(
                [w, w * (scl == 4), w * (scl == 5), lat * w, lat * lat * w],
                axis=1,
            )
            sums = merged(
                jax.ops.segment_sum(
                    data, seg, num_segments=num_segments + 1
                )[:-1]
            )
            ts_max = merged(
                jax.ops.segment_max(
                    jnp.where(vs, ts, 0), seg, num_segments=num_segments + 1
                )[:-1],
                op="max",
            )
        # empty segments carry segment_max's int32-min identity: report 0,
        # matching the single-device window_stats
        ts_max = jnp.where(sums[:, 0] > 0, ts_max, 0)

        # two-pass variance, like the single-device path: the naive
        # E[x^2]-E[x]^2 form cancels catastrophically in float32. The
        # merged mean is replicated after the first collective, so each
        # shard scatters its local squared residuals and ONE more merge
        # yields the exact pooled residual sum.
        count = sums[:, 0]
        mean = sums[:, 3] / jnp.maximum(count, 1)
        resid = (lat - mean[jnp.minimum(seg, num_segments - 1)]) * w
        if backend.startswith("pallas"):
            from kmamiz_tpu.ops.pallas_kernels import segment_stats_matmul

            local_rs, _ = segment_stats_matmul(
                (resid * resid)[None, :].astype(jnp.float32),
                seg,
                jnp.zeros_like(ts),
                num_segments,
                interpret=backend == "pallas_interpret",
            )
            resid_sq = merged(local_rs[0])
        else:
            resid_sq = merged(
                jax.ops.segment_sum(
                    resid * resid, seg, num_segments=num_segments + 1
                )[:-1]
            )
        return (
            count,
            sums[:, 1],
            sums[:, 2],
            sums[:, 3],
            sums[:, 4],
            resid_sq,
            ts_max,
        )

    count, e4, e5, lat_sum, lat_sq, resid_sq, ts_max = shard_map(
        local_stats,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec),
        out_specs=(P(), P(), P(), P(), P(), P(), P()),
        # ring/hierarchical replication arises from ppermute hops, which
        # the static varying-axes check cannot prove; pallas_call does
        # not declare vma on its output shapes either
        check_vma=(merge == "psum" and not backend.startswith("pallas")),
    )(rt_endpoint_id, status_id, status_class, latency_ms, timestamp_rel, valid_server)

    safe_count = jnp.maximum(count, 1)
    mean = lat_sum / safe_count
    variance = jnp.maximum(resid_sq / safe_count, 0.0)
    cv = jnp.where(
        mean != 0, jnp.sqrt(variance) / jnp.maximum(mean, 1e-30), 0.0
    )
    return window_ops.WindowStats(
        count=count,
        error_4xx=e4,
        error_5xx=e5,
        latency_sum=lat_sum,
        latency_sq_sum=lat_sq,
        latency_mean=jnp.where(count > 0, mean, 0.0),
        latency_cv=jnp.where(count > 0, cv, 0.0),
        latest_timestamp_rel=ts_max,
    )


@partial(
    jax.jit,
    static_argnames=("mesh", "max_depth", "axis"),
)
def sharded_dependency_edges(
    mesh: Mesh,
    parent_idx: jnp.ndarray,
    kind: jnp.ndarray,
    valid: jnp.ndarray,
    endpoint_id: jnp.ndarray,
    max_depth: int = window_ops.MAX_DEPTH,
    axis: str = "spans",
):
    """Per-shard ancestor walk via the FLAT gather kernel (fallback for
    windows pack_trace_rows cannot lay out: overlong traces, cross-trace
    parents). The packed MXU variant below is the production path — the
    flat gather loses >=50x to it on TPU (bench: walk_flat_gather_ms vs
    walk_mxu_packed_ms). Edges stay sharded on the span axis for
    downstream sharded dedup/merge."""
    spec = P(axis)

    def local_edges(p, k, v, e):
        edges = window_ops.dependency_edges(p, k, v, e, max_depth=max_depth)
        return edges.ancestor_ep, edges.descendant_ep, edges.distance, edges.mask

    return shard_map(
        local_edges,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec, spec),
    )(parent_idx, kind, valid, endpoint_id)


def shard_window_packed(sharded: ShardedWindow):
    """Trace-row pack each shard of a ShardedWindow for the MXU walk
    (VERDICT r2 #4: the sharded path previously only had the flat gather).

    Traces were round-robined whole into shards (shard_window), so parent
    chains are shard-local and each shard packs independently with
    core.spans.pack_trace_rows — the same layout the single-device
    graph-store merge uses (graph/store.py::_merge_window_locked). Shards
    pad to a common row count so the leading dim shards evenly.

    Returns (parent_slot2, kind2, valid2, ep2) of shape
    [n_shards * rows_per_shard, ROW_SLOTS] plus the pow2-bucketed walk
    depth cap, or None when any shard cannot pack (caller falls back to
    sharded_dependency_edges on the flat layout)."""
    from kmamiz_tpu.core.spans import ROW_SLOTS, _pad_size, pack_trace_rows
    from kmamiz_tpu.ops.window import MAX_DEPTH

    packs = []
    max_rows = 1
    max_chain = 1
    for b in sharded.batches:
        if b.n_spans == 0:
            # an empty shard packs trivially as all-invalid rows; only a
            # shard pack_trace_rows genuinely cannot lay out (overlong
            # trace, cross-trace parent) forces the flat fallback
            packs.append(None)
            continue
        pk = pack_trace_rows(b.trace_of, b.n_spans, b.parent_idx)
        if pk is None:
            return None
        packs.append(pk)
        max_rows = max(max_rows, pk.n_rows)
        max_chain = max(max_chain, pk.max_trace_len - 1)
    n_shards = len(packs)
    rows = _pad_size(max_rows)

    pslot2 = np.full((n_shards, rows, ROW_SLOTS), -1, dtype=np.int32)
    kind2 = np.zeros((n_shards, rows, ROW_SLOTS), dtype=np.int8)
    valid2 = np.zeros((n_shards, rows, ROW_SLOTS), dtype=bool)
    ep2 = np.zeros((n_shards, rows, ROW_SLOTS), dtype=np.int32)
    for s, (pk, b) in enumerate(zip(packs, sharded.batches)):
        if pk is None:
            continue  # empty shard: all-invalid rows already in place
        n = b.n_spans
        pslot2[s, : pk.n_rows] = pk.pack(pk.parent_slots(b.parent_idx), -1)
        kind2[s, : pk.n_rows] = pk.pack(b.kind[:n], 0)
        valid2[s, : pk.n_rows] = pk.pack(b.valid[:n], False)
        ep2[s, : pk.n_rows] = pk.pack(b.endpoint_id[:n], 0)

    depth = min(MAX_DEPTH, _pad_size(max(1, max_chain), minimum=4))
    flat = lambda a: a.reshape(n_shards * rows, ROW_SLOTS)
    return flat(pslot2), flat(kind2), flat(valid2), flat(ep2), depth


@partial(
    jax.jit,
    static_argnames=("mesh", "max_depth", "axis"),
)
def sharded_dependency_edges_packed(
    mesh: Mesh,
    parent_slot: jnp.ndarray,
    kind: jnp.ndarray,
    valid: jnp.ndarray,
    endpoint_id: jnp.ndarray,
    max_depth: int = window_ops.MAX_DEPTH,
    axis: str = "spans",
):
    """Per-shard MXU ancestor walk over trace-packed [rows, ROW_SLOTS]
    blocks (leading dim sharded over `axis`): each device runs the
    one-hot-einsum walk (ops.window.dependency_edges_packed) on its rows —
    no cross-shard traffic, the walk is embarrassingly parallel once
    traces are shard-local. Edges stay sharded for downstream merge."""
    spec = P(axis)

    def local_edges(p, k, v, e):
        edges = window_ops.dependency_edges_packed(
            p, k, v, e, max_depth=max_depth
        )
        return edges.ancestor_ep, edges.descendant_ep, edges.distance, edges.mask

    return shard_map(
        local_edges,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec, spec),
    )(parent_slot, kind, valid, endpoint_id)


@partial(
    jax.jit,
    static_argnames=("mesh", "max_depth", "stage_cap", "packed_key", "axis"),
)
def sharded_window_edges_compact(
    mesh: Mesh,
    parent_slot: jnp.ndarray,
    kind: jnp.ndarray,
    valid: jnp.ndarray,
    endpoint_id: jnp.ndarray,
    max_depth: int,
    stage_cap: int,
    packed_key: bool,
    axis: str = "spans",
):
    """The DEPLOYED staged-merge kernel over the mesh (VERDICT r4 #1):
    the multi-device twin of graph.store._window_edges_compact. Each
    device walks its own trace-packed rows (the MXU one-hot-einsum walk
    — embarrassingly parallel once whole traces are shard-local) and
    locally compacts its candidates to a sorted unique prefix of
    stage_cap rows. Outputs stay device-sharded: [n * stage_cap] edge
    columns plus an [n] per-shard true-unique count, so the store's
    drain union sees n small sorted prefixes instead of the full padded
    candidate arrays, and any shard whose prefix truncated triggers the
    re-walk fallback (sharded_dependency_edges_packed on the same pinned
    inputs).

    This replaces the reference's single-threaded combine-merge
    (/root/reference/src/classes/CombinedRealtimeDataList.ts:278-315 and
    EndpointDependencies.ts:499-563) in the serving path: per-shard
    dedup runs as data parallelism over the spans axis; the cross-shard
    set-union rides the one batched drain sort."""
    from kmamiz_tpu.ops.sortutil import (
        compact_unique,
        compact_unique_edges_packed,
    )

    spec = P(axis)

    def local(p, k, v, e):
        edges = window_ops.dependency_edges_packed(
            p, k, v, e, max_depth=max_depth
        )
        cols = (
            edges.ancestor_ep.reshape(-1),
            edges.descendant_ep.reshape(-1),
            edges.distance.reshape(-1),
        )
        mask = edges.mask.reshape(-1)
        if packed_key:
            (s, d, ds), vv = compact_unique_edges_packed(*cols, mask)
        else:
            (s, d, ds), vv = compact_unique(cols, mask)
        return s[:stage_cap], d[:stage_cap], ds[:stage_cap], vv.sum()[None]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec, spec),
    )(parent_slot, kind, valid, endpoint_id)


def make_sharded_slot_grad(mesh: Mesh, grad_fn, axis: str = "slots"):
    """Data-parallel gradient over a SLOT MICROBATCH of training windows
    (the GraphSAGE trainer's stacked slots, models/stacked.py).

    grad_fn is value_and_grad(loss_fn, has_aux=True) with the models/common
    loss signature: grad_fn(params, features, src, dst, edge_mask,
    target_latency, target_anomaly, node_mask) -> ((loss, (lat_l, ano_l)),
    grads).

    The returned batch_grads(params, feats[B,Nb,F], tl[B,Nb], ta[B,Nb],
    nm[B,Nb], src, dst, edge_mask, w[B]) shards the batch axis across the
    mesh: each device vmaps grad_fn over ITS B/n slots (weighted, so padded
    batch entries contribute zero), locally sums, and ONE psum over ICI
    merges grads and losses — params and the edge topology are replicated
    (they are small next to the [B, Nb, F] feature block). Dividing the
    psum'd sums by the psum'd weight total makes the result EQUAL to the
    unsharded weighted batch mean on one device (tests/test_parallel.py
    asserts this grad parity), so the optimizer update is
    device-count-invariant."""
    n = mesh.shape[axis]
    spec = P(axis)

    def local(params, feats, tl, ta, nm, src, dst, em, w):
        def per_slot(f, l, a, m, wi):
            (loss, (lat_l, ano_l)), g = grad_fn(params, f, src, dst, em, l, a, m)
            g = jax.tree_util.tree_map(lambda x: x * wi, g)
            return g, loss * wi, lat_l * wi, ano_l * wi

        gs, ls, lat, ano = jax.vmap(per_slot)(feats, tl, ta, nm, w)
        sums = jax.lax.psum(
            jnp.stack([ls.sum(), lat.sum(), ano.sum(), w.sum()]), axis
        )
        wsum = jnp.maximum(sums[3], 1.0)
        g = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x.sum(0), axis) / wsum, gs
        )
        return g, sums[0] / wsum, sums[1] / wsum, sums[2] / wsum

    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), spec, spec, spec, spec, P(), P(), P(), spec),
        out_specs=(P(), P(), P(), P()),
    )

    def batch_grads(params, feats, tl, ta, nm, src, dst, em, w):
        if feats.shape[0] % n:
            raise ValueError(
                f"slot batch of {feats.shape[0]} does not shard over "
                f"{n} devices; pick a batch size divisible by the mesh"
            )
        return sharded(params, feats, tl, ta, nm, src, dst, em, w)

    return batch_grads


@partial(jax.jit, static_argnames=("mesh", "num_services", "axis"))
def sharded_service_scores(
    mesh: Mesh,
    src_ep: jnp.ndarray,
    dst_ep: jnp.ndarray,
    dist: jnp.ndarray,
    mask: jnp.ndarray,
    ep_service: jnp.ndarray,
    ep_ml: jnp.ndarray,
    ep_has_record: jnp.ndarray,
    num_services: int,
    axis: str = "spans",
):
    """service_scores with the edge->tuple expansion, local dedup, and
    degree partials sharded over the mesh (VERDICT r4 #5a: the scorer
    segment reductions split across devices).

    Stage 1 (shard_map): each device expands ITS edge rows into both
    direction tuples, lex-sorts and locally dedups them (the n parallel
    local sorts replace one global-size sort), and contributes its
    partial depended-by degrees via one psum over ICI. Stage 2: the
    locally-deduped tuple prefixes feed the same counting core the
    single-device scorer uses (ops.scorers.score_tuple_rows) — its
    global lex_unique collapses cross-shard duplicates, so results are
    exactly the single-device scorer's. Inputs reshard automatically
    under jit; ep tables are replicated (they are per-endpoint lookups,
    small next to the edge set)."""
    from kmamiz_tpu.ops import scorers as scorer_ops
    from kmamiz_tpu.ops.sortutil import lex_unique, scatter_compact

    spec = P(axis)
    num_endpoints = ep_service.shape[0]

    def local(srcs, dsts, dists, masks, ep_svc, ep_ml_t, ep_rec_t):
        rows = scorer_ops.edge_direction_tuples(
            srcs, dsts, dists, masks, ep_svc, ep_ml_t, ep_rec_t
        )
        cols, uniq = lex_unique(rows[:-1], rows[-1])
        comp, valid = scatter_compact(cols, uniq)
        # partial depended-by degrees; ONE psum merges shards over ICI
        bd = jax.ops.segment_sum(
            masks.astype(jnp.float32),
            jnp.where(masks, dsts, num_endpoints),
            num_segments=num_endpoints + 1,
        )[:-1]
        bd = jax.lax.psum(bd, axis)
        return (*comp, valid, bd)

    o, l, dr, dd, ml, valid, by_deg = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, P(), P(), P()),
        out_specs=(spec, spec, spec, spec, spec, spec, P()),
    )(src_ep, dst_ep, dist, mask, ep_service, ep_ml, ep_has_record)

    is_gateway = scorer_ops.gateway_mask(
        dst_ep, mask, ep_service, ep_has_record, num_services, by_deg=by_deg
    )
    return scorer_ops.score_tuple_rows(
        o, l, dr, dd, ml, valid, is_gateway, num_services=num_services
    )
