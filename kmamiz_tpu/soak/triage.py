"""Auto-triage: attribute every failed sweep cell to a blamed
phase / tenant / gate, then dedupe failures into bugs.

On any gate failure the scenario runner freezes a ``flight-*.json``
evidence box; the sweep keeps the LAST PASSING flight profile per
archetype as a baseline. Triage bisects the two with the graftprof
per-phase p95 diff (``telemetry/profiling/report.diff`` — the same
thresholds ``tools/graftprof.py --diff`` gates on) and combines three
deterministic attributions into one record:

* **blamed gate** — the first failed gate, sorted (stable across runs)
* **blamed phase** — the gate's owning pipeline phase (static map),
  with any diff-regressed phases attached as supporting evidence
* **blamed tenant** — the tenant whose live signature diverged from
  the reference (bit-exactness failures), else the tenant named by the
  first error line, else the cell's only tenant

The *triage signature* ``archetype|gate|phase|tenant`` is built purely
from those deterministic parts — two cells failing the same way carry
the same signature, so the soak report can say "1 bug, N occurrences"
instead of listing N raw failures (same spirit as crash-bucket dedupe
in a crash reporter).
"""
from __future__ import annotations

from typing import Dict, List, Optional

#: gate -> owning pipeline phase (the place an operator starts reading)
GATE_PHASE: Dict[str, str] = {
    "no_errors": "drive",
    "bit_exact": "merge",
    "zero_lost_spans": "ingest",
    "zero_steady_recompiles": "compile",
    "bucket_crossed": "capacity",
    "stale_bounded": "serve",
    "quarantine_exact": "quarantine",
    "recovered_to_fresh": "recovery",
    "wal_replayed": "wal-replay",
    "replayed_all": "wal-replay",
    "freshness_slo": "freshness",
    "crashed": "compose",
    "soak_poison": "poison",
}


def failed_gates(card: dict) -> List[str]:
    return sorted(g for g, ok in card.get("gates", {}).items() if not ok)


def blamed_tenant(card: dict) -> str:
    """Deterministic tenant attribution from the scorecard alone."""
    live = card.get("signatures") or {}
    ref = card.get("ref_signatures") or {}
    diverged = sorted(t for t in live if t in ref and live[t] != ref[t])
    if diverged:
        return diverged[0]
    tenants = card.get("tenants") or []
    for err in card.get("errors") or []:
        for tenant in sorted(tenants):
            if tenant in str(err):
                return tenant
    if len(tenants) == 1:
        return tenants[0]
    return "matrix"


def _regressed_phases(
    baseline: Optional[dict], flight: Optional[dict]
) -> List[dict]:
    """graftprof bisection: per-phase p95 regressions of the failing
    cell's flight against the archetype's last passing flight. Best
    effort — missing or unparseable artifacts yield no evidence, never
    an exception (triage runs on the failure path)."""
    if not baseline or not flight:
        return []
    try:
        from kmamiz_tpu.telemetry.profiling import report

        return report.diff(report.from_any(baseline), report.from_any(flight))
    except Exception:  # noqa: BLE001 - evidence is optional, blame is not
        return []


def triage_card(
    card: dict,
    baseline: Optional[dict] = None,
    flight: Optional[dict] = None,
) -> dict:
    """The triage record for one failed cell. Always attributes —
    a missing baseline or flight degrades the evidence, not the blame."""
    gates = failed_gates(card)
    gate = gates[0] if gates else "unknown"
    phase = GATE_PHASE.get(gate, "unknown")
    tenant = blamed_tenant(card)
    regressions = _regressed_phases(baseline, flight)
    record = {
        "blamed_gate": gate,
        "blamed_phase": phase,
        "blamed_tenant": tenant,
        "failed_gates": gates,
        "signature": f"{card.get('archetype', '?')}|{gate}|{phase}|{tenant}",
        "baseline": bool(baseline),
        "regressed_phases": [
            {
                "phase": r["phase"],
                "baseline_p95_ms": r["baseline_p95_ms"],
                "candidate_p95_ms": r["candidate_p95_ms"],
            }
            for r in regressions[:4]
        ],
    }
    return record


def dedupe(failures: List[dict]) -> List[dict]:
    """Group failed cell records by triage signature: same blame = one
    bug, N occurrences. Input records carry ``triage`` + ``id``."""
    bugs: Dict[str, dict] = {}
    for rec in failures:
        tri = rec.get("triage") or {}
        sig = tri.get("signature", "untriaged")
        bug = bugs.setdefault(
            sig,
            {
                "signature": sig,
                "blamed_gate": tri.get("blamed_gate", "unknown"),
                "blamed_phase": tri.get("blamed_phase", "unknown"),
                "blamed_tenant": tri.get("blamed_tenant", "unknown"),
                "count": 0,
                "cells": [],
            },
        )
        bug["count"] += 1
        bug["cells"].append(rec.get("id", rec.get("name", "?")))
    out = sorted(bugs.values(), key=lambda b: (-b["count"], b["signature"]))
    for bug in out:
        bug["cells"] = sorted(bug["cells"])[:8]
    return out
