"""Sweep cell enumeration: (archetype, seed) grid, cost-ordered.

A *cell* is one scenario the sweep will run: an archetype name plus a
matrix seed. Cell identity is ``<archetype>-s<seed>`` and each cell
composes its spec at the archetype's CANONICAL matrix index — the same
``build_scenario(archetype, seed, index, ticks)`` call no matter how
large the sweep is, so a cell re-run in isolation reproduces the sweep
cell bit-exactly (``spec_signature`` is the oracle).

Two archetype groups are opt-in for sweeps (override with
``KMAMIZ_SOAK_ARCHETYPES=name,name,...``):

* ``SUBPROCESS_HEAVY`` — archetypes that fork whole interpreter trees
  per cell (kill-9 crash children, the 4-worker fleet ring): at
  thousands of cells they would multiply process spawns without adding
  coverage the nightly matrix gate doesn't already have.
* ``COLD_PROCESS`` — archetypes whose verdict is only deterministic in
  a cold interpreter. ``capacity-growth-chain`` fits its between-tick
  prewarm predictor from the compile-cost evidence its own warmup
  generates; in a warm sweep worker the program registry serves cached
  shapes, warmup compiles nothing, the predictor has nothing to fit,
  and the consolidation's compiles land mid-tick or not depending on
  which cells ran before — an order-dependent verdict that would poison
  a four-nines pass rate and the resume-bit-identical report contract.
  The nightly matrix (one cold process) still gates it.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from kmamiz_tpu.cost.scenario import fit_observed, predicted_scenario_cost_s
from kmamiz_tpu.scenarios.factory import ARCHETYPES, build_scenario

#: subprocess-per-cell archetypes, excluded from sweeps by default
SUBPROCESS_HEAVY = ("kill9-wal-replay", "fleet-migration")

#: archetypes whose gates are only deterministic in a cold interpreter
#: (see module docstring), excluded from sweeps by default
COLD_PROCESS = ("capacity-growth-chain",)

DEFAULT_SWEEP_TICKS = 6


def sweep_ticks() -> int:
    try:
        return max(
            1, int(os.environ.get("KMAMIZ_SOAK_TICKS", DEFAULT_SWEEP_TICKS))
        )
    except ValueError:
        return DEFAULT_SWEEP_TICKS


def sweep_archetypes() -> List[str]:
    """The archetype vocabulary a sweep cycles through."""
    raw = os.environ.get("KMAMIZ_SOAK_ARCHETYPES", "")
    known = [name for name, _t in ARCHETYPES]
    if raw.strip():
        picked = [a.strip() for a in raw.split(",") if a.strip()]
        bad = [a for a in picked if a not in known]
        if bad:
            raise ValueError(f"unknown archetype(s) in KMAMIZ_SOAK_ARCHETYPES: {bad}")
        return picked
    excluded = set(SUBPROCESS_HEAVY) | set(COLD_PROCESS)
    return [a for a in known if a not in excluded]


def archetype_index(archetype: str) -> int:
    """The archetype's canonical matrix index (its ARCHETYPES slot)."""
    for i, (name, _t) in enumerate(ARCHETYPES):
        if name == archetype:
            return i
    raise ValueError(f"unknown archetype: {archetype!r}")


def cell_id(archetype: str, seed: int) -> str:
    return f"{archetype}-s{seed}"


def enumerate_cells(
    n_cells: int,
    seed0: int = 0,
    archetypes: Optional[Sequence[str]] = None,
    ticks: Optional[int] = None,
    observed: Optional[Dict[str, float]] = None,
) -> List[dict]:
    """The sweep plan: ``n_cells`` cells cycling the archetype
    vocabulary across ascending seeds, each priced by the graftcost
    scenario plane and sorted longest-first (LPT — the expensive tail
    starts immediately instead of straggling last)."""
    archs = list(archetypes) if archetypes else sweep_archetypes()
    ticks = sweep_ticks() if ticks is None else ticks
    cells = []
    for i in range(n_cells):
        archetype = archs[i % len(archs)]
        seed = seed0 + i // len(archs)
        spec = build_scenario(archetype, seed, archetype_index(archetype), ticks)
        cells.append(
            {
                "id": cell_id(archetype, seed),
                "archetype": archetype,
                "seed": seed,
                "index": archetype_index(archetype),
                "ticks": ticks,
                "predicted_s": predicted_scenario_cost_s(spec, observed),
            }
        )
    cells.sort(key=lambda c: (-c["predicted_s"], c["id"]))
    return cells


def observed_ratios(results: Dict[str, dict]) -> Dict[str, float]:
    """Per-archetype cost corrections from a prior (partial) sweep's
    finished records — resumed and repeated sweeps order by what cells
    actually cost last time."""
    return fit_observed(results.values())
