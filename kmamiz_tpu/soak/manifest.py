"""On-disk sweep manifest: atomic per-cell records, crash-safe resume.

Layout under the sweep directory (``KMAMIZ_SOAK_DIR``):

    manifest.json            the planned cell list (cost-ordered)
    results/<cell>.json      one atomic record per finished cell
    claims/<cell>.claim      O_EXCL worker claims (in-flight cells)
    baselines/<arch>.json    last passing flight profile per archetype
    flights/                 per-cell flight boxes (KMAMIZ_PROF_FLIGHT_DIR)

Every write is tmp + ``os.replace`` so a kill -9 at any instant leaves
either the old record or the new one, never a torn file. A claim is a
single ``O_CREAT|O_EXCL`` create — the only cross-process mutual
exclusion the sweep needs; workers that die leave a stale claim with no
result, and ``clear_stale_claims`` (called by the engine before workers
exist) releases them so a resumed sweep re-runs exactly the unfinished
cells.
"""
from __future__ import annotations

import errno
import json
import os
from typing import Dict, List, Optional

MANIFEST_KIND = "kmamiz-soak-manifest"
MANIFEST_VERSION = 1


def default_soak_dir() -> str:
    return os.environ.get("KMAMIZ_SOAK_DIR") or os.path.join(
        "kmamiz-data", "soak"
    )


def write_json_atomic(path: str, doc: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, separators=(",", ":"), sort_keys=True)
    os.replace(tmp, path)


def read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class SoakManifest:
    """One sweep directory: the cell plan plus its mutable on-disk
    state. Safe for concurrent use by N worker processes."""

    def __init__(self, root: Optional[str] = None) -> None:
        self.root = root or default_soak_dir()

    # -- paths ---------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.root, "manifest.json")

    @property
    def results_dir(self) -> str:
        return os.path.join(self.root, "results")

    @property
    def claims_dir(self) -> str:
        return os.path.join(self.root, "claims")

    @property
    def baselines_dir(self) -> str:
        return os.path.join(self.root, "baselines")

    @property
    def flights_dir(self) -> str:
        return os.path.join(self.root, "flights")

    def result_path(self, cell_id: str) -> str:
        return os.path.join(self.results_dir, f"{cell_id}.json")

    def baseline_path(self, archetype: str) -> str:
        return os.path.join(self.baselines_dir, f"{archetype}.json")

    # -- manifest ------------------------------------------------------------

    def write(self, doc: dict) -> None:
        doc = {"kind": MANIFEST_KIND, "version": MANIFEST_VERSION, **doc}
        for sub in (
            self.results_dir,
            self.claims_dir,
            self.baselines_dir,
            self.flights_dir,
        ):
            os.makedirs(sub, exist_ok=True)
        write_json_atomic(self.manifest_path, doc)

    def load(self) -> Optional[dict]:
        doc = read_json(self.manifest_path)
        if doc is None or doc.get("kind") != MANIFEST_KIND:
            return None
        return doc

    # -- per-cell records ----------------------------------------------------

    def record_result(self, cell_id: str, doc: dict) -> None:
        write_json_atomic(self.result_path(cell_id), doc)

    def load_results(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        try:
            names = os.listdir(self.results_dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            doc = read_json(os.path.join(self.results_dir, name))
            if doc is not None:
                out[name[: -len(".json")]] = doc
        return out

    def drop_result(self, cell_id: str) -> None:
        try:
            os.remove(self.result_path(cell_id))
        except OSError:
            pass

    # -- claims --------------------------------------------------------------

    def claim(self, cell_id: str) -> bool:
        """Atomically claim a cell for this process. True iff won."""
        os.makedirs(self.claims_dir, exist_ok=True)
        path = os.path.join(self.claims_dir, f"{cell_id}.claim")
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError as exc:
            if exc.errno == errno.EEXIST:
                return False
            raise
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(str(os.getpid()))
        return True

    def clear_stale_claims(self) -> List[str]:
        """Release claims that have no finished result — the in-flight
        cells of a killed sweep. Only the engine calls this, before any
        worker of the new run exists, so no live claim can be cleared."""
        cleared: List[str] = []
        try:
            names = os.listdir(self.claims_dir)
        except OSError:
            return cleared
        for name in names:
            if not name.endswith(".claim"):
                continue
            cell_id = name[: -len(".claim")]
            if os.path.exists(self.result_path(cell_id)):
                continue
            try:
                os.remove(os.path.join(self.claims_dir, name))
                cleared.append(cell_id)
            except OSError:
                pass
        return cleared

    # -- incremental planning ------------------------------------------------

    def pending_cells(self, rerun_failed: bool = True) -> List[dict]:
        """Manifest cells still needing execution, in manifest (cost)
        order: no result yet, a result from a different plan (the
        manifest was re-planned with e.g. another tick count — a stale
        record must not pass for the new cell), or a failed result when
        ``rerun_failed``. Superseded records are dropped so the
        worker's claim/record cycle stays uniform."""
        doc = self.load()
        if doc is None:
            return []
        results = self.load_results()
        pending = []
        for cell in doc.get("cells", []):
            rec = results.get(cell["id"])
            stale = rec is not None and rec.get("ticks") != cell.get("ticks")
            if rec is not None and (
                stale or (rerun_failed and not rec.get("pass"))
            ):
                self.drop_result(cell["id"])
                try:
                    os.remove(
                        os.path.join(self.claims_dir, f"{cell['id']}.claim")
                    )
                except OSError:
                    pass
                rec = None
            if rec is None:
                pending.append(cell)
        return pending
