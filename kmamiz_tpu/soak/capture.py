"""Snapshot a live server's WAL into a replayable scenario bundle.

    # from a running server (the tenant's WAL namespace over HTTP —
    # the same /fleet/wal handoff blob the migration protocol ships)
    python -m kmamiz_tpu.soak.capture --url http://127.0.0.1:8080 --out bundle/

    # from a WAL directory on disk (segment files copied VERBATIM, so
    # legacy v1 frames stay v1 — replay exercises the mixed decoder)
    python -m kmamiz_tpu.soak.capture --wal-dir kmamiz-data/wal --out bundle/

The bundle is a directory: ``bundle.json`` metadata plus ``wal/``
holding real WAL segments. Point ``KMAMIZ_SOAK_BUNDLE`` at it and the
``wal-replay`` archetype (scenario matrix slot 11, tools/graftsoak.py
sweeps) replays the recording through a live server, gated bit-exact
against a reference built from the same records. Capture itself is
dependency-light — no jax, no server boot — so it can run beside
production.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
import urllib.request

from kmamiz_tpu.resilience.wal import IngestWAL
from kmamiz_tpu.soak.walreplay import bundle_wal_dir, write_bundle_meta


def capture_from_wal_dir(wal_dir: str, out_dir: str) -> dict:
    """Copy the WAL's segment files verbatim (frame versions intact),
    count the durable records via the stop-clean replay iterator."""
    src = IngestWAL(wal_dir)
    try:
        records = sum(1 for _ in src.replay_records())
    finally:
        src.close()
    dest = bundle_wal_dir(out_dir)
    os.makedirs(dest, exist_ok=True)
    copied = 0
    for name in sorted(os.listdir(wal_dir)):
        if name.endswith(".wal"):
            shutil.copy2(os.path.join(wal_dir, name), os.path.join(dest, name))
            copied += 1
    return write_bundle_meta(
        out_dir,
        records=records,
        segments=copied,
        source=f"wal-dir:{os.path.abspath(wal_dir)}",
        created_unix=int(time.time()),
    )


def capture_from_url(url: str, out_dir: str, tenant: str = "default") -> dict:
    """Fetch the live server's WAL namespace as one handoff blob
    (GET /fleet/wal) and import it into the bundle's own WAL."""
    prefix = "" if tenant == "default" else f"/t/{tenant}"
    req = urllib.request.Request(f"{url.rstrip('/')}{prefix}/fleet/wal")
    with urllib.request.urlopen(req, timeout=60) as resp:
        blob = resp.read()
    dest = IngestWAL(bundle_wal_dir(out_dir), fsync=False)
    try:
        records = dest.import_handoff(blob)
    finally:
        dest.close()
    return write_bundle_meta(
        out_dir,
        records=records,
        tenant=tenant,
        source=f"url:{url}",
        created_unix=int(time.time()),
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", required=True, help="bundle directory to write")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="live server base URL (GET /fleet/wal)")
    src.add_argument("--wal-dir", help="WAL directory on disk")
    ap.add_argument("--tenant", default="default", help="tenant namespace")
    args = ap.parse_args(argv)

    if args.wal_dir:
        meta = capture_from_wal_dir(args.wal_dir, args.out)
    else:
        meta = capture_from_url(args.url, args.out, args.tenant)
    print(
        f"captured {meta['records']} records -> {args.out}", file=sys.stderr
    )
    print(json.dumps({"bundle": args.out, **meta}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
