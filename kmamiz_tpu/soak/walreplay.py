"""WAL-replay scenario source: recorded production windows as a
first-class scenario (archetype 11, ``wal-replay``).

The fsynced WAL v2 already captures every raw ingest window bit-exact;
this module closes the loop by replaying a recorded window back through
the factory harness — a real ``DataProcessorServer`` fed each durable
record over POST /ingest — and holding the result to the same gates as
every other archetype. The reference signature is computed from the
SAME records (``resilience/wal.replay_records`` into a fresh
processor), so the oracle is the recording itself: real traffic
shapes, bit-exact or the gate fails.

Bundle resolution:

* ``KMAMIZ_SOAK_BUNDLE`` points at a captured bundle directory
  (``python -m kmamiz_tpu.soak.capture`` writes one from a live
  server's WAL or a WAL directory on disk) — the production-replay
  path.
* Otherwise the cell SYNTHESIZES a bundle from its own composed spec
  (topology × traffic through a real WAL append, every third window
  columnar-framed), so archetype 11 runs self-contained in the matrix
  and the sweep — same replay machinery, deterministic content.

Torn tails truncate clean by construction: both the reference and the
live replay iterate ``replay_records``, whose stop-clean contract drops
a torn trailing frame on BOTH sides — the cell scores the intact
prefix instead of failing.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

from kmamiz_tpu.resilience.wal import IngestWAL
from kmamiz_tpu.telemetry.profiling import events as prof_events

BUNDLE_KIND = "kmamiz-soak-bundle"
BUNDLE_VERSION = 1


def bundle_env() -> Optional[str]:
    return os.environ.get("KMAMIZ_SOAK_BUNDLE") or None


def bundle_wal_dir(bundle_dir: str) -> str:
    return os.path.join(bundle_dir, "wal")


def write_bundle_meta(bundle_dir: str, **fields) -> dict:
    meta = {
        "kind": BUNDLE_KIND,
        "version": BUNDLE_VERSION,
        **fields,
    }
    os.makedirs(bundle_dir, exist_ok=True)
    tmp = os.path.join(bundle_dir, "bundle.json.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(bundle_dir, "bundle.json"))
    return meta


def read_bundle_meta(bundle_dir: str) -> dict:
    with open(os.path.join(bundle_dir, "bundle.json"), encoding="utf-8") as f:
        meta = json.load(f)
    if meta.get("kind") != BUNDLE_KIND:
        raise ValueError(f"not a soak bundle: {bundle_dir}")
    return meta


def load_bundle_records(bundle_dir: str) -> List[Tuple[int, bytes]]:
    """Every durable record of the bundle's WAL, oldest first, torn
    tail dropped (stop-clean). Read-only: replay never appends."""
    wal = IngestWAL(bundle_wal_dir(bundle_dir))
    try:
        return list(wal.replay_records())
    finally:
        wal.close()


def synthesize_bundle(spec, bundle_dir: str) -> dict:
    """A deterministic stand-in recording composed from the spec's own
    topology × traffic: one window per tick through a REAL WAL append
    (v2 frames, fsync off — content is what's under test), every third
    window columnar so KIND_COLUMNAR replays are always exercised."""
    from kmamiz_tpu.core import wire
    from kmamiz_tpu.scenarios.topology import trace_group

    plan = spec.tenants[0]
    wal = IngestWAL(bundle_wal_dir(bundle_dir), fsync=False)
    windows = 0
    try:
        for tick in range(spec.n_ticks):
            groups = [
                trace_group(
                    plan.topology, f"{spec.name}-rep", tick, i
                )
                for i in range(max(1, plan.traffic[tick]))
            ]
            if tick % 3 == 2:
                wal.append(wire.encode_groups(groups))
            else:
                wal.append(json.dumps(groups).encode())
            windows += 1
    finally:
        wal.close()
    return write_bundle_meta(
        bundle_dir,
        records=windows,
        tenant=plan.tenant,
        source=f"synthesized:{spec.name}",
        created_unix=int(prof_events.wall_ms() / 1000),
    )


def run_wal_replay_scenario(spec, tmpdir: str, verbose: bool = False) -> dict:
    """Drive one wal-replay cell end to end; returns its scorecard
    (same gate vocabulary as the other archetypes)."""
    import urllib.error

    from kmamiz_tpu.core import programs
    from kmamiz_tpu.resilience.chaos import graph_signature
    from kmamiz_tpu.scenarios.factory import spec_signature
    from kmamiz_tpu.scenarios.runner import _post_ingest
    from kmamiz_tpu.server.dp_server import DataProcessorServer, _make_runtime
    from kmamiz_tpu.server.processor import DataProcessor
    from kmamiz_tpu.telemetry.slo import percentile
    from kmamiz_tpu.tenancy.router import TickRouter

    t_start = prof_events.now_ms()
    tenant = spec.tenants[0].tenant
    errors: List[str] = []

    bundle_dir = bundle_env()
    if bundle_dir is None:
        bundle_dir = os.path.join(tmpdir, "bundle")
        meta = synthesize_bundle(spec, bundle_dir)
    else:
        meta = read_bundle_meta(bundle_dir)
    records = load_bundle_records(bundle_dir)
    torn_dropped = max(0, int(meta.get("records", len(records))) - len(records))

    # reference pass: the recording itself is the oracle — a fresh
    # processor ingests every durable record directly; this also warms
    # every program shape the live replay will need (the registry is
    # process-global), so the steady-state recompile gate below
    # measures the replay alone
    ref_dp = DataProcessor(
        trace_source=lambda *_a: [], use_device_stats=False
    )
    ref_spans = 0
    for _kind, payload in records:
        ref_spans += int(ref_dp.ingest_raw_window(payload).get("spans", 0))
    ref_sig = graph_signature(ref_dp.graph)

    snapshot = programs.snapshot()

    # live pass through the factory harness: each record POSTed to a
    # real server, exactly the path production ingest takes
    live_dp = DataProcessor(
        trace_source=lambda *_a: [], use_device_stats=False, tenant=tenant
    )
    router = TickRouter(lambda t: _make_runtime(t, live_dp))
    server = DataProcessorServer(
        live_dp, host="127.0.0.1", port=0, router=router
    )
    server.start()
    latencies: List[float] = []
    live_spans = 0
    quarantined = 0
    posts = 0
    try:
        for _kind, payload in records:
            t0 = prof_events.now_ms()
            try:
                resp = _post_ingest(server.port, tenant, payload)
            except (OSError, urllib.error.URLError) as exc:
                errors.append(f"ingest: {type(exc).__name__}: {exc}")
                continue
            latencies.append(prof_events.now_ms() - t0)
            posts += 1
            live_spans += int(resp.get("spans", 0))
            quarantined += int(resp.get("quarantined", 0))
        live_sig = graph_signature(live_dp.graph)
    finally:
        server.stop()

    steady_recompiles = sum(programs.new_compiles_since(snapshot).values())
    lat = sorted(latencies)
    gates = {
        "no_errors": not errors,
        "bit_exact": live_sig == ref_sig,
        "replayed_all": posts == len(records),
        "zero_lost_spans": live_spans == ref_spans,
        "zero_steady_recompiles": steady_recompiles == 0,
        "quarantine_exact": quarantined == 0,
    }
    card = {
        "name": spec.name,
        "archetype": spec.archetype,
        "spec_signature": spec_signature(spec),
        "n_ticks": spec.n_ticks,
        "tenants": [tenant],
        "posts": posts,
        "stale_serves": 0,
        "stale_rate": 0.0,
        "p50_tick_ms": round(percentile(lat, 0.50), 2),
        "p95_tick_ms": round(percentile(lat, 0.95), 2),
        "p99_tick_ms": round(percentile(lat, 0.99), 2),
        "lost_spans": max(0, ref_spans - live_spans),
        "missing_traces": [],
        "quarantined": quarantined,
        "expected_poisons": 0,
        "recovery_ms": 0.0,
        "recoveries": {},
        "steady_recompiles": steady_recompiles,
        "mid_tick_compiles": 0,
        "mid_tick_detail": [],
        "capacity": {},
        "signatures": {tenant: live_sig},
        "ref_signatures": {tenant: ref_sig},
        "freshness": {},
        "wal": {
            "ok": gates["replayed_all"] and gates["bit_exact"],
            "records": len(records),
            "spans": live_spans,
            "torn_dropped": torn_dropped,
            "source": meta.get("source", bundle_dir),
        },
        "errors": errors[:4],
        "gates": gates,
        "pass": all(gates.values()),
        "wall_s": round((prof_events.now_ms() - t_start) / 1000, 1),
    }
    if not card["pass"]:
        from kmamiz_tpu.scenarios.factory import SEED_STRIDE
        from kmamiz_tpu.telemetry.profiling import recorder

        base_seed = (spec.seed - spec.index) // SEED_STRIDE
        failed = sorted(g for g, ok in gates.items() if not ok)
        card["flight_artifact"] = recorder.record(
            f"scenario-{spec.name}",
            ",".join(failed),
            force=True,
            namespace=f"{spec.archetype}-{base_seed}",
        )
    if verbose:
        import sys

        print(
            f"{spec.name}: pass={card['pass']} gates={gates}",
            file=sys.stderr,
        )
    return card
