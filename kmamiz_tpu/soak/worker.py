"""Sweep worker subprocess: claim cells, run scenarios, record results.

``python -m kmamiz_tpu.soak.worker --dir <sweep>`` loops over the
manifest's pending cells IN MANIFEST ORDER (the engine wrote them
longest-predicted-first), claims the first unowned one atomically, runs
it inside its own temp sandbox, and writes the cell's result record
atomically. Per cell:

* a compose or run exception becomes a ``crashed``-gate card (one bad
  cell never takes the worker, let alone the sweep);
* a PASSING cell refreshes ``baselines/<archetype>.json`` — the "last
  passing flight" the auto-triage bisects failures against;
* a FAILING cell keeps its namespaced ``flight-*.json`` evidence box
  and gets a triage record (blamed gate/phase/tenant + signature)
  bisected against the archetype baseline;
* a cell marked ``poison`` in the manifest is forced to fail after
  running — the sweep's own canary that failure evidence, triage, and
  dedupe actually fire.

The worker exits 0 when a full scan finds nothing left to claim.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

from kmamiz_tpu.soak import triage as triage_mod
from kmamiz_tpu.soak.manifest import SoakManifest, read_json
from kmamiz_tpu.telemetry.profiling import events as prof_events


def _flight_namespace(cell: dict) -> str:
    return f"{cell['archetype']}-{cell['seed']}"


def run_cell(man: SoakManifest, cell: dict, verbose: bool = False) -> dict:
    """Run one claimed cell end to end and write its result record."""
    from kmamiz_tpu.scenarios import factory, runner
    from kmamiz_tpu.telemetry.profiling import recorder

    t0 = prof_events.now_ms()
    spec = None
    try:
        spec = factory.build_scenario(
            cell["archetype"], cell["seed"], cell["index"], cell["ticks"]
        )
        with tempfile.TemporaryDirectory(prefix="kmamiz-cell-") as tmp:
            card = runner.run_scenario(spec, tmpdir=tmp)
    except Exception as exc:  # noqa: BLE001 - one bad cell must not end the sweep
        card = runner.crashed_card(
            spec, exc, archetype=cell["archetype"],
            wall_s=(prof_events.now_ms() - t0) / 1000,
        )

    if cell.get("poison") and card.get("pass"):
        # seeded canary: force the failure path so the sweep proves its
        # own evidence + triage machinery end to end
        card = dict(card)
        card["gates"] = {**card.get("gates", {}), "soak_poison": False}
        card["pass"] = False
        if not card.get("flight_artifact"):
            card["flight_artifact"] = recorder.record(
                f"scenario-{card.get('name', cell['id'])}",
                "soak_poison",
                force=True,
                namespace=_flight_namespace(cell),
            )

    tri = None
    if card.get("pass"):
        # refresh the archetype's last-passing-flight baseline (atomic
        # replace; concurrent workers race benignly — last writer wins)
        from kmamiz_tpu.soak.manifest import write_json_atomic

        write_json_atomic(
            man.baseline_path(cell["archetype"]),
            recorder.build_artifact(
                f"soak-baseline-{cell['id']}", "last passing cell"
            ),
        )
    else:
        baseline = read_json(man.baseline_path(cell["archetype"]))
        flight = (
            read_json(card["flight_artifact"])
            if card.get("flight_artifact")
            else None
        )
        tri = triage_mod.triage_card(card, baseline, flight)

    record = {
        "id": cell["id"],
        "archetype": cell["archetype"],
        "seed": cell["seed"],
        "index": cell["index"],
        "ticks": cell["ticks"],
        "predicted_s": cell.get("predicted_s"),
        "poison": bool(cell.get("poison")),
        "spec_signature": card.get("spec_signature"),
        "pass": bool(card.get("pass")),
        "gates_failed": triage_mod.failed_gates(card),
        "p99_tick_ms": card.get("p99_tick_ms", 0.0),
        "lost_spans": card.get("lost_spans", 0),
        "errors": (card.get("errors") or [])[:2],
        "flight_artifact": card.get("flight_artifact"),
        "triage": tri,
        "wall_s": round((prof_events.now_ms() - t0) / 1000, 2),
        "worker_pid": os.getpid(),
        "run_id": os.environ.get("KMAMIZ_SOAK_RUN_ID"),
        "finished_unix": int(prof_events.wall_ms() / 1000),
    }
    man.record_result(cell["id"], record)
    if verbose:
        state = "PASS" if record["pass"] else "FAIL"
        blame = f"  blame={tri['signature']}" if tri else ""
        print(
            f"[soak-worker {os.getpid()}] {cell['id']} {state} "
            f"wall={record['wall_s']}s{blame}",
            file=sys.stderr,
        )
    return record


def work_loop(root: str, verbose: bool = False) -> int:
    man = SoakManifest(root)
    if man.load() is None:
        print(f"no manifest under {root}", file=sys.stderr)
        return 2
    # per-cell evidence lands inside the sweep dir; namespaced names
    # keep cells from evicting each other's boxes
    os.environ["KMAMIZ_PROF_FLIGHT_DIR"] = man.flights_dir
    ran = 0
    while True:
        claimed = None
        for cell in man.pending_cells(rerun_failed=False):
            if man.claim(cell["id"]):
                claimed = cell
                break
        if claimed is None:
            break
        run_cell(man, claimed, verbose=verbose)
        ran += 1
    if verbose:
        print(f"[soak-worker {os.getpid()}] done: {ran} cells", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", required=True, help="sweep directory")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    return work_loop(args.dir, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
