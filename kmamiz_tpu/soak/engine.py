"""Sweep engine: plan, fan out worker subprocesses, aggregate, triage.

``run_sweep`` is the whole lifecycle of one soak sweep:

1. **Plan** — enumerate ``(archetype, seed)`` cells, price each with
   the graftcost scenario plane (corrected by any observed walls
   already in the sweep dir), order longest-first, and write the
   manifest atomically. A matching manifest already on disk is REUSED
   verbatim, so resuming a killed sweep keeps the original plan.
2. **Resume bookkeeping** — stale claims (in-flight cells of a killed
   run) are released; failed results are dropped for re-execution when
   ``rerun_failed`` (the default: reruns are incremental, only
   new/failed cells execute).
3. **Fan out** — N worker subprocesses (``kmamiz_tpu.soak.worker``)
   claim cells from the shared manifest until none remain. A worker
   that dies mid-cell only orphans its claim; the engine clears it and
   respawns (bounded rounds), so the sweep converges even through
   worker loss.
4. **Aggregate** — per-cell records roll up into the soak report:
   pass rate over non-poison cells, triaged fraction over ALL
   failures, and the deduped bug list (same triage signature = one
   bug, N occurrences).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import List, Optional, Sequence

from kmamiz_tpu.soak import cells as cells_mod
from kmamiz_tpu.soak import triage as triage_mod
from kmamiz_tpu.soak.manifest import SoakManifest

_SWEEPS_LOCK = threading.Lock()
_SWEEPS: List[dict] = []

DEFAULT_CELLS = 100
#: acceptance floor: four nines of non-poison cells pass
DEFAULT_PASS_FLOOR = 0.9999
_SPAWN_ROUNDS = 3


def soak_workers() -> int:
    try:
        return max(
            1,
            int(
                os.environ.get(
                    "KMAMIZ_SOAK_WORKERS",
                    min(4, max(1, (os.cpu_count() or 1))),
                )
            ),
        )
    except ValueError:
        return 1


def pass_floor() -> float:
    try:
        return float(os.environ.get("KMAMIZ_SOAK_PASS_FLOOR", DEFAULT_PASS_FLOOR))
    except ValueError:
        return DEFAULT_PASS_FLOOR


def _poison_ids(cells: List[dict], n_poison: int) -> List[str]:
    """Deterministic poison pick: the lexically-first ``n_poison`` cell
    ids — stable across plans, resumes, and cost reorderings."""
    return sorted(c["id"] for c in cells)[: max(0, n_poison)]


def plan_sweep(
    man: SoakManifest,
    n_cells: int,
    seed: int = 0,
    archetypes: Optional[Sequence[str]] = None,
    ticks: Optional[int] = None,
    poison: int = 0,
) -> dict:
    """Write (or reuse) the sweep manifest. An existing manifest with
    the same cell set, ticks, and poison pick is kept verbatim — the
    resume contract."""
    observed = cells_mod.observed_ratios(man.load_results())
    cells = cells_mod.enumerate_cells(
        n_cells, seed0=seed, archetypes=archetypes, ticks=ticks,
        observed=observed,
    )
    poison_ids = set(_poison_ids(cells, poison))
    for cell in cells:
        if cell["id"] in poison_ids:
            cell["poison"] = True
    existing = man.load()
    if existing is not None:
        same_cells = {
            (c["id"], c["ticks"], bool(c.get("poison")))
            for c in existing.get("cells", [])
        } == {(c["id"], c["ticks"], bool(c.get("poison"))) for c in cells}
        if same_cells:
            return existing
    doc = {
        "seed": seed,
        "n_cells": n_cells,
        "poison": sorted(poison_ids),
        "cells": cells,
        "created_unix": int(time.time()),
    }
    man.write(doc)
    return man.load()


def _spawn_workers(man: SoakManifest, n: int, run_id: str, verbose: bool):
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = {
        **os.environ,
        "KMAMIZ_SOAK_RUN_ID": run_id,
        "KMAMIZ_PROF_FLIGHT_DIR": man.flights_dir,
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
    }
    cmd = [sys.executable, "-m", "kmamiz_tpu.soak.worker", "--dir", man.root]
    if verbose:
        cmd.append("--verbose")
    return [
        subprocess.Popen(cmd, env=env, cwd=repo_root, stdout=sys.stderr)
        for _ in range(n)
    ]


def build_report(man: SoakManifest) -> dict:
    """Roll the per-cell records up into the soak report. Every field
    that feeds the gate (pass rate, triage, bugs, per-cell verdicts) is
    deterministic for a deterministic cell set — resuming a killed
    sweep reproduces it bit-identically."""
    doc = man.load() or {"cells": []}
    results = man.load_results()
    cells = doc.get("cells", [])
    finished = [results[c["id"]] for c in cells if c["id"] in results]
    nonpoison = [r for r in finished if not r.get("poison")]
    passed = [r for r in nonpoison if r.get("pass")]
    failures = [r for r in finished if not r.get("pass")]
    real_failures = [r for r in failures if not r.get("poison")]
    triaged = [
        r for r in failures if (r.get("triage") or {}).get("signature")
    ]
    pass_rate = (
        round(len(passed) / len(nonpoison), 6) if nonpoison else 0.0
    )
    triaged_fraction = (
        round(len(triaged) / len(failures), 6) if failures else 1.0
    )
    complete = len(finished) == len(cells) and bool(cells)
    floor = pass_floor()
    return {
        "cells_total": len(cells),
        "cells_finished": len(finished),
        "cells_passed": len(passed),
        "cells_failed": len(failures),
        "real_failures": len(real_failures),
        "poison_cells": sorted(doc.get("poison", [])),
        "pass_rate": pass_rate,
        "pass_floor": floor,
        "triaged_fraction": triaged_fraction,
        "bugs": triage_mod.dedupe(failures),
        "failures": [
            {
                "id": r["id"],
                "gates_failed": r.get("gates_failed", []),
                "triage": r.get("triage"),
                "flight_artifact": r.get("flight_artifact"),
            }
            for r in sorted(failures, key=lambda r: r["id"])[:32]
        ],
        "complete": complete,
        "soak_pass": complete
        and pass_rate >= floor
        and triaged_fraction >= 1.0,
        "cells": [
            {
                "id": r["id"],
                "pass": bool(r.get("pass")),
                "gates_failed": r.get("gates_failed", []),
                "triage_signature": (r.get("triage") or {}).get("signature"),
            }
            for r in sorted(finished, key=lambda r: r["id"])
        ],
    }


def run_sweep(
    n_cells: int = DEFAULT_CELLS,
    seed: int = 0,
    workers: Optional[int] = None,
    ticks: Optional[int] = None,
    archetypes: Optional[Sequence[str]] = None,
    poison: int = 0,
    soak_dir: Optional[str] = None,
    rerun_failed: bool = True,
    verbose: bool = False,
) -> dict:
    """The full sweep lifecycle; returns the soak report plus this
    run's execution stats (cells executed, wall, cells/min)."""
    man = SoakManifest(soak_dir)
    plan_sweep(
        man, n_cells, seed=seed, archetypes=archetypes, ticks=ticks,
        poison=poison,
    )
    man.clear_stale_claims()
    if rerun_failed:
        man.pending_cells(rerun_failed=True)  # drops failed records+claims
    run_id = f"run-{os.getpid()}-{int(time.time() * 1000)}"
    t0 = time.time()
    n_workers = soak_workers() if workers is None else max(1, workers)
    rounds = 0
    while man.pending_cells(rerun_failed=False) and rounds < _SPAWN_ROUNDS:
        rounds += 1
        procs = _spawn_workers(man, n_workers, run_id, verbose)
        for p in procs:
            p.wait()
        # a worker that died mid-cell left a claim with no result;
        # clear it so the next round picks the cell up
        if man.clear_stale_claims() and verbose:
            print("[soak] cleared stale claims, respawning", file=sys.stderr)
    wall_s = time.time() - t0
    report = build_report(man)
    executed = [
        r
        for r in man.load_results().values()
        if r.get("run_id") == run_id
    ]
    report["soak_dir"] = man.root
    report["run_id"] = run_id
    report["cells_executed"] = len(executed)
    report["wall_s"] = round(wall_s, 1)
    report["cells_per_min"] = (
        round(len(executed) / wall_s * 60.0, 2) if wall_s > 0 else 0.0
    )
    with _SWEEPS_LOCK:
        _SWEEPS.append(report)
    return report


def recorded_sweeps() -> List[dict]:
    with _SWEEPS_LOCK:
        return list(_SWEEPS)


def reset_for_tests() -> None:
    with _SWEEPS_LOCK:
        _SWEEPS.clear()
