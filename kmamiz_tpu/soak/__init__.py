"""graftsoak: thousand-scenario production-replay soak at four nines.

Three pillars over the existing scenario factory (docs/SCENARIOS.md):

* **Sweep engine** (:mod:`.engine`, :mod:`.manifest`, :mod:`.cells`,
  driven by ``tools/graftsoak.py``) — a multiprocess pool fanning
  ``(archetype, seed)`` cells across worker subprocesses, ordered by
  graftcost-predicted per-scenario cost (longest first), with a
  resumable on-disk manifest of atomic per-cell result records under
  ``KMAMIZ_SOAK_DIR``.
* **WAL-replay scenario source** (:mod:`.walreplay`, recorded by
  ``python -m kmamiz_tpu.soak.capture``) — a recorded WAL v2 window
  replayed through the factory harness as archetype 11, gated
  bit-exact against a reference built from the same records.
* **Auto-triage** (:mod:`.triage`) — every failing cell's flight box
  bisected against the archetype's last passing flight, blamed
  phase/tenant/gate emitted into the cell record, failures deduped by
  triage signature in the soak report.
"""
from kmamiz_tpu.soak.cells import (
    COLD_PROCESS,
    SUBPROCESS_HEAVY,
    enumerate_cells,
    sweep_archetypes,
    sweep_ticks,
)
from kmamiz_tpu.soak.engine import (
    build_report,
    pass_floor,
    plan_sweep,
    recorded_sweeps,
    run_sweep,
    soak_workers,
)
from kmamiz_tpu.soak.manifest import SoakManifest, default_soak_dir
from kmamiz_tpu.soak.triage import dedupe, triage_card
from kmamiz_tpu.soak.walreplay import run_wal_replay_scenario

__all__ = [
    "COLD_PROCESS",
    "SUBPROCESS_HEAVY",
    "SoakManifest",
    "build_report",
    "dedupe",
    "default_soak_dir",
    "enumerate_cells",
    "pass_floor",
    "plan_sweep",
    "recorded_sweeps",
    "reset_for_tests",
    "run_sweep",
    "run_wal_replay_scenario",
    "soak_workers",
    "sweep_archetypes",
    "sweep_ticks",
    "triage_card",
]


def reset_for_tests() -> None:
    """Clear soak-global state (the completed-sweep registry)."""
    from kmamiz_tpu.soak import engine

    engine.reset_for_tests()
