"""String interning: the bridge between the string-keyed reference model and
id-indexed device arrays.

The reference keys everything by tab-joined strings
(uniqueServiceName = "svc\\tns\\tversion",
uniqueEndpointName = "svc\\tns\\tver\\tMETHOD\\turl"; see
/root/reference/src/classes/Traces.ts:35,46). On TPU those become int32 ids
into per-kind intern tables; all device arrays are id-indexed and strings
never leave the host.
"""
from __future__ import annotations

import threading
from typing import Dict, Hashable, Iterable, List, Optional


class StringInterner:
    """Bidirectional key<->int32 table with insertion-order ids.

    Keys are usually strings (the reference's tab-joined names), but any
    hashable value is a valid key: the DP status interner keys segments by
    the RAW http.status_code value (str, int, or None for spans without the
    tag) so that device segments align with the host's raw-status groupby.
    """

    __slots__ = ("_to_id", "_strings")

    def __init__(self, strings: Optional[Iterable[Hashable]] = None) -> None:
        self._to_id: Dict[Hashable, int] = {}
        self._strings: List[Hashable] = []
        if strings:
            for s in strings:
                self.intern(s)

    def intern(self, s: Hashable) -> int:
        i = self._to_id.get(s)
        if i is None:
            i = len(self._strings)
            self._to_id[s] = i
            self._strings.append(s)
        return i

    def get(self, s: Hashable) -> Optional[int]:
        return self._to_id.get(s)

    def lookup(self, i: int) -> Hashable:
        return self._strings[i]

    def __len__(self) -> int:
        return len(self._strings)

    def __contains__(self, s: Hashable) -> bool:
        return s in self._to_id

    @property
    def strings(self) -> List[Hashable]:
        return self._strings


class EndpointInterner:
    """Intern tables for the graph's naming hierarchy.

    endpoints (uniqueEndpointName), services (uniqueServiceName), the
    endpoint->service mapping as a growable int32 relation, and optional
    per-endpoint metadata (TEndpointInfo dicts) kept in lockstep with the
    endpoint table.
    """

    def __init__(self) -> None:
        self.endpoints = StringInterner()
        self.services = StringInterner()
        self._endpoint_service: List[int] = []
        self._endpoint_infos: List[Optional[dict]] = []
        # per-endpoint info timestamp MIRROR in lockstep with
        # _endpoint_infos (0.0 while info is None): lets bulk consumers
        # (graph-store recency metadata, the raw-ingest session's
        # freshest-timestamp refresh) read all timestamps as one numpy
        # array instead of walking 10k+ info dicts per window
        self._info_ts: List[float] = []
        # shared across ingest threads (the /ingest backfill races the
        # realtime tick, and the streaming pipeline overlaps the parse of
        # chunk k+1 with the merge of chunk k): the GIL makes dict ops
        # atomic but not the check-then-insert sequence, which could hand
        # two ids to one endpoint. Interning is O(#shapes) per window on
        # the raw path, so the lock is off the per-span hot loop.
        self._intern_lock = threading.RLock()

    def intern_endpoint(
        self, unique_endpoint_name: str, info: Optional[dict] = None
    ) -> int:
        """Intern an endpoint name; optionally attach/refresh its metadata
        (the freshest-timestamp info wins)."""
        with self._intern_lock:
            eid = self.endpoints.get(unique_endpoint_name)
            if eid is None:
                eid = self.endpoints.intern(unique_endpoint_name)
                parts = unique_endpoint_name.split("\t")
                service_name = "\t".join(parts[:3])
                sid = self.services.intern(service_name)
                self._endpoint_service.append(sid)
                self._endpoint_infos.append(None)
                self._info_ts.append(0.0)
            if info is not None:
                existing = self._endpoint_infos[eid]
                if existing is None or info.get("timestamp", 0) > existing.get(
                    "timestamp", 0
                ):
                    self._endpoint_infos[eid] = info
                    self._info_ts[eid] = float(info.get("timestamp", 0) or 0)
            return eid

    def info_timestamps(self):
        """Snapshot of the per-endpoint info-timestamp mirror as a
        float64 numpy array (index = endpoint id; 0.0 = no info)."""
        import numpy as np

        with self._intern_lock:
            # graftlint: disable=dtype-drift -- host-side mirror; epoch-ms exceeds f32 integer range
            return np.asarray(self._info_ts, dtype=np.float64)

    def refresh_info_timestamps(self, eids, ts_ms, expected_ts=None):
        """Bulk freshest-timestamp refresh: for each (eid, ts) pair,
        advance the existing info's timestamp in place when strictly
        newer — the session ingest path's vectorized equivalent of
        re-interning `{**info, "timestamp": ts}` per endpoint. Info
        CONTENT is unchanged by design: callers use this only when the
        winning naming shape for the endpoint is the one already
        applied.

        `expected_ts` makes the update a compare-and-set: position i
        applies only if the info's CURRENT timestamp equals
        expected_ts[i] — a mismatch means another writer (e.g. the
        dict-path realtime tick) refreshed the info since the caller
        last applied, possibly with different content that an in-place
        stamp must not bless. Returns the list of positions that did
        NOT apply (missing info, stale expectation); callers route
        those through the full intern_endpoint slow path. The check and
        the write share one lock hold, closing the snapshot-then-apply
        race a separate mirror read would leave open (review r5)."""
        failed: List[int] = []
        eids_l = eids.tolist() if hasattr(eids, "tolist") else list(eids)
        ts_l = ts_ms.tolist() if hasattr(ts_ms, "tolist") else list(ts_ms)
        exp_l = (
            None
            if expected_ts is None
            else (
                expected_ts.tolist()
                if hasattr(expected_ts, "tolist")
                else list(expected_ts)
            )
        )
        with self._intern_lock:
            infos = self._endpoint_infos
            mirror = self._info_ts
            for i, (eid, ts) in enumerate(zip(eids_l, ts_l)):
                info = infos[eid]
                if info is None:
                    failed.append(i)
                    continue
                cur = info.get("timestamp", 0)
                if exp_l is not None and cur != exp_l[i]:
                    failed.append(i)
                    continue
                if ts > cur:
                    info["timestamp"] = ts
                    mirror[eid] = ts
        return failed

    def service_of(self, endpoint_id: int) -> int:
        return self._endpoint_service[endpoint_id]

    def info_of(self, endpoint_id: int) -> Optional[dict]:
        return self._endpoint_infos[endpoint_id]

    @property
    def endpoint_service_ids(self) -> List[int]:
        return self._endpoint_service

    @property
    def endpoint_infos(self) -> List[Optional[dict]]:
        return self._endpoint_infos
