"""Envoy telemetry-filter equivalent: KMamiz log-line emission.

Equivalent of the reference's Go proxy-wasm plugin
(/root/reference/envoy/wasm/main.go): it logs a `[Request id/trace/span/
parent] [METHOD host/path] [ContentType ...] [Body ...]` line per request
and the `[Response ...] [Status] ...` twin on stream close, with JSON
bodies desensitized to type-preserving zero values before they ever leave
the pod (main.go:210-240).

In this framework the "filter" is a library: the simulator and tests use
it to synthesize istio-proxy container logs that the ingestion parser
(kmamiz_tpu.core.envoy) round-trips, and any sidecar-less deployment can
emit the same lines from process middleware. Note the WASM scrubber keeps
booleans/null as-is (main.go:216-225) while the simulator's body scrubber
zeroes them — both reference behaviors exist; this module follows the WASM
one.
"""
from __future__ import annotations

import json
from datetime import datetime, timezone
from typing import Any, List, Optional

NO_ID = "NO_ID"


def desensitize_value(value: Any) -> Any:
    """WASM parseObject semantics: strings -> "", numbers -> 0, booleans and
    null preserved; containers keep their shape (main.go:210-240)."""
    if isinstance(value, list):
        return [desensitize_value(v) for v in value]
    if isinstance(value, dict):
        return {k: desensitize_value(v) for k, v in value.items()}
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, str):
        return ""
    if isinstance(value, (int, float)):
        return 0
    return value


def _reject_constant(_name: str):
    # the wasm filter's strict grammar has no NaN/Infinity; json.loads
    # would accept them
    raise ValueError("non-JSON constant")


def _scan_string_end(s: str, i: int) -> int:
    """i points AFTER the opening quote of a known-valid JSON string;
    returns the index after the closing quote."""
    while True:
        c = s[i]
        if c == "\\":
            i += 2
            continue
        if c == '"':
            return i + 1
        i += 1


def _desens_tokens(s: str) -> str:
    """Rebuild a KNOWN-VALID JSON text with values scrubbed, keeping the
    RAW source tokens for keys and literals — exactly what the wasm
    filter's streaming transform (and the reference's gjson walk,
    main.go:210-240) emit. json.dumps-style re-encoding of keys would
    diverge on non-ASCII or non-canonically-escaped keys (e.g. the
    source token "uni\\u00E9" must survive byte-for-byte). Separators
    normalize to ", " / ": ", matching the filter's output."""
    out: list = []
    i, n = 0, len(s)
    # per-open-container marker: True/False = object (key expected /
    # not), None = array (never expects keys)
    expect_key: list = []
    while i < n:
        c = s[i]
        if c in " \t\n\r":
            i += 1
            continue
        if c == "{":
            out.append("{")
            expect_key.append(True)
            i += 1
        elif c == "[":
            out.append("[")
            expect_key.append(None)
            i += 1
        elif c in "}]":
            out.append(c)
            expect_key.pop()
            i += 1
        elif c == ",":
            out.append(", ")
            if expect_key and expect_key[-1] is not None:
                expect_key[-1] = True
            i += 1
        elif c == ":":
            out.append(": ")
            expect_key[-1] = False
            i += 1
        elif c == '"':
            end = _scan_string_end(s, i + 1)
            if expect_key and expect_key[-1]:
                out.append(s[i:end])  # raw key token, byte-for-byte
            else:
                out.append('""')
            i = end
        elif s.startswith("true", i):
            out.append("true")
            i += 4
        elif s.startswith("false", i):
            out.append("false")
            i += 5
        elif s.startswith("null", i):
            out.append("null")
            i += 4
        else:  # number token
            out.append("0")
            while i < n and s[i] not in ",}] \t\n\r":
                i += 1
    return "".join(out)


def desensitize_body(body: str) -> Optional[str]:
    """JSON body -> desensitized JSON string; None when it doesn't parse
    (the filter drops unparseable bodies, main.go:213-218). Validation
    rides json.loads' strict grammar (with NaN/Infinity rejected, like
    the filter); the output is rebuilt from the RAW source tokens so
    keys, duplicate keys, and literal spellings match the wasm
    filter's streaming transform exactly."""
    try:
        json.loads(body, parse_constant=_reject_constant)
    except (json.JSONDecodeError, TypeError, ValueError, RecursionError):
        return None
    return _desens_tokens(body)


def _id_block(kind: str, request_id: str, trace_id: str, span_id: str, parent_span_id: str) -> str:
    return f"[{kind} {request_id}/{trace_id}/{span_id}/{parent_span_id}]"


def format_request_log(
    method: str,
    host: str,
    path: str,
    request_id: str = NO_ID,
    trace_id: str = NO_ID,
    span_id: str = NO_ID,
    parent_span_id: str = NO_ID,
    content_type: str = "",
    body: str = "",
) -> str:
    """main.go:177-189 plus the body block appended on buffer end."""
    line = (
        _id_block("Request", request_id, trace_id, span_id, parent_span_id)
        + f" [{method} {host}{path}]"
    )
    if content_type:
        line += f" [ContentType {content_type}]"
    if body and content_type == "application/json":
        scrubbed = desensitize_body(body)
        if scrubbed is not None:
            line += f" [Body] {scrubbed}"
    return line


def format_response_log(
    status: str,
    request_id: str = NO_ID,
    trace_id: str = NO_ID,
    span_id: str = NO_ID,
    parent_span_id: str = NO_ID,
    content_type: str = "",
    body: str = "",
) -> str:
    """main.go:190-201 plus the body block."""
    line = (
        _id_block("Response", request_id, trace_id, span_id, parent_span_id)
        + f" [Status] {status}"
    )
    if content_type:
        line += f" [ContentType {content_type}]"
    if body and content_type == "application/json":
        scrubbed = desensitize_body(body)
        if scrubbed is not None:
            line += f" [Body] {scrubbed}"
    return line


def emit_stream_logs(
    timestamp_ms: float,
    method: str,
    host: str,
    path: str,
    status: str,
    request_id: str = NO_ID,
    trace_id: str = NO_ID,
    span_id: str = NO_ID,
    parent_span_id: str = NO_ID,
    request_content_type: str = "",
    request_body: str = "",
    response_content_type: str = "",
    response_body: str = "",
) -> List[str]:
    """One HTTP stream -> the Request/Response line pair in the
    'time\\tpayload' shape the ingestion parser consumes
    (OnHttpStreamDone, main.go:52-63)."""
    stamp = (
        datetime.fromtimestamp(timestamp_ms / 1000, tz=timezone.utc)
        .isoformat(timespec="microseconds")
        .replace("+00:00", "Z")
    )
    request_line = format_request_log(
        method,
        host,
        path,
        request_id,
        trace_id,
        span_id,
        parent_span_id,
        request_content_type,
        request_body,
    )
    response_line = format_response_log(
        status,
        request_id,
        trace_id,
        span_id,
        parent_span_id,
        response_content_type,
        response_body,
    )
    return [f"{stamp}\t{request_line}", f"{stamp}\t{response_line}"]
