"""Envoy telemetry-filter equivalent: KMamiz log-line emission.

Equivalent of the reference's Go proxy-wasm plugin
(/root/reference/envoy/wasm/main.go): it logs a `[Request id/trace/span/
parent] [METHOD host/path] [ContentType ...] [Body ...]` line per request
and the `[Response ...] [Status] ...` twin on stream close, with JSON
bodies desensitized to type-preserving zero values before they ever leave
the pod (main.go:210-240).

In this framework the "filter" is a library: the simulator and tests use
it to synthesize istio-proxy container logs that the ingestion parser
(kmamiz_tpu.core.envoy) round-trips, and any sidecar-less deployment can
emit the same lines from process middleware. Note the WASM scrubber keeps
booleans/null as-is (main.go:216-225) while the simulator's body scrubber
zeroes them — both reference behaviors exist; this module follows the WASM
one.
"""
from __future__ import annotations

import json
from datetime import datetime, timezone
from typing import Any, List, Optional

NO_ID = "NO_ID"


def desensitize_value(value: Any) -> Any:
    """WASM parseObject semantics: strings -> "", numbers -> 0, booleans and
    null preserved; containers keep their shape (main.go:210-240)."""
    if isinstance(value, list):
        return [desensitize_value(v) for v in value]
    if isinstance(value, dict):
        return {k: desensitize_value(v) for k, v in value.items()}
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, str):
        return ""
    if isinstance(value, (int, float)):
        return 0
    return value


def desensitize_body(body: str) -> Optional[str]:
    """JSON body -> desensitized JSON string; None when it doesn't parse
    (the filter drops unparseable bodies, main.go:213-218)."""
    try:
        parsed = json.loads(body)
    except (json.JSONDecodeError, TypeError):
        return None
    return json.dumps(desensitize_value(parsed), separators=(", ", ": "))


def _id_block(kind: str, request_id: str, trace_id: str, span_id: str, parent_span_id: str) -> str:
    return f"[{kind} {request_id}/{trace_id}/{span_id}/{parent_span_id}]"


def format_request_log(
    method: str,
    host: str,
    path: str,
    request_id: str = NO_ID,
    trace_id: str = NO_ID,
    span_id: str = NO_ID,
    parent_span_id: str = NO_ID,
    content_type: str = "",
    body: str = "",
) -> str:
    """main.go:177-189 plus the body block appended on buffer end."""
    line = (
        _id_block("Request", request_id, trace_id, span_id, parent_span_id)
        + f" [{method} {host}{path}]"
    )
    if content_type:
        line += f" [ContentType {content_type}]"
    if body and content_type == "application/json":
        scrubbed = desensitize_body(body)
        if scrubbed is not None:
            line += f" [Body] {scrubbed}"
    return line


def format_response_log(
    status: str,
    request_id: str = NO_ID,
    trace_id: str = NO_ID,
    span_id: str = NO_ID,
    parent_span_id: str = NO_ID,
    content_type: str = "",
    body: str = "",
) -> str:
    """main.go:190-201 plus the body block."""
    line = (
        _id_block("Response", request_id, trace_id, span_id, parent_span_id)
        + f" [Status] {status}"
    )
    if content_type:
        line += f" [ContentType {content_type}]"
    if body and content_type == "application/json":
        scrubbed = desensitize_body(body)
        if scrubbed is not None:
            line += f" [Body] {scrubbed}"
    return line


def emit_stream_logs(
    timestamp_ms: float,
    method: str,
    host: str,
    path: str,
    status: str,
    request_id: str = NO_ID,
    trace_id: str = NO_ID,
    span_id: str = NO_ID,
    parent_span_id: str = NO_ID,
    request_content_type: str = "",
    request_body: str = "",
    response_content_type: str = "",
    response_body: str = "",
) -> List[str]:
    """One HTTP stream -> the Request/Response line pair in the
    'time\\tpayload' shape the ingestion parser consumes
    (OnHttpStreamDone, main.go:52-63)."""
    stamp = (
        datetime.fromtimestamp(timestamp_ms / 1000, tz=timezone.utc)
        .isoformat(timespec="microseconds")
        .replace("+00:00", "Z")
    )
    request_line = format_request_log(
        method,
        host,
        path,
        request_id,
        trace_id,
        span_id,
        parent_span_id,
        request_content_type,
        request_body,
    )
    response_line = format_response_log(
        status,
        request_id,
        trace_id,
        span_id,
        parent_span_id,
        response_content_type,
        response_body,
    )
    return [f"{stamp}\t{request_line}", f"{stamp}\t{response_line}"]
