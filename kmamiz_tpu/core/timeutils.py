"""UTC time-bucketing helpers.

Parity with /root/reference/src/utils/Utils.ts:113-141 (BelongsToDate/Hour/
MinuteTimestamp): floor an epoch-milliseconds timestamp to its containing
UTC day / hour / minute, returning epoch milliseconds.
"""
from __future__ import annotations

MS_PER_MINUTE = 60_000
MS_PER_HOUR = 3_600_000
MS_PER_DAY = 86_400_000


def belongs_to_minute_timestamp(timestamp_ms: float) -> int:
    return int(timestamp_ms // MS_PER_MINUTE) * MS_PER_MINUTE


def belongs_to_hour_timestamp(timestamp_ms: float) -> int:
    return int(timestamp_ms // MS_PER_HOUR) * MS_PER_HOUR


def belongs_to_date_timestamp(timestamp_ms: float) -> int:
    return int(timestamp_ms // MS_PER_DAY) * MS_PER_DAY


def to_precise(num: float) -> float:
    """Round to 14 decimal places (reference Utils.ToPrecise, Utils.ts:311)."""
    eps = 2.220446049250313e-16
    import math

    return math.floor((num + eps) * 1e14 + 0.5) / 1e14
