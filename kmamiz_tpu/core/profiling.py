"""Self-profiling: per-tick step timing + optional XLA profiler traces.

The reference's self-profiling is one uniqueId->start-time latency map
logged per realtime tick (ServiceOperator.ts:43,76-81) and debug-level
counts in the Rust DP (data_processor.rs:111-118). SURVEY.md §5 asks the
TPU build for real step timing plus `jax.profiler` traces; this module
provides both:

- `StepTimer` — named phase timings with running mean/max, cheap enough
  to wrap every DP tick; exposed via `summary()` for logs or the API.
- `trace()` — context manager that captures a TensorBoard-loadable XLA
  profile into KMAMIZ_PROFILE_DIR when set (no-op otherwise), so a
  production tick can be profiled by setting one env var.
"""
from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading
import time
from typing import Dict, Iterator, Optional

from kmamiz_tpu.telemetry.registry import REGISTRY

#: phase-duration histograms: same numbers as the /timings means, but
#: with buckets, so /metrics gets percentiles. One handle per phase
#: name, created on first use and cached (phase names are a small fixed
#: vocabulary — see docs/TICK_PIPELINE.md)
_PHASE_HIST = REGISTRY.histogram_family(
    "kmamiz_step_phase_ms", "DP step-timer phase wall time (ms)", ("phase",)
)


class StepTimer:
    """Running per-phase wall-time stats (count / mean / max, in ms)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, float]] = {}
        self._hists: Dict[str, object] = {}

    def _fold(self, name: str, elapsed_ms: float) -> None:
        with self._lock:
            entry = self._stats.setdefault(
                name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
            )
            entry["count"] += 1
            entry["total_ms"] += elapsed_ms
            entry["max_ms"] = max(entry["max_ms"], elapsed_ms)
            hist = self._hists.get(name)
            if hist is None:
                # first use of a phase name only; cached thereafter
                hist = _PHASE_HIST.handle(name)  # graftlint: disable=hot-path-metric-label -- first-use registration, cached in _hists thereafter
                self._hists[name] = hist
        hist.observe(elapsed_ms)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self._fold(name, (time.perf_counter() - start) * 1000)

    def record(self, name: str, elapsed_ms: float) -> None:
        """Fold an externally measured duration into the same stats shape
        as phase(): used where the region is already timed for its own
        accounting (device transfers) or runs on a worker thread whose
        wall time would double-count an enclosing phase."""
        self._fold(name, elapsed_ms)

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "count": entry["count"],
                    "mean_ms": entry["total_ms"] / max(entry["count"], 1),
                    "max_ms": entry["max_ms"],
                }
                for name, entry in self._stats.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


#: process-wide timer used by the DP tick; importable anywhere
step_timer = StepTimer()

logger = logging.getLogger("kmamiz_tpu.profiling")


@contextlib.contextmanager
def trace(label: str = "kmamiz") -> Iterator[None]:
    """Capture an XLA profiler trace when KMAMIZ_PROFILE_DIR is set.

    The trace directory is TensorBoard-loadable (`tensorboard --logdir`).
    Nested/overlapping traces are not supported by jax.profiler, so only
    the first concurrent caller captures; the rest proceed unprofiled.
    At most KMAMIZ_PROFILE_COUNT traces (default 8) are captured per
    process — the DP tick fires every few seconds forever, and an
    unbounded capture would fill the profile volume.
    """
    global _traces_left
    profile_dir = os.environ.get("KMAMIZ_PROFILE_DIR")
    if not profile_dir or _traces_left == 0:
        yield
        return
    if not _trace_guard.acquire(blocking=False):
        yield
        return
    try:
        if _traces_left < 0:  # first capture: read the cap once
            raw_cap = os.environ.get("KMAMIZ_PROFILE_COUNT", "8")
            try:
                _traces_left = max(int(raw_cap), 0)
            except ValueError:
                logger.warning(
                    "KMAMIZ_PROFILE_COUNT=%r is not an integer; using 8", raw_cap
                )
                _traces_left = 8
        if _traces_left == 0:  # re-check under the lock: a concurrent
            yield  # caller may have spent the last slot after our pre-check
            return
        _traces_left -= 1
        # a broken profiler (unwritable dir, plugin init failure) must never
        # break the DP tick it wraps: disable further captures and carry on
        capture = None
        try:
            import jax

            capture = jax.profiler.trace(
                os.path.join(profile_dir, label), create_perfetto_link=False
            )
            capture.__enter__()
        except Exception as err:
            capture = None
            _traces_left = 0
            logger.warning("profiler capture failed, disabling: %s", err)

        def close(exc_info):
            global _traces_left
            if capture is None:
                return
            try:
                capture.__exit__(*exc_info)
            except Exception as err:
                _traces_left = 0
                logger.warning(
                    "profiler capture teardown failed, disabling: %s", err
                )

        try:
            yield
        except BaseException:
            close(sys.exc_info())
            raise
        else:
            close((None, None, None))
    finally:
        _trace_guard.release()


_trace_guard = threading.Lock()
_traces_left = -1  # -1 = cap not yet read from the environment
