"""Persistent XLA compilation cache + boot-time program pre-warm.

The graph-union programs compile in the tens of seconds on a cold
process (BENCH_r04 graph_scale_merge_walls_ms recorded 50-70 s compile
walls per (window-bucket, store-capacity) shape over the dev tunnel).
Two policies keep that cost off the serving path (VERDICT r4 #5b):

- **persistent cache**: KMAMIZ_COMPILE_CACHE_DIR wires
  jax_compilation_cache_dir, so a production RESTART reloads every
  previously compiled program from disk instead of re-compiling — the
  capacity-doubling design already bounds the program set to
  ~log2(max_edges) union shapes per lifetime (graph/store.py).
- **boot pre-warm**: the boot prewarm plan (core/programs.py) replays
  the persisted shape hints — the exact (program, bucket) pairs the
  previous process compiled — before the first tick, so a restart never
  eats a compile wall while a request waits. On a cold cache it falls
  back to EndpointGraph.prewarm_compile's default merge buckets.

The persistent cache alone is NOT enough for a fast restart: reloading
a program from disk still pays the jit trace+lower on first dispatch
(multi-second for the union programs). The registry's dispatch-replay
prewarm exists precisely to move that residue off the serving path; the
hint file lives next to this cache (KMAMIZ_SHAPE_HINTS defaults into
KMAMIZ_COMPILE_CACHE_DIR).
"""
from __future__ import annotations

import logging
import os

logger = logging.getLogger("kmamiz_tpu.compile_cache")

_enabled = False


def enable_from_env() -> bool:
    """Point jax at a persistent compilation cache directory when
    KMAMIZ_COMPILE_CACHE_DIR is set. Idempotent; call before the first
    jit dispatch (app boot, DP-server main). Returns True when active."""
    global _enabled
    if _enabled:
        return True
    directory = os.environ.get("KMAMIZ_COMPILE_CACHE_DIR")
    if not directory:
        return False
    import jax

    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    # cache everything: the 50-70 s union compiles are the headline win,
    # but a first tick also runs a dozen sub-second kernels whose
    # compiles SUM to seconds — with the default 1 s floor they would
    # re-compile on every restart
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _enabled = True
    logger.info("persistent XLA compilation cache at %s", directory)
    return True
