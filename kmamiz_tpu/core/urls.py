"""URL parsing utilities.

Behavioral parity with the reference's URL handling
(/root/reference/src/utils/Utils.ts:83-106, 242-273 and
/root/reference/kmamiz_data_processor/src/http_client/url_matcher.rs):
`explode_url` splits any URL into (host, port, path) and, for Kubernetes
service URLs, additionally (service, namespace, cluster).
"""
from __future__ import annotations

import math
import re
from functools import lru_cache
from typing import List, NamedTuple, Optional

_SCHEME_RE = re.compile(r"[a-z]+://")
_HOST_RE = re.compile(r"://([^:/]*)([:0-9]*)(.*)", re.S)
#: the dot before `svc` is deliberately UNESCAPED — the reference's
#: /(.*).svc[\.]*(.*)/ (Utils.ts:90; url_matcher.rs:9) matches ANY
#: character there, so a host like "books-svc:8080" parses the same way
#: it does upstream (review r5: escaping it diverged the service naming
#: for hosts containing "svc" without a literal dot)
_SVC_RE = re.compile(r"(.*).svc[\.]*(.*)")


class ExplodedUrl(NamedTuple):
    host: str
    port: str
    path: str
    service: Optional[str] = None
    namespace: Optional[str] = None
    cluster: Optional[str] = None


@lru_cache(maxsize=4096)
def explode_url(url: str, is_service_url: bool = False) -> ExplodedUrl:
    """Split a URL into meaningful parts.

    Returns (host, port, path[, service, namespace, cluster]); the port keeps
    its leading ':' to match the reference's output shape. Cached: a window
    of spans repeats a small set of URLs thousands of times, and the result
    is an immutable tuple of strings.
    """
    if _SCHEME_RE.search(url) is None:
        url = "://" + url
    m = _HOST_RE.search(url)
    host, port, path = (m.group(1), m.group(2), m.group(3)) if m else ("", "", "")
    if not is_service_url:
        return ExplodedUrl(host, port, path)

    service = namespace = cluster = None
    svc_match = _SVC_RE.match(host)
    if svc_match:
        service_full, cluster_part = svc_match.group(1), svc_match.group(2)
        divider = service_full.rfind(".")
        service = service_full[:divider]
        namespace = service_full[divider + 1:]
        cluster = cluster_part or "cluster.local"
    return ExplodedUrl(host, port, path, service, namespace, cluster)


_PARAM_SPLIT_RE = re.compile(r"([?&][^?&]*)")
_PARAM_KV_RE = re.compile(r"[?&]([^=]*)=([^?&]*)")


_FLOAT_PREFIX_RE = re.compile(r"^[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?")


def _is_finite_number(s: str) -> bool:
    # parseFloat semantics: a leading numeric prefix counts ("12abc" -> 12)
    m = _FLOAT_PREFIX_RE.match(s.strip())
    if not m:
        return False
    try:
        return math.isfinite(float(m.group(0)))
    except ValueError:
        return False


def get_params_from_url(url: str) -> Optional[List[dict]]:
    """Extract GET parameters as [{"param", "type"}] pairs, None if absent."""
    chunks = _PARAM_SPLIT_RE.findall(url)
    if not chunks:
        return None
    pairs = []
    for chunk in chunks:
        kv = _PARAM_KV_RE.match(chunk)
        if kv:
            pairs.append(
                {
                    "param": kv.group(1),
                    "type": "number" if _is_finite_number(kv.group(2)) else "string",
                }
            )
    return unique_params(pairs)


def unique_params(parameters: List[dict]) -> List[dict]:
    """De-duplicate GET parameters; conflicting types degrade to string."""
    merged: dict = {}
    for p in parameters:
        param, ptype = p["param"], p["type"]
        if param in merged and merged[param]["type"] != ptype:
            ptype = "string"
        merged[param] = {"param": merged.get(param, p)["param"], "type": ptype}
    return list(merged.values())
