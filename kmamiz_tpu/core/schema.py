"""JSON body schema inference and interface-string utilities.

Behavioral parity with the reference's schema tooling:
- ObjectToInterfaceString / json-to-ts emission
  (/root/reference/src/utils/Utils.ts:14-75; the Rust twin is
  /root/reference/kmamiz_data_processor/src/json_utils.rs:35-108)
- interface field extraction + cosine similarity (Utils.ts:150-177)
- JSON merging with array limit (Utils.ts:279-309)
- OpenAPI type mapping (Utils.ts:207-235)

The emitted "TypeScript interface" strings are a wire format consumed by the
frontend and by the cohesion (SIDC) scorer, so the exact text matters:
sorted keys, shared-subtype dedup, singularized array item names, and
`field?: any;` for nulls all mirror the reference.
"""
from __future__ import annotations

import json
import math
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple


def is_primitive(obj: Any) -> bool:
    return not isinstance(obj, (dict, list))


def js_typeof(value: Any) -> str:
    if isinstance(value, bool):
        return "boolean"
    if isinstance(value, (int, float)):
        return "number"
    if isinstance(value, str):
        return "string"
    if value is None:
        return "object"  # JS: typeof null === "object"
    return "object"


def sort_object(obj: Any) -> Any:
    """Recursively sort object keys (reference Utils.sortObject)."""
    if isinstance(obj, list):
        if all(is_primitive(o) for o in obj):
            return obj
        return [sort_object(o) for o in obj if not is_primitive(o)]
    if not isinstance(obj, dict):
        return obj
    out: Dict[str, Any] = {}
    for k in sorted(obj.keys()):
        o = obj[k]
        if isinstance(o, list):
            if o and all(isinstance(i, dict) for i in o):
                o = [sort_object(i) for i in o]
        elif isinstance(o, dict):
            o = sort_object(o)
        out[k] = o
    return out


def _singular(word: str) -> str:
    """Naive singularization matching common json-to-ts outputs."""
    if word.endswith("ies") and len(word) > 3:
        return word[:-3] + "y"
    if word.endswith("ses") and len(word) > 3:
        return word[:-2]
    if word.endswith("s") and not word.endswith("ss") and len(word) > 1:
        return word[:-1]
    return word


def _capitalize(word: str) -> str:
    return word[:1].upper() + word[1:] if word else word


class _InterfaceEmitter:
    """Emits json-to-ts-style interface declarations with subtype dedup."""

    def __init__(self) -> None:
        self._sig_to_name: Dict[Tuple, str] = {}
        self._used_names: Set[str] = set()
        self._out: List[Tuple[str, List[str]]] = []

    def render(self) -> str:
        decls = []
        for name, lines in self._out:
            if lines:
                decls.append(f"interface {name} {{\n" + "\n".join(lines) + "\n}")
            else:
                decls.append(f"interface {name} {{\n}}")
        return "\n".join(decls)

    # -- structural signatures (for shared-subtype dedup) --

    def _merge_fields(
        self, samples: Sequence[dict]
    ) -> List[Tuple[str, List[Any], bool]]:
        keys: List[str] = []
        seen: Set[str] = set()
        for s in samples:
            for k in s.keys():
                if k not in seen:
                    seen.add(k)
                    keys.append(k)
        fields = []
        for k in keys:
            present = [s[k] for s in samples if k in s]
            optional = len(present) < len(samples) or any(v is None for v in present)
            values = [v for v in present if v is not None]
            fields.append((k, values, optional))
        return fields

    def _value_sig(self, values: List[Any]) -> Tuple:
        if not values:
            return ("any",)
        if all(isinstance(v, dict) for v in values):
            return ("obj", self._shape_sig(values))
        if all(isinstance(v, list) for v in values):
            items = [i for v in values for i in v]
            if not items:
                return ("arr", ("any",))
            if all(is_primitive(i) for i in items):
                types = {js_typeof(i) for i in items if i is not None}
                return ("arr", (types.pop(),) if len(types) == 1 else ("any",))
            if all(isinstance(i, dict) for i in items):
                return ("arr", ("obj", self._shape_sig(items)))
            return ("arr", ("any",))
        if all(is_primitive(v) for v in values):
            types = {js_typeof(v) for v in values}
            return (types.pop(),) if len(types) == 1 else ("any",)
        return ("any",)

    def _shape_sig(self, samples: Sequence[dict]) -> Tuple:
        return tuple(
            (k, optional, self._value_sig(values))
            for k, values, optional in self._merge_fields(samples)
        )

    # -- emission --

    def _unique_name(self, hint: str) -> str:
        name = _capitalize(hint) or "Root"
        if name not in self._used_names:
            self._used_names.add(name)
            return name
        i = 2
        while f"{name}{i}" in self._used_names:
            i += 1
        self._used_names.add(f"{name}{i}")
        return f"{name}{i}"

    def process_shape(self, name_hint: str, samples: Sequence[dict]) -> str:
        # a top-level array can mix dicts with nested arrays; only dict
        # samples contribute fields (non-dicts would crash the key walk)
        samples = [s for s in samples if isinstance(s, dict)]
        sig = self._shape_sig(samples)
        existing = self._sig_to_name.get(sig)
        if existing is not None:
            return existing
        name = self._unique_name(name_hint)
        self._sig_to_name[sig] = name
        lines: List[str] = []
        self._out.append((name, lines))
        for key, values, optional in self._merge_fields(samples):
            rendered = self._render_type(key, values)
            q = "?" if optional else ""
            lines.append(f"  {key}{q}: {rendered};")
        return name

    def _render_type(self, key: str, values: List[Any]) -> str:
        if not values:
            return "any"
        if all(isinstance(v, dict) for v in values):
            return self.process_shape(key, values)
        if all(isinstance(v, list) for v in values):
            items = [i for v in values for i in v]
            if not items:
                return "any[]"
            if all(is_primitive(i) for i in items):
                types = {js_typeof(i) for i in items if i is not None}
                return (types.pop() if len(types) == 1 else "any") + "[]"
            if all(isinstance(i, dict) for i in items):
                return self.process_shape(_singular(key), items) + "[]"
            return "any[]"
        if all(is_primitive(v) for v in values):
            types = {js_typeof(v) for v in values}
            return types.pop() if len(types) == 1 else "any"
        return "any"


def json_to_ts(obj: Any, root_name: str = "Root") -> str:
    """Render an object (or list of objects) as interface declarations."""
    emitter = _InterfaceEmitter()
    samples = obj if isinstance(obj, list) else [obj]
    emitter.process_shape(root_name, samples)
    return emitter.render()


def _primitive_interface(obj: Any) -> Optional[str]:
    if not isinstance(obj, list):
        return None
    primitive_types = [js_typeof(o) for o in obj if is_primitive(o)]
    if not primitive_types:
        return None
    uniq = list(dict.fromkeys(primitive_types))
    return "[\n" + ",\n".join(f"  {t}" for t in uniq) + "\n]"


def object_to_interface_string(obj: Any, name: str = "Root") -> str:
    """Craft a TypeScript interface string from an object (Utils.ts:14-36)."""
    if is_primitive(obj):
        return js_typeof(obj)
    sorted_obj = sort_object(obj)
    if isinstance(sorted_obj, list):
        array_type = "Array<any>{}"
        appending = ""
        if len(obj) > 0:
            if is_primitive(obj[0]):
                array_type = f"Array<{js_typeof(obj[0])}>{{}}"
            else:
                array_type = "Array<ArrayItem>{}\n"
                appending = json_to_ts(sorted_obj, root_name="ArrayItem")
        return f"interface {name} extends {array_type}{appending}"
    primitive_part = _primitive_interface(obj)
    obj_part = json_to_ts(sorted_obj, root_name=name) if isinstance(sorted_obj, dict) else None
    return (obj_part or "") + (primitive_part or "")


# ---------------------------------------------------------------------------
# interface field extraction + cosine similarity (Utils.ts:150-177)
# ---------------------------------------------------------------------------

_FIELD_LINE_RE = re.compile(r"^[ ]+([^{}\n])*", re.M)
_EXTENDS_RE = re.compile(r"extends (Array<[^>]*>)")


def match_interface_field_and_trim(interface_str: str) -> Set[str]:
    fields = set()
    for m in _FIELD_LINE_RE.finditer(interface_str):
        fields.add(m.group(0).strip())
    for m in _EXTENDS_RE.finditer(interface_str):
        fields.add(m.group(0).strip())
    return fields


def create_standard_vector(base: Sequence[str], vector: Set[str]) -> List[float]:
    v = [1.0 if b in vector else 0.0 for b in base]
    mag = math.sqrt(sum(x * x for x in v))
    return [x / mag if mag else 0.0 for x in v]


def cos_sim(vector_a: Sequence[float], vector_b: Sequence[float]) -> float:
    return sum(a * b for a, b in zip(vector_a, vector_b))


def interface_cosine_similarity(interface_a: str, interface_b: str) -> float:
    set_a = match_interface_field_and_trim(interface_a)
    set_b = match_interface_field_and_trim(interface_b)
    base = sorted(set_a | set_b)
    return cos_sim(
        create_standard_vector(base, set_a), create_standard_vector(base, set_b)
    )


# ---------------------------------------------------------------------------
# JSON merging (Utils.ts:279-309)
# ---------------------------------------------------------------------------


def js_str(value: Any) -> str:
    """JS template-literal coercion: undefined -> 'undefined', booleans to
    lowercase; used where the reference embeds possibly-missing values in
    tab-joined keys."""
    if value is None:
        return "undefined"
    if value is True:
        return "true"
    if value is False:
        return "false"
    return str(value)


def js_truthy(value: Any) -> bool:
    if value is None or value is False:
        return False
    if isinstance(value, bool):
        return value
    if isinstance(value, (int, float)):
        return value != 0 and value == value  # 0 and NaN are falsy
    if isinstance(value, str):
        return value != ""
    return True  # {}, [] are truthy in JS


def merge(a: Any, b: Any) -> Any:
    if isinstance(a, list) and isinstance(b, list):
        return merge_array(a, b)
    if not isinstance(a, list) and not isinstance(b, list):
        return merge_object(a, b)
    return a if js_truthy(a) else b


def _spread(value: Any) -> dict:
    """JS object-spread semantics: dicts spread their entries, ARRAYS
    their index-keyed elements ({...[x]} === {"0": x} — array-bodied
    JSON samples must reach the interface inference, review r5),
    strings their indexed characters, everything else (null/number/
    bool) spreads to nothing."""
    if isinstance(value, dict):
        return value
    if isinstance(value, (list, tuple)):
        return {str(i): v for i, v in enumerate(value)}
    if isinstance(value, str):
        return {str(i): c for i, c in enumerate(value)}
    return {}


def merge_object(a: Any, b: Any) -> Any:
    return {**_spread(a), **_spread(b)}


def merge_array(a: List[Any], b: List[Any], limit: int = 10) -> List[Any]:
    return a[:limit] + b[:limit]


_UNPARSED = object()  # JS `undefined` (distinct from parsed JSON null)


def merge_string_body(a: Optional[str], b: Optional[str]) -> Optional[str]:
    if a and b:
        parsed_a = parsed_b = _UNPARSED
        try:
            parsed_a = json.loads(a)
        except (json.JSONDecodeError, TypeError):
            pass
        try:
            parsed_b = json.loads(b)
        except (json.JSONDecodeError, TypeError):
            pass
        a_truthy = parsed_a is not _UNPARSED and js_truthy(parsed_a)
        b_truthy = parsed_b is not _UNPARSED and js_truthy(parsed_b)
        if a_truthy and b_truthy:
            return json_stringify(merge(parsed_a, parsed_b))
        chosen = parsed_a if a_truthy else parsed_b
        if chosen is _UNPARSED:
            return None  # JS: JSON.stringify(undefined) -> undefined
        return json_stringify(chosen)
    return a or b


def json_stringify(obj: Any) -> str:
    """JSON.stringify-compatible serialization (compact separators)."""
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False)


def fold_string_bodies(bodies: Sequence[Optional[str]]) -> Optional[str]:
    """Left-fold merge_string_body over a group's bodies (the per-group loop
    in RealtimeDataList.toCombinedRealtimeData)."""
    if not bodies:
        return None
    acc = bodies[0]
    for body in bodies[1:]:
        acc = merge_string_body(acc, body)
    return acc


def _parse_and_infer(
    merged: Optional[str],
    content_type: Optional[str],
    precomputed_interface: Optional[str] = None,
) -> Tuple[Optional[Any], Optional[str]]:
    """json.loads the merged body and infer its interface when the content
    type is JSON (one side of parse_request_response_body)."""
    if content_type != "application/json":
        return None, None
    try:
        body = json.loads(merged)
    except (json.JSONDecodeError, TypeError):
        return None, None
    interface = (
        precomputed_interface
        if precomputed_interface is not None
        else object_to_interface_string(body)
    )
    return body, interface


def body_pairs_for_groups(
    row_groups: Sequence[Sequence[dict]],
) -> List[Tuple[List[Optional[str]], Optional[str]]]:
    """Build the (bodies, content_type) pairs merge_and_infer_bodies expects
    from per-(endpoint, status) row groups: two pairs per group, request at
    2*i and response at 2*i+1 (the convention both the realtime combine and
    the DataProcessor assembly rely on)."""
    pairs: List[Tuple[List[Optional[str]], Optional[str]]] = []
    for rows in row_groups:
        pairs.append(
            (
                [r.get("requestBody") for r in rows],
                rows[0].get("requestContentType"),
            )
        )
        pairs.append(
            (
                [r.get("responseBody") for r in rows],
                rows[0].get("responseContentType"),
            )
        )
    return pairs


def merge_and_infer_bodies(
    pairs: Sequence[Tuple[Sequence[Optional[str]], Optional[str]]],
) -> List[Tuple[Optional[Any], Optional[str]]]:
    """Batched body pipeline: for each (bodies, content_type) pair, fold the
    group's raw JSON bodies with merge_string_body and, for JSON content,
    return (parsed_merged_body, interface_string).

    Runs on the native C++ path (native/kmamiz_json.cpp — the Rust
    json_utils.rs twin) when available, falling back per group or wholesale
    to the pure-Python implementations above.
    """
    from kmamiz_tpu import native

    results = native.process_body_groups(
        [(bodies, ct == "application/json") for bodies, ct in pairs]
    )
    out: List[Tuple[Optional[Any], Optional[str]]] = []
    if results is None or len(results) != len(pairs):
        for bodies, ct in pairs:
            out.append(_parse_and_infer(fold_string_bodies(bodies), ct))
        return out
    for (bodies, ct), res in zip(pairs, results):
        if res is None:  # native delegated this group (deep nesting)
            out.append(_parse_and_infer(fold_string_bodies(bodies), ct))
            continue
        merged, interface, needs_python = res
        out.append(
            _parse_and_infer(merged, ct, None if needs_python else interface)
        )
    return out


# ---------------------------------------------------------------------------
# OpenAPI type mapping (Utils.ts:207-235)
# ---------------------------------------------------------------------------


def map_object_to_openapi_types(o: Any) -> dict:
    if isinstance(o, list):
        item_types = None
        if len(o) > 0:
            if is_primitive(o[0]):
                item_types = {"type": js_typeof(o[0])}
            else:
                combined: Any = {}
                for item in o:
                    combined = merge(combined, item)
                item_types = map_object_to_openapi_types(combined)
        result = {"type": "array", "items": item_types or {"type": "object"}}
        if item_types is None:
            result["example"] = []
        return result
    if not js_truthy(o):
        return {"type": "object", "nullable": True}
    if not isinstance(o, dict):
        return {"type": "object", "properties": {}}
    properties: Dict[str, Any] = {}
    for k, v in o.items():
        if isinstance(v, (dict, list)) or v is None:
            # typeof null === "object": nulls recurse to a nullable object
            properties[k] = map_object_to_openapi_types(v)
        else:
            properties[k] = {"type": js_typeof(v)}
    return {"type": "object", "properties": properties}
