"""Central registry of hot jitted programs: telemetry, shape hints, prewarm.

Every jitted entry point that can sit on the serving path registers here
(graph merges, the packed ancestor walk, window stats, the scorers, the
stacked GraphSAGE epoch block, the forecast forward). Registration wraps
the jitted callable in a :class:`Program` proxy that

- counts per-program compiles and compile milliseconds (a dispatch whose
  jit cache grew paid a trace/lower/compile wall — the /health/timings
  ``programs`` section exposes the counters, and a steady-state tick
  after warm-up must add 0);
- records the exact argument *spec* (shapes + dtypes + static values) of
  every newly compiled entry as a **shape hint**, persisted next to the
  persistent XLA cache (core.compile_cache), so a restarted process can
  prewarm exactly the (program, bucket) pairs production traffic
  exercised;
- replays those specs at boot with zero-filled arguments
  (:meth:`Program.prewarm_spec`). A replayed dispatch populates the jit
  *dispatch* cache — unlike ``fn.lower(...).compile()``, which AOT-fills
  only the persistent XLA cache and still leaves the first live call a
  multi-second trace+lower wall (measured on jax 0.4.37: lower+compile
  leaves ``_cache_size()`` at 0; the first call re-traces).

Boot flow (dp_server.main / api.app): ``start_background_prewarm()``
runs the plan on a daemon thread; ``warm_state()`` drives the /health
readiness gate (503 + status "WARMING" until done, see
api/handlers/health.py and deploy/kmamiz-tpu.yaml's readinessProbe).

Env:
- ``KMAMIZ_SHAPE_HINTS``: hint-file path (default
  ``$KMAMIZ_COMPILE_CACHE_DIR/shape_hints.json``; hints are disabled
  when neither is set).
- ``KMAMIZ_PREWARM``: "0" disables boot prewarm, "sync" blocks boot on
  it, anything else (default "1") prewarms on a background thread.
- ``KMAMIZ_PREWARM_READY_GATE``: "0" keeps /health answering 200 while
  warming (gate off); default "1" answers 503.
"""
from __future__ import annotations

import importlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("kmamiz_tpu.programs")

# graftprof compile-cause hook: every real compile (cache-entry growth)
# lands in the device-attribution log with its program name and wall
# cost. Guarded — the registry must keep working under a partial
# telemetry install (core is importable before/without telemetry).
try:
    from kmamiz_tpu.telemetry.profiling import device_attr as _prof_device_attr
except Exception:  # noqa: BLE001 - profiling is optional at this layer
    _prof_device_attr = None

_MAX_HINTS_PER_PROGRAM = 16

_registry_lock = threading.Lock()
_REGISTRY: Dict[str, "Program"] = {}
#: family base name -> resolver(key) -> Program; dynamic programs
#: (per-model jits built by lru_cache factories) register instances
#: under "base[key]" and a resolver so a restart can rebuild them from
#: a persisted hint before any live call exists.
_FAMILIES: Dict[str, Callable[[str], Optional["Program"]]] = {}


class UnencodableSpec(ValueError):
    """Argument not expressible as a shape hint (opaque object leaf)."""


# ---------------------------------------------------------------------------
# argument-spec encode/decode
#
# A spec is the JSON-able skeleton of one dispatch's (args, kwargs):
# array leaves become {"__arr__": [shape, dtype, weak]}, tuples and
# namedtuples keep their container identity (the jit cache keys on the
# pytree structure, so a tuple→list roundtrip would miss the cache),
# and plain Python scalars stay literal — replaying a literal through
# the jit boundary reproduces the live call's weak-type/static-arg
# cache key exactly.
# ---------------------------------------------------------------------------


def _encode(x: Any) -> Any:
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return {
            "__arr__": [
                [int(d) for d in x.shape],
                str(x.dtype),
                bool(getattr(x, "weak_type", False)),
            ]
        }
    if isinstance(x, tuple):
        fields = getattr(x, "_fields", None)
        if fields is not None:  # namedtuple: keep the class for the pytree
            cls = type(x)
            return {
                "__nt__": [cls.__module__, cls.__qualname__],
                "items": [_encode(v) for v in x],
            }
        return {"__tuple__": [_encode(v) for v in x]}
    if isinstance(x, list):
        return [_encode(v) for v in x]
    if isinstance(x, dict):
        if not all(isinstance(k, str) for k in x):
            raise UnencodableSpec(f"non-string dict keys: {list(x)[:3]}")
        return {str(k): _encode(v) for k, v in x.items()}
    raise UnencodableSpec(f"opaque leaf {type(x).__name__}")


def _resolve_qualname(module: str, qualname: str):
    obj = importlib.import_module(module)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _decode_zeros(x: Any) -> Any:
    """Spec -> concrete zero-filled arguments for a prewarm dispatch."""
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, list):
        return [_decode_zeros(v) for v in x]
    if isinstance(x, dict):
        if "__arr__" in x:
            shape, dtype, weak = x["__arr__"]
            if weak and not shape:
                # weak-typed scalar: replay as the Python literal that
                # produced it, so the cache key matches the live call
                kind = str(dtype)
                if kind.startswith("bool"):
                    return False
                if kind.startswith(("int", "uint")):
                    return 0
                return 0.0
            import jax.numpy as jnp

            return jnp.zeros(tuple(shape), dtype=str(dtype))
        if "__tuple__" in x:
            return tuple(_decode_zeros(v) for v in x["__tuple__"])
        if "__nt__" in x:
            cls = _resolve_qualname(*x["__nt__"])
            return cls(*[_decode_zeros(v) for v in x["items"]])
        return {k: _decode_zeros(v) for k, v in x.items()}
    raise UnencodableSpec(f"bad spec node {type(x).__name__}")


def _bucket_label(spec: Any) -> str:
    """Compact human-readable bucket descriptor for telemetry tables:
    array shapes and static scalars, pytree internals elided."""
    args, kwargs = spec

    def leaf(x):
        if isinstance(x, dict):
            if "__arr__" in x:
                shape, dtype, _ = x["__arr__"]
                return "x".join(str(d) for d in shape) or "scalar"
            return "tree"
        if isinstance(x, (list,)):
            return "tree"
        return repr(x)

    parts = [leaf(a) for a in args]
    parts += [f"{k}={leaf(v)}" for k, v in sorted(kwargs.items())]
    return "(" + ",".join(parts) + ")"


# ---------------------------------------------------------------------------
# Program proxy
# ---------------------------------------------------------------------------


class Program:
    """Instrumented wrapper around one jitted callable.

    Transparent for callers: ``__call__`` delegates, and jit attributes
    (``lower``, ``_cache_size`` — bench.py reads it) pass through via
    ``__getattr__``. Telemetry costs two ``_cache_size()`` reads and one
    timer per dispatch.
    """

    def __init__(self, name: str, fn: Callable) -> None:
        self.name = name
        self.fn = fn
        self._lock = threading.Lock()
        self.calls = 0
        self.compiles = 0
        self.compile_ms = 0.0
        self.last_compile_ms = 0.0
        self.prewarmed = 0
        self.prewarm_ms = 0.0
        self.run_ewma_ms = 0.0  # warm-dispatch wall EWMA (graftcost label)
        self._specs: Dict[str, Any] = {}  # canonical json -> spec
        # canonical json -> (spec, compile_ms, run_ms): the cost-model
        # training labels (run_ms 0.0 until a warm call lands)
        self._labels: Dict[str, Tuple[Any, float, float]] = {}
        self._suppress_record = False

    # -- delegation ---------------------------------------------------------
    def __call__(self, *args, **kwargs):
        before = self._cache_entries()
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        grew = 0
        if before is not None:
            after = self._cache_entries()
            if after is not None and after > before:
                grew = after - before
        with self._lock:
            self.calls += 1
            if grew:
                self.compiles += grew
                self.compile_ms += elapsed_ms
                self.last_compile_ms = elapsed_ms
            elif before is not None:
                # warm dispatch: the per-program run-cost label the
                # graftcost regressor trains its run-ms head on
                self.run_ewma_ms = (
                    elapsed_ms
                    if self.run_ewma_ms == 0.0
                    else 0.8 * self.run_ewma_ms + 0.2 * elapsed_ms
                )
        if grew:
            if _prof_device_attr is not None:
                _prof_device_attr.note_compile(self.name, grew, elapsed_ms)
            if not self._suppress_record:
                self._record_spec(args, kwargs, compile_ms=elapsed_ms)
        return out

    def __getattr__(self, item):
        return getattr(self.fn, item)

    def _cache_entries(self) -> Optional[int]:
        try:
            return int(self.fn._cache_size())
        except Exception:  # noqa: BLE001 - non-jit callables track calls only
            return None

    # -- shape hints --------------------------------------------------------
    def _record_spec(self, args, kwargs, compile_ms: float = 0.0) -> None:
        try:
            import jax

            if not jax.core.trace_state_clean():
                return  # inner-jit retrace: not a top-level dispatch shape
        except Exception:  # noqa: BLE001 - private API moved: record anyway
            pass
        try:
            spec = (
                [_encode(a) for a in args],
                {k: _encode(v) for k, v in sorted(kwargs.items())},
            )
        except UnencodableSpec:
            return
        key = json.dumps(spec, sort_keys=True)
        with self._lock:
            if compile_ms > 0.0 and (
                key in self._labels or len(self._labels) < _MAX_HINTS_PER_PROGRAM
            ):
                # keep the max observed wall per bucket: a cache-evicted
                # recompile of a known spec still paid the full trace
                prev = self._labels.get(key)
                if prev is None or compile_ms > prev[1]:
                    self._labels[key] = (spec, compile_ms, 0.0)
            if key in self._specs:
                return
            if len(self._specs) >= _MAX_HINTS_PER_PROGRAM:
                return
            self._specs[key] = spec
        _autosave_hints()

    def specs(self) -> List[Any]:
        with self._lock:
            return list(self._specs.values())

    def adopt_specs(self, specs: List[Any]) -> None:
        """Merge persisted hint specs (restart path) without re-saving."""
        with self._lock:
            for spec in specs:
                key = json.dumps(spec, sort_keys=True)
                if (
                    key not in self._specs
                    and len(self._specs) < _MAX_HINTS_PER_PROGRAM
                ):
                    self._specs[key] = spec

    # -- cost labels (graftcost training rows) ------------------------------
    def labels(self) -> List[Tuple[Any, float, float]]:
        """(spec, compile_ms, run_ms) rows observed by this process plus
        adopted history. A live row whose warm wall hasn't landed yet
        borrows the program-level run EWMA."""
        with self._lock:
            ewma = self.run_ewma_ms
            return [
                (spec, compile_ms, run_ms if run_ms > 0.0 else ewma)
                for spec, compile_ms, run_ms in self._labels.values()
            ]

    def adopt_labels(self, labelled: List[Tuple[Any, float, float]]) -> None:
        """Merge persisted label rows (restart path): live observations
        of the same bucket win."""
        with self._lock:
            for spec, compile_ms, run_ms in labelled:
                key = json.dumps(spec, sort_keys=True)
                if (
                    key not in self._labels
                    and len(self._labels) < _MAX_HINTS_PER_PROGRAM
                ):
                    self._labels[key] = (
                        spec,
                        float(compile_ms),
                        float(run_ms),
                    )

    # -- prewarm ------------------------------------------------------------
    def prewarm_spec(self, spec: Any) -> bool:
        """Dispatch this program once with zero-filled arguments matching
        ``spec``, so the jit dispatch cache (and the persistent XLA
        cache) hold the program before live traffic arrives. Pure
        kernels only — outputs are discarded."""
        try:
            args, kwargs = spec
            concrete_args = [_decode_zeros(a) for a in args]
            concrete_kwargs = {k: _decode_zeros(v) for k, v in kwargs.items()}
        except Exception as e:  # noqa: BLE001 - stale/foreign hint
            logger.warning("%s: undecodable hint (%s)", self.name, e)
            return False
        t0 = time.perf_counter()  # graftlint: disable=hot-path-clock -- boot-time prewarm accounting, off the tick
        self._suppress_record = True
        try:
            import jax

            out = self(*concrete_args, **concrete_kwargs)
            # graftlint: disable=host-sync-in-hot-path -- prewarm deliberately blocks at boot, off the tick
            jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001 - a bad hint must not kill boot
            logger.warning("%s: prewarm failed (%s)", self.name, e)
            return False
        finally:
            self._suppress_record = False
        with self._lock:
            self.prewarmed += 1
            self.prewarm_ms += (time.perf_counter() - t0) * 1000.0  # graftlint: disable=hot-path-clock -- boot-time prewarm accounting, off the tick
        self.adopt_specs([spec])
        return True

    # -- reporting ----------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "calls": self.calls,
                "compiles": self.compiles,
                "compileMs": round(self.compile_ms, 1),
                "lastCompileMs": round(self.last_compile_ms, 1),
                "prewarmed": self.prewarmed,
                "prewarmMs": round(self.prewarm_ms, 1),
                "runEwmaMs": round(self.run_ewma_ms, 3),
                "cacheSize": self._cache_entries(),
                "buckets": [_bucket_label(s) for s in self._specs.values()],
            }


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------


def register(name: str, fn: Optional[Callable] = None):
    """Register a jitted callable under ``name``; usable as a decorator::

        @programs.register("graph.merge_edges")
        @jax.jit
        def _merge_edges(...): ...
    """
    def _wrap(f: Callable) -> Program:
        with _registry_lock:
            existing = _REGISTRY.get(name)
            if existing is not None and existing.fn is f:
                return existing
            prog = Program(name, f)
            _REGISTRY[name] = prog
            return prog

    return _wrap if fn is None else _wrap(fn)


def register_instance(base: str, key: str, fn: Callable) -> Program:
    """Register a dynamically created jit (one per model/config) under
    ``base[key]``. Idempotent per (name, fn)."""
    return register(f"{base}[{key}]", fn)


def register_family(base: str, resolver: Callable[[str], Optional[Program]]):
    """Install a resolver that can rebuild ``base[key]`` instances from a
    persisted hint at boot (before any live call constructs them)."""
    with _registry_lock:
        _FAMILIES[base] = resolver


def get(name: str) -> Optional[Program]:
    with _registry_lock:
        prog = _REGISTRY.get(name)
    if prog is not None:
        return prog
    if name.endswith("]") and "[" in name:
        base, key = name[:-1].split("[", 1)
        with _registry_lock:
            resolver = _FAMILIES.get(base)
        if resolver is not None:
            try:
                return resolver(key)
            except Exception as e:  # noqa: BLE001 - unresolvable hint
                logger.warning("cannot rebuild %s: %s", name, e)
    return None


def all_programs() -> Dict[str, Program]:
    with _registry_lock:
        return dict(_REGISTRY)


def _ensure_registered() -> None:
    """Import every module that registers hot programs, so summaries,
    hints, and the prewarm plan see the full registry regardless of
    which subsystem the process booted first."""
    for mod in (
        "kmamiz_tpu.graph.store",
        "kmamiz_tpu.ops.window",
        "kmamiz_tpu.ops.scorers",
        "kmamiz_tpu.server.processor",
        "kmamiz_tpu.models.serving",
        "kmamiz_tpu.models.stacked",
        "kmamiz_tpu.models.stlgt.trainer",
        "kmamiz_tpu.models.stlgt.serving",
        "kmamiz_tpu.cost.model",
    ):
        try:
            importlib.import_module(mod)
        except Exception as e:  # noqa: BLE001 - optional dep gated elsewhere
            logger.debug("registry import %s failed: %s", mod, e)


# ---------------------------------------------------------------------------
# telemetry summaries
# ---------------------------------------------------------------------------


def summary() -> dict:
    """Per-program counters for /health/timings and the warm-boot probe."""
    progs = {name: p.stats() for name, p in sorted(all_programs().items())}
    return {
        "programs": progs,
        "totalCompiles": sum(p["compiles"] for p in progs.values()),
        "totalCompileMs": round(
            sum(p["compileMs"] for p in progs.values()), 1
        ),
        "warm": warm_state(),
    }


def _scrape_programs() -> None:
    """Scrape-time mirror of the per-program counters into the telemetry
    registry — /metrics pulls the same `stats()` numbers /timings shows,
    with zero hot-path writes (the registry callback runs at render
    only)."""
    from kmamiz_tpu.telemetry.registry import REGISTRY

    calls = REGISTRY.gauge_family(
        "kmamiz_program_calls_total", "Registered-program dispatches", ("program",)
    )
    compiles = REGISTRY.gauge_family(
        "kmamiz_program_compiles_total", "Registered-program XLA compiles", ("program",)
    )
    compile_ms = REGISTRY.gauge_family(
        "kmamiz_program_compile_ms_total", "Cumulative compile wall (ms)", ("program",)
    )
    for name, p in all_programs().items():
        st = p.stats()
        calls.handle(name).set(st["calls"])
        compiles.handle(name).set(st["compiles"])
        compile_ms.handle(name).set(st["compileMs"])


def _register_scrape_callback() -> None:
    from kmamiz_tpu.telemetry.registry import REGISTRY

    REGISTRY.register_callback(_scrape_programs)


_register_scrape_callback()


def snapshot() -> Dict[str, int]:
    """Compile-count snapshot; diff with :func:`new_compiles_since`."""
    return {name: p.compiles for name, p in all_programs().items()}


def new_compiles_since(snap: Dict[str, int]) -> Dict[str, int]:
    """Programs that compiled since ``snap`` (steady state must be {})."""
    out = {}
    for name, p in all_programs().items():
        delta = p.compiles - snap.get(name, 0)
        if delta > 0:
            out[name] = delta
    return out


# ---------------------------------------------------------------------------
# persisted shape hints
# ---------------------------------------------------------------------------

_hints_lock = threading.Lock()
_HINTS_VERSION = 1


def hints_path() -> Optional[str]:
    path = os.environ.get("KMAMIZ_SHAPE_HINTS")
    if path:
        return path
    cache_dir = os.environ.get("KMAMIZ_COMPILE_CACHE_DIR")
    if cache_dir:
        return os.path.join(cache_dir, "shape_hints.json")
    return None


def save_hints(path: Optional[str] = None) -> Optional[str]:
    """Write every program's observed specs (atomic replace). Returns the
    path written, or None when hints are unconfigured."""
    path = path or hints_path()
    if not path:
        return None
    payload = {
        "version": _HINTS_VERSION,
        "programs": {
            name: p.specs()
            for name, p in sorted(all_programs().items())
            if p.specs()
        },
        # sibling key, same version: readers of "programs" (including
        # older processes — load_hints filters on len(spec) == 2 and
        # never looks here) are unaffected. These are the graftcost
        # training rows that survive a restart.
        "labels": {
            name: [
                {"spec": spec, "compileMs": round(c, 3), "runMs": round(r, 3)}
                for spec, c, r in p.labels()
            ]
            for name, p in sorted(all_programs().items())
            if p.labels()
        },
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with _hints_lock:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    return path


def load_hints(path: Optional[str] = None) -> Dict[str, List[Any]]:
    path = path or hints_path()
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            payload = json.load(f)
        if payload.get("version") != _HINTS_VERSION:
            return {}
        out = {}
        for name, specs in payload.get("programs", {}).items():
            out[name] = [
                (spec[0], spec[1]) for spec in specs if len(spec) == 2
            ]
        return out
    except (OSError, ValueError, TypeError) as e:
        logger.warning("bad shape-hint file %s: %s", path, e)
        return {}


def load_labels(
    path: Optional[str] = None,
) -> Dict[str, List[Tuple[Any, float, float]]]:
    """Persisted cost labels: {name: [(spec, compile_ms, run_ms)]}.
    Empty when unconfigured, absent (pre-label hint file), or bad."""
    path = path or hints_path()
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            payload = json.load(f)
        if payload.get("version") != _HINTS_VERSION:
            return {}
        out: Dict[str, List[Tuple[Any, float, float]]] = {}
        for name, rows in payload.get("labels", {}).items():
            keep = []
            for row in rows:
                spec = row.get("spec")
                if not (isinstance(spec, list) and len(spec) == 2):
                    continue
                keep.append(
                    (
                        (spec[0], spec[1]),
                        float(row.get("compileMs", 0.0)),
                        float(row.get("runMs", 0.0)),
                    )
                )
            if keep:
                out[name] = keep
        return out
    except (OSError, ValueError, TypeError) as e:
        logger.warning("bad shape-hint labels in %s: %s", path, e)
        return {}


def adopt_labels(
    labelled: Dict[str, List[Tuple[Any, float, float]]]
) -> None:
    """Feed persisted label history back into the live programs so the
    cost model trains from day-one history at boot."""
    for name, rows in labelled.items():
        prog = get(name)
        if prog is not None:
            prog.adopt_labels(rows)


def _autosave_hints() -> None:
    """Persist on every NEW bucket observation (rare by construction:
    pow2 bucketing bounds distinct specs to O(log) per program)."""
    try:
        save_hints()
    except OSError as e:
        logger.warning("shape-hint save failed: %s", e)


# ---------------------------------------------------------------------------
# boot prewarm plan + readiness state
# ---------------------------------------------------------------------------

_warm_lock = threading.Lock()
_warm: Dict[str, Any] = {"status": "cold"}
_warm_thread: Optional[threading.Thread] = None


def warm_state() -> dict:
    with _warm_lock:
        return dict(_warm)


def is_warming() -> bool:
    return warm_state().get("status") == "warming"


def ready_gate_enabled() -> bool:
    return os.environ.get("KMAMIZ_PREWARM_READY_GATE", "1") != "0"


def run_prewarm(
    graph=None, hints: Optional[Dict[str, List[Any]]] = None
) -> dict:
    """Execute the boot prewarm plan synchronously:

    1. replay every persisted (program, spec) hint — the exact buckets
       the previous process compiled for production traffic;
    2. for the graph-store merge family only, when NO hint covered it,
       fall back to ``graph.prewarm_compile()`` default (rows, depth)
       buckets (everything else is hint-driven: defaults for scorer or
       model programs would guess capacities the deployment never uses).

    Returns a report dict (also stored in :func:`warm_state`).
    """
    _ensure_registered()
    t0 = time.perf_counter()  # graftlint: disable=hot-path-clock -- boot-time prewarm accounting, off the tick
    # the native extension's one-time lazy build (or its cached-failure
    # probe) otherwise lands inside the first tick's combine phase — it
    # is boot work, so the plan pays it here alongside the XLA warms
    try:
        from kmamiz_tpu import native

        native.available()
    except Exception:  # noqa: BLE001 - never let the probe block boot
        logger.exception("native prewarm probe failed")
    hints = load_hints() if hints is None else hints
    labels = load_labels()
    adopt_labels(labels)
    report = {
        "hintedPrograms": len(hints),
        "warmed": 0,
        "failed": 0,
        "ranked": False,
        "defaultGraphPrograms": 0,
    }
    pairs: List[Tuple[str, Any]] = []
    for name, specs in sorted(hints.items()):
        if get(name) is None:
            report["failed"] += len(specs)
            logger.warning("hint for unregistered program %s", name)
            continue
        pairs.extend((name, spec) for spec in specs)
    # graftcost boot ranking: longest predicted compile first, so
    # readiness is bounded by the expensive programs instead of queuing
    # them behind trivia. Falls back to the stable name order on any
    # failure — ranking must never block a cold boot.
    try:
        from kmamiz_tpu import cost as _cost

        pairs = _cost.ranked_prewarm_order(pairs, labels)
        report["ranked"] = True
    except Exception:  # noqa: BLE001 - name-ordered replay still correct
        logger.exception("prewarm ranking failed; using name order")
    for name, spec in pairs:
        prog = get(name)
        if prog is not None and prog.prewarm_spec(spec):
            report["warmed"] += 1
        else:
            report["failed"] += 1
    graph_hinted = any(n.startswith("graph.") for n in hints)
    if graph is not None and not graph_hinted:
        try:
            report["defaultGraphPrograms"] = graph.prewarm_compile()
        except Exception as e:  # noqa: BLE001 - boot must survive
            logger.warning("default graph prewarm failed: %s", e)
    report["elapsedS"] = round(time.perf_counter() - t0, 2)  # graftlint: disable=hot-path-clock -- boot-time prewarm accounting, off the tick
    return report


def start_background_prewarm(graph=None) -> Optional[threading.Thread]:
    """Run the prewarm plan on a daemon thread; /health reports WARMING
    (503 when the ready gate is on) until it completes. Idempotent."""
    global _warm_thread
    with _warm_lock:
        if _warm["status"] in ("warming", "ready", "error"):
            return _warm_thread
        _warm.clear()
        _warm.update({"status": "warming", "startedAt": time.time()})  # graftlint: disable=hot-path-clock -- boot wall stamp for /health warm state, off the tick

    def _run() -> None:
        status = "ready"
        report: Dict[str, Any] = {}
        try:
            report = run_prewarm(graph=graph)
        except Exception as e:  # noqa: BLE001 - serve degraded, don't die
            logger.exception("background prewarm failed")
            status, report = "error", {"error": str(e)}
        with _warm_lock:
            _warm["status"] = status
            _warm["report"] = report
        logger.info("prewarm %s: %s", status, report)

    _warm_thread = threading.Thread(
        target=_run, name="kmamiz-prewarm", daemon=True
    )
    _warm_thread.start()
    return _warm_thread


def boot_prewarm_from_env(graph=None) -> None:
    """KMAMIZ_PREWARM dispatcher for server mains: "0" off, "sync"
    blocking, default background + readiness gate."""
    mode = os.environ.get("KMAMIZ_PREWARM", "1")
    if mode == "0":
        with _warm_lock:
            _warm.update({"status": "disabled"})
        return
    if mode == "sync":
        with _warm_lock:
            _warm.update({"status": "warming", "startedAt": time.time()})  # graftlint: disable=hot-path-clock -- boot wall stamp for /health warm state, off the tick
        report = run_prewarm(graph=graph)
        with _warm_lock:
            _warm.update({"status": "ready", "report": report})
        return
    start_background_prewarm(graph=graph)


# ---------------------------------------------------------------------------
# jit-site inventory (tier-1 guard test: tests/test_programs.py)
#
# Every `jax.jit` call site under kmamiz_tpu/ must appear in exactly one
# of these tables, keyed "relative/path.py" -> {function name}. REGISTERED
# sites are wrapped in a Program above/in their module; ALLOWLISTED sites
# carry the reason they are exempt from registry coverage.
# ---------------------------------------------------------------------------

REGISTERED_JIT_SITES: Dict[str, set] = {
    "kmamiz_tpu/graph/store.py": {
        "_merge_edges",
        "_window_merge",
        "_window_edges_packed",
        "_window_edges_compact",
        "_window_merge_packed",
        "_edge_mask",
        "_fit_edges",
        "_split_segments",
        "_bulk_dist_bounds",
        "_cat_segments",
    },
    "kmamiz_tpu/ops/sparse.py": {
        "fused_gated_bias",
        "fused_neighbor_sums",
    },
    "kmamiz_tpu/ops/window.py": {
        "skip_client_parents",
        "dependency_edges",
        "dependency_edges_packed",
        "window_stats",
        "service_stats",
    },
    "kmamiz_tpu/ops/scorers.py": {
        "service_scores_xla",
        "service_scores_sparse",
        "usage_cohesion",
        "risk_scores",
        "dirty_edge_subset",
        "merge_service_lanes",
    },
    "kmamiz_tpu/server/processor.py": {"_pack_stats"},
    # graftcost continual trainer (registered as cost.ridge_fit)
    "kmamiz_tpu/cost/model.py": {"_ridge_fit"},
    # scanner resolves inline jits to the nearest def: "fwd" is the
    # body _jitted_forward jits (registered as models.forecast_forward),
    # "run" the epoch blocks of epoch_runner/dp_epoch_runner
    "kmamiz_tpu/models/serving.py": {"fwd"},
    "kmamiz_tpu/models/stacked.py": {"run", "_batched_forward"},
    # STLGT: "run" is the continual-refresh epoch block (registered as
    # models.stlgt_epoch_block), "fwd" the quantile serving forward
    # (models.stlgt_quantile_forward)
    "kmamiz_tpu/models/stlgt/trainer.py": {"run"},
    "kmamiz_tpu/models/stlgt/serving.py": {"fwd"},
}

ALLOWLISTED_JIT_SITES: Dict[str, Dict[str, str]] = {
    "kmamiz_tpu/parallel/mesh.py": {
        "sharded_window_stats": "multi-chip only; prewarmed via the "
        "sharded branch of EndpointGraph.prewarm_compile",
        "sharded_dependency_edges": "multi-chip only (see above)",
        "sharded_dependency_edges_packed": "multi-chip only (see above)",
        "sharded_window_edges_compact": "multi-chip only (see above)",
        "sharded_service_scores": "multi-chip only (see above)",
    },
    "kmamiz_tpu/ops/pallas_kernels.py": {
        "segment_stats_matmul": "inner kernel: dispatched only inside "
        "window_stats' trace (registered there)",
    },
    "kmamiz_tpu/models/common.py": {
        "train_step": "legacy per-slot trainer loop "
        "(KMAMIZ_SAGE_FUSED=0 parity reference), off the serving path",
    },
}
