"""SoA span batches: Zipkin JSON -> fixed-shape device arrays.

This is the design translation at the heart of the TPU backend (SURVEY.md
§7): the reference walks per-span object graphs
(/root/reference/src/classes/Traces.ts:112-211, Rust twin
kmamiz_data_processor/src/data/trace.rs:110-212); here a window of spans
becomes id-indexed arrays. Parent span-ids are resolved to row indices on
the host (strings never reach the device); the CLIENT-skip ancestor walk and
all groupby statistics then run as jitted kernels (kmamiz_tpu.ops.window).

Batches are padded to power-of-two sizes so XLA compiles a bounded number of
program shapes.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from kmamiz_tpu.core.interning import EndpointInterner, StringInterner
from kmamiz_tpu.core.schema import js_str as _js
from kmamiz_tpu.domain.traces import to_endpoint_info

KIND_OTHER = 0
KIND_SERVER = 1
KIND_CLIENT = 2


def _pad_size(n: int, base: int = 2, minimum: int = 8) -> int:
    size = minimum
    while size < n:
        size *= base
    return size


@dataclass
class SpanBatch:
    """One window of spans in structure-of-arrays form.

    All arrays share length `capacity` (padded); rows [n_spans:] are padding
    with valid=False. Ids index the accompanying interner tables.
    """

    n_spans: int
    valid: np.ndarray  # bool[capacity]
    kind: np.ndarray  # int8[capacity] (KIND_*)
    parent_idx: np.ndarray  # int32[capacity], -1 = no parent in window
    # graph id space: ToEndpointInfo naming (.svc. parse w/ istio fallback)
    endpoint_id: np.ndarray  # int32[capacity]
    service_id: np.ndarray  # int32[capacity]
    # realtime id space: istio-tag naming used by the stats/combined path
    # (the reference names endpoints differently in toRealTimeData /
    # combineLogsToRealtimeData vs toEndpointDependencies)
    rt_endpoint_id: np.ndarray  # int32[capacity]
    rt_service_id: np.ndarray  # int32[capacity]
    status_id: np.ndarray  # int32[capacity]
    status_class: np.ndarray  # int8[capacity] (first digit of http status)
    latency_ms: np.ndarray  # float64[capacity] (duration / 1000)
    timestamp_us: np.ndarray  # int64[capacity] (host-side absolute)
    timestamp_rel: np.ndarray  # int32[capacity] (µs offset from ts_base_us;
    # absolute µs don't fit int32 and the TPU path runs with x64 off)
    ts_base_us: int
    # trace membership (index of the first trace group a span id appeared
    # in); feeds pack_trace_rows for the MXU ancestor-walk layout
    trace_of: np.ndarray  # int32[capacity]

    interner: EndpointInterner
    statuses: StringInterner
    # per-endpoint metadata for reconstructing protocol output
    endpoint_infos: List[dict]

    @property
    def capacity(self) -> int:
        return len(self.valid)

    @property
    def num_endpoints(self) -> int:
        return len(self.interner.endpoints)

    @property
    def num_services(self) -> int:
        return len(self.interner.services)

    @property
    def num_statuses(self) -> int:
        return len(self.statuses)


class _NamingEntry(NamedTuple):
    """One distinct naming shape's resolved ids and info templates."""

    eid: int
    sid: int
    rt_eid: int
    rt_sid: int
    uen: str
    info_base: dict
    rt_uen: str
    rt_base: dict


def _make_naming_entry(
    span_like: dict, tags: dict, interner: EndpointInterner
) -> _NamingEntry:
    """Resolve one distinct naming shape: graph-space naming via
    to_endpoint_info (Traces.ts:213-241) and realtime-space naming via the
    istio tags, interning both. Shared by the dict path (spans_to_batch)
    and the native raw-bytes path (raw_spans_to_batch)."""
    info = to_endpoint_info(span_like)
    uen = info["uniqueEndpointName"]
    info_base = {k_: v for k_, v in info.items() if k_ != "timestamp"}
    eid = interner.intern_endpoint(uen, info)
    rt_usn = (
        f"{_js(tags.get('istio.canonical_service'))}"
        f"\t{_js(tags.get('istio.namespace'))}"
        f"\t{_js(tags.get('istio.canonical_revision'))}"
    )
    rt_uen = (
        f"{rt_usn}\t{_js(tags.get('http.method'))}"
        f"\t{_js(tags.get('http.url'))}"
    )
    # metadata for the rt-space endpoint carries the rt naming
    # (istio tags), not the graph-space info
    rt_base = {
        **info_base,
        "service": tags.get("istio.canonical_service"),
        "namespace": tags.get("istio.namespace"),
        "version": tags.get("istio.canonical_revision"),
        "uniqueServiceName": rt_usn,
        "uniqueEndpointName": rt_uen,
    }
    rt_eid = interner.intern_endpoint(
        rt_uen, {**rt_base, "timestamp": info["timestamp"]}
    )
    return _NamingEntry(
        eid=eid,
        sid=interner.service_of(eid),
        rt_eid=rt_eid,
        rt_sid=interner.service_of(rt_eid),
        uen=uen,
        info_base=info_base,
        rt_uen=rt_uen,
        rt_base=rt_base,
    )


def _apply_best_ts(
    best_ts: "Dict[int, Tuple[float, _NamingEntry]]", interner: EndpointInterner
) -> None:
    """Apply the freshest timestamp per endpoint (intern_endpoint keeps the
    max vs any info already stored by earlier windows)."""
    for key_eid, (ts_ms, hit) in best_ts.items():
        if key_eid == hit.eid:
            interner.intern_endpoint(hit.uen, {**hit.info_base, "timestamp": ts_ms})
        else:
            interner.intern_endpoint(
                hit.rt_uen, {**hit.rt_base, "timestamp": ts_ms}
            )


def _entry_from_decoded(
    dec: Tuple[str, ...],
    url_present: bool,
    bits: int,
    interner: EndpointInterner,
) -> _NamingEntry:
    """Decoded native shape fields + presence bits -> resolved naming
    entry (the one definition the per-call and session ingest paths
    share). Timestamp 0: the freshest-timestamp info is applied by the
    caller from the per-shape max, which dominates any intermediate."""
    from kmamiz_tpu import native as native_mod

    name, url, method, svc, ns, rev, mesh = dec
    tags: Dict[str, str] = {}
    if url_present:
        tags["http.url"] = url
    if bits & native_mod.SHAPE_HAS_METHOD:
        tags["http.method"] = method
    if bits & native_mod.SHAPE_HAS_SVC:
        tags["istio.canonical_service"] = svc
    if bits & native_mod.SHAPE_HAS_NS:
        tags["istio.namespace"] = ns
    if bits & native_mod.SHAPE_HAS_REV:
        tags["istio.canonical_revision"] = rev
    if bits & native_mod.SHAPE_HAS_MESH:
        tags["istio.mesh_id"] = mesh
    return _make_naming_entry(
        {"name": name, "timestamp": 0, "tags": tags}, tags, interner
    )


def _compute_timestamp_rel(
    timestamp_us: np.ndarray, n: int, capacity: int, ts_base_us: Optional[int]
) -> Tuple[np.ndarray, int]:
    if ts_base_us is not None:
        ts_base = ts_base_us
    else:
        ts_base = int(timestamp_us[:n].min()) if n else 0
    timestamp_rel = np.zeros(capacity, dtype=np.int32)
    if n:
        span_rel = timestamp_us[:n] - ts_base
        if span_rel.max() > np.iinfo(np.int32).max:
            # one batch must fit int32 µs offsets (~35 min); realtime windows
            # are 30 s — long replays/backfills must split into batches
            raise ValueError(
                "span window exceeds int32 µs range; split the batch "
                f"(span of {span_rel.max() / 1e6:.0f}s)"
            )
        timestamp_rel[:n] = span_rel.astype(np.int32)
    return timestamp_rel, ts_base


def spans_to_batch(
    trace_groups: Sequence[Sequence[dict]],
    interner: Optional[EndpointInterner] = None,
    statuses: Optional[StringInterner] = None,
    pad: bool = True,
    ts_base_us: Optional[int] = None,
) -> SpanBatch:
    """Flatten Zipkin trace groups into a SpanBatch.

    Mirrors the reference's span-map construction: spans are keyed by id with
    last-wins/first-position semantics (JS Map), and parent ids resolve only
    within the window.
    """
    interner = interner or EndpointInterner()
    statuses = statuses or StringInterner()

    span_map: Dict[str, dict] = {}
    trace_of_id: Dict[str, int] = {}
    for g, group in enumerate(trace_groups):
        for span in group:
            span_map[span["id"]] = span
            # first-position wins, like the span map itself
            trace_of_id.setdefault(span["id"], g)
    spans = list(span_map.values())
    index_of = {span_id: i for i, span_id in enumerate(span_map.keys())}

    n = len(spans)
    capacity = _pad_size(n) if pad else max(n, 1)

    # per-window memo: spans repeat a small set of naming shapes, so the
    # string formatting / URL explode / interning runs once per distinct
    # (name, url, method, istio tags) combination instead of per span
    # (~3x host ingest). Statuses cache separately (an endpoint emitting
    # five statuses still resolves its naming once). Freshest-timestamp
    # info semantics are preserved by tracking the max-ts span per
    # endpoint and applying it after the loop. The per-span columns
    # accumulate in Python lists and land in the arrays as one bulk
    # assignment each — per-element numpy scalar stores were the single
    # largest host cost of the pack.
    naming_cache: Dict[tuple, "_NamingEntry"] = {}
    status_cache: Dict[Optional[str], Tuple[int, int]] = {}
    best_ts: Dict[int, Tuple[float, "_NamingEntry"]] = {}

    kind_l = []
    parent_l = []
    eid_l = []
    sid_l = []
    rt_eid_l = []
    rt_sid_l = []
    stid_l = []
    stcl_l = []
    lat_l = []
    ts_l = []
    trace_l = []

    for span in spans:
        trace_l.append(trace_of_id[span["id"]])
        k = span.get("kind")
        kind_l.append(
            KIND_SERVER if k == "SERVER" else KIND_CLIENT if k == "CLIENT" else KIND_OTHER
        )
        parent = span.get("parentId")
        if parent is not None:
            parent_l.append(index_of.get(parent, -1))
        else:
            parent_l.append(-1)

        tags = span.get("tags", {})
        key = (
            span.get("name", ""),
            tags.get("http.url", ""),
            tags.get("http.method"),
            tags.get("istio.canonical_service"),
            tags.get("istio.namespace"),
            tags.get("istio.canonical_revision"),
            tags.get("istio.mesh_id"),
        )
        hit = naming_cache.get(key)
        if hit is None:
            hit = _make_naming_entry(span, tags, interner)
            naming_cache[key] = hit

        raw_status = tags.get("http.status_code")
        st = status_cache.get(raw_status)
        if st is None:
            status = raw_status or ""
            st = (
                statuses.intern(status),
                int(status[0]) if status[:1].isdigit() else 0,
            )
            status_cache[raw_status] = st

        eid_l.append(hit.eid)
        sid_l.append(hit.sid)
        rt_eid_l.append(hit.rt_eid)
        rt_sid_l.append(hit.rt_sid)
        stid_l.append(st[0])
        stcl_l.append(st[1])
        lat_l.append(span.get("duration", 0) / 1000)
        ts_us = span.get("timestamp", 0)
        ts_l.append(ts_us)
        ts_ms = ts_us / 1000
        for key_eid in (hit.eid, hit.rt_eid):
            prev = best_ts.get(key_eid)
            if prev is None or ts_ms > prev[0]:
                best_ts[key_eid] = (ts_ms, hit)

    valid = np.zeros(capacity, dtype=bool)
    kind = np.zeros(capacity, dtype=np.int8)
    parent_idx = np.full(capacity, -1, dtype=np.int32)
    endpoint_id = np.zeros(capacity, dtype=np.int32)
    service_id = np.zeros(capacity, dtype=np.int32)
    rt_endpoint_id = np.zeros(capacity, dtype=np.int32)
    rt_service_id = np.zeros(capacity, dtype=np.int32)
    status_id = np.zeros(capacity, dtype=np.int32)
    status_class = np.zeros(capacity, dtype=np.int8)
    # graftlint: disable=dtype-drift -- host span column: latency sums stay exact in f64; device path downcasts at upload
    latency_ms = np.zeros(capacity, dtype=np.float64)
    timestamp_us = np.zeros(capacity, dtype=np.int64)
    trace_of = np.zeros(capacity, dtype=np.int32)
    if n:
        valid[:n] = True
        kind[:n] = kind_l
        parent_idx[:n] = parent_l
        endpoint_id[:n] = eid_l
        service_id[:n] = sid_l
        rt_endpoint_id[:n] = rt_eid_l
        rt_service_id[:n] = rt_sid_l
        status_id[:n] = stid_l
        status_class[:n] = stcl_l
        latency_ms[:n] = lat_l
        timestamp_us[:n] = ts_l
        trace_of[:n] = trace_l

    _apply_best_ts(best_ts, interner)
    endpoint_infos = [i for i in interner.endpoint_infos if i is not None]
    timestamp_rel, ts_base = _compute_timestamp_rel(
        timestamp_us, n, capacity, ts_base_us
    )
    return SpanBatch(
        n_spans=n,
        valid=valid,
        kind=kind,
        parent_idx=parent_idx,
        endpoint_id=endpoint_id,
        service_id=service_id,
        rt_endpoint_id=rt_endpoint_id,
        rt_service_id=rt_service_id,
        status_id=status_id,
        status_class=status_class,
        latency_ms=latency_ms,
        timestamp_us=timestamp_us,
        timestamp_rel=timestamp_rel,
        ts_base_us=ts_base,
        trace_of=trace_of,
        interner=interner,
        statuses=statuses,
        endpoint_infos=endpoint_infos,
    )


def raw_spans_to_batch(
    raw: bytes,
    interner: Optional[EndpointInterner] = None,
    statuses: Optional[StringInterner] = None,
    pad: bool = True,
    ts_base_us: Optional[int] = None,
    skip_trace_ids: Sequence = (),
    skip_blob: Optional[bytes] = None,
    skipset=None,
    session: "Optional[RawIngestSession]" = None,
):
    """Native ingest: raw Zipkin response bytes -> (SpanBatch, kept trace
    ids), bypassing json.loads and the per-span dict walk (VERDICT r1 #1).

    The C++ scanner (native/kmamiz_spans.cpp) emits SoA arrays plus the
    distinct naming shapes; only O(#shapes) string work (URL explode,
    naming, interning) runs here, through the SAME _make_naming_entry the
    dict path uses — semantics are byte-identical to
    spans_to_batch(json.loads(raw)) after DataProcessor._filter_traces
    with `skip_trace_ids` as the processed set.

    Returns None when the native extension is unavailable or the payload is
    malformed; callers fall back to the dict path.
    """
    from kmamiz_tpu import native as native_mod

    # the session path resolves against ITS OWN interner/status tables
    # and carries dedup state ONLY via the skipset handle: taking it
    # with a mismatched interner or blob-style skip args would silently
    # ignore what the caller passed, so those route to the per-call
    # path instead
    if (
        session is not None
        and session.available
        and not skip_trace_ids
        and skip_blob is None
        and (interner is None or interner is session.interner)
        and (statuses is None or statuses is session.statuses)
    ):
        return _raw_spans_to_batch_session(
            raw, session, pad, ts_base_us, skipset
        )

    parsed = native_mod.parse_spans(
        raw, list(skip_trace_ids), skip_blob=skip_blob, skipset=skipset
    )
    if parsed is None:
        return None

    interner = interner or EndpointInterner()
    statuses = statuses or StringInterner()
    n = parsed["n_spans"]

    # resolve each distinct naming shape once (same order the dict path
    # would first-encounter them in). Resolutions cache on the interner
    # across calls: a chunked stream re-encounters the same shapes every
    # page, and re-resolving ~10k shapes (URL explode + naming joins) per
    # chunk costs more than the native parse saves at production
    # endpoint diversity. _NamingEntry is immutable ids, and a cache hit
    # skips only work whose outputs are already interned.
    shape_cache = getattr(interner, "_raw_shape_cache", None)
    if shape_cache is None:
        shape_cache = interner._raw_shape_cache = {}
    # fields arrive as raw bytes (native marshalling defers the decode
    # to the miss path — the warm path never needs it). ALL misses
    # decode BEFORE any interning: a malformed shape must reject the
    # payload with the documented None return, not raise mid-loop after
    # earlier shapes already mutated the shared interner.
    try:
        decoded = {
            key: tuple(
                f.decode("utf-8", "surrogatepass") for f in key[0]
            )
            for shape in parsed["shapes"]
            if (key := (shape[0], shape[1], shape[2])) not in shape_cache
        }
    except UnicodeDecodeError:
        return None
    entries: List[_NamingEntry] = []
    for fields, url_present, bits in parsed["shapes"]:
        cache_key = (fields, url_present, bits)
        entry = shape_cache.get(cache_key)
        if entry is None:
            entry = _entry_from_decoded(
                decoded[cache_key], url_present, bits, interner
            )
            shape_cache[cache_key] = entry
        entries.append(entry)

    # distinct statuses -> interner ids + status classes
    st_ids = np.empty(max(len(parsed["statuses"]), 1), dtype=np.int32)
    st_cls = np.zeros(max(len(parsed["statuses"]), 1), dtype=np.int8)
    for i, s in enumerate(parsed["statuses"]):
        st_ids[i] = statuses.intern(s)
        st_cls[i] = int(s[0]) if s[:1].isdigit() else 0

    # freshest timestamp per endpoint (same strict-> update order as the
    # per-span loop: shapes are in first-appearance order)
    best_ts: Dict[int, Tuple[float, _NamingEntry]] = {}
    for shape_idx, hit in enumerate(entries):
        ts_ms = float(parsed["shape_max_ts_ms"][shape_idx])
        for key_eid in (hit.eid, hit.rt_eid):
            prev = best_ts.get(key_eid)
            if prev is None or ts_ms > prev[0]:
                best_ts[key_eid] = (ts_ms, hit)
    _apply_best_ts(best_ts, interner)

    capacity = _pad_size(n) if pad else max(n, 1)
    valid = np.zeros(capacity, dtype=bool)
    valid[:n] = True

    def _padded(arr: np.ndarray, dtype, fill=0):
        out = np.full(capacity, fill, dtype=dtype)
        out[:n] = arr[:n]
        return out

    shape_ids = parsed["shape_id"][:n]
    eid_of = np.array([e.eid for e in entries] or [0], dtype=np.int32)
    sid_of = np.array([e.sid for e in entries] or [0], dtype=np.int32)
    rt_eid_of = np.array([e.rt_eid for e in entries] or [0], dtype=np.int32)
    rt_sid_of = np.array([e.rt_sid for e in entries] or [0], dtype=np.int32)

    endpoint_id = np.zeros(capacity, dtype=np.int32)
    service_id = np.zeros(capacity, dtype=np.int32)
    rt_endpoint_id = np.zeros(capacity, dtype=np.int32)
    rt_service_id = np.zeros(capacity, dtype=np.int32)
    status_id = np.zeros(capacity, dtype=np.int32)
    status_class = np.zeros(capacity, dtype=np.int8)
    if n:
        endpoint_id[:n] = eid_of[shape_ids]
        service_id[:n] = sid_of[shape_ids]
        rt_endpoint_id[:n] = rt_eid_of[shape_ids]
        rt_service_id[:n] = rt_sid_of[shape_ids]
        status_id[:n] = st_ids[parsed["status_id"][:n]]
        status_class[:n] = st_cls[parsed["status_id"][:n]]

    timestamp_us = _padded(parsed["timestamp_us"], np.int64)
    timestamp_rel, ts_base = _compute_timestamp_rel(
        timestamp_us, n, capacity, ts_base_us
    )

    batch = SpanBatch(
        n_spans=n,
        valid=valid,
        kind=_padded(parsed["kind"], np.int8),
        parent_idx=_padded(parsed["parent_idx"], np.int32, fill=-1),
        endpoint_id=endpoint_id,
        service_id=service_id,
        rt_endpoint_id=rt_endpoint_id,
        rt_service_id=rt_service_id,
        status_id=status_id,
        status_class=status_class,
        latency_ms=_padded(parsed["latency_ms"], np.float64),  # graftlint: disable=dtype-drift -- host span column, f64 by design (see spans_to_batch)
        timestamp_us=timestamp_us,
        timestamp_rel=timestamp_rel,
        ts_base_us=ts_base,
        trace_of=_padded(parsed["trace_of"], np.int32),
        interner=interner,
        statuses=statuses,
        endpoint_infos=[i for i in interner.endpoint_infos if i is not None],
    )
    return batch, parsed["trace_ids"]


class KeptTraceIds(list):
    """Kept trace ids (list semantics, None markers preserved) plus the
    raw interleaved skip-entry bytes of the SAME records — byte-identical
    to native.encode_skip_entry output, so the dedup registration can
    append one slice instead of re-encoding every id."""

    __slots__ = ("blob",)

    def __init__(self, ids, blob: Optional[bytes] = None) -> None:
        super().__init__(ids)
        self.blob = blob


class RawIngestSession:
    """Cross-chunk state for the persistent-session ingest path.

    Pairs the native ParseSession (persistent shape/status tables,
    delta string emission) with the Python-side resolutions those
    global ids index: naming entries per session shape id, interner id
    gather arrays, status ids/classes, and the per-endpoint
    freshest-timestamp bookkeeping that replaces the per-chunk
    _apply_best_ts walk with vectorized winner selection. One session
    per (DataProcessor, interner); a rejected payload resets it (the
    native tables may hold entries Python never consumed)."""

    def __init__(
        self,
        interner: EndpointInterner,
        statuses: Optional[StringInterner] = None,
    ) -> None:
        from kmamiz_tpu import native as native_mod

        self.interner = interner
        self.statuses = statuses or StringInterner()
        self._native_mod = native_mod
        self.native = native_mod.ParseSession()
        # one consumer at a time: the python-side views must extend in
        # the same order the native watermark advances (concurrent raw
        # ingests — stream chunks racing a one-shot backfill — queue
        # here instead of tripping the desync reset)
        self.lock = threading.Lock()
        self._reset_views()

    def _reset_views(self) -> None:
        self.entries: List[_NamingEntry] = []
        self.eid_of = np.zeros(0, np.int32)
        self.sid_of = np.zeros(0, np.int32)
        self.rt_eid_of = np.zeros(0, np.int32)
        self.rt_sid_of = np.zeros(0, np.int32)
        self.st_ids = np.zeros(0, np.int32)
        self.st_cls = np.zeros(0, np.int8)
        # per-ENDPOINT winner bookkeeping: code = 2*shape_idx + is_rt
        # (session shape ids are stable, so codes stay comparable)
        self.applied_code = np.full(0, -1, np.int64)
        self.applied_ts = np.zeros(0, np.float64)  # graftlint: disable=dtype-drift -- epoch-ms bookkeeping exceeds f32 integer range

    @property
    def available(self) -> bool:
        return self.native.handle is not None

    def reset(self) -> None:
        """Fresh native session + cleared views (after a rejected
        payload, whose native-side interns Python never consumed)."""
        self.native = self._native_mod.ParseSession()
        self._reset_views()

    def _grow_applied(self, n_ep: int) -> None:
        if self.applied_ts.size < n_ep:
            grow = n_ep - self.applied_ts.size
            self.applied_ts = np.concatenate(
                [self.applied_ts, np.zeros(grow)]
            )
            self.applied_code = np.concatenate(
                [self.applied_code, np.full(grow, -1, np.int64)]
            )


def _raw_spans_to_batch_session(
    raw: bytes,
    session: RawIngestSession,
    pad: bool,
    ts_base_us: Optional[int],
    skipset,
):
    """Session twin of raw_spans_to_batch's body: span columns arrive
    with session-global shape/status ids, so the warm path does pure
    array gathers — no per-shape dict walks, no string decode. Exactness
    notes are inline; every deviation from the per-chunk path is a
    monotone-max equivalence."""
    from kmamiz_tpu import native as native_mod

    with session.lock:
        return _session_batch_locked(
            raw, session, pad, ts_base_us, skipset, native_mod
        )


def _session_batch_locked(
    raw, session, pad, ts_base_us, skipset, native_mod
):
    interner = session.interner
    statuses = session.statuses
    parsed = native_mod.parse_spans(
        raw, skipset=skipset, session=session.native
    )
    if parsed is None or not parsed.get("session_format"):
        # malformed payload (native tables may hold unconsumed interns)
        # or a stale .so without session support: reset so the next call
        # starts clean / falls back
        session.reset()
        return None
    if len(session.entries) != parsed["shape_base"]:
        session.reset()  # desynced watermark (shared-session misuse)
        return None

    # -- new shapes: decode EVERYTHING first (reject-before-intern), then
    # resolve through the shared helper — via the interner-level shape
    # cache, so a session reset re-resolves warm shapes cheaply and
    # session-resolved shapes warm the per-call fallback path too
    new_shapes = parsed["new_shapes"]
    if new_shapes:
        shape_cache = getattr(interner, "_raw_shape_cache", None)
        if shape_cache is None:
            shape_cache = interner._raw_shape_cache = {}
        try:
            decoded = [
                tuple(f.decode("utf-8", "surrogatepass") for f in fields)
                for fields, _, _ in new_shapes
            ]
        except UnicodeDecodeError:
            session.reset()
            return None
        base = len(session.entries)
        for (fields, url_present, bits), dec in zip(new_shapes, decoded):
            cache_key = (fields, url_present, bits)
            entry = shape_cache.get(cache_key)
            if entry is None:
                entry = _entry_from_decoded(dec, url_present, bits, interner)
                shape_cache[cache_key] = entry
            session.entries.append(entry)
        fresh = session.entries[base:]
        session.eid_of = np.concatenate(
            [session.eid_of, np.array([e.eid for e in fresh], np.int32)]
        )
        session.sid_of = np.concatenate(
            [session.sid_of, np.array([e.sid for e in fresh], np.int32)]
        )
        session.rt_eid_of = np.concatenate(
            [session.rt_eid_of, np.array([e.rt_eid for e in fresh], np.int32)]
        )
        session.rt_sid_of = np.concatenate(
            [session.rt_sid_of, np.array([e.rt_sid for e in fresh], np.int32)]
        )

    if parsed["new_statuses"]:
        add_ids = np.empty(len(parsed["new_statuses"]), np.int32)
        add_cls = np.zeros(len(parsed["new_statuses"]), np.int8)
        for i, s in enumerate(parsed["new_statuses"]):
            add_ids[i] = statuses.intern(s)
            add_cls[i] = int(s[0]) if s[:1].isdigit() else 0
        session.st_ids = np.concatenate([session.st_ids, add_ids])
        session.st_cls = np.concatenate([session.st_cls, add_cls])

    # everything decoded + resolved: acknowledge so the next parse stops
    # re-emitting these shapes/statuses
    session.native.ack(parsed["shapes_total"], parsed["statuses_total"])

    # -- freshest timestamp per endpoint, vectorized -------------------------
    # Winner selection matches the per-chunk loop exactly: max cumulative
    # shape ts per endpoint, ties broken by lowest (shape, eid-before-rt)
    # code; application is strict-> so replaying an already-applied max
    # is a no-op (the session ts is cumulative where the per-chunk path
    # saw window-local maxima — a monotone-max equivalence).
    n_shapes = parsed["shapes_total"]
    if n_shapes:
        # graftlint: disable=dtype-drift -- epoch-ms timestamps exceed f32 integer range
        shape_ts = np.asarray(parsed["shape_max_ts_ms"], dtype=np.float64)
        idx = np.arange(n_shapes, dtype=np.int64)
        eids_all = np.concatenate(
            [session.eid_of, session.rt_eid_of]
        ).astype(np.int64)
        ts_all = np.concatenate([shape_ts, shape_ts])
        code_all = np.concatenate([2 * idx, 2 * idx + 1])
        order = np.lexsort((code_all, -ts_all, eids_all))
        e_sorted = eids_all[order]
        first = np.ones(e_sorted.size, bool)
        first[1:] = e_sorted[1:] != e_sorted[:-1]
        win_eid = e_sorted[first]
        win_ts = ts_all[order][first]
        win_code = code_all[order][first]
        n_ep = len(interner.endpoints)
        session._grow_applied(n_ep)
        adv = win_ts > session.applied_ts[win_eid]
        # in-place fast path: same winner as last time AND (checked
        # atomically inside the interner lock) nothing else — e.g. the
        # dict-path tick — refreshed the info since we did, so only the
        # timestamp moves and content is already right. A compare-and-
        # set failure routes that endpoint through the exact slow path.
        fast = adv & (win_code == session.applied_code[win_eid])
        fast_pos = np.flatnonzero(fast)
        slow_pos = np.flatnonzero(adv & ~fast)
        if fast_pos.size:
            failed = interner.refresh_info_timestamps(
                win_eid[fast_pos],
                win_ts[fast_pos],
                expected_ts=session.applied_ts[win_eid[fast_pos]],
            )
            if failed:
                slow_pos = np.concatenate([slow_pos, fast_pos[failed]])
        for p in slow_pos.tolist():
            e, t, c = int(win_eid[p]), float(win_ts[p]), int(win_code[p])
            hit = session.entries[c >> 1]
            if c & 1:
                interner.intern_endpoint(
                    hit.rt_uen, {**hit.rt_base, "timestamp": t}
                )
            else:
                interner.intern_endpoint(
                    hit.uen, {**hit.info_base, "timestamp": t}
                )
        session.applied_ts[win_eid[adv]] = win_ts[adv]
        session.applied_code[win_eid[adv]] = win_code[adv]

    # -- span columns: pure gathers ------------------------------------------
    n = parsed["n_spans"]
    capacity = _pad_size(n) if pad else max(n, 1)
    valid = np.zeros(capacity, dtype=bool)
    valid[:n] = True

    def _padded(arr: np.ndarray, dtype, fill=0):
        out = np.full(capacity, fill, dtype=dtype)
        out[:n] = arr[:n]
        return out

    shape_ids = parsed["shape_id"][:n]
    endpoint_id = np.zeros(capacity, dtype=np.int32)
    service_id = np.zeros(capacity, dtype=np.int32)
    rt_endpoint_id = np.zeros(capacity, dtype=np.int32)
    rt_service_id = np.zeros(capacity, dtype=np.int32)
    status_id = np.zeros(capacity, dtype=np.int32)
    status_class = np.zeros(capacity, dtype=np.int8)
    if n:
        endpoint_id[:n] = session.eid_of[shape_ids]
        service_id[:n] = session.sid_of[shape_ids]
        rt_endpoint_id[:n] = session.rt_eid_of[shape_ids]
        rt_service_id[:n] = session.rt_sid_of[shape_ids]
        status_id[:n] = session.st_ids[parsed["status_id"][:n]]
        status_class[:n] = session.st_cls[parsed["status_id"][:n]]

    timestamp_us = _padded(parsed["timestamp_us"], np.int64)
    timestamp_rel, ts_base = _compute_timestamp_rel(
        timestamp_us, n, capacity, ts_base_us
    )

    batch = SpanBatch(
        n_spans=n,
        valid=valid,
        kind=_padded(parsed["kind"], np.int8),
        parent_idx=_padded(parsed["parent_idx"], np.int32, fill=-1),
        endpoint_id=endpoint_id,
        service_id=service_id,
        rt_endpoint_id=rt_endpoint_id,
        rt_service_id=rt_service_id,
        status_id=status_id,
        status_class=status_class,
        latency_ms=_padded(parsed["latency_ms"], np.float64),  # graftlint: disable=dtype-drift -- host span column, f64 by design (see spans_to_batch)
        timestamp_us=timestamp_us,
        timestamp_rel=timestamp_rel,
        ts_base_us=ts_base,
        trace_of=_padded(parsed["trace_of"], np.int32),
        interner=interner,
        statuses=statuses,
        endpoint_infos=[i for i in interner.endpoint_infos if i is not None],
    )
    return batch, KeptTraceIds(
        parsed["trace_ids"], parsed.get("trace_ids_blob")
    )


ROW_SLOTS = 64  # spans per packed trace row (the MXU ancestor-walk tile)


class PackedRows:
    """Trace-row packing of a SpanBatch for the matmul ancestor walk.

    Each trace occupies a contiguous run of slots inside one ROW_SLOTS-slot
    row, so parent pointers become row-local and the CLIENT-skip /
    ancestor-chain gathers lower to batched one-hot einsums on the MXU
    (kmamiz_tpu.ops.window.dependency_edges_packed) instead of HBM gathers.
    Traces are bucketed by next-power-of-two size (vectorized packing, at
    most 2x slot waste); rows are padded to a power of two.
    """

    __slots__ = ("row_of", "slot_of", "n_rows", "n_spans", "max_trace_len")

    def __init__(self, row_of, slot_of, n_rows, n_spans, max_trace_len):
        self.row_of = row_of
        self.slot_of = slot_of
        self.n_rows = n_rows
        self.n_spans = n_spans
        # longest trace in the window: ancestor chains cannot exceed
        # max_trace_len - 1 hops, so the MXU walk can cap its depth
        self.max_trace_len = max_trace_len

    def pack(self, values: np.ndarray, fill) -> np.ndarray:
        """Scatter a flat per-span array into [n_rows, ROW_SLOTS] layout."""
        out = np.full((self.n_rows, ROW_SLOTS), fill, dtype=values.dtype)
        out[self.row_of, self.slot_of] = values[: self.n_spans]
        return out

    def parent_slots(self, parent_idx: np.ndarray) -> np.ndarray:
        """Translate flat parent indices to row-local parent slots (-1 for
        no parent); feed the result through pack(..., -1)."""
        pslot = np.full(self.n_spans, -1, dtype=np.int32)
        has = parent_idx[: self.n_spans] >= 0
        pslot[has] = self.slot_of[parent_idx[: self.n_spans][has]]
        return pslot


def max_ancestor_chain(parent_idx: np.ndarray, n_spans: int) -> int:
    """Longest parent-chain length in HOPS across the window, memoized
    O(n) (each span's depth computes once). Used by the flat-gather
    merge fallback — the path taken exactly when pack_trace_rows cannot
    lay the window out (overlong traces, cross-trace parents) — to size
    its walk depth: a fixed cap there silently dropped ancestors past it
    while the reference walk is unbounded (review r5). A parent CYCLE
    (possible only under adversarial duplicate span ids; the reference's
    while-loop would not terminate on one) counts as a chain end at the
    revisited span."""
    if n_spans == 0:
        return 0
    p = np.asarray(parent_idx[:n_spans], dtype=np.int64)
    depth = np.full(n_spans, 0, dtype=np.int64)  # 0 = unknown; else nodes
    VISITING = -1
    for i in range(n_spans):
        if depth[i] > 0:
            continue
        path = []
        j = i
        while j >= 0 and depth[j] <= 0:
            if depth[j] == VISITING:
                j = -1  # cycle: treat the revisited span as a root edge
                break
            depth[j] = VISITING
            path.append(j)
            nxt = p[j]
            j = int(nxt) if 0 <= nxt < n_spans else -1
        base = int(depth[j]) if j >= 0 else 0
        for k in reversed(path):
            base += 1
            depth[k] = base
    return int(depth.max()) - 1  # hops = chain nodes - 1


def pack_trace_rows(
    trace_of: np.ndarray, n_spans: int, parent_idx: Optional[np.ndarray] = None
) -> Optional[PackedRows]:
    """Assign each span a (row, slot) so its whole trace shares one row.

    Returns None when the layout cannot hold the window — a trace longer
    than ROW_SLOTS, non-contiguous trace membership, or a parent pointer
    crossing traces — in which case callers use the flat gather path.
    """
    if n_spans == 0:
        return None
    t = np.asarray(trace_of[:n_spans])
    if np.any(np.diff(t) < 0):
        return None  # trace ids must be non-decreasing (contiguous traces)
    sizes = np.bincount(t)
    if sizes.size == 0 or sizes.max() > ROW_SLOTS or sizes.min() == 0:
        return None

    n_traces = sizes.size
    first_span = np.zeros(n_traces, dtype=np.int64)
    first_span[1:] = np.cumsum(sizes)[:-1]

    # bucket traces by pow2 size; rows are filled per bucket, vectorized
    bucket = np.maximum(
        1 << (np.ceil(np.log2(np.maximum(sizes, 1))).astype(np.int64)), 1
    )
    row_of_trace = np.zeros(n_traces, dtype=np.int64)
    base_of_trace = np.zeros(n_traces, dtype=np.int64)
    next_row = 0
    for b in np.unique(bucket):
        ids = np.nonzero(bucket == b)[0]
        per_row = ROW_SLOTS // int(b)
        rank = np.arange(len(ids))
        row_of_trace[ids] = next_row + rank // per_row
        base_of_trace[ids] = (rank % per_row) * int(b)
        next_row += -(-len(ids) // per_row)

    offs = np.arange(n_spans, dtype=np.int64) - first_span[t]
    row_of = row_of_trace[t]
    slot_of = base_of_trace[t] + offs
    n_rows = _pad_size(next_row, minimum=1)

    if parent_idx is not None:
        p = np.asarray(parent_idx[:n_spans])
        has_parent = p >= 0
        if np.any(row_of[p[has_parent]] != row_of[has_parent.nonzero()[0]]):
            return None  # cross-trace parent (span-id collision): bail out
    return PackedRows(row_of, slot_of, int(n_rows), n_spans, int(sizes.max()))
