"""SoA span batches: Zipkin JSON -> fixed-shape device arrays.

This is the design translation at the heart of the TPU backend (SURVEY.md
§7): the reference walks per-span object graphs
(/root/reference/src/classes/Traces.ts:112-211, Rust twin
kmamiz_data_processor/src/data/trace.rs:110-212); here a window of spans
becomes id-indexed arrays. Parent span-ids are resolved to row indices on
the host (strings never reach the device); the CLIENT-skip ancestor walk and
all groupby statistics then run as jitted kernels (kmamiz_tpu.ops.window).

Batches are padded to power-of-two sizes so XLA compiles a bounded number of
program shapes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kmamiz_tpu.core.interning import EndpointInterner, StringInterner
from kmamiz_tpu.core.schema import js_str as _js
from kmamiz_tpu.domain.traces import to_endpoint_info

KIND_OTHER = 0
KIND_SERVER = 1
KIND_CLIENT = 2


def _pad_size(n: int, base: int = 2, minimum: int = 8) -> int:
    size = minimum
    while size < n:
        size *= base
    return size


@dataclass
class SpanBatch:
    """One window of spans in structure-of-arrays form.

    All arrays share length `capacity` (padded); rows [n_spans:] are padding
    with valid=False. Ids index the accompanying interner tables.
    """

    n_spans: int
    valid: np.ndarray  # bool[capacity]
    kind: np.ndarray  # int8[capacity] (KIND_*)
    parent_idx: np.ndarray  # int32[capacity], -1 = no parent in window
    # graph id space: ToEndpointInfo naming (.svc. parse w/ istio fallback)
    endpoint_id: np.ndarray  # int32[capacity]
    service_id: np.ndarray  # int32[capacity]
    # realtime id space: istio-tag naming used by the stats/combined path
    # (the reference names endpoints differently in toRealTimeData /
    # combineLogsToRealtimeData vs toEndpointDependencies)
    rt_endpoint_id: np.ndarray  # int32[capacity]
    rt_service_id: np.ndarray  # int32[capacity]
    status_id: np.ndarray  # int32[capacity]
    status_class: np.ndarray  # int8[capacity] (first digit of http status)
    latency_ms: np.ndarray  # float64[capacity] (duration / 1000)
    timestamp_us: np.ndarray  # int64[capacity] (host-side absolute)
    timestamp_rel: np.ndarray  # int32[capacity] (µs offset from ts_base_us;
    # absolute µs don't fit int32 and the TPU path runs with x64 off)
    ts_base_us: int

    interner: EndpointInterner
    statuses: StringInterner
    # per-endpoint metadata for reconstructing protocol output
    endpoint_infos: List[dict]

    @property
    def capacity(self) -> int:
        return len(self.valid)

    @property
    def num_endpoints(self) -> int:
        return len(self.interner.endpoints)

    @property
    def num_services(self) -> int:
        return len(self.interner.services)

    @property
    def num_statuses(self) -> int:
        return len(self.statuses)


def spans_to_batch(
    trace_groups: Sequence[Sequence[dict]],
    interner: Optional[EndpointInterner] = None,
    statuses: Optional[StringInterner] = None,
    pad: bool = True,
    ts_base_us: Optional[int] = None,
) -> SpanBatch:
    """Flatten Zipkin trace groups into a SpanBatch.

    Mirrors the reference's span-map construction: spans are keyed by id with
    last-wins/first-position semantics (JS Map), and parent ids resolve only
    within the window.
    """
    interner = interner or EndpointInterner()
    statuses = statuses or StringInterner()

    span_map: Dict[str, dict] = {}
    for group in trace_groups:
        for span in group:
            span_map[span["id"]] = span
    spans = list(span_map.values())
    index_of = {span_id: i for i, span_id in enumerate(span_map.keys())}

    n = len(spans)
    capacity = _pad_size(n) if pad else max(n, 1)

    valid = np.zeros(capacity, dtype=bool)
    kind = np.zeros(capacity, dtype=np.int8)
    parent_idx = np.full(capacity, -1, dtype=np.int32)
    endpoint_id = np.zeros(capacity, dtype=np.int32)
    service_id = np.zeros(capacity, dtype=np.int32)
    rt_endpoint_id = np.zeros(capacity, dtype=np.int32)
    rt_service_id = np.zeros(capacity, dtype=np.int32)
    status_id = np.zeros(capacity, dtype=np.int32)
    status_class = np.zeros(capacity, dtype=np.int8)
    latency_ms = np.zeros(capacity, dtype=np.float64)
    timestamp_us = np.zeros(capacity, dtype=np.int64)

    for i, span in enumerate(spans):
        valid[i] = True
        k = span.get("kind")
        kind[i] = (
            KIND_SERVER if k == "SERVER" else KIND_CLIENT if k == "CLIENT" else KIND_OTHER
        )
        parent = span.get("parentId")
        if parent is not None and parent in index_of:
            parent_idx[i] = index_of[parent]

        info = to_endpoint_info(span)
        eid = interner.intern_endpoint(info["uniqueEndpointName"], info)
        endpoint_id[i] = eid
        service_id[i] = interner.service_of(eid)

        tags = span.get("tags", {})
        rt_usn = (
            f"{_js(tags.get('istio.canonical_service'))}"
            f"\t{_js(tags.get('istio.namespace'))}"
            f"\t{_js(tags.get('istio.canonical_revision'))}"
        )
        rt_uen = (
            f"{rt_usn}\t{_js(tags.get('http.method'))}\t{_js(tags.get('http.url'))}"
        )
        # metadata for the rt-space endpoint carries the rt naming (istio
        # tags), not the graph-space info
        rt_eid = interner.intern_endpoint(
            rt_uen,
            {
                **info,
                "service": tags.get("istio.canonical_service"),
                "namespace": tags.get("istio.namespace"),
                "version": tags.get("istio.canonical_revision"),
                "uniqueServiceName": rt_usn,
                "uniqueEndpointName": rt_uen,
            },
        )
        rt_endpoint_id[i] = rt_eid
        rt_service_id[i] = interner.service_of(rt_eid)

        status = tags.get("http.status_code") or ""
        status_id[i] = statuses.intern(status)
        status_class[i] = int(status[0]) if status[:1].isdigit() else 0
        latency_ms[i] = span.get("duration", 0) / 1000
        timestamp_us[i] = span.get("timestamp", 0)

    endpoint_infos = [i for i in interner.endpoint_infos if i is not None]
    if ts_base_us is not None:
        ts_base = ts_base_us
    else:
        ts_base = int(timestamp_us[:n].min()) if n else 0
    timestamp_rel = np.zeros(capacity, dtype=np.int32)
    if n:
        span_rel = timestamp_us[:n] - ts_base
        if span_rel.max() > np.iinfo(np.int32).max:
            # one batch must fit int32 µs offsets (~35 min); realtime windows
            # are 30 s — long replays/backfills must split into batches
            raise ValueError(
                "span window exceeds int32 µs range; split the batch "
                f"(span of {span_rel.max() / 1e6:.0f}s)"
            )
        timestamp_rel[:n] = span_rel.astype(np.int32)
    return SpanBatch(
        n_spans=n,
        valid=valid,
        kind=kind,
        parent_idx=parent_idx,
        endpoint_id=endpoint_id,
        service_id=service_id,
        rt_endpoint_id=rt_endpoint_id,
        rt_service_id=rt_service_id,
        status_id=status_id,
        status_class=status_class,
        latency_ms=latency_ms,
        timestamp_us=timestamp_us,
        timestamp_rel=timestamp_rel,
        ts_base_us=ts_base,
        interner=interner,
        statuses=statuses,
        endpoint_infos=endpoint_infos,
    )
