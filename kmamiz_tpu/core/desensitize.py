"""Type-preserving JSON de-identification.

Equivalent of the reference's two body-scrubbing implementations:
- the Envoy WASM filter's gjson walk that replaces every JSON value with a
  type-preserving zero value before logging (/root/reference/envoy/wasm/main.go:210-240);
- the simulator's sample de-identification
  (/root/reference/src/MicroViSim-simulator/classes/SimConfigPreprocessor/
  SimConfigServicesInfoPreprocessor.ts:253-284).

Strings -> "", numbers -> 0, booleans -> false, anything else -> null;
containers keep their shape. (The WASM filter's scrubber, which preserves
booleans/null, lives in kmamiz_tpu.core.envoy_filter.)
"""
from __future__ import annotations

from typing import Any

_TYPE_ZERO = {"string": "", "number": 0, "boolean": False}


def deidentify_sample(value: Any) -> Any:
    """Replace every leaf of a parsed JSON sample with its zero value."""
    if isinstance(value, list):
        return [deidentify_sample(v) for v in value]
    if isinstance(value, dict):
        return {k: deidentify_sample(v) for k, v in value.items()}
    if isinstance(value, bool):  # bool before int: True is an int in Python
        return False
    if isinstance(value, str):
        return ""
    if isinstance(value, (int, float)):
        return 0
    return None


def deidentify_type_definition(value: Any) -> Any:
    """Replace type-name leaves ("string"/"number"/"boolean") of a parsed
    type-definition JSON with zero values; unknown names become null."""
    if isinstance(value, list):
        return [deidentify_type_definition(v) for v in value]
    if isinstance(value, dict):
        return {k: deidentify_type_definition(v) for k, v in value.items()}
    if isinstance(value, str) and value in _TYPE_ZERO:
        return _TYPE_ZERO[value]
    return None
