"""Leveled logger with the reference's fatal semantics.

Equivalent of /root/reference/src/utils/Logger.ts: a thin wrapper over the
stdlib logging stack with verbose/info/warn/error levels driven by the
LOG_LEVEL setting and a `fatal()` that logs and signals the process to
terminate (Logger.ts:45-52 sends SIGTERM so the graceful-exit hook flushes
caches before death; kmamiz_tpu.api.app installs that hook).
"""
from __future__ import annotations

import logging
import os
import signal
from typing import Optional

VERBOSE = 5
logging.addLevelName(VERBOSE, "VERBOSE")

_LEVELS = {
    "verbose": VERBOSE,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "error": logging.ERROR,
}


def configure(level: Optional[str] = None) -> None:
    """Apply LOG_LEVEL (verbose|info|warn|error, Logger.ts:22-30)."""
    from kmamiz_tpu.config import settings

    name = (level or settings.log_level or "info").lower()
    logging.getLogger("kmamiz_tpu").setLevel(_LEVELS.get(name, logging.INFO))


def get(name: str) -> logging.Logger:
    """Prefixed child logger (Logger.prefixed)."""
    return logging.getLogger(f"kmamiz_tpu.{name}")


def verbose(logger: logging.Logger, msg: str, *args) -> None:
    logger.log(VERBOSE, msg, *args)


def fatal(logger: logging.Logger, msg: str, *args) -> None:
    """Log at error level and terminate via SIGTERM (Logger.ts:45-52).
    The graceful cache-flush teardown only runs where a SIGTERM handler is
    installed (kmamiz_tpu.api.app.main); other entry points just die."""
    logger.error("FATAL: " + msg, *args)
    os.kill(os.getpid(), signal.SIGTERM)
