"""Envoy WASM-filter log parsing and request/response pairing.

Parity with the reference's log pipeline:
- line parsing: /root/reference/src/services/KubernetesService.ts:201-242
  (Rust twin: kmamiz_data_processor/src/http_client/log_matcher.rs)
- request/response structuring with span-id match, stack-based fallback when
  spanId=NO_ID, cross-pod combine and parent-id fill:
  /root/reference/src/classes/EnvoyLog.ts
"""
from __future__ import annotations

import re
from datetime import datetime, timezone
from typing import Dict, List, Optional

_HEADER_RE = re.compile(
    r"\[(Request|Response) ([\w-]+)/(\w+)/(\w+)/(\w+)\]"
)
_STATUS_RE = re.compile(r"\[Status\] ([0-9]+)")
_METHOD_PATH_RE = re.compile(r"(GET|POST|PUT|DELETE|PATCH|HEAD|OPTIONS) ([^\]]+)")
_CONTENT_TYPE_RE = re.compile(r"\[ContentType ([^\]]*)\]")
_BODY_RE = re.compile(r"\[Body\] (.*)")

_ISTIO_PROXY_PREFIX_RE = re.compile(
    r"\t.*envoy (lua|wasm).*\t(script|wasm) log[^:]*: "
)


def parse_timestamp_ms(time_str: str) -> float:
    """RFC3339 timestamp -> epoch milliseconds."""
    try:
        dt = datetime.fromisoformat(time_str.replace("Z", "+00:00"))
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return dt.timestamp() * 1000
    except ValueError:
        return float("nan")


def strip_istio_proxy_prefix(lines: List[str]) -> List[str]:
    """Reduce raw istio-proxy container log lines to 'time\\tpayload' form
    (KubernetesService.getEnvoyLogs filtering). Uses the native C++ parser
    when built (native/kmamiz_native.cpp), else pure Python."""
    from kmamiz_tpu import native

    native_out = native.strip_istio_proxy_prefix(lines)
    if native_out is not None:
        return native_out
    out = []
    for line in lines:
        if "script log: " not in line and "wasm log " not in line:
            continue
        out.append(_ISTIO_PROXY_PREFIX_RE.sub("\t", line))
    return out


def parse_envoy_logs(
    logs: List[str], namespace: str, pod_name: str
) -> "EnvoyLogs":
    """Parse 'time\\t[Request|Response ...]' lines into TEnvoyLog dicts
    (KubernetesService.ParseEnvoyLogs). Uses the native C++ parser when
    built (native/kmamiz_native.cpp), else pure Python."""
    from kmamiz_tpu import native

    records = native.parse_envoy_lines(logs)
    if records is None:
        records = _parse_envoy_lines_py(logs)

    # shared decoration: timestamp parse, pod identity, and the
    # "first non-NO_ID traceId wins per requestId" backfill
    id_map: Dict[str, str] = {}
    envoy_logs: List[dict] = []
    for r in records:
        if r["requestId"] not in id_map and r["traceId"] != "NO_ID":
            id_map[r["requestId"]] = r["traceId"]
        entry = dict(r)
        entry["timestamp"] = parse_timestamp_ms(entry.pop("time"))
        entry["namespace"] = namespace
        entry["podName"] = pod_name
        envoy_logs.append(entry)
    for e in envoy_logs:
        e["traceId"] = id_map.get(e["requestId"], "NO_ID")
    return EnvoyLogs(envoy_logs)


def _parse_envoy_lines_py(logs: List[str]) -> List[dict]:
    """Pure-Python twin of native.parse_envoy_lines: raw undecorated field
    records, one per parseable line."""
    records: List[dict] = []
    for l in logs:
        parts = l.split("\t", 1)
        if len(parts) != 2:
            continue
        time_str, log = parts
        header = _HEADER_RE.search(log)
        if not header:
            continue
        log_type, request_id, trace_id, span_id, parent_span_id = header.groups()
        status = (_STATUS_RE.search(log) or [None, None])[1]
        mp = _METHOD_PATH_RE.search(log)
        method, path = (mp.group(1), mp.group(2)) if mp else (None, None)
        ct = _CONTENT_TYPE_RE.search(log)
        body = _BODY_RE.search(log)
        records.append(
            {
                "time": time_str,
                "type": log_type,
                "requestId": request_id,
                "traceId": trace_id,
                "spanId": span_id,
                "parentSpanId": parent_span_id,
                "method": method,
                "path": path,
                "status": status,
                "contentType": ct.group(1) if ct else None,
                "body": body.group(1) if body else None,
            }
        )
    return records


class EnvoyLogs:
    def __init__(self, envoy_logs: List[dict]) -> None:
        self._logs = envoy_logs

    def to_json(self) -> List[dict]:
        return self._logs

    # -- structuring (EnvoyLog.ts:17-99) -------------------------------------

    def to_structured(self) -> List[dict]:
        if not self._logs:
            return []
        log_map: Dict[str, Dict[str, dict]] = {}
        span_ids = set()
        for e in self._logs:
            key = f"{e['requestId']}/{e['traceId']}"
            log_map.setdefault(key, {})[e["spanId"]] = e
            span_ids.add(e["spanId"])
        if "NO_ID" in span_ids:
            return self.to_structured_fallback()

        structured = []
        for key, span_map in log_map.items():
            request_id, trace_id = key.split("/")
            traces = []
            for span_id, log in span_map.items():
                parent = span_map.get(log["parentSpanId"])
                if log["type"] == "Response" and parent and parent["type"] == "Request":
                    traces.append(
                        {
                            "traceId": trace_id,
                            "spanId": span_id,
                            "parentSpanId": log["parentSpanId"],
                            "request": parent,
                            "response": log,
                            "isFallback": False,
                        }
                    )
            structured.append({"requestId": request_id, "traces": traces})
        return structured

    def to_structured_fallback(self) -> List[dict]:
        if not self._logs:
            return []
        logs_map: Dict[str, List[dict]] = {}
        for log in self._logs:
            if not log.get("requestId"):
                continue
            logs_map.setdefault(f"{log['requestId']}/{log['traceId']}", []).append(log)

        structured = []
        for key, logs in logs_map.items():
            request_id, trace_id = key.split("/")
            trace_stack: List[dict] = []
            trace_map: Dict[str, dict] = {}
            for log in logs:
                if log["type"] == "Request":
                    trace_stack.append(log)
                if log["type"] == "Response":
                    if not trace_stack:
                        continue
                    req = trace_stack.pop()
                    trace_map[req["spanId"]] = {
                        "traceId": trace_id,
                        "request": req,
                        "response": log,
                        "spanId": req["spanId"],
                        "parentSpanId": req["parentSpanId"],
                        "isFallback": True,
                    }
            structured.append(
                {"requestId": request_id, "traces": list(trace_map.values())}
            )
        return structured

    # -- cross-pod combine (EnvoyLog.ts:101-149) -----------------------------

    @staticmethod
    def combine_to_structured_envoy_logs(logs: List["EnvoyLogs"]) -> List[dict]:
        combined = EnvoyLogs.combine_structured([l.to_structured() for l in logs])
        return EnvoyLogs.fill_missing_ids(combined)

    @staticmethod
    def combine_structured(logs: List[List[dict]]) -> List[dict]:
        log_map: Dict[str, List[dict]] = {}
        for service_log in logs:
            for log in service_log:
                log_map.setdefault(log["requestId"], []).extend(log["traces"])
        # Deliberate deviation: the reference passes a one-argument comparator
        # (EnvoyLog.ts:124) so its "sort" never actually orders traces; a true
        # ascending request-timestamp sort is what the code intends.
        return [
            {
                "requestId": request_id,
                "traces": sorted(
                    traces, key=lambda t: t["request"]["timestamp"]
                ),
            }
            for request_id, traces in log_map.items()
        ]

    @staticmethod
    def fill_missing_ids(logs: List[dict]) -> List[dict]:
        id_map: Dict[str, str] = {}
        for l in logs:
            for t in l["traces"]:
                if t.get("parentSpanId") and t["parentSpanId"] != "NO_ID":
                    id_map[f"{l['requestId']}/{t['spanId']}"] = t["parentSpanId"]
        for l in logs:
            for t in l["traces"]:
                t["parentSpanId"] = id_map.get(
                    f"{l['requestId']}/{t['spanId']}", t.get("parentSpanId")
                )
        return logs
