"""Columnar ingest wire format ("KMZC" frames).

Reference Python codec for the compact SoA binary frame the Envoy WASM
filter emits so production ingest skips Zipkin JSON entirely
(docs/INGEST_WIRE.md is the layout spec; the native decoder lives in
native/kmamiz_spans.cpp `parse_columnar_window`, and the Go encoder in
envoy/filter/main.go mirrors `encode_groups` byte for byte).

Three uses:
- `encode_groups` builds frames for tests/benches and documents the
  encoder contract the filter implements.
- `decode_groups` / `columnar_to_json` are the pure-Python FALLBACK: a
  stale prebuilt .so without `km_wire_caps` transcodes the frame back to
  Zipkin trace groups and parses through the JSON path — same result,
  host-speed only.
- `is_columnar` is the sniff every ingest surface shares.

Parity contract: a frame round-trips to the exact rows the JSON scanner
would produce — sid -1 means ABSENT (key omitted in JSON), distinct from
an empty string; kind 0 carries "neither SERVER nor CLIENT"; timestamps
and durations are integer microseconds (the only shape Zipkin emits).
Any malformed byte (magic, version, length, CRC, out-of-range sid, bad
kind) rejects the WHOLE frame with None — mirroring malformed JSON into
the same quarantine path.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Optional

MAGIC = b"KMZC"
VERSION = 1
_HEADER = struct.Struct("<4sBBHII")  # magic, ver, flags, reserved, len, crc
# per-span fixed-width column record width: 10 x i32 + 1 x i8 + 2 x i64
_SPAN_BYTES = 10 * 4 + 1 + 2 * 8

_KIND_TO_CODE = {"SERVER": 1, "CLIENT": 2}
_CODE_TO_KIND = {1: "SERVER", 2: "CLIENT"}

# (span key, tag key) per i32 sid column, encoder order. id/parent are
# span-level; the naming fields ride in Zipkin tags exactly as the JSON
# scanner reads them (tag_handler in native/kmamiz_spans.cpp).
_TAG_COLUMNS = (
    "http.url",
    "http.method",
    "istio.canonical_service",
    "istio.namespace",
    "istio.canonical_revision",
    "istio.mesh_id",
)


def is_columnar(raw: bytes) -> bool:
    return raw[:4] == MAGIC


class _StringTable:
    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}
        self.entries: List[bytes] = []

    def sid(self, value: Optional[str]) -> int:
        if value is None:
            return -1
        got = self._ids.get(value)
        if got is None:
            got = self._ids[value] = len(self.entries)
            self.entries.append(value.encode("utf-8"))
        return got


def encode_groups(groups: List[List[Dict[str, Any]]]) -> bytes:
    """Zipkin trace groups (the /ingest body shape) -> one KMZC frame.

    The group's traceId is taken from its first span (absent/None maps to
    sid -1, the same collapse the JSON prescan applies). Non-string tag
    values are dropped like the JSON scanner drops them.
    """
    tab = _StringTable()
    group_recs: List[tuple] = []
    cols: List[List[int]] = [[] for _ in range(10)]
    kinds: List[int] = []
    ts_col: List[int] = []
    dur_col: List[int] = []

    for spans in groups:
        tid = None
        if spans:
            tid = spans[0].get("traceId")
            if not isinstance(tid, str):
                tid = None
        group_recs.append((tab.sid(tid), len(spans)))
        for span in spans:
            tags = span.get("tags")
            if not isinstance(tags, dict):
                tags = {}

            def _s(value) -> Optional[str]:
                return value if isinstance(value, str) else None

            cols[0].append(tab.sid(_s(span.get("id"))))
            cols[1].append(tab.sid(_s(span.get("parentId"))))
            cols[2].append(tab.sid(_s(span.get("name"))))
            cols[3].append(tab.sid(_s(tags.get("http.url"))))
            cols[4].append(tab.sid(_s(tags.get("http.method"))))
            cols[5].append(tab.sid(_s(tags.get("istio.canonical_service"))))
            cols[6].append(tab.sid(_s(tags.get("istio.namespace"))))
            cols[7].append(tab.sid(_s(tags.get("istio.canonical_revision"))))
            cols[8].append(tab.sid(_s(tags.get("istio.mesh_id"))))
            cols[9].append(tab.sid(_s(tags.get("http.status_code"))))
            kinds.append(_KIND_TO_CODE.get(span.get("kind"), 0))
            ts_col.append(int(span.get("timestamp") or 0))
            dur_col.append(int(span.get("duration") or 0))

    n = len(kinds)
    body = bytearray()
    body += struct.pack("<I", len(tab.entries))
    for entry in tab.entries:
        body += struct.pack("<I", len(entry))
        body += entry
    body += struct.pack("<I", len(group_recs))
    for tid_sid, cnt in group_recs:
        body += struct.pack("<iI", tid_sid, cnt)
    body += struct.pack("<I", n)
    for col in cols:
        body += struct.pack(f"<{n}i", *col)
    body += struct.pack(f"<{n}b", *kinds)
    body += struct.pack(f"<{n}q", *ts_col)
    body += struct.pack(f"<{n}q", *dur_col)

    header = _HEADER.pack(
        MAGIC, VERSION, 0, 0, len(body), zlib.crc32(bytes(body))
    )
    return header + bytes(body)


def decode_groups(raw: bytes) -> Optional[List[List[Dict[str, Any]]]]:
    """KMZC frame -> Zipkin trace groups, or None on ANY malformation
    (same all-or-nothing contract as the native decoder)."""
    try:
        if len(raw) < _HEADER.size:
            return None
        magic, ver, flags, _res, body_len, crc = _HEADER.unpack_from(raw, 0)
        if magic != MAGIC or ver != VERSION or flags != 0:
            return None
        body = raw[_HEADER.size:]
        if len(body) != body_len or zlib.crc32(body) != crc:
            return None

        off = 0
        (n_strings,) = struct.unpack_from("<I", body, off)
        off += 4
        strs: List[str] = []
        for _ in range(n_strings):
            (slen,) = struct.unpack_from("<I", body, off)
            off += 4
            if off + slen > len(body):
                return None
            strs.append(body[off : off + slen].decode("utf-8"))
            off += slen

        def _sv(sid: int) -> Optional[str]:
            if sid == -1:
                return None
            if 0 <= sid < len(strs):
                return strs[sid]
            raise ValueError("sid out of range")

        (n_groups,) = struct.unpack_from("<I", body, off)
        off += 4
        group_recs = []
        span_sum = 0
        for _ in range(n_groups):
            tid_sid, cnt = struct.unpack_from("<iI", body, off)
            off += 8
            _sv(tid_sid)
            group_recs.append((tid_sid, cnt))
            span_sum += cnt
        (n,) = struct.unpack_from("<I", body, off)
        off += 4
        if span_sum != n or len(body) - off != n * _SPAN_BYTES:
            return None

        cols = []
        for _ in range(10):
            cols.append(struct.unpack_from(f"<{n}i", body, off))
            off += 4 * n
        kinds = struct.unpack_from(f"<{n}b", body, off)
        off += n
        ts_col = struct.unpack_from(f"<{n}q", body, off)
        off += 8 * n
        dur_col = struct.unpack_from(f"<{n}q", body, off)

        groups: List[List[Dict[str, Any]]] = []
        row = 0
        for tid_sid, cnt in group_recs:
            tid = _sv(tid_sid)
            spans = []
            for i in range(row, row + cnt):
                if kinds[i] not in (0, 1, 2):
                    return None
                span: Dict[str, Any] = {}
                if tid is not None:
                    span["traceId"] = tid
                sid_val = _sv(cols[0][i])
                if sid_val is not None:
                    span["id"] = sid_val
                parent = _sv(cols[1][i])
                if parent is not None:
                    span["parentId"] = parent
                name = _sv(cols[2][i])
                if name is not None:
                    span["name"] = name
                kind = _CODE_TO_KIND.get(kinds[i])
                if kind is not None:
                    span["kind"] = kind
                span["timestamp"] = ts_col[i]
                span["duration"] = dur_col[i]
                tags: Dict[str, str] = {}
                for col_idx, key in enumerate(_TAG_COLUMNS, start=3):
                    val = _sv(cols[col_idx][i])
                    if val is not None:
                        tags[key] = val
                status = _sv(cols[9][i])
                if status is not None:
                    tags["http.status_code"] = status
                if tags:
                    span["tags"] = tags
                spans.append(span)
            row += cnt
            groups.append(spans)
        return groups
    except (struct.error, ValueError, UnicodeDecodeError):
        return None


def columnar_to_json(raw: bytes) -> Optional[bytes]:
    """Transcode a KMZC frame to the equivalent Zipkin trace-group JSON
    bytes (the stale-.so fallback path), or None on a malformed frame."""
    groups = decode_groups(raw)
    if groups is None:
        return None
    return json.dumps(groups, separators=(",", ":")).encode("utf-8")
